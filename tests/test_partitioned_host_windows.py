"""Host-mode windows inside partitions: one stage instance per key
(reference PartitionRuntime instantiating a WindowProcessor per key)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


def test_partitioned_sort_window_keeps_per_key_minima():
    m, rt, c = build("""
        define stream S (sym string, price double);
        partition with (sym of S) begin
        from S#window.sort(2, price)
        select sym, sum(price) as total insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 50.0])
    h.send(["A", 20.0])
    h.send(["A", 40.0])    # A keeps the 2 smallest: {20, 40} -> 60
    h.send(["B", 5.0])     # B independent: {5}
    m.shutdown()
    last = {}
    for e in c.events:
        last[e.data[0]] = e.data[1]
    assert last["A"] == 60.0 and last["B"] == 5.0


def test_partitioned_frequent_window():
    m, rt, c = build("""
        define stream S (sym string, item string);
        partition with (sym of S) begin
        from S#window.frequent(1, item)
        select sym, item insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    for it in ["x", "x", "y"]:
        h.send(["A", it])
    h.send(["B", "z"])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    # per-key Misra-Gries with k=1: A's slot holds x (y displaced the
    # count but x dominated), B tracks z independently
    assert ("B", "z") in got and ("A", "x") in got


def test_partitioned_expression_batch_window():
    m, rt, c = build("""
        define stream S (sym string, v int);
        partition with (sym of S) begin
        from S#window.expressionBatch('count() <= 2')
        select sym, sum(v) as total insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 1])
    h.send(["A", 2])
    h.send(["A", 3])   # breaks A's expression: flush {1,2}, start {3}
    h.send(["B", 9])   # B's own batch keeps accumulating
    m.shutdown()
    totals = [tuple(e.data) for e in c.events]
    assert ("A", 3) in totals      # the flushed batch sum 1+2
