"""Reference built-in function corpus — scenarios ported verbatim from
``query/function/``: coalesce/default/eventTimestamp (FunctionTestCase),
the full convert() type matrix, ifThenElse, maximum/minimum, and uuid."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback


class QC(QueryCallback):
    def __init__(self):
        self.events = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)


def _run(app, stream, feed):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QC()
    rt.add_callback("query1", q)
    rt.start()
    h = rt.get_input_handler(stream)
    for r in feed:
        h.send(list(r))
    m.shutdown()
    return [e.data for e in q.events]


def test_coalesce_same_type():
    """functionTest1 (FunctionTestCase:57-117): first non-null of two
    floats; both null -> null."""
    rows = _run(
        "define stream cseEventStream (symbol string, price1 float, "
        "price2 float);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, coalesce(price1, price2) as price "
        "insert into StockQuote;",
        "cseEventStream",
        [["IBM", 55.6, 70.6], ["WSO2", 65.7, 12.8], ["WSO2", 23.6, None],
         ["WSO2", None, 34.6], ["WSO2", None, None]])
    assert [round(r[1], 4) if r[1] is not None else None for r in rows] == [
        55.6, 65.7, 23.6, 34.6, None]


def test_coalesce_in_filter():
    """functionTest3 (:164-207): coalesce in the filter condition; the
    all-null row fails the > comparison and is dropped."""
    rows = _run(
        "define stream cseEventStream (symbol string, price1 float, "
        "price2 float, volume long, quantity int);"
        "@info(name = 'query1') from "
        "cseEventStream[coalesce(price1,price2) > 0f] select symbol, "
        "coalesce(price1,price2) as price,quantity "
        "insert into outputStream ;",
        "cseEventStream",
        [["WSO2", 50.0, 60.0, 60, 6], ["WSO2", 70.0, None, 40, 10],
         ["WSO2", None, 44.0, 200, 56], ["WSO2", None, None, 200, 56]])
    assert [r[1] for r in rows] == [50.0, 70.0, 44.0]


def test_coalesce_no_args_rejected():
    """functionTest4 (:208-251): coalesce() without arguments fails at
    creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream cseEventStream (symbol string, price1 float, "
            "price2 float, volume long, quantity int);"
            "@info(name = 'query1') from "
            "cseEventStream[coalesce(price1,price2) > 0f] select symbol, "
            "coalesce() as price,quantity insert into outputStream ;")
    m.shutdown()


@pytest.mark.parametrize("sel", [
    "default(temp,0.0,deviceId)",    # testFunctionQuery5: 3 args
    "default(temp,123)",             # testFunctionQuery6: type mismatch
    "eventTimestamp(time)",          # testFunctionQuery7: takes no args
])
def test_function_arg_validation(sel):
    """testFunctionQuery5/6/7 (FunctionTestCase:252-303): arg-count and
    arg-type validation fails at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream cseEventStream (temp double, roomNo int, "
            "deviceId long, symbol string, time string);"
            f"@info(name = 'query1') from cseEventStream "
            f"select {sel} as x insert into outputStream;")
    m.shutdown()


def test_event_timestamp():
    """testFunctionQuery7_1 (:304-340): eventTimestamp() returns the
    event's own timestamp."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream fooStream (symbol string, time string);"
        "@info(name = 'query1') from fooStream "
        "select symbol as name, eventTimestamp() as eventTimestamp "
        "insert into barStream;")
    q = QC()
    rt.add_callback("query1", q)
    rt.start()
    rt.get_input_handler("fooStream").send(10, ["WSO2", "t"])
    m.shutdown()
    assert q.events[0].data == ["WSO2", 10]


def test_convert_type_matrix():
    """convertFunctionTest2 (ConvertFunctionTestCase:88-183): every
    source type converted to every target type; unparsable strings
    become null, string->bool of non-'true' is False."""
    sels = []
    for src in ["typeS", "typeF", "typeD", "typeI", "typeL", "typeB"]:
        for tgt in ["string", "float", "double", "int", "long", "bool"]:
            sels.append(f"convert({src},'{tgt}') as v{len(sels)}")
    rows = _run(
        "define stream typeStream (typeS string, typeF float, "
        "typeD double, typeI int, typeL long, typeB bool);"
        "@info(name = 'query1') from typeStream select "
        + ", ".join(sels) + " insert into outputStream;",
        "typeStream",
        [["WSO2", 2.0, 3.0, 4, 5, True]])
    d = rows[0]
    # string source: only string/bool produce values
    assert d[0] == "WSO2"
    assert d[1] is None and d[2] is None and d[3] is None and d[4] is None
    assert d[5] is False
    # float source 2.0
    assert isinstance(d[6], str) and d[7] == 2.0 and d[8] == 2.0
    assert d[9] == 2 and isinstance(d[9], int) and d[10] == 2
    assert d[11] is False
    # double source 3.0
    assert d[13] == 3.0 and d[15] == 3 and d[17] is False
    # int source 4
    assert d[18] == "4" and d[19] == 4.0 and d[21] == 4 and d[23] is False
    # long source 5
    assert d[24] == "5" and d[27] == 5 and d[29] is False
    # bool source true
    assert isinstance(d[30], str) and d[35] is True


def test_convert_to_bool_truthy():
    """convertFunctionTest3 (:185-223): 'true', 1f, 1d, 1, 1L, true all
    convert to bool True."""
    rows = _run(
        "define stream typeStream (typeS string, typeF float, "
        "typeD double, typeI int, typeL long, typeB bool);"
        "@info(name = 'query1') from typeStream "
        "select convert(typeS,'bool') as b1, convert(typeF,'bool') as b2, "
        "convert(typeD,'bool') as b3, convert(typeI,'bool') as b4, "
        "convert(typeL,'bool') as b5, convert(typeB,'bool') as b6 "
        "insert into outputStream;",
        "typeStream",
        [["true", 1.0, 1.0, 1, 1, True]])
    assert rows[0] == [True] * 6


@pytest.mark.parametrize("sel", [
    "convert(typeS)",                 # test4: missing target
    "convert(typeS,'string','int')",  # test5: too many args
    "convert(typeS,'234')",           # test7: unknown target type name
])
def test_convert_validation(sel):
    """convertFunctionTest4/5/7 (:225-300): malformed convert calls fail
    at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream typeStream (typeS string, typeF float, "
            "typeD double, typeI int, typeL long, typeB bool);"
            f"@info(name = 'query1') from typeStream select {sel} as v "
            "insert into outputStream;")
    m.shutdown()


def test_if_then_else():
    """ifFunctionExtensionTestCase1 (IfThenElse:43-86)."""
    rows = _run(
        "define stream sensorEventStream (sensorValue double, "
        "status string);"
        "@info(name = 'query1') from sensorEventStream "
        "select sensorValue, ifThenElse(sensorValue>35,'High','Low') "
        "as status insert into outputStream;",
        "sensorEventStream",
        [[50.4, "x"], [20.4, "x"]])
    assert [tuple(r) for r in rows] == [(50.4, "High"), (20.4, "Low")]


@pytest.mark.parametrize("sel", [
    "ifThenElse(sensorValue>35,'High',5)",   # branch type mismatch
    "ifThenElse(35,'High','Low')",           # non-bool condition
])
def test_if_then_else_validation(sel):
    """ifFunctionExtensionTestCase2/3 (:88-180)."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream sensorEventStream (sensorValue double, "
            "status string);"
            f"@info(name = 'query1') from sensorEventStream "
            f"select sensorValue, {sel} as status insert into outputStream;")
    m.shutdown()


def test_maximum_per_row():
    """testMaxFunctionExtension1 (MaximumFunctionExtension:48-103):
    row-wise max of three columns."""
    rows = _run(
        "define stream inputStream (price1 double, price2 double, "
        "price3 double);"
        "@info(name = 'query1') from inputStream "
        "select maximum(price1, price2, price3) as max "
        "insert into outputStream;",
        "inputStream",
        [[36.0, 36.75, 35.75], [37.88, 38.12, 37.62], [39.00, 39.25, 38.62],
         [36.88, 37.75, 36.75], [38.12, 38.12, 37.75], [38.12, 40.0, 37.75]])
    assert [r[0] for r in rows] == [36.75, 38.12, 39.25, 37.75, 38.12, 40.0]


def test_minimum_per_row():
    """testMinFunctionExtension1 (MinimumFunctionExtension:48-103)."""
    rows = _run(
        "define stream inputStream (price1 double, price2 double, "
        "price3 double);"
        "@info(name = 'query1') from inputStream "
        "select minimum(price1, price2, price3) as min "
        "insert into outputStream;",
        "inputStream",
        [[36.0, 36.75, 35.75], [37.88, 38.12, 37.62], [39.00, 39.25, 38.62]])
    assert [r[0] for r in rows] == [35.75, 37.62, 38.62]


def test_uuid_generates_distinct():
    """UUIDFunctionTestCase (:44-80): uuid() yields a distinct string per
    event."""
    rows = _run(
        "define stream S (symbol string);"
        "@info(name = 'query1') from S select symbol, uuid() as id "
        "insert into outputStream;",
        "S",
        [["a"], ["b"], ["c"]])
    ids = [r[1] for r in rows]
    assert len(set(ids)) == 3
    assert all(isinstance(i, str) and len(i) == 36 for i in ids)


# --------------------------------------------- ExtensionTestCase corpus


def test_custom_function_extension():
    """extensionTest2 (ExtensionTestCase:84-126): a registered custom
    scalar function (`custom:plus`) runs in the select."""
    from siddhi_tpu.extension import ScalarFunction
    from siddhi_tpu.query_api.definitions import AttrType

    class Plus(ScalarFunction):
        return_type = AttrType.LONG

        @staticmethod
        def apply(xp, a, b):
            return a + b

    m = SiddhiManager()
    m.set_extension("function:custom:plus", Plus)
    rt = m.create_siddhi_app_runtime(
        "define stream cseEventStream (symbol string, price long, "
        "volume long);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol , custom:plus(price,volume) as totalCount "
        "insert into mailOutput;")
    q = QC()
    rt.add_callback("query1", q)
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    h.send(["IBM", 700, 100])
    h.send(["WSO2", 605, 200])
    h.send(["ABC", 60, 200])
    m.shutdown()
    assert [e.data[1] for e in q.events] == [800, 805, 260]


def test_unknown_extension_rejected():
    """extensionTest3 (ExtensionTestCase:127-170): referencing an
    unregistered namespace:function fails at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream cseEventStream (symbol string, price long, "
            "volume long);"
            "@info(name = 'query1') from cseEventStream "
            "select price , email:getAllNew(symbol,'') as toConcat "
            "insert into mailOutput;")
    m.shutdown()
