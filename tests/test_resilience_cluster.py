"""Kill 1 of 2 REAL jax.distributed processes mid-stream; the survivor
recovers through the full resilience protocol — the supervisor's peer
heartbeat monitor notices the death, abandons the old runtime, rebuilds
on ``local_survivor_mesh()``, restores the last persisted revision from
the shared store, replays the ingest-WAL suffix, and resumes — and its
post-recovery output stream exactly matches an uninterrupted run
(VERDICT next-item #5's "done" bar; ISSUE 1 acceptance).

Detection note: this jaxlib's CPU backend cannot compile cross-process
computations at all ("Multiprocess computations aren't implemented on
the CPU backend" — see test_multihost.py), so the blocked-collective
detection path (``guarded_pull`` → ``ClusterPeerError``) is exercised by
the single-process drop_peer test in test_resilience.py; here the REAL
kill is detected by the supervisor's ``PeerMonitor`` socket heartbeats —
the mechanism that also covers peers dying while no collective is in
flight."""

import json
import os
import socket
import subprocess
import sys
import tempfile
import textwrap

APP = """
    @app:name('recoApp')
    @app:playback
    define stream A (k string, v double);
    define stream B (k string, v double);
    partition with (k of A, k of B)
    begin
      @info(name = 'q')
      from every e1=A -> e2=B[e2.v > e1.v] within 5 sec
      select e1.v as v1, e2.v as v2
      insert into Out;
    end;
"""

SEG_A = [(1000 + i * 50, f"P{i % 4}", float((i * 3) % 7)) for i in range(6)]
SEG_B = [(2000 + i * 50, f"P{i % 4}", float((i * 5) % 7)) for i in range(4)]
SEG_C = [(3000 + i * 50, f"P{i % 4}", float((i * 2) % 7)) for i in range(4)]

# Two real jax.distributed processes; each also binds a PeerMonitor
# heartbeat listener on a pre-allocated port and watches the other's.
# Process 1 dies abruptly right after the shared checkpoint — but only
# once process 0 confirms (ready flag) that its monitor saw the peer
# ALIVE, so the death is a detected TRANSITION, not a never-seen peer.
# Process 0's supervisor then loses the heartbeat and drives recovery.
_WORKER = textwrap.dedent("""
    import gc
    gc.disable()      # GC during jax tracing segfaults this build
    import json
    import os
    import sys
    import time
    import traceback

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "")
    sys.path.insert(0, "/root/repo")

    (coord, pid, flag, store_dir, my_port, peer_port) = (
        sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4],
        int(sys.argv[5]), int(sys.argv[6]))
    ready = flag + ".ready"

    def _die(tp, v, tb):
        # an uncaught failure must EXIT, not park in jax.distributed's
        # atexit shutdown barrier (it waits on the already-dead peer)
        traceback.print_exception(tp, v, tb)
        sys.stderr.flush()
        os._exit(3)
    sys.excepthook = _die
    from siddhi_tpu.parallel.mesh import force_host_devices

    force_host_devices(2)
    from siddhi_tpu.parallel.distributed import (
        initialize_cluster, local_survivor_mesh)

    # huge heartbeat budget: the coordination service must not tear the
    # survivor down for the peer death the supervisor is going to handle
    initialize_cluster(coordinator_address=coord, num_processes=2,
                       process_id=pid, max_missing_heartbeats=10_000)
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.core.util.persistence import FileSystemPersistenceStore
    from siddhi_tpu.parallel.mesh import shard_query_step
    from siddhi_tpu.resilience import PeerMonitor, PeerRecovery

    APP = %r
    SEG_A = %r
    SEG_B = %r
    SEG_C = %r

    class C(StreamCallback):
        def __init__(self):
            self.rows = []

        def receive(self, events):
            self.rows.extend([e.timestamp] + list(e.data) for e in events)

    monitor = PeerMonitor(listen_port=my_port, probe_timeout_s=0.5,
                          misses=3)
    store = FileSystemPersistenceStore(store_dir)
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    c1 = C()
    rt.add_callback("Out", c1)
    # this jaxlib cannot compile cross-process computations on CPU (see
    # module docstring): state shards over each process's LOCAL devices
    shard_query_step(rt.query_runtimes["q"], local_survivor_mesh())
    wal = rt.enable_wal()
    ha = rt.get_input_handler("A")
    hb = rt.get_input_handler("B")

    for t, k, v in SEG_A:
        ha.send(t, [k, v])
        hb.send(t + 1, [k, v + 1.0])
    rt.persist()

    if pid == 1:
        # stay alive (heartbeat listener up) until the survivor confirms
        # its monitor saw this peer ALIVE — the kill must be a detected
        # transition, not a peer that never came up
        t0 = time.time()
        while not os.path.exists(ready):
            assert time.time() - t0 < 120, "survivor never confirmed"
            time.sleep(0.05)
        open(flag, "w").write("dead")
        os._exit(17)                  # abrupt peer death, no cleanup

    # ---- survivor ----
    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    c2 = C()

    def rebuild():
        rt2 = m2.create_siddhi_app_runtime(APP)
        rt2.add_callback("Out", c2)
        shard_query_step(rt2.query_runtimes["q"], local_survivor_mesh())
        return rt2

    monitor.watch("127.0.0.1", peer_port)
    sup = rt.supervise(interval_s=0.2,
                       peer_recovery=PeerRecovery(rebuild, wal=wal),
                       peer_monitor=monitor)
    # confirm the monitor saw the peer ALIVE before it dies (no
    # false-positive detection path)
    t0 = time.time()
    while not monitor._peers[("127.0.0.1", peer_port)]["seen"]:
        assert time.time() - t0 < 120, "peer heartbeat never came up"
        time.sleep(0.05)
    open(ready, "w").write("go")      # release the victim to die

    while not os.path.exists(flag):
        time.sleep(0.05)
    # mid-stream: these batches land after the checkpoint — accepted,
    # WAL-recorded, and processed by the doomed incarnation while the
    # supervisor is still counting missed heartbeats
    for t, k, v in SEG_B:
        ha.send(t, [k, v])
        hb.send(t + 1, [k, v + 1.0])

    result = sup.wait_recovered(120.0)
    assert result is not None, "peer death was never detected"
    new_rt, revision = result
    assert revision is not None, "no revision restored"

    for t, k, v in SEG_C:
        ha2 = new_rt.get_input_handler("A")
        hb2 = new_rt.get_input_handler("B")
        ha2.send(t, [k, v])
        hb2.send(t + 1, [k, v + 1.0])

    print(json.dumps({
        "pre": c1.rows, "post": c2.rows,
        "replayed": wal.replayed_batches,
    }), flush=True)
    os._exit(0)   # the half-dead cluster cannot barrier a clean teardown
""") % (APP, SEG_A, SEG_B, SEG_C)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _expected_rows():
    """The same feed against a plain single-process runtime, split at the
    checkpoint."""
    from siddhi_tpu import SiddhiManager, StreamCallback

    class C(StreamCallback):
        def __init__(self):
            self.rows = []

        def receive(self, events):
            self.rows.extend([e.timestamp] + list(e.data) for e in events)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    c = C()
    rt.add_callback("Out", c)
    ha = rt.get_input_handler("A")
    hb = rt.get_input_handler("B")
    for t, k, v in SEG_A:
        ha.send(t, [k, v])
        hb.send(t + 1, [k, v + 1.0])
    n_pre = len(c.rows)
    for t, k, v in SEG_B + SEG_C:
        ha.send(t, [k, v])
        hb.send(t + 1, [k, v + 1.0])
    m.shutdown()
    return c.rows[:n_pre], c.rows[n_pre:]


def test_kill_one_of_two_peers_supervised_recovery_exact_outputs():
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    hb_ports = {0: _free_port(), 1: _free_port()}
    flag = tempfile.mktemp(prefix="siddhi-reco-flag-")
    store_dir = tempfile.mkdtemp(prefix="siddhi-reco-store-")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, coord, str(pid), flag,
             store_dir, str(hb_ports[pid]), str(hb_ports[1 - pid])],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for pid in (0, 1)
    ]
    try:
        out1, _err1 = procs[1].communicate(timeout=300)
        assert procs[1].returncode == 17          # victim died on cue
        try:
            out0, err0 = procs[0].communicate(timeout=300)
        except subprocess.TimeoutExpired:
            raise AssertionError("survivor hung after peer death")
        assert procs[0].returncode == 0, f"survivor failed:\n{err0[-4000:]}"
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()

    payload = json.loads(out0.strip().splitlines()[-1])
    expected_pre, expected_post = _expected_rows()
    # pre-death: the sharded runtime matched the single-process run
    assert payload["pre"][:len(expected_pre)] == expected_pre
    # post-recovery: restore + WAL replay + resumed feed — the output
    # stream continues exactly where the checkpoint left off (the
    # mid-death batches came back via the replay; nothing lost, nothing
    # doubled in the recovered stream)
    assert payload["post"] == expected_post
    assert payload["replayed"] == 2 * len(SEG_B)


# --------------------------------------------------- router-side fabric


def _column_feed(send):
    """The same A/B interleave as the segments, one row per batch (the
    pattern is order-sensitive across both streams)."""
    import numpy as np

    for seg in (SEG_A, SEG_B, SEG_C):
        for t, k, v in seg:
            send("A", {"k": np.array([k], object),
                       "v": np.array([v])},
                 np.array([t], np.int64))
            send("B", {"k": np.array([k], object),
                       "v": np.array([v + 1.0])},
                 np.array([t + 1], np.int64))
        yield


def test_router_kill_one_of_two_workers_exact_egress():
    """The cluster-fabric half of the recovery story (ISSUE 17): the
    ROUTER owns the WAL and the supervisor owns the processes. One of
    two REAL worker processes is SIGKILLed between segments — after the
    deploy handshake proved it up (the ready-flag discipline) and after
    a checkpoint barrier cut its WAL — and the merged egress stream
    must exactly match an uninterrupted single-process run: zero lost
    rows, zero doubled rows, original order."""
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.cluster import ClusterRuntime
    from siddhi_tpu.cluster.protocol import py_value

    class C(StreamCallback):
        def __init__(self):
            self.rows = []

        def receive(self, events):
            self.rows.extend(
                (int(e.timestamp), tuple(py_value(v) for v in e.data))
                for e in events)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    c = C()
    rt.add_callback("Out", c)
    rt.start()

    def base_send(stream, data, tss):
        rt.get_input_handler(stream).send_columns(data, timestamps=tss)

    for _ in _column_feed(base_send):
        pass
    m.shutdown()

    cluster = ClusterRuntime(n_workers=2, heartbeat_s=0.2)
    try:
        cluster.wait_ready(60)
        cluster.deploy(APP, partition_keys={"A": "k", "B": "k"},
                       sinks=["Out"])

        def cl_send(stream, data, tss):
            cluster.send_columns("recoApp", stream, data, timestamps=tss)

        feed = _column_feed(cl_send)
        next(feed)                       # SEG_A delivered
        cluster.checkpoint()             # cut + trim both worker WALs
        cluster.supervisor.kill(1)       # SIGKILL mid-stream
        for _ in feed:                   # SEG_B + SEG_C keep flowing
            pass
        assert cluster.quiesce(180), "egress never quiesced after kill"
        got = [(ts, tuple(vals)) for ts, vals in
               cluster.egress.stream_rows("recoApp", "Out")]
        assert got == c.rows
        assert sum(cluster.supervisor.respawns) >= 1
    finally:
        cluster.shutdown()
