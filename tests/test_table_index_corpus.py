"""Reference @Index table corpus — scenarios ported verbatim from
``query/table/IndexTableTestCase.java``: secondary-index probes across
compare operators, and updates through the indexed column."""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


def build_q(app, query="query2"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback(query, q)
    return m, rt, q


IDX_SYMBOL = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define stream UpdateStockStream (symbol string, price float, volume long);
    @Index('symbol')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""

IDX_VOLUME = IDX_SYMBOL.replace("@Index('symbol')", "@Index('volume')")


def test_index_equality_pair_join():
    """indexTableTest1 (:56-119): two equality conjuncts, one through the
    @Index column."""
    m, rt, q = build_q(IDX_SYMBOL + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.volume == StockTable.volume AND CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["IBM", 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 100), ("WSO2", 100)]


def test_index_inequality_join():
    """indexTableTest2 (:121-184): != through the indexed column falls back
    to a scan of the other rows."""
    m, rt, q = build_q(IDX_SYMBOL + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol != StockTable.symbol
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["GOOG", 100])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("GOOG", "IBM", 100), ("GOOG", "WSO2", 100)]


def test_index_range_gt_join():
    """indexTableTest3 (:186-256): `CheckStockStream.volume >
    StockTable.volume` over the numeric index."""
    m, rt, q = build_q(IDX_VOLUME + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.volume > StockTable.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    check.send(["IBM", 100])
    check.send(["FOO", 60])
    m.shutdown()
    got = [tuple(e.data) for e in q.events]
    assert sorted(got[:2]) == [("IBM", "ABC", 70), ("IBM", "GOOG", 50)]
    assert got[2:] == [("FOO", "GOOG", 50)]


def test_index_range_ge_join():
    """indexTableTest7 (:456-520): `StockTable.volume >=
    CheckStockStream.volume`."""
    m, rt, q = build_q(IDX_VOLUME + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    rt.get_input_handler("CheckStockStream").send(["IBM", 70])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "WSO2", 200)]


def test_index_duplicate_key_rows_both_match():
    """indexTableTest8 (:522-590): @Index (unlike @PrimaryKey) keeps BOTH
    volume-200 rows and a >= probe returns all three matches."""
    m, rt, q = build_q(IDX_VOLUME + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["FOO", 50.6, 200])
    stock.send(["WSO2", 55.6, 200])
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    rt.get_input_handler("CheckStockStream").send(["IBM", 70])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "FOO", 200), ("IBM", "WSO2", 200)]


def test_index_update_through_indexed_column():
    """indexTableTest9 (:592-666): an update through the indexed symbol is
    visible to later joins at the NEW volume."""
    m, rt, q = build_q(IDX_SYMBOL + """
        @info(name = 'query2')
        from UpdateStockStream update StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    update.send(["IBM", 77.6, 200])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100), ("WSO2", 100), ("IBM", 200), ("WSO2", 100)]


def test_index_relational_update_condition():
    """indexTableTest13 (:914-...): `update ... on StockTable.volume >=
    volume` through the numeric index rewrites the matching row's price."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        define stream UpdateStockStream (symbol string, price float, volume long);
        @Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume >= volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume <= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    check.send(["WSO2", 200])
    update.send(["FOO", 77.6, 200])
    check.send(["BAR", 200])
    m.shutdown()
    assert [(round(e.data[0], 4), e.data[1]) for e in q.events] == [
        (55.6, 200), (77.6, 200)]
