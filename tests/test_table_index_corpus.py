"""Reference @Index table corpus — scenarios ported verbatim from
``query/table/IndexTableTestCase.java``: secondary-index probes across
compare operators, and updates through the indexed column."""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


def build_q(app, query="query2"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback(query, q)
    return m, rt, q


IDX_SYMBOL = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define stream UpdateStockStream (symbol string, price float, volume long);
    @Index('symbol')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""

IDX_VOLUME = IDX_SYMBOL.replace("@Index('symbol')", "@Index('volume')")


def test_index_equality_pair_join():
    """indexTableTest1 (:56-119): two equality conjuncts, one through the
    @Index column."""
    m, rt, q = build_q(IDX_SYMBOL + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.volume == StockTable.volume AND CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["IBM", 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 100), ("WSO2", 100)]


def test_index_inequality_join():
    """indexTableTest2 (:121-184): != through the indexed column falls back
    to a scan of the other rows."""
    m, rt, q = build_q(IDX_SYMBOL + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol != StockTable.symbol
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["GOOG", 100])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("GOOG", "IBM", 100), ("GOOG", "WSO2", 100)]


def test_index_range_gt_join():
    """indexTableTest3 (:186-256): `CheckStockStream.volume >
    StockTable.volume` over the numeric index."""
    m, rt, q = build_q(IDX_VOLUME + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.volume > StockTable.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    check.send(["IBM", 100])
    check.send(["FOO", 60])
    m.shutdown()
    got = [tuple(e.data) for e in q.events]
    assert sorted(got[:2]) == [("IBM", "ABC", 70), ("IBM", "GOOG", 50)]
    assert got[2:] == [("FOO", "GOOG", 50)]


def test_index_range_ge_join():
    """indexTableTest7 (:456-520): `StockTable.volume >=
    CheckStockStream.volume`."""
    m, rt, q = build_q(IDX_VOLUME + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    rt.get_input_handler("CheckStockStream").send(["IBM", 70])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "WSO2", 200)]


def test_index_duplicate_key_rows_both_match():
    """indexTableTest8 (:522-590): @Index (unlike @PrimaryKey) keeps BOTH
    volume-200 rows and a >= probe returns all three matches."""
    m, rt, q = build_q(IDX_VOLUME + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["FOO", 50.6, 200])
    stock.send(["WSO2", 55.6, 200])
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])
    rt.get_input_handler("CheckStockStream").send(["IBM", 70])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", "ABC", 70), ("IBM", "FOO", 200), ("IBM", "WSO2", 200)]


def test_index_update_through_indexed_column():
    """indexTableTest9 (:592-666): an update through the indexed symbol is
    visible to later joins at the NEW volume."""
    m, rt, q = build_q(IDX_SYMBOL + """
        @info(name = 'query2')
        from UpdateStockStream update StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    update.send(["IBM", 77.6, 200])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100), ("WSO2", 100), ("IBM", 200), ("WSO2", 100)]


def test_index_relational_update_condition():
    """indexTableTest13 (:914-...): `update ... on StockTable.volume >=
    volume` through the numeric index rewrites the matching row's price."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        define stream UpdateStockStream (symbol string, price float, volume long);
        @Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume >= volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume <= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    check.send(["WSO2", 200])
    update.send(["FOO", 77.6, 200])
    check.send(["BAR", 200])
    m.shutdown()
    assert [(round(e.data[0], 4), e.data[1]) for e in q.events] == [
        (55.6, 200), (77.6, 200)]


# ---------------------------------------------------------------- round 5:
# the remainder of IndexTableTestCase.java (test35-analog timing races are
# covered deterministically by tests/test_index_probes.py)

IDX_VOLUME = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define stream UpdateStockStream (symbol string, price float, volume long);
    @Index('volume')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""

IDX_SYMBOL = IDX_VOLUME.replace("@Index('volume')", "@Index('symbol')")


def _range_feed(rt):
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["GOOG", 50.6, 50])
    stock.send(["ABC", 5.6, 70])


def _idx_range_case(op, probe, expected):
    m, rt, q = build_q(IDX_VOLUME + f"""
        @info(name = 'query2') from CheckStockStream join StockTable
        on {op}
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;
    """)
    _range_feed(rt)
    rt.get_input_handler("CheckStockStream").send(list(probe))
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == sorted(expected)


def test_index_lt_join():
    """indexTableTest4 (:258-321)."""
    _idx_range_case("StockTable.volume < CheckStockStream.volume",
                    ("IBM", 200),
                    [("IBM", "ABC", 70), ("IBM", "GOOG", 50)])


def test_index_le_join():
    """indexTableTest5 (:324-387)."""
    _idx_range_case("StockTable.volume <= CheckStockStream.volume",
                    ("IBM", 70),
                    [("IBM", "ABC", 70), ("IBM", "GOOG", 50)])


def test_index_gt_join():
    """indexTableTest6 (:390-453)."""
    _idx_range_case("StockTable.volume > CheckStockStream.volume",
                    ("IBM", 50),
                    [("IBM", "WSO2", 200), ("IBM", "ABC", 70)])


def test_index_ne_update_then_ne_join():
    """indexTableTest10 (:668-747): update on symbol != 'IBM' rewrites the
    WSO2 row to the update event's values; != probes before and after."""
    m, rt, q = build_q(IDX_SYMBOL + """
        @info(name = 'query2') from UpdateStockStream
        update StockTable on StockTable.symbol!=symbol;
        @info(name = 'query3') from CheckStockStream join StockTable
        on CheckStockStream.symbol!=StockTable.symbol
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    upd = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    upd.send(["IBM", 77.6, 200])     # updates WSO2 -> (IBM, 77.6, 200)
    check.send(["WSO2", 100])
    m.shutdown()
    rows = [tuple(e.data) for e in q.events]
    assert rows[:2] == [("WSO2", 100), ("IBM", 100)]
    assert sorted(rows[2:]) == [("IBM", 100), ("IBM", 200)]


def _idx_update_case(update_on, expected1, expected2):
    m, rt, q = build_q(IDX_VOLUME + f"""
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on {update_on};
        @info(name = 'query3') from CheckStockStream join StockTable
        on CheckStockStream.volume >= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 200])
    rt.get_input_handler("UpdateStockStream").send(["FOO", 77.6, 200])
    rt.get_input_handler("CheckStockStream").send(["BAR", 200])
    m.shutdown()
    rows = [(round(float(e.data[0]), 4), e.data[1]) for e in q.events]
    assert sorted(rows[:2]) == sorted(expected1)
    assert sorted(rows[2:]) == sorted(expected2)


def test_index_update_le_no_pk_allows_collision():
    """indexTableTest11 (:750-829): with a plain @Index (no primary key)
    the volume<=200 update rewrites BOTH rows to (77.6, 200) — duplicates
    are legal in an indexed (non-PK) table."""
    _idx_update_case("StockTable.volume <= volume",
                     [(55.6, 200), (55.6, 100)],
                     [(77.6, 200), (77.6, 200)])


def test_index_update_lt():
    """indexTableTest12 (:832-911): volume<200 rewrites IBM only."""
    _idx_update_case("StockTable.volume < volume",
                     [(55.6, 200), (55.6, 100)],
                     [(55.6, 200), (77.6, 200)])


def test_index_update_gt():
    """indexTableTest14 (:989-1062): volume>150 rewrites WSO2 to
    (77.6, 150); probe join is check.volume <= table.volume."""
    m, rt, q = build_q(IDX_VOLUME + """
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume > volume;
        @info(name = 'query3') from CheckStockStream join StockTable
        on CheckStockStream.volume <= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 150])
    rt.get_input_handler("UpdateStockStream").send(["FOO", 77.6, 150])
    rt.get_input_handler("CheckStockStream").send(["BAR", 150])
    m.shutdown()
    rows = [(round(float(e.data[0]), 4), e.data[1]) for e in q.events]
    assert rows == [(55.6, 200), (77.6, 150)]


IDX_DELETE = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define stream DeleteStockStream (symbol string, price float, volume long);
    @Index('{attr}')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""


def _idx_delete_case(attr, delete_on, feed, before, after):
    m, rt, q = build_q(IDX_DELETE.format(attr=attr) + f"""
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on {delete_on};
        @info(name = 'query3') from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    for row in feed:
        stock.send(list(row))
    rt.get_input_handler("CheckStockStream").send(["WSO2", 100])
    rt.get_input_handler("DeleteStockStream").send(["IBM", 77.6, 150 if "150" in delete_on else 200])
    rt.get_input_handler("CheckStockStream").send(["FOO", 100])
    m.shutdown()
    rows = [tuple(e.data) for e in q.events]
    assert sorted(rows[:len(before)]) == sorted(before)
    assert rows[len(before):] == after


def test_index_delete_eq():
    """indexTableTest15 (:1065-1140)."""
    _idx_delete_case("symbol", "StockTable.symbol==symbol",
                     [("WSO2", 55.6, 100), ("IBM", 55.6, 100)],
                     [("IBM", 100), ("WSO2", 100)], [("WSO2", 100)])


def test_index_delete_ne():
    """indexTableTest16 (:1143-1218)."""
    _idx_delete_case("symbol", "StockTable.symbol!=symbol",
                     [("WSO2", 55.6, 100), ("IBM", 55.6, 100)],
                     [("IBM", 100), ("WSO2", 100)], [("IBM", 100)])


def test_index_delete_gt():
    """indexTableTest17 (:1221-1296): delete volume > 150."""
    m, rt, q = build_q(IDX_DELETE.format(attr="volume") + """
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume>volume;
        @info(name = 'query3') from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 100])
    rt.get_input_handler("DeleteStockStream").send(["IBM", 77.6, 150])
    rt.get_input_handler("CheckStockStream").send(["FOO", 100])
    m.shutdown()
    rows = [tuple(e.data) for e in q.events]
    assert sorted(rows[:2]) == [("IBM", 100), ("WSO2", 200)]
    assert rows[2:] == [("IBM", 100)]


def test_index_delete_ge():
    """indexTableTest18 (:1299-1375): delete volume >= 200."""
    m, rt, q = build_q(IDX_DELETE.format(attr="volume") + """
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume>=volume;
        @info(name = 'query3') from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 100])
    rt.get_input_handler("DeleteStockStream").send(["IBM", 77.6, 200])
    rt.get_input_handler("CheckStockStream").send(["FOO", 100])
    m.shutdown()
    rows = [tuple(e.data) for e in q.events]
    assert sorted(rows[:2]) == [("IBM", 100), ("WSO2", 200)]
    assert rows[2:] == [("IBM", 100)]


def test_index_delete_lt():
    """indexTableTest19 (:1378-1453): delete volume < 150."""
    m, rt, q = build_q(IDX_DELETE.format(attr="volume") + """
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume < volume;
        @info(name = 'query3') from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 100])
    rt.get_input_handler("DeleteStockStream").send(["IBM", 77.6, 150])
    rt.get_input_handler("CheckStockStream").send(["FOO", 100])
    m.shutdown()
    rows = [tuple(e.data) for e in q.events]
    assert sorted(rows[:2]) == [("IBM", 100), ("WSO2", 200)]
    assert rows[2:] == [("WSO2", 200)]


def test_index_delete_le():
    """indexTableTest20 (:1456-1533): delete volume <= 150 removes IBM and
    BAR."""
    m, rt, q = build_q(IDX_DELETE.format(attr="volume") + """
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume <= volume;
        @info(name = 'query3') from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["BAR", 55.6, 150])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 100])
    rt.get_input_handler("DeleteStockStream").send(["IBM", 77.6, 150])
    rt.get_input_handler("CheckStockStream").send(["FOO", 100])
    m.shutdown()
    rows = [tuple(e.data) for e in q.events]
    assert sorted(rows[:3]) == [("BAR", 150), ("IBM", 100), ("WSO2", 200)]
    assert rows[3:] == [("WSO2", 200)]


IDX_IN = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    @Index('{attr}')
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""


def _idx_in_case(attr, cond, probes, expected):
    m, rt, q = build_q(IDX_IN.format(attr=attr) + f"""
        @info(name = 'query2')
        from CheckStockStream[{cond}]
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 200])
    stock.send(["BAR", 55.6, 150])
    stock.send(["IBM", 55.6, 100])
    for p in probes:
        rt.get_input_handler("CheckStockStream").send(list(p))
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == sorted(expected)


def test_index_in_eq():
    """indexTableTest21 (:1536-1596)."""
    _idx_in_case("symbol", "(symbol==StockTable.symbol) in StockTable",
                 [("FOO", 100), ("WSO2", 100)], [("WSO2", 100)])


def test_index_in_ne():
    """indexTableTest22 (:1599-1661)."""
    _idx_in_case("symbol", "(symbol!=StockTable.symbol) in StockTable",
                 [("FOO", 100), ("WSO2", 100)],
                 [("FOO", 100), ("WSO2", 100)])


def test_index_in_gt():
    """indexTableTest23 (:1664-1726)."""
    _idx_in_case("volume", "(volume > StockTable.volume) in StockTable",
                 [("FOO", 170), ("FOO", 500)], [("FOO", 170), ("FOO", 500)])


def test_index_in_lt():
    """indexTableTest24 (:1729-1789)."""
    _idx_in_case("volume", "(volume < StockTable.volume) in StockTable",
                 [("FOO", 170), ("FOO", 500)], [("FOO", 170)])


def test_index_in_le():
    """indexTableTest25 (:1792-1853)."""
    _idx_in_case("volume", "(volume <= StockTable.volume) in StockTable",
                 [("FOO", 170), ("FOO", 200)], [("FOO", 170), ("FOO", 200)])


def test_index_in_ge():
    """indexTableTest26 (:1856-1917)."""
    _idx_in_case("volume", "(volume >= StockTable.volume) in StockTable",
                 [("FOO", 170), ("FOO", 100)], [("FOO", 170), ("FOO", 100)])


def test_index_left_outer_upsert_then_triple_in_probe():
    """indexTableTest27 (:1920-1996): left-outer enrichment upsert with
    ifThenElse null fill; 3-way composite `in` probes count matches."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long, price float);
        define stream UpdateStockStream (comp string, vol long);
        @Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2') from UpdateStockStream left outer join StockTable
        on UpdateStockStream.comp == StockTable.symbol
        select comp as symbol, ifThenElse(price is null,0f,price) as price,
               vol as volume
        update or insert into StockTable on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol==StockTable.symbol
                               and volume==StockTable.volume
                               and price==StockTable.price) in StockTable]
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    upd = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    check.send(["IBM", 100, 155.6])
    check.send(["WSO2", 100, 155.6])
    upd.send(["IBM", 200])
    upd.send(["WSO2", 300])
    check.send(["IBM", 200, 0.0])
    check.send(["WSO2", 300, 55.6])
    m.shutdown()
    assert [(e.data[0], e.data[1], round(float(e.data[2]), 4))
            for e in q.events] == [("IBM", 200, 0.0), ("WSO2", 300, 55.6)]


def test_index_with_primary_key_and_two_indexes():
    """indexTableTest28 (:1999-2064): @PrimaryKey + two @Index annotations
    coexist."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('symbol') @Index('price') @Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2') from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["IBM", 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 100), ("WSO2", 100)]


def test_index_two_indexes_no_pk():
    """indexTableTest29 (:2067-2130): two distinct @Index annotations."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @Index('symbol') @Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2') from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("CheckStockStream").send(["IBM", 100])
    rt.get_input_handler("CheckStockStream").send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 100), ("WSO2", 100)]


def _expect_rejected(table_ann):
    import pytest

    from tests.test_table_define_corpus import CREATION_ERRORS
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime(f"""
            define stream StockStream (symbol string, price float, volume long);
            {table_ann}
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable;
        """)


def test_index_empty_attribute_rejected():
    """indexTableTest30 (:2133-2156, AttributeNotExistException)."""
    _expect_rejected("@Index('')")


def test_index_multi_attribute_annotation_rejected():
    """indexTableTest31 (:2159-2182, SiddhiAppValidationException): one
    @Index annotation may name only one attribute."""
    _expect_rejected("@Index('symbol', 'volume')")


def test_index_duplicate_annotation_rejected():
    """indexTableTest32 (:2185-2209, SiddhiAppValidationException)."""
    _expect_rejected("@Index('symbol') @Index('symbol')")


def test_index_unknown_attribute_rejected():
    """indexTableTest33 (:2212-2235, AttributeNotExistException)."""
    _expect_rejected("@Index('foo')")
