"""M0 golden tests: filter + project end-to-end through the public API.

Style mirrors the reference's black-box behavioral tests
(``query/filter/FilterTestCase1.java``): SiddhiQL in, events in, assert
outputs via callbacks.
"""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback
from siddhi_tpu.core.stream.output.stream_callback import StreamCallback


class CollectingStreamCallback(StreamCallback):
    def __init__(self):
        self.events = []

    def receive(self, events):
        self.events.extend(events)


class CollectingQueryCallback(QueryCallback):
    def __init__(self):
        self.in_events = []
        self.remove_events = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.in_events.extend(in_events)
        if remove_events:
            self.remove_events.extend(remove_events)


def test_filter_and_project():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream StockStream (symbol string, price float, volume long);
        @info(name = 'query1')
        from StockStream[price > 100.0]
        select symbol, price
        insert into OutStream;
        """
    )
    cb = CollectingStreamCallback()
    rt.add_callback("OutStream", cb)
    h = rt.get_input_handler("StockStream")
    h.send(100, ["IBM", 150.0, 10])
    h.send(101, ["WSO2", 55.0, 20])
    h.send(102, ["GOOG", 120.5, 30])
    assert [e.data for e in cb.events] == [["IBM", 150.0], ["GOOG", 120.5]]
    assert [e.timestamp for e in cb.events] == [100, 102]
    manager.shutdown()


def test_query_callback_current_events():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (a int, b int);
        @info(name = 'q')
        from S[a > b] select a + b as total, a - b as diff insert into Out;
        """
    )
    qcb = CollectingQueryCallback()
    rt.add_callback("q", qcb)
    h = rt.get_input_handler("S")
    h.send([5, 3])
    h.send([1, 9])
    h.send([7, 2])
    assert [e.data for e in qcb.in_events] == [[8, 2], [9, 5]]
    manager.shutdown()


def test_chained_queries():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (v int);
        from S[v > 0] select v * 2 as v2 insert into Mid;
        from Mid[v2 > 10] select v2 insert into Out;
        """
    )
    cb = CollectingStreamCallback()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    for v in [1, 4, 6, -2, 10]:
        h.send([v])
    assert [e.data for e in cb.events] == [[12], [20]]
    manager.shutdown()


def test_bool_and_string_conditions():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (symbol string, price double, active bool);
        from S[symbol == 'IBM' and active == true and not (price < 10.0)]
        select symbol, price insert into Out;
        """
    )
    cb = CollectingStreamCallback()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send(["IBM", 50.0, True])
    h.send(["IBM", 5.0, True])
    h.send(["WSO2", 50.0, True])
    h.send(["IBM", 50.0, False])
    assert [e.data for e in cb.events] == [["IBM", 50.0]]
    manager.shutdown()


def test_arithmetic_java_semantics():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (a int, b int);
        from S select a / b as q, a % b as r insert into Out;
        """
    )
    cb = CollectingStreamCallback()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send([-7, 2])
    h.send([7, 2])
    # Java: -7/2 == -3 (truncation), -7%2 == -1 (dividend sign)
    assert cb.events[0].data == [-3, -1]
    assert cb.events[1].data == [3, 1]
    manager.shutdown()


def test_ifthenelse_and_functions():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (v double);
        from S select ifThenElse(v > 0.0, 'pos', 'neg') as sign,
                      maximum(v, 10.0) as mx,
                      cast(v, 'int') as vi
        insert into Out;
        """
    )
    cb = CollectingStreamCallback()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send([25.5])
    h.send([-3.0])
    assert cb.events[0].data == ["pos", 25.5, 25]
    assert cb.events[1].data == ["neg", 10.0, -3]
    manager.shutdown()


def test_event_order_preserved_in_batch_send():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (v int);
        from S[v % 2 == 0] select v insert into Out;
        """
    )
    cb = CollectingStreamCallback()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    from siddhi_tpu.core.event import Event

    h.send([Event(timestamp=i, data=[i]) for i in range(20)])
    assert [e.data[0] for e in cb.events] == list(range(0, 20, 2))
    manager.shutdown()


def test_deferred_meta_batching():
    """siddhi_tpu.defer_meta=4 is DEPRECATED: it maps onto the dispatch
    pipeline (pipeline_depth=4, core/query/completion.py) with a
    DeprecationWarning. Unlike the old hold-N queue, outputs no longer
    lag a defer window — synchronous sends observe them immediately —
    and nothing is lost at shutdown."""
    import pytest

    from siddhi_tpu.core.util.config import InMemoryConfigManager

    manager = SiddhiManager()
    manager.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.defer_meta": "4"}))
    with pytest.warns(DeprecationWarning, match="defer_meta"):
        rt = manager.create_siddhi_app_runtime("""
            define stream S (sym string, v int);
            @info(name='q')
            from S[v > 0] select sym, v insert into Out;
        """)
    assert rt.app_context.pipeline_depth == 4
    assert rt.app_context.defer_meta == 1
    seen = []

    class C(StreamCallback):
        def receive(self, events):
            seen.extend(tuple(e.data) for e in events)

    rt.add_callback("Out", C())
    h = rt.get_input_handler("S")
    for i in range(1, 5):
        h.send(["a", i])
    # no defer lag: every synchronous send flushed the pipeline
    assert seen == [("a", 1), ("a", 2), ("a", 3), ("a", 4)]
    h.send(["b", 5])
    manager.shutdown()
    assert seen[-1] == ("b", 5)
