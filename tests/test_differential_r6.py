"""Differential harness round 6: incremental aggregation cube vs a
plain-Python bucket model over random out-of-order traces, and on-demand
table CRUD vs a dict model."""

import collections

import numpy as np

from siddhi_tpu import SiddhiManager


def test_differential_incremental_aggregation_ooo():
    rng = np.random.default_rng(53)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream Trades (symbol string, price double, ts long);
        define aggregation TradeAgg
        from Trades
        select symbol, sum(price) as total, count() as n, avg(price) as ap,
               min(price) as lo, max(price) as hi
        group by symbol
        aggregate by ts every sec ... min;
    """)
    h = rt.get_input_handler("Trades")
    buckets = collections.defaultdict(list)   # (sec_bucket, sym) -> prices
    base = 1_700_000_000_000
    for _ in range(400):
        # out-of-order timestamps across a 30-second span
        ts = base + int(rng.integers(0, 30_000))
        sym = f"s{int(rng.integers(0, 4))}"
        p = float(rng.integers(1, 100))
        h.send([sym, p, ts])
        buckets[(ts // 1000, sym)].append(p)

    rows = rt.query(
        f"from TradeAgg within {base}L, {base + 60_000}L per 'seconds' "
        "select AGG_TIMESTAMP, symbol, total, n, ap, lo, hi")
    got = {}
    for e in rows:
        ts_b, sym, total, n, ap, lo, hi = e.data
        got[(ts_b // 1000, sym)] = (total, n, ap, lo, hi)
    m.shutdown()

    assert len(got) == len(buckets)
    for key, prices in buckets.items():
        total, n, ap, lo, hi = got[key]
        assert n == len(prices)
        assert abs(total - sum(prices)) < 1e-6
        assert abs(ap - sum(prices) / len(prices)) < 1e-9
        assert lo == min(prices) and hi == max(prices)


def test_differential_table_crud_random():
    rng = np.random.default_rng(59)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream Ins (k string, v long);
        define stream Del (k string);
        define stream Upd (k string, v long);
        @primaryKey('k')
        define table T (k string, v long);
        from Ins select k, v update or insert into T
            set T.v = v on T.k == k;
        from Del delete T on T.k == k;
        from Upd update T set T.v = v on T.k == k;
    """)
    hi = rt.get_input_handler("Ins")
    hd = rt.get_input_handler("Del")
    hu = rt.get_input_handler("Upd")
    model = {}
    for _ in range(300):
        op = rng.random()
        k = f"k{int(rng.integers(0, 12))}"
        if op < 0.5:
            v = int(rng.integers(0, 1000))
            hi.send([k, v])
            model[k] = v
        elif op < 0.75:
            hd.send([k])
            model.pop(k, None)
        else:
            v = int(rng.integers(0, 1000))
            hu.send([k, v])
            if k in model:
                model[k] = v
    rows = rt.query("from T select k, v")
    got = {e.data[0]: e.data[1] for e in rows}
    m.shutdown()
    assert got == model


def test_differential_checkpoint_restore_equivalence():
    """A trace interrupted by persist() -> fresh runtime -> restore must
    produce the same outputs as an uninterrupted run (SnapshotService
    parity over a stateful windowed aggregation)."""
    from siddhi_tpu import StreamCallback
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    APP = """
        define stream S (sym string, v long);
        from S#window.length(7)
        select sym, sum(v) as total, count() as n
        group by sym insert into Out;
    """

    class C(StreamCallback):
        def __init__(self):
            super().__init__()
            self.rows = []

        def receive(self, events):
            self.rows.extend(tuple(e.data) for e in events)

    rng = np.random.default_rng(61)
    sends = [(f"s{int(rng.integers(0, 5))}", int(rng.integers(1, 50)))
             for _ in range(200)]
    cut = 117

    # uninterrupted
    m1 = SiddhiManager()
    rt1 = m1.create_siddhi_app_runtime(APP)
    c1 = C(); rt1.add_callback("Out", c1)
    h1 = rt1.get_input_handler("S")
    for row in sends:
        h1.send(list(row))
    m1.shutdown()

    # interrupted at `cut`: persist, tear down, restore into a new runtime
    store = InMemoryPersistenceStore()
    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    c2 = C(); rt2.add_callback("Out", c2)
    h2 = rt2.get_input_handler("S")
    for row in sends[:cut]:
        h2.send(list(row))
    rt2.persist()
    pre = list(c2.rows)
    m2.shutdown()

    m3 = SiddhiManager()
    m3.set_persistence_store(store)
    rt3 = m3.create_siddhi_app_runtime(APP)
    c3 = C(); rt3.add_callback("Out", c3)
    rt3.restore_last_revision()
    h3 = rt3.get_input_handler("S")
    for row in sends[cut:]:
        h3.send(list(row))
    m3.shutdown()

    assert pre + c3.rows == c1.rows
