"""graftlint self-tests: each rule flags its known-bad fixture, and the
real tree is clean.

The fixtures under ``tests/fixtures/lint/`` are loaded with SYNTHETIC
repo-relative paths (a production-looking location per rule) so the
rules' path scoping — R5 only looks at hot-path packages, R2 skips
tests/ — applies exactly as it would in the tree."""

from __future__ import annotations

import os

import pytest

from siddhi_tpu.analysis import default_rules, load_modules, run_lint
from siddhi_tpu.analysis.engine import LintContext, ModuleInfo

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "fixtures", "lint")

# fixture file -> virtual repo path (rule scoping applies to the path)
FIXTURE_PATHS = {
    "r1_backend_init.py": "siddhi_tpu/parallel/bad_backend.py",
    "r2_adhoc_knob.py": "siddhi_tpu/core/bad_knobs.py",
    "r3_metric_family.py": "siddhi_tpu/observability/bad_metrics.py",
    "r3_stage_family.py": "siddhi_tpu/observability/bad_stage_metrics.py",
    "r4_lock_order.py": "siddhi_tpu/core/query/bad_locks.py",
    "r5_host_pull.py": "siddhi_tpu/core/query/bad_steps.py",
    "r6_instruments.py": "siddhi_tpu/core/query/bad_instruments.py",
    "r7_actuators.py": "siddhi_tpu/autopilot/bad_actuators.py",
    "r8_guards.py": "siddhi_tpu/core/query/bad_guards.py",
}


def _load_fixture(name: str) -> ModuleInfo:
    return ModuleInfo.load(os.path.join(FIXTURES, name),
                           FIXTURE_PATHS[name])


def _lint_fixture(name: str):
    # the real export.py supplies the R3 declarations
    export = ModuleInfo.load(
        os.path.join(REPO, "siddhi_tpu/observability/export.py"),
        "siddhi_tpu/observability/export.py")
    mods = [_load_fixture(name), export]
    findings = run_lint(mods)
    # only findings against the fixture itself (export.py may report
    # dead prefixes in this tiny two-file tree — not under test here)
    return [f for f in findings if f.path == FIXTURE_PATHS[name]]


@pytest.mark.parametrize("name,rule,min_hits", [
    ("r1_backend_init.py", "R1", 3),   # module const, jax.devices, default
    ("r2_adhoc_knob.py", "R2", 3),     # f-string key, literal key, env var
    ("r3_metric_family.py", "R3", 3),  # prefix x2 + family literal
    # critical-path profiler families (stage.* / siddhi_stage_ms):
    # unremoved gauge under the new prefix + family literal
    ("r3_stage_family.py", "R3", 2),
    ("r4_lock_order.py", "R4", 2),     # pump->owner and owner->barrier
    ("r5_host_pull.py", "R5", 4),      # float, .item, np.asarray, bool
    # undeclared data slot + consumer-less check slot
    ("r6_instruments.py", "R6", 2),
    # untyped knob + dead actuator + undeclared actuation path
    ("r7_actuators.py", "R7", 3),
    # stale declaration, unlocked write, unlocked read, undeclared
    # thread-spawning class
    ("r8_guards.py", "R8", 4),
])
def test_rule_flags_its_fixture(name, rule, min_hits):
    findings = _lint_fixture(name)
    hits = [f for f in findings if f.rule == rule]
    assert len(hits) >= min_hits, (
        f"{name}: wanted >= {min_hits} {rule} findings, got "
        f"{[f.format() for f in findings]}")


def test_fixture_findings_are_single_rule():
    # each fixture is crafted for exactly one rule — cross-rule noise
    # would mean the fixtures (or rules) drifted
    for name, path in FIXTURE_PATHS.items():
        rule = name[:2].upper()
        wrong = [f for f in _lint_fixture(name) if f.rule != rule]
        assert not wrong, (
            f"{name} tripped other rules: "
            f"{[f.format() for f in wrong]}")


def test_clean_tree_zero_findings():
    """The acceptance bar: the repaired production tree lints clean."""
    modules = load_modules(
        ("siddhi_tpu", "tools", "bench.py", "__graft_entry__.py"), REPO)
    findings = run_lint(modules)
    assert not findings, "\n".join(f.format() for f in findings)


def test_suppression_comments():
    import tempfile

    src = ("import jax.numpy as jnp\n"
           "X = jnp.int64(1)  # graftlint: disable=R1\n"
           "Y = jnp.int64(2)\n")

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(src)
        tmp = f.name
    try:
        m = ModuleInfo.load(tmp, "siddhi_tpu/s.py")
        findings = run_lint([m])
        r1 = [f for f in findings if f.rule == "R1"]
        assert len(r1) == 1 and r1[0].line == 3, \
            [f.format() for f in findings]
        # file-scope suppression silences both
        with open(tmp, "w") as fh:
            fh.write("# graftlint: disable-file=R1\n" + src)
        m = ModuleInfo.load(tmp, "siddhi_tpu/s.py")
        assert not [f for f in run_lint([m]) if f.rule == "R1"]
    finally:
        os.unlink(tmp)


def test_rule_registry_lists_eight_rules():
    rules = default_rules()
    assert [r.id for r in rules] == ["R1", "R2", "R3", "R4", "R5", "R6",
                                     "R7", "R8"]


def test_instrument_parity_bidirectional():
    """A DEVICE_SLOTS entry no Slot(...) produces — and a check slot no
    _consume_check_slot handles — are findings too (fixture export.py,
    the real one stays untouched)."""
    import ast

    exp_src = ('TELEMETRY_PREFIXES = ("device",)\n'
               'PROCESS_LIFETIME_GAUGES = ("device.*",)\n'
               'DEVICE_SLOTS = ("win_fill", "never_computed")\n'
               'DEVICE_CHECK_SLOTS = ("seq",)\n')
    reg_src = ('from siddhi_tpu.observability.instruments import Slot\n'
               'def wire(tel, q):\n'
               '    tel.gauge(f"device.{q}.win_fill", lambda: 0)\n'
               'def spec():\n'
               '    return [Slot("win_fill"), Slot("seq", kind="check")]\n'
               'class R:\n'
               '    def _consume_check_slot(self, name, vals):\n'
               '        if name == "seq":\n'
               '            pass\n')
    mods = [
        ModuleInfo(path="siddhi_tpu/observability/export.py", src=exp_src,
                   tree=ast.parse(exp_src)),
        ModuleInfo(path="siddhi_tpu/core/wire.py", src=reg_src,
                   tree=ast.parse(reg_src)),
    ]
    findings = [f for f in run_lint(mods) if f.rule == "R6"]
    dead = [f for f in findings if "never_computed" in f.message]
    assert dead, [f.format() for f in findings]
    # the matched pair raises nothing else
    assert all("never_computed" in f.message for f in findings), \
        [f.format() for f in findings]


def test_metric_prefix_parity_bidirectional():
    """A declared-but-unused prefix is a finding too (dead declaration),
    using a fixture export.py so the real one stays untouched."""
    import ast

    exp_src = ('TELEMETRY_PREFIXES = ("junction", "ghost")\n'
               'PROCESS_LIFETIME_GAUGES = ("junction.*",)\n')
    reg_src = ('def wire(tel, sid):\n'
               '    tel.gauge(f"junction.{sid}.queue_depth", lambda: 0)\n')
    mods = [
        ModuleInfo(path="siddhi_tpu/observability/export.py", src=exp_src,
                   tree=ast.parse(exp_src)),
        ModuleInfo(path="siddhi_tpu/core/wire.py", src=reg_src,
                   tree=ast.parse(reg_src)),
    ]
    findings = run_lint(mods)
    ghosts = [f for f in findings if "ghost" in f.message]
    assert ghosts, [f.format() for f in findings]


def test_knob_parity_bidirectional():
    """A knob declared in the registry that no production code reads is
    a finding — in both consumption styles (attr=None needs a
    read_knob literal, attr='x' needs the attribute consumed). Uses a
    fixture knobs.py so the real registry stays untouched."""
    import ast

    reg_src = ('KNOBS = _declare(\n'
               '    Knob("window_capacity", "int",'
               ' attr="window_capacity"),\n'
               '    Knob("ghost_attr", "int", attr="ghost_attr"),\n'
               '    Knob("quota_queue_depth", "int"),\n'
               '    Knob("ghost_key", "float"),\n'
               ')\n')
    use_src = ('def wire(ctx, cm):\n'
               '    cap = getattr(ctx, "window_capacity", 4096)\n'
               '    depth = read_knob(cm, "quota_queue_depth")\n'
               '    return cap, depth\n')
    mods = [
        ModuleInfo(path="siddhi_tpu/core/util/knobs.py", src=reg_src,
                   tree=ast.parse(reg_src)),
        ModuleInfo(path="siddhi_tpu/core/wire.py", src=use_src,
                   tree=ast.parse(use_src)),
    ]
    findings = [f for f in run_lint(mods) if f.rule == "R2"]
    msgs = [f.message for f in findings]
    assert any("ghost_attr" in m for m in msgs), msgs
    assert any("ghost_key" in m for m in msgs), msgs
    # the two consumed knobs raise nothing
    assert not any("window_capacity" in m or "quota_queue_depth" in m
                   for m in msgs), msgs


def test_step_registry_resolves():
    """Every declared jitted step builder still exists where declared
    (hlo_audit trusts this registry for its coverage assertion)."""
    from siddhi_tpu.analysis.step_registry import JIT_STEP_BUILDERS, resolve

    assert len(JIT_STEP_BUILDERS) >= 7
    for name in JIT_STEP_BUILDERS:
        assert resolve(name) is not None


def test_graftlint_driver_exits_zero():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py")],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 findings" in proc.stdout
