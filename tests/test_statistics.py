"""Statistics subsystem + EventPrinter tests (reference
``statistics/*TestCase`` shapes: throughput per junction, latency per
query, level switching)."""

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.utils.event_printer import PrintingQueryCallback, print_events


def test_basic_throughput_tracking():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:statistics('true')
        define stream S (sym string, v int);
        @info(name='q')
        from S[v > 0] select sym, v insert into Out;
    """)

    class C(StreamCallback):
        def receive(self, events):
            pass

    rt.add_callback("Out", C())
    h = rt.get_input_handler("S")
    for i in range(5):
        h.send(["a", i + 1])
    stats = rt.statistics()
    m.shutdown()
    assert stats["level"] == "basic"
    assert stats["throughput"]["S"]["events"] == 5
    assert stats["throughput"]["Out"]["events"] == 5


def test_detail_latency_tracking():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:statistics(level='detail')
        define stream S (sym string, v int);
        @info(name='q')
        from S select sym, v insert into Out;
    """)
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    h.send(["b", 2])
    stats = rt.statistics()
    m.shutdown()
    assert stats["level"] == "detail"
    lat = stats["latency"]["q"]
    assert lat["batches"] == 2 and lat["avg_ms"] > 0


def test_level_switch_and_off_default():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v int);
        from S select sym insert into Out;
    """)
    assert rt.statistics() == {"level": "off"}
    rt.set_statistics_level("basic")
    h = rt.get_input_handler("S")
    h.send(["a", 1])
    stats = rt.statistics()
    m.shutdown()
    assert stats["throughput"]["S"]["events"] == 1


def test_event_printer(capsys):
    print_events(123, [1, 2], None)
    cb = PrintingQueryCallback()
    cb.receive(456, ["x"], None)
    out = capsys.readouterr().out
    assert "@timestamp = 123" in out and "@timestamp = 456" in out


def test_profiler_trace_roundtrip(tmp_path):
    # §5.1 tracing: device-level XLA profiler wrapped on the app runtime
    from siddhi_tpu import SiddhiManager

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (v int); from S[v > 0] select v insert into O;")
    d = str(tmp_path / "trace")
    rt.start_trace(d)
    rt.get_input_handler("S").send([5])
    rt.stop_trace()
    m.shutdown()
    import os
    found = []
    for root, _dirs, files in os.walk(d):
        found += files
    assert found  # trace events written


def test_console_reporter_detail_report(capsys):
    """statisticsTest1 (managment/StatisticsTestCase:53-107): the console
    reporter at DETAIL level prints throughput, latency, and memory
    metrics; both filter queries stay live (3 outputs)."""
    import time

    got = []

    class C(StreamCallback):
        def receive(self, events):
            got.extend(events)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:statistics(reporter = 'console', interval = '1 sec' )"
        " define stream cseEventStream (symbol string, price float, "
        "volume int);"
        "define stream cseEventStream2 (symbol string, price float, "
        "volume int);"
        "@info(name = 'query1') from cseEventStream[70 > price] select * "
        "insert into outputStream ;"
        "@info(name = 'query2') from cseEventStream[volume > 90] select * "
        "insert into outputStream ;")
    rt.add_callback("outputStream", C())
    rt.start()
    rt.set_statistics_level("detail")
    h = rt.get_input_handler("cseEventStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 100])
    time.sleep(1.6)           # let the periodic reporter fire once
    m.shutdown()
    assert len(got) == 3
    assert all(e.data[0] in ("IBM", "WSO2") for e in got)
    out = capsys.readouterr().out
    assert "query1" in out and "latency" in out.lower()
    assert "memory" in out.lower()
    assert "cseEventStream" in out


def test_console_reporter_off_level_silent(capsys):
    """statisticsTest2 (:122-192): with statistics OFF nothing is
    reported but events still flow."""
    import time

    got = []

    class C(StreamCallback):
        def receive(self, events):
            got.extend(events)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:statistics(reporter = 'console', interval = '1 sec' )"
        " define stream cseEventStream (symbol string, price float, "
        "volume int);"
        "@info(name = 'query1') from cseEventStream[70 > price] select * "
        "insert into outputStream ;")
    rt.add_callback("outputStream", C())
    rt.start()
    rt.set_statistics_level("off")
    h = rt.get_input_handler("cseEventStream")
    h.send(["WSO2", 55.6, 100])
    time.sleep(1.3)
    m.shutdown()
    assert len(got) == 1
    out = capsys.readouterr().out
    assert "latency" not in out.lower()
