"""`<cond> in Table` filter conditions — reference
InConditionExpressionExecutor (exists-probe over table contents)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


def test_in_table_membership_filter():
    m, rt, c = build("""
        define stream Feed (sym string, v long);
        define stream Allow (sym string);
        define table AllowT (sym string);
        from Allow select sym insert into AllowT;
        from Feed[AllowT.sym == sym in AllowT]
        select sym, v insert into OutStream;
    """)
    rt.get_input_handler("Allow").send(["ACME"])
    h = rt.get_input_handler("Feed")
    h.send(["ACME", 1])
    h.send(["EVIL", 2])
    rt.get_input_handler("Allow").send(["EVIL"])   # table grows live
    h.send(["EVIL", 3])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("ACME", 1), ("EVIL", 3)]


def test_in_table_combined_with_other_conditions():
    m, rt, c = build("""
        define stream Feed (sym string, v long);
        define table T (sym string, lim long);
        define stream Seed (sym string, lim long);
        from Seed select sym, lim insert into T;
        from Feed[v > 10 and (T.sym == sym and T.lim < v) in T]
        select sym, v insert into OutStream;
    """)
    rt.get_input_handler("Seed").send(["A", 20])
    h = rt.get_input_handler("Feed")
    h.send(["A", 15])    # v>10 but lim(20) !< 15
    h.send(["A", 25])    # passes both
    h.send(["B", 99])    # not in table
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("A", 25)]


def test_in_condition_bad_qualifier_rejected():
    import pytest

    from siddhi_tpu.ops.expressions import CompileError

    with pytest.raises(CompileError):
        build("""
            define stream Feed (sym string, v long);
            define table AllowT (sym string);
            from Feed[Bogus.sym == sym in AllowT]
            select sym insert into OutStream;
        """)


def test_in_condition_post_window_rejected():
    import pytest

    from siddhi_tpu.ops.expressions import CompileError

    with pytest.raises(CompileError, match="in <table>"):
        build("""
            define stream Feed (sym string, v long);
            define table AllowT (sym string);
            from Feed#window.length(2)[AllowT.sym == sym in AllowT]
            select sym insert into OutStream;
        """)
