"""Reference snapshot-rate-limit corpus — all 28 scenarios ported verbatim
from ``query/ratelimit/SnapshotOutputRateLimitTestCase.java``.

Timing convention: the reference anchors the snapshot cycle at app START
(scheduledTime = start + value); here a priming Tick at ts=0 pins the
playback anchor to 0, events use the reference's cumulative sleep offsets,
and a final Tick at the reference's assert moment drains the pending ticks
— bundle/event counts then map 1:1.

Variant semantics (reference ``ratelimit/snapshot/*.java``):
- no window:            re-emit last event / last-per-group each tick
- window, no agg:       re-emit the window's contents (group-by ignored)
- window, ALL agg:      re-emit last aggregate row; expiry clears it
  (per-group holders with live counts when grouped)
- window, some agg:     window contents with aggregate positions patched to
  the latest values; ONE row per group when grouped
- empty flushes reach QueryCallbacks as (null, null) (q21) but never
  stream callbacks (q12).
"""

from siddhi_tpu import SiddhiManager, QueryCallback, StreamCallback


class Bundles(StreamCallback):
    """Collects each delivery as one bundle of (data...) rows."""

    def __init__(self):
        super().__init__()
        self.bundles = []

    def receive(self, events):
        self.bundles.append([tuple(e.data) for e in events])

    @property
    def events(self):
        return [r for b in self.bundles for r in b]


class QBundles(QueryCallback):
    def __init__(self):
        self.receives = 0          # every receive, incl. (null, null)
        self.in_bundles = []       # non-null inEvents deliveries

    def receive(self, timestamp, in_events, remove_events):
        self.receives += 1
        if in_events:
            self.in_bundles.append([tuple(e.data) for e in in_events])

    @property
    def in_events(self):
        return [r for b in self.in_bundles for r in b]


def build(query_body, stream_attrs="timestamp long, ip string", cb=None,
          on="uniqueIps"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(f"""@app:playback
        define stream LoginEvents ({stream_attrs});
        define stream Tick (x int);
        @info(name = 'query1')
        {query_body}
        from Tick select x insert into TickOut;
    """)
    c = cb if cb is not None else Bundles()
    rt.add_callback(on, c)
    rt.start()
    h = rt.get_input_handler("LoginEvents")
    tick = rt.get_input_handler("Tick")
    tick.send(0, [0])  # pin the snapshot anchor at t=0 (= reference app start)
    return m, c, h, tick


IP5, IP3, IP9, IP4 = "192.10.1.5", "192.10.1.3", "192.10.1.9", "192.10.1.4"
IP6, IP7, IP8, IP30 = "192.10.1.6", "192.10.1.7", "192.10.1.8", "192.10.1.30"


def test_snapshot_q1_last_event_reemitted():
    """q1 (:53-107): no window — each tick re-emits only the LAST event."""
    m, c, h, tick = build(
        "from LoginEvents select ip output snapshot every 1 sec "
        "insert all events into uniqueIps;")
    h.send(0, [0, IP5])
    h.send(10, [10, IP3])
    tick.send(1500, [0])
    m.shutdown()
    assert c.bundles == [[(IP3,)]]


def test_snapshot_q2_last_repeats_every_tick():
    """q2 (:110-162): the held last event re-emits on EVERY tick (2 ticks
    before shutdown -> 2 copies); the empty pre-event tick emits nothing."""
    m, c, h, tick = build(
        "from LoginEvents select ip output snapshot every 1 sec "
        "insert all events into uniqueIps;")
    h.send(1200, [0, IP5])
    h.send(1700, [0, IP3])
    tick.send(3900, [0])
    m.shutdown()
    assert c.bundles == [[(IP3,)], [(IP3,)]]


def test_snapshot_q3_last_switches_mid_stream():
    """q3 (:165-224): last-event snapshot follows the newest event."""
    m, c, h, tick = build(
        "from LoginEvents select ip output snapshot every 1 sec "
        "insert all events into uniqueIps;")
    h.send(0, [0, IP5])
    h.send(100, [0, IP3])
    h.send(2300, [0, IP9])
    h.send(2400, [0, IP4])
    tick.send(3500, [0])
    m.shutdown()
    assert c.bundles == [[(IP3,)], [(IP3,)], [(IP4,)]]


def test_snapshot_q4_group_by_last_per_group():
    """q4 (:225-283): group-by without window — last-per-group map only
    GROWS (groups never retire): 3 bundles, 2+2+3 = 7 events."""
    m, c, h, tick = build(
        "from LoginEvents select ip group by ip output snapshot every 1 sec "
        "insert all events into uniqueIps;")
    h.send(1100, [0, IP5])
    h.send(1100, [0, IP3])
    h.send(3300, [0, IP5])
    h.send(3300, [0, IP4])
    tick.send(4500, [0])
    m.shutdown()
    assert c.bundles == [[(IP5,), (IP3,)], [(IP5,), (IP3,)],
                         [(IP5,), (IP3,), (IP4,)]]


def test_snapshot_q5_group_by_running_sums():
    """q5 (:285-346): unwindowed sum group-by — snapshots carry the RUNNING
    per-group sums; bundle 3 shows (5, 16) after the second pair."""
    m, c, h, tick = build(
        "from LoginEvents select ip, sum(calls) as totalCalls group by ip "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(1100, [0, IP5, 3])
    h.send(1100, [0, IP3, 6])
    h.send(3300, [0, IP5, 2])
    h.send(3300, [0, IP3, 10])
    tick.send(4500, [0])
    m.shutdown()
    assert len(c.bundles) == 3
    assert c.bundles[0] == [(IP5, 3), (IP3, 6)]
    assert c.bundles[2] == [(IP5, 5), (IP3, 16)]


def test_snapshot_q5_1_windowed_group_by_count_dedup():
    """q5_1 (:348-397): time(2s) + count() group-by — some-agg grouped
    snapshots emit ONE row per group (constructOutputChunk dedup): every
    bundle has 2 rows, counts (2, 2)."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(2 sec) select ip, count() as totalCalls "
        "group by ip output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    for ip, calls in [(IP5, 3), (IP3, 6), (IP5, 2), (IP3, 10)]:
        h.send(1100, [0, ip, calls])
    tick.send(4100, [0])
    m.shutdown()
    assert len(c.bundles) == 2
    for b in c.bundles:
        assert b == [(IP5, 2), (IP3, 2)]


def test_snapshot_q6_windowed_all_agg_group_by():
    """q6 (:399-454): time(1s) + `select sum(calls)` group-by (ALL outputs
    aggregated): per-group last-value holders; a group whose window empties
    stops emitting. Bundles: (3,6) then (2,10)."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(1 sec) select sum(calls) as totalCalls "
        "group by ip output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(1100, [0, IP5, 3])
    h.send(1100, [0, IP3, 6])
    h.send(3300, [0, IP5, 2])
    h.send(3300, [0, IP3, 10])
    tick.send(4500, [0])
    m.shutdown()
    assert c.bundles == [[(3,), (6,)], [(2,), (10,)]]


def test_snapshot_q7_all_agg_group_by_long_window():
    """q7 (:456-511): time(5s) sum group-by — overlapping pairs: 7 bundles
    of 2 rows = 14 events; values (3,6) -> (5,16) -> (2,10)."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(5 sec) select sum(calls) as totalCalls "
        "group by ip output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(1100, [0, IP5, 3])
    h.send(1100, [0, IP3, 6])
    h.send(3400, [0, IP5, 2])
    h.send(3400, [0, IP3, 10])
    tick.send(10600, [0])
    m.shutdown()
    assert len(c.bundles) == 7
    assert len(c.events) == 14
    assert c.bundles[0] == [(3,), (6,)]
    assert c.bundles[2] == [(5,), (16,)]
    assert c.bundles[5] == [(2,), (10,)]


def test_snapshot_q8_all_agg_no_group():
    """q8 (:513-567): time(1s) sum (no group-by): last aggregate row,
    CLEARED by expiry — bundles (9) then (12)."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(1 sec) select sum(calls) as totalCalls "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(1100, [0, IP5, 3])
    h.send(1200, [0, IP3, 6])
    h.send(3400, [0, IP5, 2])
    h.send(3500, [0, IP3, 10])
    tick.send(4700, [0])
    m.shutdown()
    assert c.bundles == [[(9,)], [(12,)]]


def test_snapshot_q9_all_agg_no_group_long_window():
    """q9 (:569-625): time(5s) sum — (9), (9), (21) across three ticks."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(5 sec) select sum(calls) as totalCalls "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(1100, [0, IP5, 3])
    h.send(1200, [0, IP3, 6])
    h.send(3400, [0, IP5, 2])
    h.send(3500, [0, IP3, 10])
    tick.send(4500, [0])
    m.shutdown()
    assert c.bundles == [[(9,)], [(9,)], [(21,)]]


def test_snapshot_q10_window_contents_at_boundary():
    """q10 (:627-680): time(2s) window + snapshot every 2s, tick and expiry
    tie at t=2000 — the limiter flush (armed first) wins: both rows emit."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(2 sec) select ip "
        "output snapshot every 2 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(0, [0, IP5, None])
    h.send(0, [0, IP3, None])
    tick.send(2000, [0])
    m.shutdown()
    assert c.bundles == [[(IP5,), (IP3,)]]


def test_snapshot_q11_window_contents_before_expiry():
    """q11 (:682-735): time(1s), events at 1.2s: the 2s tick sees them
    (expiry 2.2s), the 3s tick sees an empty window."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(1 sec) select ip "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(1200, [0, IP5, None])
    h.send(1200, [0, IP3, None])
    tick.send(3400, [0])
    m.shutdown()
    assert c.bundles == [[(IP5,), (IP3,)]]


def test_snapshot_q12_one_bundle_then_window_empties():
    """q12 (:737-782): events at 0.1s expire at 1.1s — only the 1s tick
    flushes (one bundle); empty flushes never reach stream callbacks."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(1 sec) select ip "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(100, [0, IP5, None])
    h.send(100, [0, IP3, None])
    tick.send(2300, [0])
    m.shutdown()
    assert len(c.bundles) == 1


def test_snapshot_q13_long_window_two_full_bundles():
    """q13 (:784-838): time(5s): both ticks re-emit both rows = 4 events."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(5 sec) select ip "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(0, [0, IP5, None])
    h.send(0, [0, IP3, None])
    tick.send(2200, [0])
    m.shutdown()
    assert c.bundles == [[(IP5,), (IP3,)], [(IP5,), (IP3,)]]


def test_snapshot_q14_tie_at_two_seconds():
    """q14 (:838-890): time(2s) + snapshot 2s, single tick at the tie."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(2 sec) select ip "
        "output snapshot every 2 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(0, [0, IP5, None])
    h.send(0, [0, IP3, None])
    tick.send(2000, [0])
    m.shutdown()
    assert c.bundles == [[(IP5,), (IP3,)]]


def test_snapshot_q15_two_generations_two_bundles():
    """q15 (:890-945): two event pairs in disjoint windows -> exactly 2
    non-empty flushes (QueryCallback `value` counts only non-null)."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.time(1 sec) select ip "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    h.send(100, [0, IP5, None])
    h.send(100, [0, IP3, None])
    h.send(2300, [0, IP5, None])
    h.send(2300, [0, IP3, None])
    tick.send(4500, [0])
    m.shutdown()
    assert len(qc.in_bundles) == 2


def test_snapshot_q16_group_by_ignored_without_agg():
    """q16 (:945-1002): time(1s) `select ip group by ip` — no aggregation,
    so the WINDOWED snapshot (not the group-by one) applies: window
    contents re-emit; 2 bundles, 4 events."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(1 sec) select ip group by ip "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(1100, [0, IP5, None])
    h.send(1100, [0, IP3, None])
    h.send(3300, [0, IP5, None])
    h.send(3300, [0, IP3, None])
    tick.send(4500, [0])
    m.shutdown()
    assert c.bundles == [[(IP5,), (IP3,)], [(IP5,), (IP3,)]]


def test_snapshot_q17_long_window_overlap():
    """q17 (:1004-1059): time(5s) no-agg: 2+2+4+4+4+2+2 = 20 events over
    7 bundles as the two pairs overlap then retire."""
    m, c, h, tick = build(
        "from LoginEvents#window.time(5 sec) select ip group by ip "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int")
    h.send(1100, [0, IP5, None])
    h.send(1100, [0, IP3, None])
    h.send(3300, [0, IP5, None])
    h.send(3300, [0, IP3, None])
    tick.send(10500, [0])
    m.shutdown()
    assert len(c.bundles) == 7
    assert len(c.events) == 20
    assert [len(b) for b in c.bundles] == [2, 2, 4, 4, 4, 2, 2]


def test_snapshot_q18_some_agg_patches_rows():
    """q18 (:1059-1116): time(1s) `select ip, sum(calls)` — window rows
    re-emit with the aggregate position patched to the LATEST sum: both
    first-bundle rows show 9, both second-bundle rows show 12."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.time(1 sec) select ip, sum(calls) as totalCalls "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    h.send(1100, [0, IP5, 3])
    h.send(1200, [0, IP3, 6])
    h.send(3400, [0, IP5, 2])
    h.send(3500, [0, IP3, 10])
    tick.send(4700, [0])
    m.shutdown()
    assert qc.in_bundles == [[(IP5, 9), (IP3, 9)], [(IP5, 12), (IP3, 12)]]


def test_snapshot_q19_some_agg_long_window():
    """q19 (:1116-1180): time(5s): 7 non-empty bundles; rows show 9 then 21
    (4 rows) then 12 as events expire."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.time(5 sec) select ip, sum(calls) as totalCalls "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    h.send(1100, [0, IP5, 3])
    h.send(1200, [0, IP3, 6])
    h.send(3400, [0, IP5, 2])
    h.send(3500, [0, IP3, 10])
    tick.send(10600, [0])
    m.shutdown()
    assert len(qc.in_bundles) == 7
    assert qc.in_bundles[0] == [(IP5, 9), (IP3, 9)]
    assert qc.in_bundles[2] == [(IP5, 21), (IP3, 21), (IP5, 21), (IP3, 21)]
    assert qc.in_bundles[5] == [(IP5, 12), (IP3, 12)]


def test_snapshot_q20_some_agg_group_by_one_row_per_group():
    """q20 (:1180-1243): time(5s) sum group-by — ONE row per group per
    bundle (7 bundles, 14 events): (3,6) -> (5,16) -> (2,10)."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.time(5 sec) select ip, sum(calls) as totalCalls "
        "group by ip output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    h.send(1100, [0, IP5, 3])
    h.send(1100, [0, IP3, 6])
    h.send(3300, [0, IP5, 2])
    h.send(3300, [0, IP3, 10])
    tick.send(9500, [0])
    m.shutdown()
    assert len(qc.in_bundles) == 7
    assert len(qc.in_events) == 14
    assert qc.in_bundles[0] == [(IP5, 3), (IP3, 6)]
    assert qc.in_bundles[2] == [(IP5, 5), (IP3, 16)]
    assert qc.in_bundles[5] == [(IP5, 2), (IP3, 10)]


def test_snapshot_q21_empty_flushes_reach_query_callback():
    """q21 (:1245-1306): time(1s) sum group-by — EMPTY snapshot flushes are
    delivered to QueryCallbacks as (null, null): 4 receives total (empty,
    data, empty, data), 4 events."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.time(1 sec) select ip, sum(calls) as totalCalls "
        "group by ip output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    h.send(1100, [0, IP5, 3])
    h.send(1100, [0, IP3, 6])
    h.send(3300, [0, IP5, 2])
    h.send(3300, [0, IP3, 10])
    tick.send(4500, [0])
    m.shutdown()
    assert qc.receives == 4
    assert qc.in_bundles == [[(IP5, 3), (IP3, 6)], [(IP5, 2), (IP3, 10)]]


BATCH7 = [(IP5, 3), (IP3, 6), (IP4, 2), (IP5, 1), (IP6, 1), (IP7, 2),
          (IP8, 10)]


def _batch7_feed(h):
    for ip, calls in BATCH7:
        h.send(100, [0, ip, calls])


def test_snapshot_q22_length_batch_window_contents():
    """q22 (:1306-1370): lengthBatch(3): at the 1s tick the snapshot holds
    only the SECOND batch (.5, .6, .7) — one bundle, 3 events."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(3) select ip, calls "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    _batch7_feed(h)
    tick.send(1300, [0])
    m.shutdown()
    assert qc.in_bundles == [[(IP5, 1), (IP6, 1), (IP7, 2)]]


def test_snapshot_q23_length_batch_some_agg():
    """q23 (:1370-1433): lengthBatch(3) + sum: second batch's rows patched
    to its batch sum (1+1+2 = 4)."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(3) select ip, sum(calls) as totalCalls "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    _batch7_feed(h)
    tick.send(1300, [0])
    m.shutdown()
    assert qc.in_bundles == [[(IP5, 4), (IP6, 4), (IP7, 4)]]


def test_snapshot_q24_length_batch_all_agg():
    """q24 (:1433-1492): lengthBatch(3) + `select sum(calls)` only: a single
    aggregate row (4)."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(3) select sum(calls) as totalCalls "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    _batch7_feed(h)
    tick.send(1300, [0])
    m.shutdown()
    assert qc.in_bundles == [[(4,)]]


def test_snapshot_q25_length_batch_all_agg_group_by():
    """q25 (:1492-1557): lengthBatch(3) + sum group-by (key NOT projected):
    per-group holders of the second batch: (1), (1), (2)."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(3) select sum(calls) as totalCalls "
        "group by ip output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    _batch7_feed(h)
    tick.send(1300, [0])
    m.shutdown()
    assert qc.in_bundles == [[(1,), (1,), (2,)]]


def test_snapshot_q26_length_batch_some_agg_group_by():
    """q26 (:1557-1621): lengthBatch(3) + ip,sum group-by: one row per
    group with per-group sums 1, 1, 2."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(3) select ip, sum(calls) as totalCalls "
        "group by ip output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    _batch7_feed(h)
    tick.send(1300, [0])
    m.shutdown()
    assert qc.in_bundles == [[(IP5, 1), (IP6, 1), (IP7, 2)]]


def test_snapshot_q27_length_batch_group_by_no_agg():
    """q27 (:1621-1686): lengthBatch(3) `select ip group by ip` — no agg,
    windowed snapshot: second batch contents."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(3) select ip group by ip "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    _batch7_feed(h)
    tick.send(1300, [0])
    m.shutdown()
    assert qc.in_bundles == [[(IP5,), (IP6,), (IP7,)]]


def test_snapshot_q28_batches_straddling_ticks():
    """q28 (:1686-...): batches land at 2.1s and 3.3s: ticks 1/2 flush empty
    (QueryCallback receives count them), tick 3 shows batch 1, tick 4 shows
    batch 2 — 6 data events (.5,.3,.4 then .5,.6,.7)."""
    qc = QBundles()
    m, c, h, tick = build(
        "from LoginEvents#window.lengthBatch(3) select ip group by ip "
        "output snapshot every 1 sec insert all events into uniqueIps;",
        stream_attrs="timestamp long, ip string, calls int",
        cb=qc, on="query1")
    for ip, calls in [(IP5, 3), (IP3, 6), (IP4, 2), (IP5, 1)]:
        h.send(2100, [0, ip, calls])
    for ip, calls in [(IP6, 1), (IP7, 2), (IP8, 10)]:
        h.send(3300, [0, ip, calls])
    tick.send(4500, [0])
    m.shutdown()
    assert qc.receives > 2
    assert qc.in_bundles == [[(IP5,), (IP3,), (IP4,)],
                             [(IP5,), (IP6,), (IP7,)]]
