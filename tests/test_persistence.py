"""Checkpoint/restore tests — modeled on reference
``managment/PersistenceTestCase.java:43``: run, persist, recreate the
runtime, restore, continue with state intact."""

import os

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.util.persistence import (
    FileSystemPersistenceStore,
    InMemoryPersistenceStore,
)


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


APP = """
    @app:name('persistApp')
    define stream S (symbol string, price float);
    define table T (symbol string, price float);
    @info(name = 'q1')
    from S#window.length(3)
    select symbol, sum(price) as total
    group by symbol
    insert into OutStream;
    from S insert into T;
"""


def test_persist_restore_across_runtimes():
    store = InMemoryPersistenceStore()

    m1 = SiddhiManager()
    m1.set_persistence_store(store)
    rt1 = m1.create_siddhi_app_runtime(APP)
    c1 = Collector()
    rt1.add_callback("OutStream", c1)
    h1 = rt1.get_input_handler("S")
    h1.send(["A", 1.0])
    h1.send(["A", 2.0])
    rev = rt1.persist()
    assert rev
    m1.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    c2 = Collector()
    rt2.add_callback("OutStream", c2)
    assert rt2.restore_last_revision() == rev
    h2 = rt2.get_input_handler("S")
    h2.send(["A", 4.0])   # window now holds 1,2,4 -> sum 7
    h2.send(["A", 8.0])   # slides out 1.0 -> sum 14
    totals = [e.data[1] for e in c2.events]
    assert totals == [7.0, 14.0]
    # table rows survived too
    rows = rt2.query("from T select symbol, price")
    assert sorted(e.data[1] for e in rows) == [1.0, 2.0, 4.0, 8.0]
    m2.shutdown()


def test_filesystem_store(tmp_path):
    store = FileSystemPersistenceStore(str(tmp_path))
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    h = rt.get_input_handler("S")
    h.send(["B", 5.0])
    rev1 = rt.persist()
    h.send(["B", 6.0])
    rev2 = rt.persist()
    assert store.revisions(rt.name) == [rev1, rev2]
    assert os.path.isdir(str(tmp_path))

    # restore the FIRST revision: only B=5 in the table
    rt.restore_revision(rev1)
    rows = rt.query("from T select price")
    assert [e.data[0] for e in rows] == [5.0]
    rt.clear_all_revisions()
    assert store.revisions(rt.name) == []
    m.shutdown()


def test_restore_without_store_errors():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("define stream S (x int); from S select x insert into O;")
    try:
        rt.persist()
        assert False, "expected RuntimeError"
    except RuntimeError as e:
        assert "persistence store" in str(e)
    m.shutdown()


def test_snapshot_bytes_roundtrip_pattern_and_partition():
    # NFA + partition state also survives snapshot/restore
    app = """
        define stream A (k string, v int);
        define stream B (k string, v int);
        partition with (k of A, k of B)
        begin
            from every e1=A -> e2=B[v > e1.v]
            select e1.k as k, e1.v as v1, e2.v as v2
            insert into OutStream;
        end;
    """
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("OutStream", c)
    rt.get_input_handler("A").send(["k1", 10])
    snap = rt.snapshot()

    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(app)
    c2 = Collector()
    rt2.add_callback("OutStream", c2)
    rt2.start()
    rt2.restore(snap)
    rt2.get_input_handler("B").send(["k1", 15])   # completes the restored pending
    assert [tuple(e.data) for e in c2.events] == [("k1", 10, 15)]
    m.shutdown()
    m2.shutdown()
