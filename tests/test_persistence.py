"""Checkpoint/restore tests — modeled on reference
``managment/PersistenceTestCase.java:43``: run, persist, recreate the
runtime, restore, continue with state intact."""

import os

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.util.persistence import (
    FileSystemPersistenceStore,
    InMemoryPersistenceStore,
)


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


APP = """
    @app:name('persistApp')
    define stream S (symbol string, price float);
    define table T (symbol string, price float);
    @info(name = 'q1')
    from S#window.length(3)
    select symbol, sum(price) as total
    group by symbol
    insert into OutStream;
    from S insert into T;
"""


def test_persist_restore_across_runtimes():
    store = InMemoryPersistenceStore()

    m1 = SiddhiManager()
    m1.set_persistence_store(store)
    rt1 = m1.create_siddhi_app_runtime(APP)
    c1 = Collector()
    rt1.add_callback("OutStream", c1)
    h1 = rt1.get_input_handler("S")
    h1.send(["A", 1.0])
    h1.send(["A", 2.0])
    rev = rt1.persist()
    assert rev
    m1.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    c2 = Collector()
    rt2.add_callback("OutStream", c2)
    assert rt2.restore_last_revision() == rev
    h2 = rt2.get_input_handler("S")
    h2.send(["A", 4.0])   # window now holds 1,2,4 -> sum 7
    h2.send(["A", 8.0])   # slides out 1.0 -> sum 14
    totals = [e.data[1] for e in c2.events]
    assert totals == [7.0, 14.0]
    # table rows survived too
    rows = rt2.query("from T select symbol, price")
    assert sorted(e.data[1] for e in rows) == [1.0, 2.0, 4.0, 8.0]
    m2.shutdown()


def test_filesystem_store(tmp_path):
    store = FileSystemPersistenceStore(str(tmp_path))
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    h = rt.get_input_handler("S")
    h.send(["B", 5.0])
    rev1 = rt.persist()
    h.send(["B", 6.0])
    rev2 = rt.persist()
    assert store.revisions(rt.name) == [rev1, rev2]
    assert os.path.isdir(str(tmp_path))

    # restore the FIRST revision: only B=5 in the table
    rt.restore_revision(rev1)
    rows = rt.query("from T select price")
    assert [e.data[0] for e in rows] == [5.0]
    rt.clear_all_revisions()
    assert store.revisions(rt.name) == []
    m.shutdown()


def test_restore_without_store_errors():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("define stream S (x int); from S select x insert into O;")
    try:
        rt.persist()
        assert False, "expected RuntimeError"
    except RuntimeError as e:
        assert "persistence store" in str(e)
    m.shutdown()


def test_snapshot_bytes_roundtrip_pattern_and_partition():
    # NFA + partition state also survives snapshot/restore
    app = """
        define stream A (k string, v int);
        define stream B (k string, v int);
        partition with (k of A, k of B)
        begin
            from every e1=A -> e2=B[v > e1.v]
            select e1.k as k, e1.v as v1, e2.v as v2
            insert into OutStream;
        end;
    """
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("OutStream", c)
    rt.get_input_handler("A").send(["k1", 10])
    snap = rt.snapshot()

    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(app)
    c2 = Collector()
    rt2.add_callback("OutStream", c2)
    rt2.start()
    rt2.restore(snap)
    rt2.get_input_handler("B").send(["k1", 15])   # completes the restored pending
    assert [tuple(e.data) for e in c2.events] == [("k1", 10, 15)]
    m.shutdown()
    m2.shutdown()


def test_revision_ids_unique_within_one_ms():
    # two persists in the same millisecond must not collide (revision ids
    # carry a process-monotonic counter after the ms prefix)
    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    rt.get_input_handler("S").send(["A", 1.0])
    r1 = rt.persist()
    r2 = rt.persist()
    assert r1 != r2
    assert store.get_last_revision(rt.name) == r2
    assert sorted([r1, r2]) == [r1, r2]  # sortable: later persist sorts last
    m.shutdown()


def test_restore_rearms_time_window_expiry():
    # restored time-window state must expire WITHOUT a new arrival on the
    # stream: restore re-arms the scheduler (reference re-schedules on
    # restore); the expired events then reach the callback in live mode
    import time as _time

    from siddhi_tpu import QueryCallback

    app = """
        @app:name('rearmApp')
        define stream S (symbol string, price float);
        @info(name = 'q1')
        from S#window.time(2000)
        select symbol, price
        insert all events into OutStream;
    """
    store = InMemoryPersistenceStore()
    m1 = SiddhiManager()
    m1.set_persistence_store(store)
    rt1 = m1.create_siddhi_app_runtime(app)
    rt1.get_input_handler("S").send(["A", 1.0])
    rev = rt1.persist()
    q1 = rt1.query_runtimes["q1"]
    import numpy as np

    # the snapshot must hold the event un-expired for the test to mean
    # anything (jit compile inside send() can eat wall time on a cold
    # cache); skip rather than red out when the machine was too slow
    if int(np.asarray(q1._state["win"]["expired_upto"])) != 0:
        import pytest

        m1.shutdown()
        pytest.skip("event expired before persist (cold-compile wall time)")
    m1.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(app)
    removed = []

    class QC(QueryCallback):
        def receive(self, timestamp, in_events, out_events):
            if out_events:
                removed.extend(out_events)

    rt2.add_callback("q1", QC())
    rt2.start()
    rt2.restore_revision(rev)
    deadline = _time.time() + 8.0
    while _time.time() < deadline and not removed:
        _time.sleep(0.05)
    assert removed, "restored window never expired its held event"
    assert removed[0].data == ["A", 1.0]
    m2.shutdown()


def test_incremental_persist_chain():
    """Full -> two op-log increments -> chain restore (incremental
    SnapshotService: aggregation bucket deltas + table insert journals)."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.aggregation.incremental import Duration
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    APP = """
    @app:playback
    define stream S (sym string, price double);
    define table T (sym string, price double);
    define aggregation Agg
      from S select sym, sum(price) as total
      group by sym aggregate every sec;
    from S insert into T;
    """
    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    h = rt.get_input_handler("S")
    h.send(10_000, ["A", 1.0])
    rt.persist()                       # full
    h.send(10_100, ["A", 2.0])
    h.send(12_000, ["B", 5.0])
    rt.persist_incremental()           # delta 1: touched buckets + inserts
    h.send(13_000, ["C", 7.0])
    rev = rt.persist_incremental()     # delta 2
    m.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    rt2.restore_revision(rev)
    agg = rt2.aggregations["Agg"]
    rows = {(r[0], r[1]): r[2] for r in agg.rows(Duration.SECONDS)}
    table_rows = sorted(tuple(e.data) for e in rt2.tables["T"].all_events())
    m2.shutdown()
    # bucket sums: A folded across full+delta, B and C arrive via deltas
    assert sorted(rows.values()) == [3.0, 5.0, 7.0]
    assert len(table_rows) == 4


def test_incremental_second_generation_restore_no_duplicates():
    """restore -> more inserts -> persist_incremental -> restore again:
    replayed journal rows must not re-enter the new delta (review finding:
    journal pollution on restore would duplicate table rows)."""
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    APP = """
    define stream S (sym string, price double);
    define table T (sym string, price double);
    from S insert into T;
    """
    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    rt.get_input_handler("S").send(["A", 1.0])
    rt.persist()
    rt.get_input_handler("S").send(["B", 2.0])
    rev1 = rt.persist_incremental()
    m.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    rt2.restore_revision(rev1)
    rt2.get_input_handler("S").send(["C", 3.0])
    rev2 = rt2.persist_incremental()
    m2.shutdown()

    m3 = SiddhiManager()
    m3.set_persistence_store(store)
    rt3 = m3.create_siddhi_app_runtime(APP)
    rt3.restore_revision(rev2)
    rows = sorted(tuple(e.data) for e in rt3.tables["T"].all_events())
    m3.shutdown()
    assert rows == [("A", 1.0), ("B", 2.0), ("C", 3.0)]


# --------------------------------------------- revision edge cases (ISSUE 1)
# Reference `managment` corpus behaviors around bad/absent revisions:
# PersistenceTestCase restores of unknown ids, clearAllRevisions mid-run,
# and incremental chains with no base. Triage: COVERAGE.md E-M6 row.


def test_restore_missing_revision_raises_keyerror():
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime(APP)
    rt.get_input_handler("S").send(["A", 1.0])
    import pytest

    with pytest.raises(KeyError, match="no-such-revision"):
        rt.restore_revision("no-such-revision")
    # the failed restore must not poison live state
    rt.get_input_handler("S").send(["A", 2.0])
    m.shutdown()


def test_restore_corrupt_revision_surfaces_error_and_keeps_state():
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    rev = rt.persist()
    # a torn write / bad disk: the stored bytes are not a snapshot
    store.save(rt.name, rev + "_corrupt", b"\x00garbage")
    import pytest

    with pytest.raises(Exception):
        rt.restore_revision(rev + "_corrupt")

    class C(StreamCallback):
        def __init__(self):
            super().__init__()
            self.events = []

        def receive(self, events):
            self.events.extend(events)

    c = C()
    rt.add_callback("OutStream", c)
    h.send(["A", 2.0])      # window still holds 1.0 -> sum 3.0
    assert c.events[-1].data[1] == 3.0
    m.shutdown()


def test_clear_all_revisions_mid_run_resets_the_chain():
    """clearAllRevisions between persists: the last-revision pointer must
    not dangle — restore_last_revision returns None, and the next
    persist_incremental falls back to a FULL snapshot rather than chaining
    to a wiped base."""
    import pickle

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    rt.persist()
    h.send(["A", 2.0])
    rt.persist_incremental()
    rt.clear_all_revisions()
    assert store.revisions(rt.name) == []
    assert rt.restore_last_revision() is None
    h.send(["A", 4.0])
    rev = rt.persist_incremental()      # no base left: must be full
    obj = pickle.loads(store.load(rt.name, rev))
    assert not obj.get("incremental"), "incremental chained to a wiped base"
    m.shutdown()

    # ...and the fallback snapshot restores standalone
    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    assert rt2.restore_last_revision() == rev
    rows = rt2.query("from T select symbol, price")
    assert sorted(e.data[1] for e in rows) == [1.0, 2.0, 4.0]
    m2.shutdown()


def test_incremental_on_empty_base_is_a_full_snapshot():
    import pickle

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    rt.get_input_handler("S").send(["A", 1.0])
    rev = rt.persist_incremental()      # first persist ever: no base
    obj = pickle.loads(store.load(rt.name, rev))
    assert not obj.get("incremental")
    m.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    assert rt2.restore_last_revision() == rev
    rows = rt2.query("from T select symbol, price")
    assert [e.data[1] for e in rows] == [1.0]
    m2.shutdown()


def test_restore_resets_nfa_high_water_marks():
    """Rolling back to a revision captured BEFORE any event must clear
    the NFA runtime's host high-water-mark mirror: stale post-snapshot
    HWMs would permanently classify every later batch as hard (generic
    fallback, fast kernel never used) and feed ``expire_to`` clocks from
    the abandoned timeline (ADVICE r05 low finding)."""
    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime("""
        @app:name('nfaHwmApp')
        define stream A (sym string, v double);
        define stream B (sym string, v double);
        @info(name='p')
        from every e1=A -> e2=B[e2.v > e1.v] within 2 sec
        select e1.sym as sym, e2.v as v insert into M;
    """)
    c = Collector()
    rt.add_callback("M", c)
    rev = rt.persist()          # checkpoint before any event: no nfa_hwm
    ha, hb = rt.get_input_handler("A"), rt.get_input_handler("B")
    ha.send(1_000, ["K", 1.0])
    hb.send(1_500, ["K", 2.0])
    q = rt.query_runtimes["p"]
    assert q._nfa_hwm_arr is not None       # host mirror advanced
    assert len(c.events) == 1
    rt.restore_revision(rev)
    assert q._nfa_hwm_arr is None           # rolled back with the state
    # the restored timeline re-accepts the same (pre-HWM) timestamps
    ha.send(1_000, ["K", 1.0])
    hb.send(1_500, ["K", 2.0])
    m.shutdown()
    assert len(c.events) == 2
