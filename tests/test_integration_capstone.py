"""Integration capstone: tables + in-conditions + windowed group-by +
partitions + patterns + incremental aggregation + persistence in ONE app."""

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore


class C(StreamCallback):
    def __init__(self):
        super().__init__()
        self.out = []

    def receive(self, events):
        self.out.extend(events)


def test_capstone_app():
    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime("""
        @app:name('Capstone') @app:playback
        define stream Trades (user string, sym string, price double, ts long);
        define stream Logins (user string, ok bool);
        @primaryKey('sym') define table Limits (sym string, cap double);
        define stream SeedLimits (sym string, cap double);
        define aggregation TradeCube
        from Trades select sym, sum(price) as turnover
        group by sym aggregate by ts every sec ... hour;

        from SeedLimits select sym, cap insert into Limits;

        @info(name='guard')
        from Trades[Limits.sym == sym in Limits]#window.length(100)
        select user, sym, sum(price) as vol group by user, sym
        insert into GuardedVol;

        partition with (user of Logins) begin
          @info(name='fails')
          from Logins[not ok]#window.lengthBatch(2)
          select user, count() as fails insert into FailAlerts;
        end;

        @info(name='suspect')
        from every e1=Logins[not ok] -> e2=Trades[e2.user == e1.user and price > 50.0]
             within 1 min
        select e1.user as user, e2.price as price insert into Suspects;
    """)
    g, f, s = C(), C(), C()
    rt.add_callback("GuardedVol", g)
    rt.add_callback("FailAlerts", f)
    rt.add_callback("Suspects", s)
    rt.get_input_handler("SeedLimits").send(["ACME", 100.0])
    tr = rt.get_input_handler("Trades")
    lg = rt.get_input_handler("Logins")
    base = 1_700_000_000_000
    lg.send(base, ["eve", False])
    lg.send(base + 100, ["eve", False])
    tr.send(base + 200, ["eve", "ACME", 60.0, base + 200])
    tr.send(base + 300, ["bob", "ACME", 10.0, base + 300])
    tr.send(base + 400, ["bob", "EVIL", 99.0, base + 400])  # not in Limits
    rev = rt.persist()
    rows = rt.query(
        f"from TradeCube within {base}L, {base + 10_000}L per 'seconds' "
        "select sym, turnover")
    m.shutdown()
    assert [tuple(e.data) for e in g.out] == [
        ("eve", "ACME", 60.0), ("bob", "ACME", 10.0)]
    assert [tuple(e.data) for e in f.out] == [("eve", 2)]
    # both of eve's failed logins started chains; the trade completes both
    assert [tuple(e.data) for e in s.out] == [("eve", 60.0), ("eve", 60.0)]
    assert sorted(tuple(e.data) for e in rows) == [
        ("ACME", 70.0), ("EVIL", 99.0)]
    assert rev
