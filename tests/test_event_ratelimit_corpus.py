"""Reference event-rate-limit corpus — all 18 scenarios ported verbatim from
``query/ratelimit/EventOutputRateLimitTestCase.java`` (feeds and expected
output counts; the reference's 1 s sleeps need no analog because event-count
limiters fire synchronously).

Semantics under test (reference ``query/output/ratelimit/event/*.java``):
- ``output all every N events``: accumulate, flush all N at the N-th event
  (AllPerEventOutputRateLimiter.java:49-76).
- ``output first every N events``: emit the 1st event of each N-window.
- ``output last every N events``: emit the N-th event of each N-window.
- group-by + first: per-group counter, re-armed after the group's N-th event
  (FirstGroupByPerEventOutputRateLimiter.java:49-76).
- group-by + last: GLOBAL counter, last-per-group LinkedHashMap flushed at
  the N-th event (LastGroupByPerEventOutputRateLimiter.java:50-83).
"""

from siddhi_tpu import SiddhiManager, QueryCallback


class Counter(QueryCallback):
    def __init__(self):
        self.count = 0
        self.remove_count = 0
        self.in_rows = []
        self.remove_rows = []
        self.arrived = False

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.count += len(in_events)
            self.in_rows.extend(tuple(e.data) for e in in_events)
        if remove_events:
            self.remove_count += len(remove_events)
            self.remove_rows.extend(tuple(e.data) for e in remove_events)
        self.arrived = True


def run(output_clause, feed, select="select ip", window=""):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(f"""
        define stream LoginEvents (timestamp long, ip string);
        @info(name = 'query1')
        from LoginEvents{window}
        {select}
        {output_clause}
        insert into uniqueIps;
    """)
    c = Counter()
    rt.add_callback("query1", c)
    h = rt.get_input_handler("LoginEvents")
    rt.start()
    for ip in feed:
        h.send([0, ip])
    m.shutdown()
    return c


FEED5 = ["192.10.1.3", "192.10.1.3", "192.10.1.4", "192.10.1.3", "192.10.1.5"]
FEED8 = ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
         "192.10.1.4", "192.10.1.4", "192.10.1.4", "192.10.1.30"]
FEED12 = ["192.10.1.5", "192.10.1.3", "192.10.1.3", "192.10.1.9",
          "192.10.1.3", "192.10.1.4", "192.10.1.4", "192.10.1.4",
          "192.10.1.30", "192.10.1.31", "192.10.1.32", "192.10.1.33"]


def test_event_rate_q1_all_every_2():
    """testEventOutputRateLimitQuery1 (:45-97): `output all every 2 events`,
    5 sends -> two full pairs flushed = 4; trailing odd event held back."""
    c = run("output all every 2 events", FEED5)
    assert c.arrived and c.remove_count == 0
    assert c.count == 4


def test_event_rate_q2_bare_output_every_2():
    """testEventOutputRateLimitQuery2 (:99-149): bare `output every 2 events`
    defaults to ALL (OutputRate.java type default) — same 4 as q1."""
    c = run("output every 2 events", FEED5)
    assert c.arrived and c.remove_count == 0
    assert c.count == 4


def test_event_rate_q3_all_every_5_of_8():
    """testEventOutputRateLimitQuery3 (:151-205): every 5 of 8 sends -> one
    flush of 5; the trailing 3 are held."""
    c = run("output every 5 events", FEED8)
    assert c.arrived and c.remove_count == 0
    assert c.count == 5


def test_event_rate_q4_first_every_2():
    """testEventOutputRateLimitQuery4 (:207-260): `output first every 2
    events` over 5 sends emits events 1,3,5 — the reference also asserts
    every emitted ip is one of .5/.9/.3."""
    feed = ["192.10.1.5", "192.10.1.3", "192.10.1.9", "192.10.1.4", "192.10.1.3"]
    c = run("output first every 2 events", feed)
    assert c.count == 3
    assert [r[0] for r in c.in_rows] == ["192.10.1.5", "192.10.1.9", "192.10.1.3"]


def test_event_rate_q5_first_every_3():
    """testEventOutputRateLimitQuery5 (:262-314): first every 3 over 5 sends
    emits events 1,4 (.5 and .4)."""
    feed = ["192.10.1.5", "192.10.1.3", "192.10.1.9", "192.10.1.4", "192.10.1.3"]
    c = run("output first every 3 events", feed)
    assert c.count == 2
    assert [r[0] for r in c.in_rows] == ["192.10.1.5", "192.10.1.4"]


def test_event_rate_q6_last_every_2():
    """testEventOutputRateLimitQuery6 (:316-368): last every 2 over 5 sends
    emits events 2,4 (.5 and .4); trailing odd event held."""
    feed = ["192.10.1.3", "192.10.1.5", "192.10.1.3", "192.10.1.4", "192.10.1.3"]
    c = run("output last every 2 events", feed)
    assert c.count == 2
    assert [r[0] for r in c.in_rows] == ["192.10.1.5", "192.10.1.4"]


def test_event_rate_q7_last_every_4():
    """testEventOutputRateLimitQuery7 (:370-421): last every 4 over 5 sends
    emits only event 4 (.4)."""
    feed = ["192.10.1.3", "192.10.1.5", "192.10.1.3", "192.10.1.4", "192.10.1.3"]
    c = run("output last every 4 events", feed)
    assert c.count == 1
    assert [r[0] for r in c.in_rows] == ["192.10.1.4"]


def test_event_rate_q8_group_by_first_every_5():
    """testEventOutputRateLimitQuery8 (:423-476): group by ip + first every 5:
    per-group counters -> .5,.3,.9,.4,.30 each emit on first sight = 5."""
    c = run("output first every 5 events", FEED8, select="select ip group by ip")
    assert c.count == 5


def test_event_rate_q9_group_by_last_every_5():
    """testEventOutputRateLimitQuery9 (:478-533): group by ip + last every 5:
    GLOBAL counter hits 5 once in 8 events -> flush last-per-group
    {.5,.3,.9,.4} = 4."""
    c = run("output last every 5 events", FEED8, select="select ip group by ip")
    assert c.count == 4


def test_event_rate_q10_group_by_first_rearm():
    """testEventOutputRateLimitQuery10 (:535-590): first every 5 with a group
    seen 6x: the 5th occurrence re-arms the group but does NOT emit; the 6th
    (per-group) occurrence would emit — here .4's run of 5 re-arms at its
    5th so only the initial sighting of each of 5 groups emits = 5."""
    feed = ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
            "192.10.1.4", "192.10.1.4", "192.10.1.4", "192.10.1.4",
            "192.10.1.4", "192.10.1.30"]
    c = run("output first every 5 events", feed, select="select ip group by ip")
    assert c.count == 5


def test_event_rate_q11_group_by_last_two_flushes():
    """testEventOutputRateLimitQuery11 (:592-648): last every 5 group-by over
    10 events: flush at event 5 = {.5,.3,.9,.4} (4), flush at event 10 =
    {.4,.30,.3} (3) -> 7."""
    feed = ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
            "192.10.1.4", "192.10.1.4", "192.10.1.4", "192.10.1.30",
            "192.10.1.3", "192.10.1.30"]
    c = run("output last every 5 events", feed, select="select ip group by ip")
    assert c.count == 7


def test_event_rate_q12_batch_window_group_by_last():
    """testEventOutputRateLimitQuery12 (:651-710): lengthBatch(4) + group-by
    selector emits ONE event per group per batch (QuerySelector batched
    group-by path); limiter sees 3+2+4 selector outputs, global counter hits
    5 once -> flush last-per-group {.5,.3,.9,.4} = 4."""
    c = run("output last every 5 events", FEED12,
            select="select ip, count() as total group by ip",
            window="#window.lengthBatch(4)")
    assert c.count == 4


def test_event_rate_q13_batch_window_last_every_2():
    """testEventOutputRateLimitQuery13 (:712-769): lengthBatch(4) without
    group-by emits one aggregated event per batch (3 batches); last every 2
    fires once at the 2nd batch output -> 1."""
    c = run("output last every 2 events", FEED12,
            select="select ip, count() as total",
            window="#window.lengthBatch(4)")
    assert c.count == 1


def test_event_rate_q14_batch_window_last_expired():
    """testEventOutputRateLimitQuery14 (:771-828): as q13 but `insert expired
    events` — the limiter counts currents AND expireds; exactly 1 expired
    event reaches the callback and no currents."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream LoginEvents (timestamp long, ip string);
        @info(name = 'query1')
        from LoginEvents#window.lengthBatch(4)
        select ip, count() as total
        output last every 2 events
        insert expired events into uniqueIps;
    """)
    c = Counter()
    rt.add_callback("query1", c)
    h = rt.get_input_handler("LoginEvents")
    rt.start()
    for ip in FEED12:
        h.send([0, ip])
    m.shutdown()
    assert c.count == 0
    assert c.remove_count == 1


def test_event_rate_q15_batch_window_all_expired():
    """testEventOutputRateLimitQuery15 (:831-888): all every 2 + `insert
    expired events` over 3 lengthBatch(4) flushes -> 2 expired events reach
    the callback (the 3rd is held in an incomplete pair)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream LoginEvents (timestamp long, ip string);
        @info(name = 'query1')
        from LoginEvents#window.lengthBatch(4)
        select ip, count() as total
        output all every 2 events
        insert expired events into uniqueIps;
    """)
    c = Counter()
    rt.add_callback("query1", c)
    h = rt.get_input_handler("LoginEvents")
    rt.start()
    for ip in FEED12:
        h.send([0, ip])
    m.shutdown()
    assert c.count == 0
    assert c.remove_count == 2


def test_event_rate_q16_batch_window_group_by_all_expired():
    """testEventOutputRateLimitQuery16 (:890-948): group-by + all every 2 +
    `insert expired events`: 4 expired events reach the callback."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream LoginEvents (timestamp long, ip string);
        @info(name = 'query1')
        from LoginEvents#window.lengthBatch(4)
        select ip, count() as total
        group by ip
        output all every 2 events
        insert expired events into uniqueIps;
    """)
    c = Counter()
    rt.add_callback("query1", c)
    h = rt.get_input_handler("LoginEvents")
    rt.start()
    for ip in FEED12:
        h.send([0, ip])
    m.shutdown()
    assert c.count == 0
    assert c.remove_count == 4


def test_event_rate_q17_group_by_first_every_2():
    """testEventOutputRateLimitQuery17 (:950-1006): first every 2 group-by:
    per-group window of 2 (emit 1st, swallow 2nd, re-arm) over 11 events = 8."""
    feed = ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.5",
            "192.10.1.5", "192.10.1.9", "192.10.1.4", "192.10.1.4",
            "192.10.1.4", "192.10.1.5", "192.10.1.30"]
    c = run("output first every 2 events", feed, select="select ip group by ip")
    assert c.count == 8


def test_event_rate_q18_first_every_2_values():
    """testEventOutputRateLimitQuery18 (:1008-1067): first every 2 (no
    group-by) over 11 events emits positions 1,3,5,7,9,11 = 6, every emitted
    ip in {.5,.4} per the feed layout."""
    feed = ["192.10.1.5", "192.10.1.3", "192.10.1.5", "192.10.1.5",
            "192.10.1.5", "192.10.1.9", "192.10.1.4", "192.10.1.4",
            "192.10.1.4", "192.10.1.30", "192.10.1.5"]
    c = run("output first every 2 events", feed)
    assert c.count == 6
    assert all(r[0] in ("192.10.1.5", "192.10.1.4") for r in c.in_rows)
