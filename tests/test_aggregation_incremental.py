"""Incremental aggregation tests — modeled on reference
``aggregation/AggregationTestCase`` patterns (define aggregation, on-demand
within/per queries)."""

from siddhi_tpu import SiddhiManager


APP = """
    define stream TradeStream (symbol string, price double, volume long, ts long);
    define aggregation TradeAgg
    from TradeStream
    select symbol, sum(price) as total, avg(price) as avgPrice, count() as n
    group by symbol
    aggregate by ts every sec ... year;
"""


def _mk():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    h = rt.get_input_handler("TradeStream")
    # two seconds worth of trades, two symbols
    h.send(["A", 10.0, 1, 1000])
    h.send(["A", 20.0, 1, 1500])
    h.send(["B", 5.0, 1, 1700])
    h.send(["A", 40.0, 1, 2200])
    return m, rt


def test_seconds_granularity():
    m, rt = _mk()
    rows = rt.query(
        "from TradeAgg within 0L, 100000L per 'seconds' "
        "select AGG_TIMESTAMP, symbol, total, n")
    got = sorted(tuple(e.data) for e in rows)
    assert got == [
        (1000, "A", 30.0, 2),
        (1000, "B", 5.0, 1),
        (2000, "A", 40.0, 1),
    ]
    m.shutdown()


def test_coarser_granularity_and_avg():
    m, rt = _mk()
    rows = rt.query(
        "from TradeAgg within 0L, 100000L per 'hours' "
        "select symbol, total, avgPrice, n")
    got = sorted(tuple(e.data) for e in rows)
    assert got == [("A", 70.0, 70.0 / 3, 3), ("B", 5.0, 5.0, 1)]
    m.shutdown()


def test_within_filters_buckets():
    m, rt = _mk()
    rows = rt.query(
        "from TradeAgg within 2000L, 3000L per 'seconds' select symbol, total")
    got = [tuple(e.data) for e in rows]
    assert got == [("A", 40.0)]
    m.shutdown()


def test_on_demand_condition_and_aggregation():
    m, rt = _mk()
    rows = rt.query(
        "from TradeAgg on symbol == 'A' within 0L, 100000L per 'seconds' "
        "select sum(total) as grand")
    assert rows[-1].data == [70.0]
    m.shutdown()


def test_aggregation_snapshot_roundtrip():
    m, rt = _mk()
    snap = rt.snapshot()
    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(APP)
    rt2.start()
    rt2.restore(snap)
    h2 = rt2.get_input_handler("TradeStream")
    h2.send(["A", 100.0, 1, 2500])
    rows = rt2.query(
        "from TradeAgg within 0L, 100000L per 'years' select symbol, total")
    got = sorted(tuple(e.data) for e in rows)
    assert got == [("A", 170.0), ("B", 5.0)]
    m.shutdown()
    m2.shutdown()


def test_null_arguments_skip_bases():
    # null attribute values must not fold into sum/min/avg bases (reference
    # incremental aggregators skip nulls); min must not corrupt to 0, avg
    # must not count null rows
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream T (symbol string, price double, ts long);
        define aggregation NullAgg
        from T
        select symbol, sum(price) as total, avg(price) as avgP,
               min(price) as mn, count() as n
        group by symbol
        aggregate by ts every sec;
    """)
    h = rt.get_input_handler("T")
    h.send(["A", 10.0, 1000])
    h.send(["A", None, 1200])
    h.send(["A", 30.0, 1400])
    rows = rt.query(
        "from NullAgg within 0L, 100000L per 'seconds' "
        "select symbol, total, avgP, mn, n")
    got = [tuple(e.data) for e in rows]
    # count() counts all 3 rows; the value bases saw only 10 and 30
    assert got == [("A", 40.0, 20.0, 10.0, 3)]
    m.shutdown()
