"""Device-resident partitioned join engine (siddhi_tpu/core/join/).

ISSUE-9 acceptance set: eligible stream-stream window joins attach the
PanJoin-style engine and become pipeline-eligible (entries ride the
CompletionPump at depth >= 2 with per-side notify attribution), join
checkpoint/restore is exactly-once with a NON-empty pipeline, snapshots
cross-restore between the partitioned build-state layout and the legacy
``[W]`` ring at any ``join_partitions`` value, join overflow errors name
the exact config knob, join sides fuse into fan-out groups, and
partitioned keyed joins run mesh-sharded bit-identically.

Direct ``process_side_batch`` calls are the deterministic way to park
join batches in the pipeline: junction sends flush the pump before
returning (the synchronous-semantics contract), so a test that needs
entries IN FLIGHT feeds the runtime below the junction.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.event import HostBatch
from siddhi_tpu.core.stream.junction import FatalQueryError
from siddhi_tpu.core.util.config import InMemoryConfigManager
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore


class Collector(StreamCallback):
    def __init__(self):
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


APP = """
@app:name('japp')
define stream L (sym string, lv long);
define stream R (sym string, rv long);
@info(name='jq') from L#window.length(64) join R#window.length(64)
  on L.sym == R.sym
  select L.sym as sym, L.lv as lv, R.rv as rv insert into Out;
"""


def _manager(depth=2, mode="device", P=8, extra=None):
    m = SiddhiManager()
    cfg = {
        "siddhi_tpu.pipeline_depth": str(depth),
        "siddhi_tpu.join_engine": mode,
        "siddhi_tpu.join_partitions": str(P),
    }
    cfg.update(extra or {})
    m.set_config_manager(InMemoryConfigManager(cfg))
    return m


def _build(depth=2, mode="device", P=8, extra=None, store=None, app=APP):
    m = _manager(depth, mode, P, extra)
    if store is not None:
        m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("Out", c)
    rt.start()
    return m, rt, c


def _side_batch(rt, stream, syms, vals, ts0=0):
    defn = rt.junctions[stream].definition
    col = "lv" if stream == "L" else "rv"
    return HostBatch.from_columns(
        {"sym": np.array(syms, dtype=object),
         col: np.asarray(vals, np.int64)},
        defn, rt.app_context.string_dictionary,
        timestamps=np.arange(ts0, ts0 + len(vals), dtype=np.int64))


def _feed(rt, lo, hi, seed=5):
    rng = np.random.default_rng(seed)
    picks, syms, vals = (rng.random(1000), rng.integers(0, 5, 1000),
                         rng.integers(0, 99, 1000))
    hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
    for i in range(lo, hi):
        (hl if picks[i] < .5 else hr).send([f"S{syms[i]}", int(vals[i])])


# ------------------------------------------------------------ eligibility

def test_eligible_join_attaches_engine_and_is_pipeline_ok():
    m, rt, _c = _build()
    q = rt.query_runtimes["jq"]
    assert q.engine is not None and q.engine_reason is None
    assert q.pipeline_reason is None and q._pipeline_ok
    # the equality conjunct engaged the partitioned probe on both sides
    assert q.engine.partitioned_probe
    assert all(p.use_pidx for p in q.engine.plans.values())
    m.shutdown()


def test_legacy_mode_keeps_joins_synchronous():
    m, rt, _c = _build(mode="legacy")
    q = rt.query_runtimes["jq"]
    assert q.engine is None
    assert "legacy" in (q.engine_reason or "")
    assert not q._pipeline_ok
    m.shutdown()


def test_ineligible_shapes_keep_legacy_with_reason():
    app = """
define stream L (sym string, lv long);
define table T (sym string, tv long);
@info(name='tj') from L join T on L.sym == T.sym
  select L.sym as sym, T.tv as tv insert into Out;
"""
    m = _manager()
    rt = m.create_siddhi_app_runtime(app)
    q = rt.query_runtimes["tj"]
    assert q.engine is None
    assert "shared-store" in q.engine_reason
    assert not q._pipeline_ok and "store" in q.pipeline_reason
    m.shutdown()


def test_float_key_keeps_broadcast_probe_but_still_pipelines():
    app = """
define stream L (k double, lv long);
define stream R (k double, rv long);
@info(name='fj') from L#window.length(64) join R#window.length(64)
  on L.k == R.k select L.lv as lv, R.rv as rv insert into Out;
"""
    m = _manager()
    rt = m.create_siddhi_app_runtime(app)
    q = rt.query_runtimes["fj"]
    # float equality must not hash-partition (-0.0 == 0.0, NaN), but the
    # fused in-state step still attaches and pipelines
    assert q.engine is not None and not q.engine.partitioned_probe
    assert q._pipeline_ok
    m.shutdown()


# ------------------------------------------------------- pump + sequence

def test_join_batches_ride_pump_at_depth2_and_drain_in_order():
    m, rt, c = _build(depth=4)
    q = rt.query_runtimes["jq"]
    pump = rt.app_context.completion_pump
    q.process_side_batch("right", _side_batch(rt, "R", ["A"], [100]))
    q.process_side_batch("left", _side_batch(rt, "L", ["A"], [1], ts0=1))
    q.process_side_batch("left", _side_batch(rt, "L", ["A"], [2], ts0=2))
    assert pump.inflight(q) == 3 and c.rows == []
    pump.flush_owner(q)
    # cross-stream dispatch order: right insert emitted nothing, the two
    # left probes emitted in order; drain verified the explicit sequence
    assert c.rows == [("A", 1, 100), ("A", 2, 100)]
    assert q._drain_seq == 3
    tel = rt.app_context.telemetry.snapshot()
    assert tel["counters"].get("join.seq_breaks", 0) == 0
    m.shutdown()


def test_checkpoint_restore_with_nonempty_pipeline_exactly_once():
    store = InMemoryPersistenceStore()
    m, rt, c = _build(depth=4, store=store)
    q = rt.query_runtimes["jq"]
    pump = rt.app_context.completion_pump
    q.process_side_batch("right", _side_batch(rt, "R", ["A"], [100]))
    q.process_side_batch("left", _side_batch(rt, "L", ["A"], [1], ts0=1))
    assert pump.inflight(q) == 2 and c.rows == []
    rev = rt.persist()
    # the in-flight batches emitted exactly once, inside the barrier
    assert c.rows == [("A", 1, 100)]
    assert pump.inflight(q) == 0
    # post-checkpoint in-flight work is discarded by the rollback
    q.process_side_batch("left", _side_batch(rt, "L", ["A"], [7], ts0=2))
    assert pump.inflight(q) == 1
    rt.restore_revision(rev)
    assert pump.inflight(q) == 0
    assert c.rows == [("A", 1, 100)]      # no loss, no double emission
    # restored build state: both windows hold their pre-checkpoint rows
    rt.get_input_handler("L").send(["A", 9])
    assert c.rows[-1] == ("A", 9, 100)
    m.shutdown()


# ------------------------------------------------------ snapshot layouts

@pytest.mark.parametrize("dst_mode,dst_p", [
    ("device", 1), ("device", 4), ("device", 8), ("legacy", 8)])
def test_cross_restore_partitioned_and_legacy_layouts(dst_mode, dst_p):
    """A revision captured under the partitioned engine (P=8) restores
    into P in {1, 4, 8} AND into the legacy path — and the continuation
    is bit-identical to an uninterrupted run (snapshots store only the
    canonical [W] ring layout; directories rebuild at restore)."""
    m, rt, c = _build()
    _feed(rt, 0, 120)
    ref = list(c.rows)
    m.shutdown()

    store = InMemoryPersistenceStore()
    m1, rt1, c1 = _build(store=store)
    _feed(rt1, 0, 60)
    rev = rt1.persist()
    head = list(c1.rows)
    m1.shutdown()

    m2, rt2, c2 = _build(mode=dst_mode, P=dst_p, store=store)
    rt2.restore_revision(rev)
    _feed(rt2, 60, 120)
    m2.shutdown()
    assert head + c2.rows == ref


def test_legacy_snapshot_restores_into_engine():
    store = InMemoryPersistenceStore()
    m, rt, c = _build(mode="legacy", store=store)
    _feed(rt, 0, 60)
    rev = rt.persist()
    head = list(c.rows)
    m.shutdown()

    m2, rt2, c2 = _build(mode="device", store=store)
    _feed_ref_m, _rt_ref, c_ref = _build()
    _feed(_rt_ref, 0, 120)
    _feed_ref_m.shutdown()
    rt2.restore_revision(rev)
    _feed(rt2, 60, 120)
    m2.shutdown()
    assert head + c2.rows == c_ref.rows


# ------------------------------------------------------- overflow knobs

def test_partition_subwindow_overflow_names_slack_knob():
    # growth OFF = static provisioning: skew past the Wp sub-window is a
    # FatalQueryError naming the slack knob (the adaptive default grows
    # Wp instead — covered by the test below)
    m, rt, _c = _build(extra={"siddhi_tpu.join_partition_slack": "1",
                              "siddhi_tpu.join_partition_grow": "0"},
                       app=APP.replace("length(64)", "length(32)"))
    q = rt.query_runtimes["jq"]
    assert q.engine is not None and q.engine.partitioned_probe
    assert not q.engine.grow
    h = rt.get_input_handler("L")
    with pytest.raises(FatalQueryError,
                       match="join_partition_slack"):
        # 20 rows of ONE key into a Wp = 32/8 = 4 sub-window
        h.send_columns(
            {"sym": np.array(["A"] * 20, dtype=object),
             "lv": np.arange(20, dtype=np.int64)},
            timestamps=np.arange(20, dtype=np.int64))
        rt.app_context.completion_pump.flush()
        h.send(["A", 99])     # pipelined overflow surfaces on next send
    m.shutdown()


def test_adaptive_growth_absorbs_skew_bit_identically():
    """Default (growth ON): a hot key overflowing its sub-window grows Wp
    pre-dispatch instead of dying — PanJoin's adaptive re-partitioning —
    and the output stays bit-identical to the legacy path."""
    skew_app = APP.replace("length(64)", "length(32)")

    def run(mode):
        m, rt, c = _build(extra={"siddhi_tpu.join_partition_slack": "1"},
                          app=skew_app, mode=mode)
        hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
        rng = np.random.default_rng(17)
        for i in range(120):                     # ~70% one hot key
            sym = "HOT" if rng.random() < .7 else f"S{rng.integers(0, 4)}"
            (hl if rng.random() < .5 else hr).send([sym, int(i)])
        if mode == "device":
            q = rt.query_runtimes["jq"]
            grown = max(p.Wp for p in q.engine.plans.values())
            assert grown > 4, f"sub-windows never grew (Wp={grown})"
        m.shutdown()
        return c.rows

    assert run("device") == run("legacy")


def test_window_capacity_overflow_names_capacity_knob():
    app = """
define stream L (sym string, lv long);
define stream R (sym string, rv long);
@info(name='jq') from L#window.time(10 sec) join R#window.length(8)
  on L.sym == R.sym
  select L.sym as sym, R.rv as rv insert into Out;
"""
    m, rt, _c = _build(extra={"siddhi_tpu.window_capacity": "16"}, app=app)
    q = rt.query_runtimes["jq"]
    assert q.engine is not None
    h = rt.get_input_handler("L")
    with pytest.raises(FatalQueryError, match="window_capacity"):
        # wall-clock timestamps: the 40 rows stay live inside the 10 s
        # window, overflowing the 16-slot ring
        h.send_columns(
            {"sym": np.array([f"S{i}" for i in range(40)], dtype=object),
             "lv": np.arange(40, dtype=np.int64)})
        rt.app_context.completion_pump.flush()
        h.send(["A", 99])
    m.shutdown()


def test_overflow_knob_msg_decodes_bitmask():
    m, rt, _c = _build()
    q = rt.query_runtimes["jq"]
    assert "window_capacity" in q.overflow_knob_msg(1)
    assert "index_probe_width" in q.overflow_knob_msg(2)
    assert "join_partition_slack" in q.overflow_knob_msg(4)
    assert "distinct_values_capacity" in q.overflow_knob_msg(8)
    both = q.overflow_knob_msg(5)
    assert "window_capacity" in both and "join_partition_slack" in both
    m.shutdown()


# ------------------------------------------------------------- fan-out

FANOUT_APP = """
define stream L (sym string, lv long);
define stream R (sym string, rv long);
@info(name='f0') from L[lv > 5] select sym, lv insert into F0;
@info(name='jq') from L#window.length(16) join R#window.length(16)
  on L.sym == R.sym
  select L.sym as sym, L.lv as lv, R.rv as rv insert into Out;
@info(name='f1') from L select sym, lv * 2 as dbl insert into F1;
"""


def _run_fanout(fused: bool):
    m = _manager(extra={"siddhi_tpu.fuse_fanout": "1" if fused else "0"})
    rt = m.create_siddhi_app_runtime(FANOUT_APP)
    outs = {s: Collector() for s in ("F0", "Out", "F1")}
    for s, c in outs.items():
        rt.add_callback(s, c)
    rt.start()
    if fused:
        (group,) = rt.fused_fanout_groups
        assert [mm.name for mm in group.members] == ["f0", "jq.left", "f1"]
    rng = np.random.default_rng(3)
    hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
    for _ in range(80):
        s = f"S{rng.integers(0, 4)}"
        ((hl, "lv") if rng.random() < 0.6 else (hr, "rv"))[0].send(
            [s, int(rng.integers(0, 20))])
    m.shutdown()
    return {s: c.rows for s, c in outs.items()}


def test_join_side_fuses_on_shared_junction_bit_identical():
    ref = _run_fanout(False)
    got = _run_fanout(True)
    assert got == ref
    assert ref["Out"]


def test_fused_join_side_overflow_names_partition_knob():
    """The fused drain decodes the join side's overflow BITMASK: a
    partition sub-window overflow inside a fan-out group must name
    join_partition_slack, not default to window capacity."""
    app = FANOUT_APP.replace("length(16)", "length(32)")
    m = _manager(extra={"siddhi_tpu.fuse_fanout": "1",
                        "siddhi_tpu.join_partition_slack": "1",
                        "siddhi_tpu.join_partition_grow": "false"})
    rt = m.create_siddhi_app_runtime(app)
    rt.start()
    assert rt.fused_fanout_groups
    h = rt.get_input_handler("L")
    with pytest.raises(FatalQueryError, match="join_partition_slack"):
        # 20 rows of ONE key into a Wp = 32/8 = 4 sub-window
        h.send_columns(
            {"sym": np.array(["A"] * 20, dtype=object),
             "lv": np.arange(20, dtype=np.int64)},
            timestamps=np.arange(20, dtype=np.int64))
        rt.app_context.completion_pump.flush()
        h.send(["A", 99])
    m.shutdown()


def test_join_partition_grow_accepts_boolean_spellings():
    for spelling, want in (("false", False), ("true", True), ("0", False),
                           ("on", True)):
        m, rt, _c = _build(
            extra={"siddhi_tpu.join_partition_grow": spelling})
        assert rt.query_runtimes["jq"].engine.grow is want
        m.shutdown()
    from siddhi_tpu.compiler.errors import SiddhiAppValidationException

    m = _manager(extra={"siddhi_tpu.join_partition_grow": "maybe"})
    with pytest.raises(SiddhiAppValidationException,
                       match="join_partition_grow"):
        m.create_siddhi_app_runtime(APP)
    m.shutdown()


def test_self_join_sides_do_not_fuse():
    app = """
define stream L (sym string, lv long);
@info(name='sj') from L#window.length(8) as a join L#window.length(8) as b
  on a.sym == b.sym
  select a.sym as sym, b.lv as lv insert into Out;
"""
    m = _manager()
    rt = m.create_siddhi_app_runtime(app)
    # both proxies would share one state pytree in one fused step
    assert not rt.fused_fanout_groups
    m.shutdown()


# -------------------------------------------------------- mesh-sharded

PART_APP = """
define stream L (sym string, lv long);
define stream R (sym string, rv long);
partition with (sym of L, sym of R)
begin
  @info(name='pj') from L#window.length(8) join R#window.length(8)
    on L.lv > R.rv
    select L.sym as sym, L.lv as lv, R.rv as rv insert into Out;
end;
"""


def _feed_part(rt, lo, hi, n_sym=9, seed=11):
    rng = np.random.default_rng(seed)
    picks, syms, vals = (rng.random(1000), rng.integers(0, n_sym, 1000),
                         rng.integers(0, 30, 1000))
    hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
    for i in range(lo, hi):
        (hl if picks[i] < .5 else hr).send([f"S{syms[i]}", int(vals[i])])


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_routed_partitioned_join_bit_identical(n_dev):
    from siddhi_tpu.parallel.mesh import device_route_query_step, make_mesh

    m, rt, c = _build(app=PART_APP)
    _feed_part(rt, 0, 200)
    ref = list(c.rows)
    m.shutdown()
    assert ref

    m2, rt2, c2 = _build(app=PART_APP)
    q = rt2.query_runtimes["pj"]
    device_route_query_step(q, make_mesh(n_dev), rows_per_shard=256)
    assert q._route_layout.n == n_dev
    _feed_part(rt2, 0, 200)
    m2.shutdown()
    assert c2.rows == ref


def test_routed_join_cross_restores_to_unsharded():
    from siddhi_tpu.parallel.mesh import device_route_query_step, make_mesh

    m, rt, c = _build(app=PART_APP)
    _feed_part(rt, 0, 160)
    ref = list(c.rows)
    m.shutdown()

    store = InMemoryPersistenceStore()
    m1, rt1, c1 = _build(app=PART_APP, store=store)
    q = rt1.query_runtimes["pj"]
    device_route_query_step(q, make_mesh(2), rows_per_shard=256)
    _feed_part(rt1, 0, 80)
    rev = rt1.persist()
    head = list(c1.rows)
    m1.shutdown()

    m2, rt2, c2 = _build(app=PART_APP, store=store)
    rt2.restore_revision(rev)
    _feed_part(rt2, 80, 160)
    m2.shutdown()
    assert head + c2.rows == ref


def test_route_ineligibility_reasons_for_joins():
    from siddhi_tpu.parallel.mesh import route_ineligibility

    m, rt, _c = _build()      # non-partitioned engine join
    assert "non-partitioned" in route_ineligibility(
        rt.query_runtimes["jq"])
    m.shutdown()
    m2, rt2, _c2 = _build(app=PART_APP)
    assert route_ineligibility(rt2.query_runtimes["pj"]) is None
    m2.shutdown()


# ------------------------------------------------------------- metrics

def test_join_metrics_families_on_prometheus_surface():
    from siddhi_tpu.observability.export import prometheus_text

    m, rt, _c = _build()
    rt.get_input_handler("L").send(["A", 1])
    rt.get_input_handler("R").send(["A", 2])
    text = prometheus_text(m)
    assert "siddhi_join_partition_rows{" in text
    assert 'side="left"' in text and 'partition="0"' in text
    assert "siddhi_join_probe_ms{" in text
    assert "siddhi_join_insert_ms{" in text
    # one live build row per side across the partitions
    import re

    rows = {}
    for line in text.splitlines():
        mm = re.match(r'siddhi_join_partition_rows\{.*side="(\w+)".*\} (\d+)',
                      line)
        if mm:
            rows[mm.group(1)] = rows.get(mm.group(1), 0) + int(mm.group(2))
    assert rows == {"left": 1, "right": 1}
    m.shutdown()
