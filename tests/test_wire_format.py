"""Wire-format round-trip edge cases (core/stream/input/wire.py).

Deterministic coverage of the frame protocol — empty batch, all-null
columns, dictionary delta growth, non-ASCII strings, truncation and
corruption (clean ``SiddhiAppValidationException``, never a crash or a
silent partial batch) — plus a hypothesis property sweep over random
schemas (skipped where hypothesis is absent, per the
test_property_chunking convention)."""

import struct

import numpy as np
import pytest

from siddhi_tpu.compiler.errors import SiddhiAppValidationException
from siddhi_tpu.core.event import HostBatch, StringDictionary
from siddhi_tpu.core.stream.input.wire import (
    CAP_CONTROL, CAP_DICT_DELTA, CAP_TS, CAPABILITIES, CTRL_CHECKPOINT_CUT,
    CTRL_HEARTBEAT, CTRL_HELLO, CTRL_SEQ_ACK, MAGIC, VERSION,
    DecoderRegistry, WireEncoder, decode_control, decode_frame,
    encode_control, encode_hello, is_control, negotiate_hello)
from siddhi_tpu.query_api.definitions import (
    Attribute, AttrType, StreamDefinition)


def _definition(attrs):
    return StreamDefinition("S", attributes=[
        Attribute(name, t) for name, t in attrs])


DEF3 = _definition([("sym", AttrType.STRING), ("v", AttrType.DOUBLE),
                    ("n", AttrType.LONG)])


def _decode(frame, definition=DEF3, dictionary=None, registry=None):
    # explicit None checks: an EMPTY StringDictionary is falsy (__len__)
    if dictionary is None:
        dictionary = StringDictionary()
    if registry is None:
        registry = DecoderRegistry()
    return decode_frame(frame, definition, dictionary, registry)


def _strings_of(data, dictionary, name="sym"):
    return [dictionary.decode(int(i)) if i >= 0 else None
            for i in data[name]]


# ------------------------------------------------------------ round trips


def test_round_trip_basic():
    enc = WireEncoder()
    syms = np.array(["a", "b", None, "a", "Grüße-☃"], dtype=object)
    v = np.array([1.5, -2.0, 0.0, 3.25, 1e9])
    n = np.arange(5, dtype=np.int64)
    ts = np.array([10, 20, 30, 40, 50], dtype=np.int64)
    d = StringDictionary()
    data, wts = _decode(enc.encode({"sym": syms, "v": v, "n": n},
                                   timestamps=ts), dictionary=d)
    assert _strings_of(data, d) == ["a", "b", None, "a", "Grüße-☃"]
    assert np.array_equal(np.asarray(data["v"]), v)
    assert np.array_equal(np.asarray(data["n"]), n)
    assert np.array_equal(np.asarray(wts), ts)


def test_round_trip_feeds_from_columns_bit_identically():
    """The wire path must land EXACTLY what direct send_columns lands:
    same HostBatch columns, pre-encoded ids included."""
    enc = WireEncoder()
    syms = np.array(["x", "y", None, "x"], dtype=object)
    v = np.array([1.0, 2.0, 3.0, 4.0])
    n = np.array([1, 2, 3, 4], dtype=np.int64)
    ts = np.arange(4, dtype=np.int64)
    d1, d2 = StringDictionary(), StringDictionary()
    direct = HostBatch.from_columns(
        {"sym": syms, "v": v, "n": n}, DEF3, d1, timestamps=ts)
    data, wts = _decode(enc.encode({"sym": syms, "v": v, "n": n},
                                   timestamps=ts), dictionary=d2)
    wired = HostBatch.from_columns(data, DEF3, d2, timestamps=wts)
    assert d1._to_str == d2._to_str
    for k in direct.cols:
        assert np.array_equal(direct.cols[k], wired.cols[k]), k


def test_empty_batch():
    enc = WireEncoder()
    frame = enc.encode({"sym": np.array([], dtype=object),
                        "v": np.array([], np.float64),
                        "n": np.array([], np.int64)},
                       timestamps=np.array([], np.int64))
    data, wts = _decode(frame)
    assert len(data["sym"]) == 0 and len(wts) == 0


def test_all_null_string_column():
    enc = WireEncoder()
    d = StringDictionary()
    data, _ = _decode(enc.encode(
        {"sym": np.array([None, None, None], dtype=object),
         "v": np.zeros(3), "n": np.zeros(3, np.int64)}), dictionary=d)
    assert _strings_of(data, d) == [None, None, None]
    assert len(d) == 0      # nothing inserted for an all-null column


def test_explicit_null_masks_ride():
    enc = WireEncoder()
    frame = enc.encode({"sym": np.array(["a", "b"], dtype=object),
                        "v": np.array([1.0, 2.0]),
                        "v?": np.array([False, True]),
                        "n": np.array([7, 8], np.int64)})
    data, _ = _decode(frame)
    assert np.array_equal(np.asarray(data["v?"]), [False, True])


def test_dictionary_delta_growth():
    """Frames carry only NEW strings; the server LUT grows per frame and
    ids stay stable across frames."""
    enc = WireEncoder()
    d = StringDictionary()
    reg = DecoderRegistry()

    def send(names):
        frame = enc.encode({"sym": np.array(names, dtype=object),
                            "v": np.zeros(len(names)),
                            "n": np.zeros(len(names), np.int64)})
        data, _ = decode_frame(frame, DEF3, d, reg)
        return data

    d1 = send(["a", "b"])
    d2 = send(["b", "c"])          # delta carries only "c"
    d3 = send(["a", "c", "d"])     # delta carries only "d"
    assert _strings_of(d1, d) == ["a", "b"]
    assert _strings_of(d2, d) == ["b", "c"]
    assert _strings_of(d3, d) == ["a", "c", "d"]
    # same client string -> same server id across frames
    assert d1["sym"][0] == d3["sym"][0]
    assert d2["sym"][1] == d3["sym"][1]
    assert len(d) == 4


def test_delta_gap_rejected_and_reset_recovers():
    """A decoder that lost the LUT (restart/eviction) rejects the next
    delta frame with a clean error; WireEncoder.reset() resends from a
    full dictionary and recovery is exact."""
    enc = WireEncoder()
    d = StringDictionary()
    reg = DecoderRegistry()
    f1 = enc.encode({"sym": np.array(["a", "b"], dtype=object),
                     "v": np.zeros(2), "n": np.zeros(2, np.int64)})
    decode_frame(f1, DEF3, d, reg)
    f2 = enc.encode({"sym": np.array(["c"], dtype=object),
                     "v": np.zeros(1), "n": np.zeros(1, np.int64)})
    fresh = DecoderRegistry()      # the server lost its state
    with pytest.raises(SiddhiAppValidationException,
                       match="dictionary delta gap"):
        decode_frame(f2, DEF3, d, fresh)
    enc.reset()
    f3 = enc.encode({"sym": np.array(["c", "a"], dtype=object),
                     "v": np.zeros(2), "n": np.zeros(2, np.int64)})
    data, _ = decode_frame(f3, DEF3, d, fresh)
    assert _strings_of(data, d) == ["c", "a"]


def test_registry_scope_partitions_encoder_state():
    """One encoder posting to TWO apps (scopes): each scope keeps its
    own LUT against its own dictionary — app B must never gather app
    A's server ids."""
    enc = WireEncoder()
    reg = DecoderRegistry()
    dA, dB = StringDictionary(), StringDictionary()
    dA.encode("shift-A")            # skew A's id space vs B's
    f1 = enc.encode({"sym": np.array(["x"], dtype=object),
                     "v": np.zeros(1), "n": np.zeros(1, np.int64)})
    a1, _ = decode_frame(f1, DEF3, dA, reg, scope="A")
    # same frame bytes into scope B: fresh LUT (dict_base 0), B's ids
    b1, _ = decode_frame(f1, DEF3, dB, reg, scope="B")
    assert _strings_of(a1, dA) == ["x"] and _strings_of(b1, dB) == ["x"]
    assert int(a1["sym"][0]) != int(b1["sym"][0])   # distinct id spaces
    # delta continuity advances independently per scope
    f2 = enc.encode({"sym": np.array(["y"], dtype=object),
                     "v": np.zeros(1), "n": np.zeros(1, np.int64)})
    a2, _ = decode_frame(f2, DEF3, dA, reg, scope="A")
    b2, _ = decode_frame(f2, DEF3, dB, reg, scope="B")
    assert _strings_of(a2, dA) == ["y"] and _strings_of(b2, dB) == ["y"]


def test_pre_encoded_int_string_column():
    """Numeric columns under a STRING attribute are rejected — silent
    misinterpretation of raw ints as dictionary ids is the bug class
    the type codes exist to stop."""
    enc = WireEncoder()
    frame = enc.encode({"sym": np.array([0, 1], np.int64),
                        "v": np.zeros(2), "n": np.zeros(2, np.int64)})
    with pytest.raises(SiddhiAppValidationException,
                       match="string attribute"):
        _decode(frame)


# ------------------------------------------------- corruption / truncation


def _frame():
    enc = WireEncoder()
    return enc.encode({"sym": np.array(["a", "b", "c"], dtype=object),
                       "v": np.arange(3, dtype=np.float64),
                       "n": np.arange(3, dtype=np.int64)},
                      timestamps=np.arange(3, dtype=np.int64))


@pytest.mark.parametrize("cut", [0, 3, 12, 47, 60, -8, -1])
def test_truncated_frames_rejected(cut):
    frame = _frame()
    with pytest.raises(SiddhiAppValidationException, match="wire frame"):
        _decode(frame[:cut] if cut >= 0 else frame[:len(frame) + cut])


def test_bad_magic_and_version():
    frame = bytearray(_frame())
    frame[:4] = b"NOPE"
    with pytest.raises(SiddhiAppValidationException, match="magic"):
        _decode(bytes(frame))
    frame = bytearray(_frame())
    frame[4] = 99
    with pytest.raises(SiddhiAppValidationException, match="version"):
        _decode(bytes(frame))


def test_missing_column_rejected():
    enc = WireEncoder()
    frame = enc.encode({"sym": np.array(["a"], dtype=object),
                        "v": np.zeros(1)})    # 'n' absent
    with pytest.raises(SiddhiAppValidationException,
                       match="column 'n' missing"):
        _decode(frame)


def test_client_id_out_of_dictionary_range():
    """A hand-crafted frame whose string column references an id the
    dictionary delta never defined is rejected, not gathered out of
    bounds."""
    header = struct.Struct("<4sHHQIIIHHIIQ")
    name = b"sym"
    dir_entry = (struct.pack("<H", len(name)) + name
                 + struct.pack("<BBQQ", 6, 0, 0, 8))
    payload = np.array([7, -1], np.int32).tobytes()
    frame = header.pack(MAGIC, 1, 0, 42, 0, 0, 2, 1, 0,
                        len(dir_entry), 0, len(payload)) \
        + dir_entry + payload
    with pytest.raises(SiddhiAppValidationException,
                       match="outside the 0-entry dictionary"):
        decode_frame(frame, _definition([("sym", AttrType.STRING)]),
                     StringDictionary(), DecoderRegistry())


def test_offset_escape_rejected():
    header = struct.Struct("<4sHHQIIIHHIIQ")
    name = b"v"
    dir_entry = (struct.pack("<H", len(name)) + name
                 + struct.pack("<BBQQ", 1, 0, 1 << 20, 8))
    payload = b"\0" * 16
    frame = header.pack(MAGIC, 1, 0, 1, 0, 0, 2, 1, 0,
                        len(dir_entry), 0, len(payload)) \
        + dir_entry + payload
    with pytest.raises(SiddhiAppValidationException, match="escapes"):
        decode_frame(frame, _definition([("v", AttrType.DOUBLE)]),
                     StringDictionary(), DecoderRegistry())


# ----------------------------------------- hello negotiation / control


def test_hello_round_trip():
    hello = negotiate_hello(encode_hello(sender_id=42))
    assert hello.kind == CTRL_HELLO
    assert hello.version == VERSION and hello.a == 42
    assert hello.capabilities == CAPABILITIES
    assert hello.capabilities & CAP_TS
    assert hello.capabilities & CAP_DICT_DELTA
    assert hello.capabilities & CAP_CONTROL


def test_hello_version_mismatch_names_both_versions():
    """A v2 encoder against this v1 decoder (and vice versa) fails at
    negotiation with an error naming BOTH versions — never a
    frame-parse error."""
    with pytest.raises(SiddhiAppValidationException) as ei:
        negotiate_hello(encode_hello(version=VERSION + 1))
    msg = str(ei.value)
    assert f"version {VERSION + 1}" in msg
    assert f"version {VERSION}" in msg


def test_data_frame_version_mismatch_names_both_versions():
    frame = bytearray(_frame())
    frame[4] = VERSION + 1
    with pytest.raises(SiddhiAppValidationException) as ei:
        _decode(bytes(frame))
    msg = str(ei.value)
    assert f"version {VERSION + 1}" in msg
    assert f"version {VERSION}" in msg
    assert "hello" in msg          # points at the negotiation path


def test_hello_capability_narrowing_and_requirements():
    # a peer offering extra future bits: narrowed to the mutual set
    h = negotiate_hello(encode_hello(capabilities=CAPABILITIES | (1 << 30)))
    assert h.capabilities == CAPABILITIES
    # a required capability the peer lacks is a clean negotiation error
    with pytest.raises(SiddhiAppValidationException, match="capability"):
        negotiate_hello(encode_hello(capabilities=CAP_TS),
                        required=CAP_CONTROL)


def test_control_frames_round_trip_and_stay_off_the_data_path():
    for kind, a, b, body in [
            (CTRL_HEARTBEAT, 7, 123, b""),
            (CTRL_SEQ_ACK, 1, 99, b""),
            (CTRL_CHECKPOINT_CUT, 2, 5, b'{"rev": "r1"}')]:
        buf = encode_control(kind, a=a, b=b, body=body)
        assert is_control(buf)
        cf = decode_control(buf)
        assert (cf.kind, cf.a, cf.b, cf.body) == (kind, a, b, body)
    # control frames bounce off decode_frame with a clean error...
    with pytest.raises(SiddhiAppValidationException, match="control"):
        _decode(encode_control(CTRL_HEARTBEAT))
    # ...and data frames bounce off decode_control symmetrically
    assert not is_control(_frame())
    with pytest.raises(SiddhiAppValidationException, match="data frame"):
        decode_control(_frame())
    with pytest.raises(SiddhiAppValidationException, match="truncated"):
        decode_control(encode_control(CTRL_CHECKPOINT_CUT,
                                      body=b"x" * 10)[:-4])


# ----------------------------------------------------- LRU eviction fix


def test_lru_eviction_raises_reset_error_and_counts():
    """A live connection's encoder state evicted by a tiny LRU must
    fail the NEXT frame with the documented WireEncoder.reset() error
    naming the eviction — not a generic gap error, and never (for an
    encoder with an empty LUT) silent acceptance."""
    from siddhi_tpu.observability.telemetry import global_registry

    reg = DecoderRegistry(max_encoders=2)
    d = StringDictionary()
    encs = [WireEncoder() for _ in range(3)]

    def frame_of(enc, names):
        return enc.encode({"sym": np.array(names, dtype=object),
                           "v": np.zeros(len(names)),
                           "n": np.zeros(len(names), np.int64)})

    before = global_registry().counters.get(
        "ingest.wire.decoder_evictions", 0)
    # three encoders through a 2-slot LRU: encoder 0 is evicted
    for enc in encs:
        decode_frame(frame_of(enc, ["a", "b"]), DEF3, d, reg)
    assert reg.evictions == 1
    assert global_registry().counters[
        "ingest.wire.decoder_evictions"] == before + 1
    # encoder 0's next DELTA frame: the eviction-specific error
    with pytest.raises(SiddhiAppValidationException) as ei:
        decode_frame(frame_of(encs[0], ["a", "c"]), DEF3, d, reg)
    msg = str(ei.value)
    assert "evicted" in msg and "WireEncoder.reset" in msg
    # reset() recovers exactly (dict_base 0 re-bootstraps)
    encs[0].reset()
    data, _ = decode_frame(frame_of(encs[0], ["a", "c"]), DEF3, d, reg)
    assert _strings_of(data, d) == ["a", "c"]


def test_lru_eviction_error_even_with_empty_lut():
    """The silent-corruption corner: an evicted encoder whose LUT had
    no strings yet would previously pass the generic gap check
    (0 == 0). The eviction tracker must still refuse the frame."""
    reg = DecoderRegistry(max_encoders=1)
    d = StringDictionary()
    e1, e2 = WireEncoder(), WireEncoder()

    def no_string_frame(enc, base):
        # hand-roll dict_base continuity without strings: first frame
        # establishes the state, second claims a nonzero base
        f = enc.encode({"sym": np.array(["s"] * base, dtype=object),
                        "v": np.zeros(base), "n": np.zeros(base, np.int64)})
        return f

    decode_frame(no_string_frame(e1, 1), DEF3, d, reg)     # e1 live
    decode_frame(no_string_frame(e2, 1), DEF3, d, reg)     # evicts e1
    with pytest.raises(SiddhiAppValidationException,
                       match="evicted"):
        decode_frame(e1.encode(
            {"sym": np.array(["t"], dtype=object),
             "v": np.zeros(1), "n": np.zeros(1, np.int64)}), DEF3, d, reg)


# ------------------------------------------------------ property sweep


pytestmark_hyp = pytest.importorskip  # see test_property_chunking


def test_property_random_schemas():
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    attr_types = st.sampled_from(
        [AttrType.STRING, AttrType.LONG, AttrType.DOUBLE, AttrType.BOOL])
    schemas = st.lists(attr_types, min_size=1, max_size=5)

    @settings(max_examples=40, deadline=None)
    @given(
        schema=schemas,
        n_rows=st.integers(min_value=0, max_value=64),
        seed=st.integers(min_value=0, max_value=2**31 - 1),
    )
    def check(schema, n_rows, seed):
        rng = np.random.default_rng(seed)
        definition = _definition(
            [(f"a{i}", t) for i, t in enumerate(schema)])
        data = {}
        expect = {}
        for i, t in enumerate(schema):
            name = f"a{i}"
            if t == AttrType.STRING:
                col = np.array(
                    [None if rng.random() < 0.2
                     else f"s{rng.integers(0, 10)}-é"
                     for _ in range(n_rows)], dtype=object)
            elif t == AttrType.LONG:
                col = rng.integers(-1000, 1000, n_rows, dtype=np.int64)
            elif t == AttrType.DOUBLE:
                col = rng.random(n_rows)
            else:
                col = rng.integers(0, 2, n_rows).astype(bool)
            data[name] = col
            expect[name] = col
        ts = rng.integers(0, 1000, n_rows).astype(np.int64)
        enc = WireEncoder()
        d = StringDictionary()
        decoded, wts = decode_frame(
            enc.encode(data, timestamps=ts), definition, d,
            DecoderRegistry())
        assert np.array_equal(np.asarray(wts), ts)
        for i, t in enumerate(schema):
            name = f"a{i}"
            if t == AttrType.STRING:
                assert _strings_of(decoded, d, name) == list(expect[name])
            else:
                assert np.array_equal(np.asarray(decoded[name]),
                                      expect[name]), name

    check()
