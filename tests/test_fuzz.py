"""Semantic fuzzing subsystem tests (siddhi_tpu/fuzz/).

Covers: generator well-formedness (100 seeded queries all compile),
seed reproducibility, differ exactness (order-sensitive), shrinker
minimality via the planted-divergence self-test, the committed fixture
corpus, eligibility reason codes, and the census for a known-ineligible
shape (keyed time-batch window)."""

import glob
import json
import os
import pickle

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.eligibility import (
    SURFACE_FUSION,
    SURFACE_ROUTE,
    Reason,
    ReasonCode,
    code_of,
)
from siddhi_tpu.fuzz.generator import CaseGenerator
from siddhi_tpu.fuzz.runner import (
    BASELINE,
    StrategyCombo,
    diff_outputs,
    enumerate_matrix,
    run_case,
)
from siddhi_tpu.fuzz.schema import CaseSpec
from siddhi_tpu.fuzz.shrink import shrink_case, write_fixture

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "fuzz")


# ------------------------------------------------------------- generator

def test_generator_wellformedness_100_queries():
    """100 generated queries across the corpus all compile — the typed
    grammar's by-construction validity claim."""
    gen = CaseGenerator(seed=11, events_per_case=10)
    total = 0
    i = 0
    while total < 100:
        case = gen.case(i)
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(case.app_text())
            assert rt.eligibility_census   # census registered at build
        finally:
            m.shutdown()
        total += len(case.queries)
        i += 1
    assert total >= 100


def test_generator_seed_reproducibility():
    a = CaseGenerator(seed=7).corpus(5)
    b = CaseGenerator(seed=7).corpus(5)
    assert [c.to_json() for c in a] == [c.to_json() for c in b]
    c = CaseGenerator(seed=8).corpus(5)
    assert [x.to_json() for x in a] != [x.to_json() for x in c]


def test_case_spec_json_roundtrip():
    case = CaseGenerator(seed=3).case(0)
    back = CaseSpec.from_json(case.to_json())
    assert back.app_text() == case.app_text()
    assert back.events == case.events
    assert [q.expect for q in back.queries] == \
        [q.expect for q in case.queries]


def test_generator_windows_are_deterministic():
    from siddhi_tpu.fuzz.determinism import is_deterministic

    for i in range(25):
        case = CaseGenerator(seed=5).case(i)
        for q in case.queries:
            if q.window:
                assert is_deterministic(q.window[0]), q.window
            if q.join:
                for w in (q.join.left_window, q.join.right_window):
                    assert w is None or is_deterministic(w[0]), w


# ---------------------------------------------------------------- differ

def _rows(*pairs):
    return {"Out": [(ts, tuple(vals)) for ts, vals in pairs]}


def test_differ_exact_match_is_clean():
    a = _rows((1, ["x", 2]), (2, ["y", 3]))
    assert diff_outputs(a, _rows((1, ["x", 2]), (2, ["y", 3]))) is None


def test_differ_catches_value_change():
    d = diff_outputs(_rows((1, ["x", 2])), _rows((1, ["x", 3])))
    assert d is not None and d.stream == "Out" and d.index == 0


def test_differ_is_order_sensitive():
    a = _rows((1, ["x", 2]), (2, ["y", 3]))
    b = _rows((2, ["y", 3]), (1, ["x", 2]))
    d = diff_outputs(a, b)
    assert d is not None and d.index == 0


def test_differ_catches_length_mismatch():
    a = _rows((1, ["x", 2]))
    b = _rows((1, ["x", 2]), (2, ["y", 3]))
    d = diff_outputs(a, b)
    assert d is not None and d.index == 1
    assert d.baseline_len == 1 and d.variant_len == 2


def test_differ_float_bits_not_approx():
    d = diff_outputs(_rows((1, [1.0])), _rows((1, [1.0 + 1e-12])))
    assert d is not None, "approximate equality would mask divergence"


# ---------------------------------------------------------------- matrix

def test_matrix_liveness_collapses_dead_axes():
    case = CaseGenerator(seed=0).case(0)   # join-free, route-ineligible
    assert not any(q.kind == "join" for q in case.queries)
    plan = enumerate_matrix(case)
    assert plan.combos[0] == BASELINE
    assert all(c.join_engine == "legacy" for c in plan.combos)
    assert any("join" in a for a in plan.collapsed_axes)
    # depth and pool axes always live
    assert any(c.depth == 4 for c in plan.combos)
    assert any(c.pool == 2 for c in plan.combos)


def test_matrix_cap_reports_dropped():
    case = CaseGenerator(seed=1).case(1)
    full = enumerate_matrix(case)
    capped = enumerate_matrix(case, max_combos=3)
    if len(full.combos) > 4:
        assert capped.dropped > 0
    assert len(capped.combos) <= 1 + max(
        3, len({v for c in full.combos for v in
                [("depth", c.depth)]}))  # baseline + cap (coverage may pad)


# --------------------------------------------------------- reason codes

def test_reason_is_str_compatible_and_coded():
    r = Reason(ReasonCode.STORE_SIDE, "shared-store side 'T'")
    assert "shared-store" in r             # substring asserts keep working
    assert isinstance(r, str)
    assert r.code is ReasonCode.STORE_SIDE
    assert code_of(r) is ReasonCode.STORE_SIDE
    assert code_of(None) is ReasonCode.ELIGIBLE
    assert code_of("bare legacy text") is ReasonCode.UNKNOWN
    r2 = pickle.loads(pickle.dumps(r))
    assert r2 == r and r2.code is ReasonCode.STORE_SIDE


def test_engine_reasons_carry_codes():
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("""
define stream L (ts long, sym string, lv long);
define stream R (sym string, rv long);
@info(name='j') from L#window.length(4) join R#window.length(4)
  on L.sym == R.sym
  select L.sym as sym, sum(R.rv) as total group by L.sym
  insert into Out;
""")
        q = rt.query_runtimes["j"]
        assert q.engine is not None
        assert code_of(q.engine_reason) is ReasonCode.ELIGIBLE
        assert code_of(q.pipeline_reason) is ReasonCode.GROUPED_SELECT
        assert "grouped selector" in q.pipeline_reason
    finally:
        m.shutdown()


# ----------------------------------------------------------- the census

def test_census_known_ineligible_timebatch_keyed():
    """The ISSUE's named shape: a keyed (partitioned) time-batch window
    must census route=WINDOW_NOT_GLOBAL_AWARE (and never UNKNOWN)."""
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime("""
define stream S (sym string, v long);
partition with (sym of S)
begin
  @info(name='ktb') from S#window.timeBatch(1 sec)
  select sym, sum(v) as total insert into Out;
end;
""")
        rows = rt.eligibility_census["ktb"]
        by_surface = {s: c for s, c, _d in rows}
        assert by_surface[SURFACE_ROUTE] is ReasonCode.WINDOW_NOT_GLOBAL_AWARE
        assert by_surface[SURFACE_FUSION] is ReasonCode.PARTITIONED
        assert all(c is not ReasonCode.UNKNOWN for c in by_surface.values())
        # counted on the telemetry registry for the /metrics family
        snap = rt.app_context.telemetry.snapshot()
        names = [n for n in snap.get("counters", {})
                 if n.startswith("eligibility.route.")]
        assert any("WINDOW_NOT_GLOBAL_AWARE.ktb" in n for n in names), names
    finally:
        m.shutdown()


def test_census_only_windows_build():
    """CENSUS_ONLY_WINDOWS render to SiddhiQL the engine can BUILD (the
    classify-never-diff contract) — hopping needs its two-arg form."""
    from siddhi_tpu.fuzz.determinism import (
        CENSUS_ONLY_WINDOWS, is_deterministic, window_clause)

    for kind in CENSUS_ONLY_WINDOWS:
        assert not is_deterministic(kind)
        clause = window_clause(kind, 1)
        m = SiddhiManager()
        try:
            rt = m.create_siddhi_app_runtime(
                f"define stream S (sym string, v long);\n"
                f"@info(name='q') from S{clause} "
                f"select sym, v insert into Out;\n")
            assert rt.eligibility_census["q"]
        finally:
            m.shutdown()


def test_census_renders_metrics_family():
    from siddhi_tpu.observability import export

    m = SiddhiManager()
    try:
        m.create_siddhi_app_runtime(
            "define stream S (sym string, v long);\n"
            "@info(name='q') from S select sym, v insert into Out;\n")
        text = export.prometheus_text(m)
        fam = "siddhi_" + "eligibility_total"   # family literal lives in
        lines = [l for l in text.splitlines()   # export.py (graftlint R3)
                 if l.startswith(fam + "{")]
        assert any('surface="route"' in l and 'code="UNKEYED"' in l
                   and 'query="q"' in l for l in lines), lines
    finally:
        m.shutdown()


# ---------------------------------------- planted divergence + shrinking

def test_planted_divergence_caught_and_shrunk(tmp_path):
    """Satellite self-test: the runner's planted skew (duplicate last
    row of every depth>1 variant) is caught by the differ and the
    shrinker converges to a <= 3-clause fixture — proving the whole
    find->shrink->fixture loop without a real engine bug."""
    gen = CaseGenerator(seed=3, events_per_case=24)
    case = gen.case(0)
    res = run_case(case, max_combos=3, plant=True,
                   stop_on_divergence=True)
    assert res.divergences, "planted skew not caught by the differ"
    combo, diff = res.divergences[0]
    assert combo.depth > 1                      # the skewed strategy
    shrunk = shrink_case(case, combo, diff, plant=True, max_runs=36)
    assert shrunk.case.clause_count() <= 3, shrunk.steps
    assert shrunk.diff.kind == "rows"
    path = write_fixture(shrunk.case, shrunk.combo, shrunk.diff,
                         str(tmp_path))
    data = json.loads(open(path).read())
    assert data["format"] == "siddhi-tpu-fuzz-divergence-v1"
    replay = CaseSpec.from_dict(data["case"])
    assert replay.app_text() == data["app"]


def test_unplanted_small_matrix_is_clean():
    """Sanity inverse of the planted test: the same case with no skew
    runs the same mini-matrix with zero divergences and a clean census."""
    case = CaseGenerator(seed=3, events_per_case=24).case(0)
    res = run_case(case, max_combos=3, plant=False)
    assert not res.divergences, [
        (c.label(), d.summary()) for c, d in res.divergences]
    assert not res.census_findings, res.census_findings


# ------------------------------------------------------ fixture corpus

def test_committed_fixtures_are_selfconsistent():
    """Every committed divergence fixture (the known-bad set) must load,
    re-render to its stored app text, and carry a genuinely diverging
    first-row record — the promotion contract in fixtures/fuzz/README."""
    paths = sorted(glob.glob(os.path.join(FIXTURE_DIR, "divergence_*.json")))
    if not paths:
        pytest.skip("no committed divergence fixtures")
    for p in paths:
        data = json.loads(open(p).read())
        assert data["format"] == "siddhi-tpu-fuzz-divergence-v1"
        case = CaseSpec.from_dict(data["case"])
        assert case.app_text() == data["app"], p
        assert data["clause_count"] == case.clause_count(), p
        d = data["diff"]
        if d["kind"] == "rows":
            assert d["baseline_row"] != d["variant_row"], p
