"""Cluster fabric: multi-process tier with ordered re-merge (ISSUE 17).

One real 2-worker fabric carries three apps at once — a SPLIT
partitioned window app, a PINNED filter app fed over the ingest
SOCKET (wire frames), and a SPLIT table app for scatter-gather — and
must survive a mid-feed worker kill with an egress stream that exactly
matches uninterrupted single-process runs. The ordered-egress merger
itself is pure Python, so its order/dedup/forget discipline is unit-
tested without processes.
"""

import socket

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.cluster import ClusterRuntime, OrderedEgress
from siddhi_tpu.cluster import protocol as P
from siddhi_tpu.cluster.protocol import py_value
from siddhi_tpu.core.stream.input.wire import (
    VERSION, WireEncoder, decode_control, encode_control, encode_hello)

APP_SPLIT = """
@app:name('cSplit')
@app:playback
define stream S (k string, tag string, v double, n long);
partition with (k of S)
begin
  @info(name='q')
  from S#window.length(8)
  select k, sum(n) as sn, count() as c, max(v) as mv
  insert into Out;
end;
"""

APP_PINNED = """
@app:name('cPinned')
@app:playback
define stream Ping (k string, v double);
@info(name='q')
from Ping[v > 30.0]
select k, v
insert into Out;
"""

APP_TABLE = """
@app:name('cTable')
define stream T (k string, n long);
define table Tab (k string, n long);
@info(name='q')
from T[n > 400]
select k, n
insert into Tab;
"""

N_BATCHES, B = 6, 48
_rng = np.random.default_rng(23)
BATCHES = []
_ts = 5_000
for _b in range(N_BATCHES):
    BATCHES.append((
        np.array([f"K{i}" for i in _rng.integers(0, 9 + _b, B)],
                 dtype=object),
        np.array([None if i % 6 == 2 else f"t{i % 4}" for i in range(B)],
                 dtype=object),
        np.round(_rng.random(B) * 100.0, 6),
        _rng.integers(0, 1_000, B).astype(np.int64),
        np.arange(_ts + _b * B, _ts + (_b + 1) * B, dtype=np.int64)))


class _Rows(StreamCallback):
    def __init__(self):
        self.rows = []

    def receive(self, events):
        self.rows.extend(
            (int(e.timestamp), tuple(py_value(v) for v in e.data))
            for e in events)


def _baseline(app, feeds, query=None):
    """feeds: [(stream, data_dict, timestamps)] against ONE runtime."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = _Rows()
    if "Out" in rt.junctions:
        rt.add_callback("Out", c)
    rt.start()
    for stream, data, tss in feeds:
        rt.get_input_handler(stream).send_columns(dict(data),
                                                  timestamps=tss)
    qrows = None
    if query is not None:
        qrows = sorted([py_value(v) for v in e.data]
                       for e in rt.query(query))
    m.shutdown()
    return c.rows, qrows


def _ingest_link(port):
    s = P.MessageSocket(socket.create_connection(("127.0.0.1", port),
                                                 timeout=10))
    s.send(P.MSG_HELLO, encode_hello())
    mtype, body = s.recv()
    assert mtype == P.MSG_HELLO
    return s


def test_cluster_fabric_end_to_end_with_mid_feed_kill():
    split_feeds = [("S", {"k": k, "tag": t, "v": v, "n": n}, ts)
                   for k, t, v, n, ts in BATCHES]
    pinned_feeds = [("Ping", {"k": k, "v": v}, ts)
                    for k, t, v, n, ts in BATCHES]
    table_feeds = [("T", {"k": k, "n": n}, ts)
                   for k, t, v, n, ts in BATCHES]
    base_split, _ = _baseline(APP_SPLIT, split_feeds)
    base_pinned, _ = _baseline(APP_PINNED, pinned_feeds)
    _, base_q = _baseline(APP_TABLE, table_feeds,
                          query="from Tab select k, n")

    cluster = ClusterRuntime(n_workers=2, heartbeat_s=0.2)
    try:
        cluster.wait_ready(60)
        cluster.deploy(APP_SPLIT, partition_keys={"S": "k"},
                       sinks=["Out"])
        cluster.deploy(APP_PINNED, sinks=["Out"])
        cluster.deploy(APP_TABLE, partition_keys={"T": "k"}, sinks=[])

        # the PINNED app is fed over the ingest SOCKET: client frames,
        # dictionary delta growing every batch, per-frame seq acks
        enc = WireEncoder()
        ing = _ingest_link(cluster.ingest_port)
        last_seq = 0
        for i, (k, t, v, n, ts) in enumerate(BATCHES):
            cluster.send_columns("cSplit", "S",
                                 {"k": k, "tag": t, "v": v, "n": n},
                                 timestamps=ts)
            frame = enc.encode({"k": k, "v": v}, timestamps=ts)
            ing.send(P.MSG_INGEST,
                     P.pack_data(0, 0, "cPinned", "Ping", frame))
            mtype, body = ing.recv()
            assert mtype == P.MSG_INGEST_ACK
            seq = decode_control(body).b
            assert seq > last_seq     # router stamped a fresh global seq
            last_seq = seq
            cluster.send_columns("cTable", "T", {"k": k, "n": n},
                                 timestamps=ts)
            if i == 1:
                cluster.checkpoint()
            if i == 3:
                # kill 1 of 2 workers mid-feed; links were ready (the
                # deploy handshake) so the death is a detected
                # transition, and the supervisor must respawn + the
                # router must restore-and-replay its WAL suffix
                cluster.supervisor.kill(1)
        ing.close()

        assert cluster.quiesce(120), "egress never quiesced"
        got_split = [(ts_, tuple(vals)) for ts_, vals in
                     cluster.egress.stream_rows("cSplit", "Out")]
        got_pinned = [(ts_, tuple(vals)) for ts_, vals in
                      cluster.egress.stream_rows("cPinned", "Out")]
        got_q = sorted(vals for ts_, vals in
                       (tuple(r) for r in
                        cluster.query("cTable", "from Tab select k, n")))

        assert got_split == base_split
        assert got_pinned == base_pinned
        assert got_q == base_q

        # REST tier riding the fabric: POST /query routes
        # cluster-deployed apps through the scatter-gather, GET /cluster
        # reports fabric status
        import json as _json
        from urllib.request import Request, urlopen

        from siddhi_tpu import SiddhiManager
        from siddhi_tpu.service.rest import SiddhiRestService

        m = SiddhiManager()
        svc = SiddhiRestService(m, cluster=cluster).start()
        try:
            req = Request(
                f"http://127.0.0.1:{svc.port}/query",
                data=_json.dumps({"app": "cTable",
                                  "query": "from Tab select k, n"}
                                 ).encode(),
                headers={"Content-Type": "application/json"})
            rest_rows = _json.load(urlopen(req, timeout=30))["rows"]
            assert sorted(rest_rows) == base_q
            st = _json.load(urlopen(
                f"http://127.0.0.1:{svc.port}/cluster", timeout=30))
            assert st["live"] == 2
            assert st["apps"]["cTable"]["mode"] == "split"
            assert st["apps"]["cPinned"]["mode"] == "pinned"
        finally:
            svc.stop()
            m.shutdown()

        assert sum(cluster.supervisor.respawns) >= 1
        # replay over-delivery was absorbed, never merged twice
        assert cluster.egress.duplicate_emits >= 1

        from siddhi_tpu.observability.telemetry import global_registry
        counters = global_registry().counters
        assert counters.get("cluster.ingest_batches", 0) >= 3 * N_BATCHES
        assert counters.get("cluster.checkpoints", 0) >= 1
        assert counters.get("cluster.worker.respawns.1", 0) >= 1
    finally:
        cluster.shutdown()


def test_ingest_hello_version_mismatch_names_both_versions():
    cluster = ClusterRuntime(n_workers=1, spawn=False)
    try:
        s = P.MessageSocket(socket.create_connection(
            ("127.0.0.1", cluster.ingest_port), timeout=10))
        s.send(P.MSG_HELLO, encode_hello(version=VERSION + 1))
        mtype, body = s.recv()
        assert mtype == P.MSG_ERROR
        msg = P.jload(body)["error"]
        assert f"version {VERSION + 1}" in msg
        assert f"version {VERSION}" in msg
        s.close()
    finally:
        cluster.shutdown()


# ------------------------------------------------- ordered egress (pure)


def test_egress_releases_in_global_order_despite_completion_order():
    e = OrderedEgress()
    tags = [(1, 0), (1, 1), (2, 0)]
    for t in tags:
        e.expect(t)
    e.emit((2, 0), "a", "Out", [(30, [3])])
    e.complete((2, 0))
    assert e.stream_rows("a", "Out") == []      # head still outstanding
    e.emit((1, 1), "a", "Out", [(20, [2])])
    e.complete((1, 1))
    e.emit((1, 0), "a", "Out", [(10, [1])])
    e.complete((1, 0))                          # releases all three
    assert e.stream_rows("a", "Out") == [(10, (1,)), (20, (2,)),
                                         (30, (3,))]
    assert e.outstanding() == 0
    assert e.wait_quiesced(0.1)


def test_egress_drops_replayed_duplicates_and_drop_pending():
    e = OrderedEgress()
    e.expect((1, 0))
    e.expect((1, 1))
    e.emit((1, 0), "a", "Out", [(10, [1])])
    e.complete((1, 0))
    # replayed emission + ack of the merged tag: dropped, not doubled
    assert e.emit((1, 0), "a", "Out", [(10, [1])]) is False
    assert e.complete((1, 0)) is False
    assert e.duplicate_emits == 1
    # incomplete tag emitted pre-death: replay drops the stale copy
    e.emit((1, 1), "a", "Out", [(20, [2])])
    e.drop_pending((1, 1))
    e.emit((1, 1), "a", "Out", [(20, [2])])
    e.complete((1, 1))
    assert e.stream_rows("a", "Out") == [(10, (1,)), (20, (2,))]


def test_egress_forget_releases_a_lost_head():
    e = OrderedEgress()
    e.expect((1, 0))
    e.expect((1, 1))
    e.emit((1, 1), "a", "Out", [(20, [2])])
    e.complete((1, 1))
    e.forget((1, 0))        # WAL-overflow gap: head can never complete
    assert e.stream_rows("a", "Out") == [(20, (2,))]
    assert e.outstanding() == 0


def test_egress_rejects_out_of_order_expectations():
    e = OrderedEgress()
    e.expect((2, 0))
    with pytest.raises(ValueError):
        e.expect((1, 0))
