"""Join tests — expectations mirror the reference ``query/join/*`` corpus
(JoinTestCase: window joins, outer joins, unidirectional)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


STREAMS = """
    define stream StockStream (symbol string, price float);
    define stream TwitterStream (user string, company string);
"""


def test_length_window_join():
    # JoinTestCase style: both sides keep windows; each event probes the other
    m, rt, c = build(STREAMS + """
        from StockStream#window.length(10) join TwitterStream#window.length(10)
        on StockStream.symbol == TwitterStream.company
        select StockStream.symbol as symbol, TwitterStream.user as user, price
        insert into OutStream;
    """)
    hs = rt.get_input_handler("StockStream")
    ht = rt.get_input_handler("TwitterStream")
    hs.send(["IBM", 100.0])
    ht.send(["alice", "IBM"])       # joins with buffered IBM
    ht.send(["bob", "GOOG"])        # no match
    hs.send(["GOOG", 200.0])        # joins with buffered bob tweet
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("IBM", "alice", 100.0), ("GOOG", "bob", 200.0)]


def test_join_multiple_matches():
    m, rt, c = build(STREAMS + """
        from StockStream#window.length(10) join TwitterStream#window.length(10)
        on StockStream.symbol == TwitterStream.company
        select TwitterStream.user as user, price
        insert into OutStream;
    """)
    hs = rt.get_input_handler("StockStream")
    ht = rt.get_input_handler("TwitterStream")
    ht.send(["alice", "IBM"])
    ht.send(["bob", "IBM"])
    hs.send(["IBM", 100.0])          # matches both tweets
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [("alice", 100.0), ("bob", 100.0)]


def test_left_outer_join():
    m, rt, c = build(STREAMS + """
        from StockStream#window.length(10) left outer join TwitterStream#window.length(10)
        on StockStream.symbol == TwitterStream.company
        select symbol, user, price
        insert into OutStream;
    """)
    hs = rt.get_input_handler("StockStream")
    ht = rt.get_input_handler("TwitterStream")
    hs.send(["IBM", 100.0])          # no tweets yet -> (IBM, null)
    ht.send(["alice", "IBM"])        # right event joins buffered stock
    hs.send(["IBM", 110.0])          # now matches alice
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("IBM", None, 100.0), ("IBM", "alice", 100.0), ("IBM", "alice", 110.0)]


def test_unidirectional_join():
    # only the left side triggers output; right events just fill the window
    m, rt, c = build(STREAMS + """
        from StockStream#window.length(10) unidirectional join TwitterStream#window.length(10)
        on StockStream.symbol == TwitterStream.company
        select symbol, user
        insert into OutStream;
    """)
    hs = rt.get_input_handler("StockStream")
    ht = rt.get_input_handler("TwitterStream")
    ht.send(["alice", "IBM"])        # right: no output
    hs.send(["IBM", 100.0])          # left triggers
    ht.send(["bob", "IBM"])          # right: silent again
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("IBM", "alice")]


def test_self_join_with_refs():
    m, rt, c = build("""
        define stream S (k string, v int);
        from S#window.length(5) as a join S#window.length(5) as b
        on a.v < b.v
        select a.v as v1, b.v as v2
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["x", 1])
    h.send(["y", 5])    # a=5 probes b window {1,5}: 5<nothing... a side: v=5 vs {1}: no (5<1 F); b side: buffered a {1}: 1<5 -> (1,5)
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [(1, 5)]


def test_time_window_join_playback():
    m, rt, c = build("@app:playback " + STREAMS + """
        from StockStream#window.time(10 sec) join TwitterStream#window.length(100)
        on StockStream.symbol == TwitterStream.company
        select symbol, user, price
        insert into OutStream;
    """)
    hs = rt.get_input_handler("StockStream")
    ht = rt.get_input_handler("TwitterStream")
    hs.send(1000, ["IBM", 100.0])
    ht.send(2000, ["alice", "IBM"])          # within 10s: match
    ht.send(20000, ["bob", "IBM"])           # stock expired from time window
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("IBM", "alice", 100.0)]


def test_table_only_trigger_side_rejected():
    # a join whose only triggering side is a table can never emit — reject
    # at compile time instead of building a dead query
    import pytest

    from siddhi_tpu.ops.expressions import CompileError

    m = SiddhiManager()
    with pytest.raises(CompileError, match="trigger"):
        m.create_siddhi_app_runtime("""
            define stream S (symbol string, price float);
            define table T (symbol string, ref float);
            from T unidirectional join S#window.length(10) on T.symbol == S.symbol
            select T.symbol as symbol, S.price as price
            insert into Out;
        """)
    m.shutdown()
