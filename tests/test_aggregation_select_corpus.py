"""Reference select-optimisation aggregation corpus — scenarios ported
verbatim from ``aggregation/SelectOptimisationAggregationTestCase.java``:
re-aggregating bucket reads in the join/on-demand SELECT (``sum(count)``,
``sum(totalPrice)``) with same/different/absent group-bys."""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback

STOCK = ("define stream stockStream (symbol string, price float, "
         "lastClosingPrice float, volume long, quantity int, "
         "timestamp long);")
STOCK_NAMED = STOCK.replace(
    "symbol string,", "symbol string, name string,")
INPUT = ("define stream inputStream (symbol string, value int, "
         "startTime string, endTime string, perValue string); ")

FEED = [
    ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
    ["WSO2", 70.0, None, 40, 10, 1496289950000],
    ["WSO2", 60.0, 44.0, 200, 56, 1496289952000],
    ["WSO2", 100.0, None, 200, 16, 1496289952000],
    ["IBM", 100.0, None, 200, 26, 1496289954000],
    ["IBM", 100.0, None, 200, 96, 1496289954000],
    ["IBM", 900.0, None, 200, 60, 1496289956000],
    ["IBM", 500.0, None, 200, 7, 1496289956000],
    ["IBM", 400.0, None, 200, 9, 1496290016000],
    ["IBM", 600.0, None, 200, 6, 1496290076000],
    ["CISCO", 700.0, None, 200, 20, 1496293676000],
]
# the same feed with a per-symbol name column (testcase5/6/7)
FEED_NAMED = [[r[0], nm] + r[1:] for r, nm in zip(
    FEED, ["WSO2", "WSO2", "WSO2", "WSO2", "IBM", "IBM", "IBM", "IBM",
           "IBM", "IBM", "CISCO"])]


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)


def _run(app, feed, trigger=None, stream="stockStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback("query1", q)
    rt.start()
    h = rt.get_input_handler(stream)
    for r in feed:
        h.send(list(r))
    if trigger is not None:
        rt.get_input_handler("inputStream").send(list(trigger))
    return m, rt, q


TRIGGER = ["IBM", 1, "2017-06-01 09:35:51 +05:30",
           "2017-06-01 09:35:52 +05:30", "seconds"]


def test_count_per_second_buckets():
    """aggregationFunctionTestcase2 (:155-247): count() without group by,
    read per seconds (external timestamps)."""
    m, rt, q = _run(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select count() as count aggregate by timestamp every sec, min ;"
        + INPUT +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596434876000L per "seconds" '
        "select AGG_TIMESTAMP, count order by AGG_TIMESTAMP "
        "insert all events into outputStream; ",
        FEED, TRIGGER)
    assert [tuple(e.data) for e in q.events] == [
        (1496289950000, 2), (1496289952000, 2), (1496289954000, 2),
        (1496289956000, 2), (1496290016000, 1), (1496290076000, 1),
        (1496293676000, 1)]
    m.shutdown()


def test_grouped_count_read_back():
    """aggregationFunctionTestcase3 (:248-342): per-symbol counts read
    back bucket by bucket."""
    m, rt, q = _run(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, count() as count group by symbol "
        "aggregate by timestamp every sec, min ;"
        + INPUT +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596434876000L per "seconds" '
        "select AGG_TIMESTAMP, s.symbol, s.count "
        "insert all events into outputStream; ",
        FEED, TRIGGER)
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        (1496289950000, "WSO2", 2), (1496289952000, "WSO2", 2),
        (1496289954000, "IBM", 2), (1496289956000, "IBM", 2),
        (1496290016000, "IBM", 1), (1496290076000, "IBM", 1),
        (1496293676000, "CISCO", 1)])
    m.shutdown()


def test_sum_count_same_group_by():
    """aggregationFunctionTestcase4 (:344-433): the join select
    re-aggregates bucket counts per symbol (`sum(count)`)."""
    m, rt, q = _run(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, count() as count group by symbol "
        "aggregate by timestamp every sec, min ;"
        + INPUT +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596434876000L per "seconds" '
        "select s.symbol, sum(count) as count group by s.symbol "
        "insert all events into outputStream; ",
        FEED, TRIGGER)
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        ("WSO2", 4), ("IBM", 6), ("CISCO", 1)])
    m.shutdown()


def test_sum_count_coarser_group_by_keeps_last_name():
    """aggregationFunctionTestcase5 (:435-525): aggregation groups by
    (symbol, name) but the join select groups by symbol only — name rides
    as the group's last value."""
    m, rt, q = _run(
        STOCK_NAMED +
        " define aggregation stockAggregation from stockStream "
        "select symbol, name, count() as count group by symbol, name "
        "aggregate by timestamp every sec, min ;"
        + INPUT +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596434876000L per "seconds" '
        "select s.symbol, s.name, sum(count) as count group by s.symbol "
        "insert all events into outputStream; ",
        FEED_NAMED, TRIGGER)
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        ("WSO2", "WSO2", 4), ("IBM", "IBM", 6), ("CISCO", "CISCO", 1)])
    m.shutdown()


def test_sum_count_project_one_of_two_groups():
    """aggregationFunctionTestcase6 (:527-617): same but only symbol
    projected."""
    m, rt, q = _run(
        STOCK_NAMED +
        " define aggregation stockAggregation from stockStream "
        "select symbol, name, count() as count group by symbol, name "
        "aggregate by timestamp every sec, min ;"
        + INPUT +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596434876000L per "seconds" '
        "select s.symbol, sum(count) as count group by s.symbol "
        "insert all events into outputStream; ",
        FEED_NAMED, TRIGGER)
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        ("WSO2", 4), ("IBM", 6), ("CISCO", 1)])
    m.shutdown()


def test_sum_count_distinct_names():
    """aggregationFunctionTestcase7 (:619-709): name values differ from
    symbols; the coarser group keeps each symbol's last name."""
    named = [[r[0], nm] + r[1:] for r, nm in zip(
        FEED, ["WSO21", "WSO22", "WSO21", "WSO22", "IBM1", "IBM1", "IBM1",
               "IBM1", "IBM1", "IBM1", "CISCO1"])]
    m, rt, q = _run(
        STOCK_NAMED +
        " define aggregation stockAggregation from stockStream "
        "select symbol, name, count() as count group by symbol, name "
        "aggregate by timestamp every sec, min ;"
        + INPUT +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596434876000L per "seconds" '
        "select s.symbol, s.name, sum(count) as count group by s.symbol "
        "insert all events into outputStream; ",
        named, TRIGGER)
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        ("WSO2", "WSO22", 4), ("IBM", "IBM1", 6), ("CISCO", "CISCO1", 1)])
    m.shutdown()


def test_on_demand_count_read():
    """aggregationFunctionTestcase8 (:711-787): on-demand count per
    bucket, ordered."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select count() as count aggregate by timestamp every sec, min ;")
    rt.start()
    h = rt.get_input_handler("stockStream")
    for r in FEED:
        h.send(list(r))
    events = rt.query(
        "from stockAggregation within 1496200000000L, 1596434876000L "
        'per "seconds" select AGG_TIMESTAMP, count order by AGG_TIMESTAMP;')
    assert [tuple(e.data) for e in events] == [
        (1496289950000, 2), (1496289952000, 2), (1496289954000, 2),
        (1496289956000, 2), (1496290016000, 1), (1496290076000, 1),
        (1496293676000, 1)]
    m.shutdown()


def test_on_demand_sum_count_group_by():
    """aggregationFunctionTestcase9 (:789-862): on-demand re-aggregation
    `sum(count) group by symbol`."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, count() as count group by symbol "
        "aggregate by timestamp every sec, min ;")
    rt.start()
    h = rt.get_input_handler("stockStream")
    for r in FEED:
        h.send(list(r))
    events = rt.query(
        "from stockAggregation within 1496200000000L, 1596434876000L "
        'per "seconds" select symbol, sum(count) as count '
        "group by symbol;")
    assert sorted(tuple(e.data) for e in events) == sorted([
        ("WSO2", 4), ("IBM", 6), ("CISCO", 1)])
    m.shutdown()


def test_join_on_condition_sum_total_price():
    """aggregationFunctionTestcase12 (:1125-1200): on-condition narrows to
    IBM, wildcard minute within, `sum(totalPrice)` re-aggregation."""
    m, rt, q = _run(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue, count() as count "
        "group by symbol aggregate by timestamp every sec...year ;"
        + INPUT +
        "@info(name = 'query1') "
        "from inputStream join stockAggregation "
        "on inputStream.symbol == stockAggregation.symbol "
        'within "2017-06-01 04:05:**" per "seconds" '
        "select stockAggregation.symbol, sum(totalPrice) as totalPrice "
        "group by stockAggregation.symbol order by AGG_TIMESTAMP "
        "insert all events into outputStream; ",
        [
            ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
            ["WSO2", 70.0, None, 40, 10, 1496289950000],
            ["WSO2", 60.0, 44.0, 200, 56, 1496289949000],
            ["WSO2", 100.0, None, 200, 16, 1496289949000],
            ["IBM", 100.0, None, 200, 26, 1496289948000],
            ["IBM", 100.0, None, 200, 96, 1496289948000],
            ["IBM", 900.0, None, 200, 60, 1496289947000],
            ["IBM", 500.0, None, 200, 7, 1496289947000],
            ["IBM", 400.0, None, 200, 9, 1496289946000],
        ], TRIGGER)
    assert len(q.events) == 1
    assert tuple(q.events[0].data) == ("IBM", 2000.0)
    m.shutdown()


def test_on_demand_sum_group_by_agg_timestamp():
    """last test (:1205-1267): on-demand `sum(totalPrice) group by
    AGG_TIMESTAMP` folds the per-symbol buckets per second."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " @purge(enable='false') "
        "define aggregation stockAggregation from stockStream "
        "select symbol, sum(price) as totalPrice "
        "group by symbol aggregate by timestamp every sec...hour ;")
    rt.start()
    h = rt.get_input_handler("stockStream")
    for r in [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["IBM", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289952000],
        ["IBM", 100.0, None, 200, 16, 1496289952500],
        ["IBM", 100.0, None, 200, 26, 1496289954000],
        ["WSO2", 100.0, None, 200, 96, 1496289954500],
    ]:
        h.send(list(r))
    events = rt.query(
        'from stockAggregation within "2017-06-** **:**:**" per "seconds" '
        "select AGG_TIMESTAMP, sum(totalPrice) as totalPrice "
        "group by AGG_TIMESTAMP;")
    assert sorted(tuple(e.data) for e in events) == sorted([
        (1496289950000, 120.0),
        (1496289952000, 160.0),
        (1496289954000, 200.0)])
    m.shutdown()
