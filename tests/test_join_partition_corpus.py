"""Reference join-partition corpus — scenarios ported verbatim from
``query/partition/JoinPartitionTestCase.java`` (feeds + expected counts;
sleeps become playback clock jumps). Covers keyed/keyed joins, inner
'#stream' sides, GLOBAL (non-partitioned) sides visible to every
partition instance, range partitions and unidirectional triggers."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="outputStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


TICK = """
    define stream Tick (x int);
    from Tick select x insert into TickOut;
"""

CSE_TW = """@app:playback
    define stream cseEventStream (symbol string, user string, volume int);
    define stream twitterStream (user string, tweet string, company string);
""" + TICK


def test_join_partition_1_both_sides_keyed():
    """testJoinPartition1 (:46-81): both sides partitioned by user; 2
    tweets x 1 cse row -> 2 current + 2 expired = 4."""
    m, rt, c = build(CSE_TW + """
        partition with (user of cseEventStream, user of twitterStream) begin
          @info(name = 'query1')
          from cseEventStream#window.time(1 sec)
            join twitterStream#window.time(1 sec)
            on cseEventStream.symbol == twitterStream.company
          select cseEventStream.symbol as symbol, twitterStream.tweet,
                 cseEventStream.volume
          insert all events into outputStream;
        end;
    """)
    rt.get_input_handler("cseEventStream").send(1000, ["WSO2", "User1", 100])
    tw = rt.get_input_handler("twitterStream")
    tw.send(1100, ["User1", "Hello World", "WSO2"])
    tw.send(1150, ["User1", "Hellno World", "WSO2"])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert len(c.events) == 4


def test_join_partition_2_two_users():
    """testJoinPartition2 (:87-130): two separate user instances, 2
    tweets each -> 8 events total."""
    m, rt, c = build(CSE_TW + """
        partition with (user of cseEventStream, user of twitterStream) begin
          @info(name = 'query1')
          from cseEventStream#window.time(1 sec)
            join twitterStream#window.time(1 sec)
            on cseEventStream.symbol == twitterStream.company
          select cseEventStream.symbol as symbol,
                 cseEventStream.user as user, twitterStream.tweet,
                 cseEventStream.volume
          insert all events into outputStream;
        end;
    """)
    cse = rt.get_input_handler("cseEventStream")
    tw = rt.get_input_handler("twitterStream")
    cse.send(1000, ["WSO2", "User1", 100])
    tw.send(1100, ["User1", "Hello World", "WSO2"])
    tw.send(1150, ["User1", "World", "WSO2"])
    cse.send(1200, ["IBM", "User2", 100])
    tw.send(1250, ["User2", "Hello World", "IBM"])
    tw.send(1300, ["User2", "World", "IBM"])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert len(c.events) == 8
    users = {tuple(e.data[:2]) for e in c.events}
    assert users == {("WSO2", "User1"), ("IBM", "User2")}


_INNER_CHAIN = CSE_TW + """
    partition with (user of cseEventStream, user of twitterStream) begin
      @info(name = 'query1')
      from cseEventStream#window.time(1 sec)
        join twitterStream#window.time(1 sec)
        on cseEventStream.symbol == twitterStream.company
      select cseEventStream.symbol as symbol, cseEventStream.user as user,
             twitterStream.tweet, cseEventStream.volume
      insert all events into #outputStream;
      @info(name = 'query2')
      from #outputStream select symbol, user
      insert all events into {target};
    end;
"""


def test_join_partition_3_into_inner_stream():
    """testJoinPartition3 (:137-184): the joined rows flow through an
    inner '#outputStream' into a second partition query -> 8 events."""
    m, rt, c = build(_INNER_CHAIN.format(target="outStream"), out="outStream")
    cse = rt.get_input_handler("cseEventStream")
    tw = rt.get_input_handler("twitterStream")
    cse.send(1000, ["WSO2", "User1", 100])
    tw.send(1100, ["User1", "Hello World", "WSO2"])
    tw.send(1150, ["User1", "World", "WSO2"])
    cse.send(1200, ["IBM", "User2", 100])
    tw.send(1250, ["User2", "Hello World", "IBM"])
    tw.send(1300, ["User2", "World", "IBM"])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert len(c.events) == 8
    assert {tuple(e.data) for e in c.events} == {
        ("WSO2", "User1"), ("IBM", "User2")}


def test_join_partition_4_inner_chain_plus_direct_sends():
    """testJoinPartition4 (:191-237): same inner chain targeting the
    GLOBAL outputStream, which is ALSO fed directly -> 8 + 2 = 10."""
    m, rt, c = build(_INNER_CHAIN.format(target="outputStream"))
    cse = rt.get_input_handler("cseEventStream")
    tw = rt.get_input_handler("twitterStream")
    cse.send(1000, ["WSO2", "User1", 100])
    tw.send(1100, ["User1", "Hello World", "WSO2"])
    tw.send(1150, ["User1", "World", "WSO2"])
    cse.send(1200, ["IBM", "User1", 100])
    tw.send(1250, ["User1", "Hello World", "IBM"])
    tw.send(1300, ["User1", "World", "IBM"])
    out_h = rt.get_input_handler("outputStream")
    out_h.send(1400, ["GOOG", "new_user_1"])
    out_h.send(1450, ["GOOG", "new_user_2"])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert len(c.events) == 10


def test_join_partition_5_inner_join_global_side():
    """testJoinPartition5 (:243-288): a partitioned inner '#stream' side
    joined with a GLOBAL twitterStream — global events probe EVERY
    instance's window (User1's IBM tweet matches User2's row) -> 4."""
    m, rt, c = build("""@app:playback
        define stream cseEventStream (symbol string, user string, volume int);
        define stream twitterStream (user string, tweet string, company string);
    """ + TICK + """
        partition with (user of cseEventStream) begin
          @info(name = 'query2')
          from cseEventStream
          select symbol, user, sum(volume) as volume
          insert all events into #cseInnerStream;
          @info(name = 'query1')
          from #cseInnerStream#window.time(1 sec)
            join twitterStream#window.time(1 sec)
            on twitterStream.company == #cseInnerStream.symbol
          select #cseInnerStream.user as user, twitterStream.tweet as tweet,
                 twitterStream.company, #cseInnerStream.volume as volume
          insert all events into outputStream;
        end;
    """)
    cse = rt.get_input_handler("cseEventStream")
    tw = rt.get_input_handler("twitterStream")
    cse.send(1000, ["WSO2", "User1", 200])
    cse.send(1100, ["IBM", "User2", 500])
    tw.send(1200, ["User1", "Hello World", "WSO2"])
    tw.send(1250, ["User1", "Hello World", "IBM"])
    tw.send(1300, ["User3", "Hello World", "GOOG"])
    rt.get_input_handler("Tick").send(3500, [0])
    m.shutdown()
    assert len(c.events) == 4
    pairs = {(e.data[0], e.data[2]) for e in c.events}
    assert pairs == {("User1", "WSO2"), ("User2", "IBM")}


def test_join_partition_6_inner_shadowing_stream_name():
    """testJoinPartition6 (:295-341): the inner stream shares the outer
    stream's NAME ('#cseEventStream' vs 'cseEventStream') — ids stay
    distinct -> 4 events."""
    m, rt, c = build("""@app:playback
        define stream cseEventStream (symbol string, user string, volume int);
        define stream twitterStream (user string, tweet string, company string);
    """ + TICK + """
        partition with (user of cseEventStream) begin
          @info(name = 'query2')
          from cseEventStream
          select symbol, user, sum(volume) as volume
          insert all events into #cseEventStream;
          @info(name = 'query1')
          from #cseEventStream#window.time(1 sec)
            join twitterStream#window.time(1 sec)
            on twitterStream.company == #cseEventStream.symbol
          select #cseEventStream.user as user, twitterStream.tweet as tweet,
                 twitterStream.company, #cseEventStream.volume as volume
          insert all events into outputStream;
        end;
    """)
    cse = rt.get_input_handler("cseEventStream")
    tw = rt.get_input_handler("twitterStream")
    cse.send(1000, ["WSO2", "User1", 200])
    cse.send(1100, ["IBM", "User2", 500])
    tw.send(1200, ["User1", "Hello World", "IBM"])
    tw.send(1250, ["User1", "Hello World", "WSO2"])
    rt.get_input_handler("Tick").send(3500, [0])
    m.shutdown()
    assert len(c.events) == 4


def test_join_partition_7_range_partition():
    """testJoinPartition7 (:342-390): RANGE partition (volume>=100 as
    'large', volume<100 as 'small') on both streams, on user==user ->
    2 matches per range instance -> 8 events."""
    m, rt, c = build("""@app:playback
        define stream cseEventStream (symbol string, user string, volume int);
        define stream twitterStream (user string, tweet string,
                                     company string, volume int);
    """ + TICK + """
        partition with (volume >= 100 as 'large' or volume < 100 as 'small'
                          of cseEventStream,
                        volume >= 100 as 'large' or volume < 100 as 'small'
                          of twitterStream) begin
          @info(name = 'query1')
          from cseEventStream#window.time(1 sec)
            join twitterStream#window.time(1 sec)
            on cseEventStream.user == twitterStream.user
          select cseEventStream.symbol as symbol,
                 cseEventStream.user as user, twitterStream.tweet,
                 cseEventStream.volume
          insert all events into outputStream;
        end;
    """)
    cse = rt.get_input_handler("cseEventStream")
    tw = rt.get_input_handler("twitterStream")
    cse.send(1000, ["WSO2", "User1", 200])
    tw.send(1100, ["User1", "Hello World", "WSO2", 200])
    tw.send(1150, ["User1", "World", "WSO2", 200])
    cse.send(1200, ["IBM", "User1", 10])
    tw.send(1250, ["User1", "Hello World", "WSO2", 10])
    tw.send(1300, ["User1", "World", "IBM", 10])
    rt.get_input_handler("Tick").send(3500, [0])
    m.shutdown()
    assert len(c.events) == 8
    assert {e.data[0] for e in c.events} == {"WSO2", "IBM"}


def test_join_partition_8_global_twitter_side():
    """testJoinPartition8 (:97-133 of second half): only cseEventStream
    is partitioned; the GLOBAL twitter side's tweets (any user) probe the
    keyed cse windows -> 3 current + 3 expired = 6."""
    m, rt, c = build(CSE_TW + """
        partition with (user of cseEventStream) begin
          @info(name = 'query1')
          from cseEventStream#window.time(1 sec)
            join twitterStream#window.time(1 sec)
            on cseEventStream.symbol == twitterStream.company
          select cseEventStream.symbol as symbol, twitterStream.tweet,
                 cseEventStream.volume
          insert all events into outputStream;
        end;
    """)
    rt.get_input_handler("cseEventStream").send(1000, ["WSO2", "User1", 100])
    tw = rt.get_input_handler("twitterStream")
    tw.send(1100, ["User1", "Hello World", "WSO2"])
    tw.send(1150, ["User2", "Hellno World", "WSO2"])
    tw.send(1200, ["User3", "Hellno World", "WSO2"])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert len(c.events) == 6


def test_join_partition_9_unidirectional_length_windows():
    """testJoinPartition9 (:139-180): unidirectional cse trigger,
    length(1) windows per user -> only cse events arriving AFTER their
    user's tweet match -> 2."""
    m, rt, c = build(CSE_TW + """
        partition with (user of cseEventStream, user of twitterStream) begin
          @info(name = 'query1')
          from cseEventStream#window.length(1) unidirectional
            join twitterStream#window.length(1)
            on cseEventStream.symbol == twitterStream.company
          select cseEventStream.user, cseEventStream.symbol as symbol,
                 twitterStream.tweet, cseEventStream.volume
          insert all events into outputStream;
        end;
    """)
    cse = rt.get_input_handler("cseEventStream")
    tw = rt.get_input_handler("twitterStream")
    tw.send(1000, ["User1", "Hello World", "WSO2"])
    cse.send(1100, ["WSO2", "User1", 100])
    cse.send(1200, ["WSO2", "User2", 100])
    tw.send(1250, ["User2", "Hello World", "WSO2"])
    tw.send(1300, ["User3", "Hello World", "WSO2"])
    cse.send(1350, ["WSO2", "User3", 100])
    m.shutdown()
    assert len(c.events) == 2
    assert {e.data[0] for e in c.events} == {"User1", "User3"}


def test_join_partition_10_chained_partitions_global_side():
    """testJoinPartition10 (:187-241): partition1's unidirectional join
    (no on-clause) feeds outputStream1; partition2 re-partitions it and
    cross-joins the GLOBAL twitter length(1) window — including the
    expired outputStream1 row displaced from its length(1) window -> 3."""
    m, rt, c = build("""@app:playback
        define stream cseEventStream (symbol string, user string, volume int);
        define stream twitterStream (user string, tweet string, company string);
    """ + TICK + """
        partition with (user of cseEventStream, user of twitterStream) begin
          @info(name = 'query1')
          from cseEventStream#window.length(1) unidirectional
            join twitterStream#window.length(1)
          select cseEventStream.symbol as symbol, twitterStream.tweet,
                 cseEventStream.volume, cseEventStream.user
          insert all events into outputStream1;
        end;
        partition with (user of outputStream1) begin
          @info(name = 'query2')
          from outputStream1#window.length(1)
            join twitterStream#window.length(1)
          select outputStream1.symbol as symbol, twitterStream.tweet,
                 outputStream1.volume
          insert all events into outputStream;
        end;
    """)
    cse = rt.get_input_handler("cseEventStream")
    tw = rt.get_input_handler("twitterStream")
    tw.send(1000, ["User1", "Hello World", "WSO2"])
    cse.send(1100, ["WSO2", "User1", 100])
    cse.send(1200, ["WSO2", "User2", 100])
    tw.send(1250, ["User2", "Hello World", "WSO2"])
    tw.send(1300, ["User3", "Hello World", "WSO2"])
    cse.send(1350, ["WSO2", "User3", 100])
    m.shutdown()
    assert len(c.events) == 3
