"""Reference incremental-aggregation corpus — scenarios ported verbatim
from ``aggregation/Aggregation1TestCase.java`` (feeds and expected
outputs; sec…year cascades, wildcard/offset ``within`` date strings,
per-event dynamic ``within``/``per`` on aggregation joins, string
``aggregate by`` timestamps, and the validation-error battery)."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.compiler.errors import (
    SiddhiAppValidationException,
    SiddhiParserException,
)
from siddhi_tpu.core.query.callback import QueryCallback
from siddhi_tpu.ops.expressions import CompileError


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


STOCK = ("define stream stockStream (symbol string, price float, "
         "lastClosingPrice float, volume long, quantity int, "
         "timestamp long);")
STOCK_STR_TS = STOCK.replace("timestamp long", "timestamp string")
INPUT = ("define stream inputStream (symbol string, value int, "
         "startTime string, endTime string, perValue string);")
AGG = ("define aggregation stockAggregation from stockStream "
       "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
       "(price * quantity) as lastTradeValue "
       "group by symbol aggregate by timestamp every sec...hour;")

FEED_6SEC = [
    ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
    ["WSO2", 70.0, None, 40, 10, 1496289950000],
    ["WSO2", 60.0, 44.0, 200, 56, 1496289952000],
    ["WSO2", 100.0, None, 200, 16, 1496289952000],
    ["IBM", 100.0, None, 200, 26, 1496289954000],
    ["IBM", 100.0, None, 200, 96, 1496289954000],
]
EXPECT_6SEC = [
    (1496289950000, "WSO2", 60.0, 120.0, 700.0),
    (1496289952000, "WSO2", 80.0, 160.0, 1600.0),
    (1496289954000, "IBM", 100.0, 200.0, 9600.0),
]


def _feed(rt, rows, stream="stockStream"):
    h = rt.get_input_handler(stream)
    for r in rows:
        h.send(list(r))


# ------------------------------------------------------ creation corpus


def test_creation_sec_to_min():
    """incrementalStreamProcessorTest1 (:63-79): aggregate by attr every
    sec ... min compiles."""
    m = SiddhiManager()
    m.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, "
        "price float, volume int); "
        "@info(name = 'query1') define aggregation stockAggregation "
        "from stockStream select sum(price) as sumPrice "
        "aggregate by arrival every sec ... min")
    m.shutdown()


def test_creation_no_by_attribute():
    """incrementalStreamProcessorTest2 (:81-97): `aggregate every` without
    an explicit time attribute compiles."""
    m = SiddhiManager()
    m.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, "
        "price float, volume int); "
        "define aggregation stockAggregation from stockStream "
        "select sum(price) as sumPrice aggregate every sec ... min")
    m.shutdown()


def test_creation_group_by_lists():
    """incrementalStreamProcessorTest3/4/15 (:99-136, :644-661): explicit
    duration lists and multi-attribute group by compile."""
    m = SiddhiManager()
    m.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, "
        "price float, volume int); "
        "define aggregation a1 from stockStream "
        "select sum(price) as sumPrice group by price "
        "aggregate every sec, min, hour, day")
    m.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, "
        "price float, volume int); "
        "define aggregation a2 from stockStream "
        "select sum(price) as sumPrice group by price, volume "
        "aggregate every sec, min, hour, day")
    m.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, "
        "price float, volume int); "
        "define aggregation a3 from stockStream "
        "select sum(price) as sumPrice group by price "
        "aggregate every sec, hour, day")
    m.shutdown()


def test_creation_undefined_stream_rejected():
    """incrementalStreamProcessorTest13 (:610-624): aggregation over an
    undefined stream is a creation-time error."""
    m = SiddhiManager()
    with pytest.raises((CompileError, SiddhiAppValidationException)):
        m.create_siddhi_app_runtime(
            "@info(name = 'query1') define aggregation stockAggregation "
            "from stockStream select sum(price) as sumPrice "
            "aggregate by arrival every sec ... min")
    m.shutdown()


def test_creation_week_duration_rejected():
    """incrementalStreamProcessorTest14 (:626-642): `every week` is not a
    supported duration."""
    m = SiddhiManager()
    with pytest.raises((CompileError, SiddhiParserException)):
        m.create_siddhi_app_runtime(
            "define stream stockStream (arrival long, symbol string, "
            "price float, volume int); "
            "@info(name = 'query1') define aggregation stockAggregation "
            "from stockStream select sum(price) as sumPrice "
            "aggregate by arrival every week")
    m.shutdown()


def test_join_undefined_aggregation_rejected():
    """incrementalStreamProcessorTest19 (:973-989): joining an undefined
    aggregation is a creation-time error."""
    m = SiddhiManager()
    with pytest.raises((CompileError, SiddhiAppValidationException,
                        SiddhiParserException)):
        m.create_siddhi_app_runtime(
            INPUT +
            " @info(name = 'query1') "
            "from inputStream as i join stockAggregation as s "
            'within "2017-01-01 00:00:00", "2021-01-01 00:00:00" '
            'per "months" select s.symbol, avgPrice, totalPrice '
            "insert all events into outputStream;")
    m.shutdown()


# ----------------------------------------------- on-demand read corpus


def test_on_demand_month_wildcard_within():
    """incrementalStreamProcessorTest5 (:137-189): seconds buckets read
    back with a month-wildcard within pattern."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "group by symbol aggregate by timestamp every sec...hour;")
    rt.start()
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289952000],
        ["WSO2", 100.0, None, 200, 16, 1496289952500],
        ["IBM", 100.0, None, 200, 26, 1496289954000],
        ["IBM", 100.0, None, 200, 96, 1496289954500],
    ])
    events = rt.query('from stockAggregation '
                      'within "2017-06-** **:**:**" per "seconds"')
    got = sorted(tuple(e.data) for e in events)
    assert got == sorted([
        (1496289952000, "WSO2", 80.0, 160.0, 1600.0),
        (1496289950000, "WSO2", 60.0, 120.0, 700.0),
        (1496289954000, "IBM", 100.0, 200.0, 9600.0),
    ])
    m.shutdown()


def test_on_demand_unsorted_match():
    """incrementalStreamProcessorTest24 (:1084-1135): wildcard within,
    results match as a set."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    _feed(rt, FEED_6SEC)
    events = rt.query('from stockAggregation '
                      'within "2017-06-** **:**:**" per "seconds"')
    assert sorted(tuple(e.data) for e in events) == sorted(EXPECT_6SEC)
    m.shutdown()


def test_on_demand_select_star_order_by():
    """incrementalStreamProcessorTest25 (:1137-1199): `select * order by
    AGG_TIMESTAMP` returns buckets in time order."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    _feed(rt, FEED_6SEC)
    events = rt.query('from stockAggregation '
                      'within "2017-06-** **:**:**" per "seconds" '
                      "select * order by AGG_TIMESTAMP ;")
    assert [tuple(e.data) for e in events] == EXPECT_6SEC
    m.shutdown()


def test_on_demand_year_wildcard():
    """incrementalStreamProcessorTest31 (:1409-1478): year-wildcard within
    spans buckets months apart."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    _feed(rt, FEED_6SEC + [
        ["CISCO", 100.0, None, 200, 26, 1513578087000],
        ["CISCO", 100.0, None, 200, 96, 1513578087000],
    ])
    events = rt.query('from stockAggregation '
                      'within "2017-**-** **:**:**" per "seconds" '
                      "select * order by AGG_TIMESTAMP ;")
    assert [tuple(e.data) for e in events] == EXPECT_6SEC + [
        (1513578087000, "CISCO", 100.0, 200.0, 9600.0)]
    m.shutdown()


@pytest.mark.parametrize("within", [
    '"2017-12-18 **:**:**"',            # test32: day range
    '"2017-12-18 06:**:**"',            # test33: hour range
    '"2017-12-18 06:21:**"',            # test34: minute range
    '"2017-12-18 11:51:27 +05:30"',     # test35: full second, +05:30
])
def test_on_demand_narrowing_wildcards(within):
    """incrementalStreamProcessorTest32-35 (:1480-1680): successively
    narrower within patterns isolate the CISCO second-bucket."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    _feed(rt, FEED_6SEC + [
        ["CISCO", 100.0, None, 200, 26, 1513578087000],
        ["CISCO", 100.0, None, 200, 96, 1513578087000],
    ])
    events = rt.query(f'from stockAggregation within {within} '
                      f'per "seconds" select * order by AGG_TIMESTAMP ;')
    assert [tuple(e.data) for e in events] == [
        (1513578087000, "CISCO", 100.0, 200.0, 9600.0)]
    m.shutdown()


def test_on_demand_wall_clock_on_condition():
    """incrementalStreamProcessorTest11 (:429-484): `aggregate every`
    without a by-attribute uses arrival wall-clock; read back with an
    `on` filter and the current month's +05:30 pattern."""
    from datetime import datetime, timedelta, timezone

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "group by symbol aggregate every sec...hour;")
    rt.start()
    _feed(rt, FEED_6SEC)
    now = datetime.now(timezone(timedelta(hours=5, minutes=30)))
    events = rt.query(
        'from stockAggregation on symbol == "IBM" '
        f'within "{now.year}-{now.month:02d}-** **:**:** +05:30" '
        'per "seconds"; ')
    assert len(events) == 1
    assert tuple(events[0].data)[1:] == ("IBM", 100.0, 200.0, 9600.0)
    m.shutdown()


def test_out_of_order_beyond_buffer_group_by():
    """incrementalStreamProcessorTest45 (:2348-2397): out-of-order events
    across group-by keys still land in their buckets (5 distinct
    (second, symbol) windows)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, sum(price) as totalPrice "
        "group by symbol aggregate by timestamp every sec...year ;")
    rt.start()
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["IBM", 100.0, None, 200, 16, 1496289951011],
        ["IBM", 400.0, None, 200, 9, 1496289952000],
        ["IBM", 900.0, None, 200, 60, 1496289950000],
        ["WSO2", 500.0, None, 200, 7, 1496289951011],
        ["IBM", 100.0, None, 200, 26, 1496289953000],
        ["WSO2", 100.0, None, 200, 96, 1496289953000],
    ])
    events = rt.query("from stockAggregation within 0L, 1496289953000L "
                      "per 'seconds' select AGG_TIMESTAMP, symbol, "
                      "totalPrice")
    assert len(events) == 5
    m.shutdown()


# --------------------------------------------- on-demand error corpus


def test_on_demand_undefined_aggregation():
    """incrementalStreamProcessorTest20 (:991-1010): store query on an
    undefined aggregation raises."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK)
    rt.start()
    with pytest.raises((CompileError, SiddhiAppValidationException)):
        rt.query('from stockAggregation on symbol == "IBM" '
                 'within "2017-**-** **:**:** +05:30" per "seconds"; ')
    m.shutdown()


def test_on_demand_unkept_granularity():
    """incrementalStreamProcessorTest21 (:1013-1041): `per "days"` when
    the aggregation keeps sec...hour raises."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    with pytest.raises(CompileError):
        rt.query('from stockAggregation within "2017-06-** **:**:**" '
                 'per "days"')
    m.shutdown()


def test_on_demand_non_string_per():
    """incrementalStreamProcessorTest27 (:1296-1326): numeric `per`
    raises."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    with pytest.raises(CompileError):
        rt.query('from stockAggregation within "2017-06-** **:**:**" '
                 "per 1000")
    m.shutdown()


def test_on_demand_start_after_end():
    """incrementalStreamProcessorTest28 (:1328-1358): within start must be
    before end."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    with pytest.raises(CompileError):
        rt.query('from stockAggregation within "2017-06-02 00:00:00", '
                 '"2017-06-01 00:00:00" per "hours"')
    m.shutdown()


def test_on_demand_bad_patterns():
    """incrementalStreamProcessorTest29/30 (:1360-1407): malformed within
    patterns raise (extra field; hour given under a day wildcard)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    with pytest.raises(CompileError):
        rt.query('from stockAggregation within '
                 '"2017-06-** **:**:**:1000" per "hours"')
    with pytest.raises(CompileError):
        rt.query('from stockAggregation within "2017-06-** 12:**:**" '
                 'per "hours"')
    m.shutdown()


def test_on_demand_single_numeric_within():
    """incrementalStreamProcessorTest36 (:1682-1712): a single numeric
    within bound is rejected (must be a date-pattern string)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    with pytest.raises(CompileError):
        rt.query('from stockAggregation within 1513578087000L '
                 'per "hours"')
    m.shutdown()


def test_on_demand_mixed_bounds_start_after_end():
    """incrementalStreamProcessorTest37 (:1714-1744): date-string start
    with a tiny numeric end -> start >= end raises."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    with pytest.raises(CompileError):
        rt.query('from stockAggregation within '
                 '"2017-12-18 11:51:27 +05:30", 156 per "hours"')
    m.shutdown()


def test_repeated_reads_same_runtime():
    """incrementalStreamProcessorTest44 (:2293-2345): back-to-back
    on-demand reads at different granularities both work (parsed-runtime
    cache safety)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue, count() as count "
        "aggregate by timestamp every sec...year ;")
    rt.start()
    _feed(rt, FEED_6SEC)
    e1 = rt.query("from stockAggregation within 1496289949000L, "
                  "1496289950001L per 'hours' "
                  "select AGG_TIMESTAMP, avgPrice")
    e2 = rt.query("from stockAggregation within 1496289949000L, "
                  "1496289950001L per 'days' "
                  "select AGG_TIMESTAMP, avgPrice")
    assert len(e1) == 1 and len(e2) == 1
    m.shutdown()


# --------------------------------------------------------- join corpus


def _join_collect(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback("query1", q)
    rt.start()
    return m, rt, q


def test_join_dynamic_string_bounds():
    """incrementalStreamProcessorTest6 (:190-298): per-event
    `within i.startTime, i.endTime per i.perValue` date strings."""
    m, rt, q = _join_collect(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "group by symbol aggregate by timestamp every sec...year ; "
        + INPUT +
        " @info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        "within i.startTime, i.endTime per i.perValue "
        "select AGG_TIMESTAMP, s.symbol, avgPrice, totalPrice as sumPrice, "
        "lastTradeValue order by AGG_TIMESTAMP "
        "insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["IBM", 100.0, None, 200, 26, 1496289951000],
        ["IBM", 100.0, None, 200, 96, 1496289951000],
        ["IBM", 900.0, None, 200, 60, 1496289952000],
        ["IBM", 500.0, None, 200, 7, 1496289952000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289953000],
        ["WSO2", 100.0, None, 200, 16, 1496289953000],
        ["IBM", 400.0, None, 200, 9, 1496289953000],
        ["WSO2", 140.0, None, 200, 11, 1496289953000],
        ["IBM", 600.0, None, 200, 6, 1496289954000],
        ["IBM", 1000.0, None, 200, 9, 1496290016000],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 04:05:50", "2017-06-01 04:06:57", "seconds"])
    assert [tuple(e.data) for e in q.events] == [
        (1496289950000, "WSO2", 60.0, 240.0, 700.0),
        (1496289951000, "IBM", 100.0, 200.0, 9600.0),
        (1496289952000, "IBM", 700.0, 1400.0, 3500.0),
        (1496289953000, "WSO2", 100.0, 300.0, 1540.0),
        (1496289953000, "IBM", 400.0, 400.0, 3600.0),
        (1496289954000, "IBM", 600.0, 600.0, 3600.0),
        (1496290016000, "IBM", 1000.0, 1000.0, 9000.0),
    ]
    m.shutdown()


def test_join_dynamic_long_bounds():
    """incrementalStreamProcessorTest26 (:1201-1294): per-event unix-ms
    long within bounds on the trigger event."""
    m, rt, q = _join_collect(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "group by symbol aggregate by timestamp every sec...year ; "
        "define stream inputStream (symbol string, value int, "
        "startTime long, endTime long, perValue string); "
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        "within i.startTime, i.endTime per i.perValue "
        "select AGG_TIMESTAMP, s.symbol, avgPrice, totalPrice as sumPrice, "
        "lastTradeValue insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["IBM", 100.0, None, 200, 26, 1496289951000],
        ["IBM", 100.0, None, 200, 96, 1496289951000],
        ["IBM", 900.0, None, 200, 60, 1496289952000],
        ["IBM", 500.0, None, 200, 7, 1496289952000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289953000],
        ["WSO2", 100.0, None, 200, 16, 1496289953000],
        ["IBM", 400.0, None, 200, 9, 1496289953000],
        ["WSO2", 140.0, None, 200, 11, 1496289953000],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, 1496289951000, 1496289952001, "seconds"])
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        (1496289951000, "IBM", 100.0, 200.0, 9600.0),
        (1496289952000, "IBM", 700.0, 1400.0, 3500.0),
    ])
    m.shutdown()


def test_join_static_long_bounds_days():
    """incrementalStreamProcessorTest9 (:300-427): static long within over
    day buckets with count()."""
    m, rt, q = _join_collect(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue, count() as count "
        "aggregate by timestamp every min, day, year ; "
        + INPUT +
        " @info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596434876000L per "days" '
        "select AGG_TIMESTAMP, s.avgPrice, totalPrice, lastTradeValue, "
        "count order by AGG_TIMESTAMP "
        "insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289952000],
        ["WSO2", 100.0, None, 200, 16, 1496289952000],
        ["IBM", 100.0, None, 200, 26, 1496289954000],
        ["IBM", 100.0, None, 200, 96, 1496289954000],
        ["IBM", 900.0, None, 200, 60, 1496289956000],
        ["IBM", 500.0, None, 200, 7, 1496289956000],
        ["IBM", 400.0, None, 200, 9, 1496290016000],
        ["IBM", 600.0, None, 200, 6, 1496290076000],
        ["CISCO", 700.0, None, 200, 20, 1496293676000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496297276000],
        ["CISCO", 800.0, None, 100, 10, 1496383676000],
        ["CISCO", 900.0, None, 100, 15, 1496470076000],
        ["IBM", 100.0, None, 200, 96, 1499062076000],
        ["IBM", 400.0, None, 200, 9, 1501740476000],
        ["WSO2", 60.0, 44.0, 200, 6, 1533276476000],
        ["WSO2", 260.0, 44.0, 200, 16, 1564812476000],
        ["CISCO", 260.0, 44.0, 200, 16, 1596434876000],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 09:35:51 +05:30",
         "2017-06-01 09:35:52 +05:30", "seconds"])
    assert [tuple(e.data) for e in q.events] == [
        (1496275200000, 303.3333333333333, 3640.0, 3360.0, 12),
        (1496361600000, 800.0, 800.0, 8000.0, 1),
        (1496448000000, 900.0, 900.0, 13500.0, 1),
        (1499040000000, 100.0, 100.0, 9600.0, 1),
        (1501718400000, 400.0, 400.0, 3600.0, 1),
        (1533254400000, 60.0, 60.0, 360.0, 1),
        (1564790400000, 260.0, 260.0, 4160.0, 1),
        (1596412800000, 260.0, 260.0, 4160.0, 1),
    ]
    m.shutdown()


def test_join_static_string_bounds_chained():
    """incrementalStreamProcessorTest12 (:486-608): GMT date-string static
    within, min/max aggregators, output chained through tempStream; ties
    at one AGG_TIMESTAMP may arrive in either side order."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue, max(price) as maxPrice, "
        "min(price) as minPrice "
        "group by symbol aggregate by timestamp every sec...year ; "
        + INPUT +
        " from inputStream as i join stockAggregation as s "
        'within "2017-06-01 04:05:50", "2017-06-01 04:06:57" '
        'per "seconds" '
        "select AGG_TIMESTAMP, totalPrice, avgPrice, lastTradeValue, "
        "s.symbol, maxPrice, minPrice order by AGG_TIMESTAMP "
        "insert into tempStream; "
        "@info(name = 'query1') from tempStream "
        "select AGG_TIMESTAMP, totalPrice, avgPrice, lastTradeValue, "
        "symbol, maxPrice, minPrice insert into outputStream ")
    q = QCollect()
    rt.add_callback("query1", q)
    rt.start()
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289953000],
        ["WSO2", 100.0, None, 200, 16, 1496289953000],
        ["IBM", 900.0, None, 200, 60, 1496289952000],
        ["IBM", 500.0, None, 200, 7, 1496289952000],
        ["IBM", 100.0, None, 200, 26, 1496289951000],
        ["IBM", 100.0, None, 200, 96, 1496289951000],
        ["IBM", 400.0, None, 200, 9, 1496289953000],
        ["WSO2", 140.0, None, 200, 11, 1496289953000],
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["IBM", 600.0, None, 200, 6, 1496289954000],
        ["IBM", 1000.0, None, 200, 9, 1496290016000],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 09:35:51 +05:30",
         "2017-06-01 09:35:52 +05:30", "seconds"])
    got = [tuple(e.data) for e in q.events]
    expected1 = [
        (1496289950000, 240.0, 60.0, 700.0, "WSO2", 70.0, 50.0),
        (1496289951000, 200.0, 100.0, 9600.0, "IBM", 100.0, 100.0),
        (1496289952000, 1400.0, 700.0, 3500.0, "IBM", 900.0, 500.0),
        (1496289953000, 400.0, 400.0, 3600.0, "IBM", 400.0, 400.0),
        (1496289953000, 300.0, 100.0, 1540.0, "WSO2", 140.0, 60.0),
        (1496289954000, 600.0, 600.0, 3600.0, "IBM", 600.0, 600.0),
        (1496290016000, 1000.0, 1000.0, 9000.0, "IBM", 1000.0, 1000.0),
    ]
    expected2 = [expected1[0], expected1[1], expected1[2], expected1[4],
                 expected1[3], expected1[5], expected1[6]]
    assert got in (expected1, expected2)
    m.shutdown()


def test_join_months_granularity():
    """incrementalStreamProcessorTest17 (:704-838): months buckets are
    calendar-truncated; out-of-order feeds merge."""
    m, rt, q = _join_collect(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "group by symbol aggregate by timestamp every sec...year; "
        + INPUT +
        " @info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within "2017-01-01 00:00:00", "2021-01-01 00:00:00" '
        'per "months" '
        "select AGG_TIMESTAMP, s.symbol, avgPrice, totalPrice "
        "order by AGG_TIMESTAMP insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289952000],
        ["WSO2", 100.0, None, 200, 16, 1496289952000],
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["IBM", 100.0, None, 200, 26, 1496289954000],
        ["IBM", 100.0, None, 200, 96, 1496289954000],
        ["IBM", 900.0, None, 200, 60, 1496289956000],
        ["IBM", 500.0, None, 200, 7, 1496289956000],
        ["IBM", 400.0, None, 200, 9, 1496290016000],
        ["IBM", 600.0, None, 200, 6, 1496290076000],
        ["CISCO", 700.0, None, 200, 20, 1496293676000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496297276000],
        ["CISCO", 800.0, None, 100, 10, 1496383676000],
        ["CISCO", 900.0, None, 100, 15, 1496470076000],
        ["IBM", 100.0, None, 200, 96, 1499062076000],
        ["IBM", 400.0, None, 200, 9, 1501740476000],
        ["WSO2", 60.0, 44.0, 200, 6, 1533276476000],
        ["WSO2", 260.0, 44.0, 200, 16, 1564812476000],
        ["CISCO", 260.0, 44.0, 200, 16, 1596434876000],
        ["CISCO", 260.0, 44.0, 200, 16, 1606975676000],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 09:35:51 +05:30",
         "2017-06-01 09:35:52 +05:30", "seconds"])
    got = [tuple(e.data) for e in q.events]
    assert len(got) == 9
    assert sorted(got) == sorted([
        (1496275200000, "WSO2", 65.71428571428571, 460.0),
        (1496275200000, "CISCO", 800.0, 2400.0),
        (1496275200000, "IBM", 433.3333333333333, 2600.0),
        (1498867200000, "IBM", 100.0, 100.0),
        (1501545600000, "IBM", 400.0, 400.0),
        (1533081600000, "WSO2", 60.0, 60.0),
        (1564617600000, "WSO2", 260.0, 260.0),
        (1596240000000, "CISCO", 260.0, 260.0),
        (1606780800000, "CISCO", 260.0, 260.0),
    ])
    m.shutdown()


def test_join_years_granularity():
    """incrementalStreamProcessorTest18 (:840-971): years buckets."""
    m, rt, q = _join_collect(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "group by symbol aggregate by timestamp every sec...year; "
        + INPUT +
        " @info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within "2017-01-01 00:00:00", "2021-01-01 00:00:00" '
        'per "years" '
        "select AGG_TIMESTAMP, s.symbol, avgPrice, totalPrice "
        "order by AGG_TIMESTAMP insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289952000],
        ["WSO2", 100.0, None, 200, 16, 1496289952000],
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["IBM", 100.0, None, 200, 26, 1496289954000],
        ["IBM", 100.0, None, 200, 96, 1496289954000],
        ["IBM", 900.0, None, 200, 60, 1496289956000],
        ["IBM", 500.0, None, 200, 7, 1496289956000],
        ["IBM", 400.0, None, 200, 9, 1496290016000],
        ["IBM", 600.0, None, 200, 6, 1496290076000],
        ["CISCO", 700.0, None, 200, 20, 1496293676000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496297276000],
        ["CISCO", 800.0, None, 100, 10, 1496383676000],
        ["CISCO", 900.0, None, 100, 15, 1496470076000],
        ["IBM", 100.0, None, 200, 96, 1499062076000],
        ["IBM", 400.0, None, 200, 9, 1501740476000],
        ["WSO2", 60.0, 44.0, 200, 6, 1533276476000],
        ["WSO2", 260.0, 44.0, 200, 16, 1564812476000],
        ["CISCO", 260.0, 44.0, 200, 16, 1596434876000],
        ["CISCO", 260.0, 44.0, 200, 16, 1606975676000],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 09:35:51 +05:30",
         "2017-06-01 09:35:52 +05:30", "seconds"])
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        (1483228800000, "CISCO", 800.0, 2400.0),
        (1483228800000, "IBM", 387.5, 3100.0),
        (1483228800000, "WSO2", 65.71428571428571, 460.0),
        (1514764800000, "WSO2", 60.0, 60.0),
        (1546300800000, "WSO2", 260.0, 260.0),
        (1577836800000, "CISCO", 260.0, 520.0),
    ])
    m.shutdown()


def test_join_minute_wildcard_count():
    """incrementalStreamProcessorTest41 (:2005-2101): minute-wildcard
    within isolates five second-buckets with counts."""
    m, rt, q = _join_collect(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue, count() as count "
        "aggregate by timestamp every sec...year ; "
        + INPUT +
        " @info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within "2017-06-01 04:05:**" per "seconds" '
        "select AGG_TIMESTAMP, s.avgPrice, totalPrice, lastTradeValue, "
        "count order by AGG_TIMESTAMP "
        "insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289949000],
        ["WSO2", 100.0, None, 200, 16, 1496289949000],
        ["IBM", 100.0, None, 200, 26, 1496289948000],
        ["IBM", 100.0, None, 200, 96, 1496289948000],
        ["IBM", 900.0, None, 200, 60, 1496289947000],
        ["IBM", 500.0, None, 200, 7, 1496289947000],
        ["IBM", 400.0, None, 200, 9, 1496289946000],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 09:35:51 +05:30",
         "2017-06-01 09:35:52 +05:30", "seconds"])
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        (1496289946000, 400.0, 400.0, 3600.0, 1),
        (1496289947000, 700.0, 1400.0, 3500.0, 2),
        (1496289948000, 100.0, 200.0, 9600.0, 2),
        (1496289949000, 80.0, 160.0, 1600.0, 2),
        (1496289950000, 60.0, 120.0, 700.0, 2),
    ])
    m.shutdown()


def test_join_unkept_granularity_drops_event():
    """incrementalStreamProcessorTest22 (:1043-1082): `per "days"` against
    a sec...hour aggregation logs at the processor and DROPS the trigger
    event — no exception escapes send, no output."""
    m, rt, q = _join_collect(
        STOCK + AGG + INPUT +
        " @info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within "2017-06-** **:**:**" per "days" '
        "select s.symbol, avgPrice, totalPrice as sumPrice, lastTradeValue "
        "insert all events into outputStream; ")
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 09:35:51 +05:30",
         "2017-06-01 09:35:52 +05:30", "seconds"])
    assert q.events == []
    m.shutdown()


# ------------------------------------- string aggregate-by timestamps


def test_string_timestamp_bad_format_dropped():
    """incrementalStreamProcessorTest16 (:663-702): a non-ISO date string
    in `aggregate by` drops the event with a log, no exception."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK_STR_TS +
        " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "group by symbol aggregate by timestamp every sec...year ; ")
    rt.start()
    rt.get_input_handler("stockStream").send(
        ["WSO2", 50.0, 60.0, 90, 6, "June 1, 2017 4:05:50 AM"])
    # dropped: nothing aggregated
    events = rt.query('from stockAggregation '
                      'within "2017-**-** **:**:**" per "seconds"')
    assert list(events) == []
    m.shutdown()


def test_string_timestamp_out_of_order():
    """incrementalStreamProcessorTest39 (:1841-1962): GMT date-string
    aggregate-by with out-of-order arrivals; ten second-buckets."""
    m, rt, q = _join_collect(
        STOCK_STR_TS +
        " define aggregation stockAggregation from stockStream "
        "select avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "aggregate by timestamp every sec...year; "
        + INPUT +
        " @info(name = 'query1') from inputStream join stockAggregation "
        'within "2017-06-01 04:05:49", "2017-06-01 05:07:57" '
        'per "seconds" '
        "select AGG_TIMESTAMP, avgPrice, totalPrice as sumPrice, "
        "lastTradeValue order by AGG_TIMESTAMP "
        "insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, "2017-06-01 04:05:50"],
        ["IBM", 50.0, 60.0, 90, 6, "2017-06-01 04:05:51"],
        ["WSO2", 60.0, 44.0, 200, 56, "2017-06-01 04:05:47"],
        ["WSO2", 60.0, 44.0, 200, 56, "2017-06-01 04:05:49"],
        ["WSO2", 100.0, None, 200, 16, "2017-06-01 04:05:52"],
        ["WSO2", 70.0, None, 40, 10, "2017-06-01 04:05:50"],
        ["IBM", 100.0, None, 200, 26, "2017-06-01 04:05:53"],
        ["IBM", 50.0, 60.0, 90, 6, "2017-06-01 04:05:50"],
        ["IBM", 100.0, None, 200, 96, "2017-06-01 04:05:54"],
        ["IBM", 50.0, 60.0, 90, 6, "2017-06-01 04:05:50"],
        ["IBM", 900.0, None, 200, 60, "2017-06-01 04:05:56"],
        ["IBM", 500.0, None, 200, 7, "2017-06-01 04:05:56"],
        ["IBM", 400.0, None, 200, 9, "2017-06-01 04:06:56"],
        ["IBM", 600.0, None, 200, 6, "2017-06-01 04:07:56"],
        ["IBM", 700.0, None, 200, 20, "2017-06-01 05:07:56"],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 09:35:51 +05:30",
         "2017-06-01 09:35:52 +05:30", "seconds"])
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        (1496289949000, 60.0, 60.0, 3360.0),
        (1496289950000, 55.0, 220.0, 300.0),
        (1496289951000, 50.0, 50.0, 300.0),
        (1496289952000, 100.0, 100.0, 1600.0),
        (1496289953000, 100.0, 100.0, 2600.0),
        (1496289954000, 100.0, 100.0, 9600.0),
        (1496289956000, 700.0, 1400.0, 3500.0),
        (1496290016000, 400.0, 400.0, 3600.0),
        (1496290076000, 600.0, 600.0, 3600.0),
        (1496293676000, 700.0, 700.0, 14000.0),
    ])
    m.shutdown()


def test_string_timestamp_offset_bounds_minutes():
    """incrementalStreamProcessorTest38 (:1746-1839): +05:30 static string
    bounds, bare-variable `per perValue`, minute buckets."""
    m, rt, q = _join_collect(
        STOCK_STR_TS +
        " define aggregation stockAggregation from stockStream "
        "select avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "aggregate by timestamp every sec...year; "
        + INPUT +
        " @info(name = 'query1') from inputStream join stockAggregation "
        'within "2017-06-01 09:35:00 +05:30", "2017-06-01 10:37:57 +05:30" '
        "per perValue "
        "select AGG_TIMESTAMP, avgPrice, totalPrice as sumPrice, "
        "lastTradeValue insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, "2017-06-01 04:05:50"],
        ["IBM", 50.0, 60.0, 90, 6, "2017-06-01 04:05:51"],
        ["WSO2", 60.0, 44.0, 200, 56, "2017-06-01 04:05:52"],
        ["WSO2", 100.0, None, 200, 16, "2017-06-01 04:05:52"],
        ["WSO2", 70.0, None, 40, 10, "2017-06-01 04:05:50"],
        ["IBM", 100.0, None, 200, 26, "2017-06-01 04:05:54"],
        ["IBM", 100.0, None, 200, 96, "2017-06-01 04:05:54"],
        ["IBM", 50.0, 60.0, 90, 6, "2017-06-01 04:05:50"],
        ["IBM", 900.0, None, 200, 60, "2017-06-01 04:05:56"],
        ["IBM", 500.0, None, 200, 7, "2017-06-01 04:05:56"],
        ["IBM", 400.0, None, 200, 9, "2017-06-01 04:06:56"],
        ["IBM", 600.0, None, 200, 6, "2017-06-01 04:07:56"],
        ["IBM", 700.0, None, 200, 20, "2017-06-01 05:07:56"],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 09:35:51 +05:30",
         "2017-06-01 09:35:52 +05:30", "minutes"])
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        (1496289900000, 198.0, 1980.0, 3500.0),
        (1496289960000, 400.0, 400.0, 3600.0),
        (1496293620000, 700.0, 700.0, 14000.0),
        (1496290020000, 600.0, 600.0, 3600.0),
    ])
    m.shutdown()


def test_string_timestamp_mixed_timezones_dynamic():
    """incrementalStreamProcessorTest46 (:2400-2502): mixed-offset event
    dates, bare-variable dynamic within/per, month buckets."""
    m, rt, q = _join_collect(
        STOCK_STR_TS +
        " define aggregation stockAggregation from stockStream "
        "select avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "aggregate by timestamp every sec...year; "
        + INPUT +
        " @info(name = 'query1') from inputStream join stockAggregation "
        "within startTime, endTime per perValue "
        "select AGG_TIMESTAMP, avgPrice, totalPrice as sumPrice "
        "insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, "2017-06-01 04:35:49 +05:30"],
        ["WSO2", 50.0, 60.0, 90, 6, "2017-06-01 04:05:50"],
        ["IBM", 50.0, 60.0, 90, 6, "2017-06-01 04:05:51"],
        ["WSO2", 60.0, 44.0, 200, 56, "2017-06-01 04:05:52"],
        ["WSO2", 100.0, None, 200, 16, "2017-06-01 04:05:52"],
        ["IBM", 100.0, None, 200, 26, "2017-06-01 04:05:54"],
        ["IBM", 100.0, None, 200, 96, "2017-06-01 04:05:54"],
        ["IBM", 900.0, None, 200, 60, "2017-06-01 04:05:56"],
        ["IBM", 500.0, None, 200, 7, "2017-06-01 04:05:56"],
        ["IBM", 400.0, None, 200, 9, "2017-06-01 04:06:56"],
        ["IBM", 600.0, None, 200, 6, "2017-06-01 09:36:58 +05:30"],
        ["IBM", 600.0, None, 200, 6, "2017-06-01 04:07:56 +05:30"],
        ["IBM", 700.0, None, 200, 20, "2017-06-01 11:07:56 +05:30"],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2016-05-30 08:35:51 +05:30",
         "2018-06-02 10:35:52 +05:30", "months"])
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        (1493596800000, 325.0, 650.0),
        (1496275200000, 323.6363636363636, 3560.0),
    ])
    m.shutdown()


# ---------------------------------------- Aggregation2TestCase corpus


def test_minutes_granularity_long_bounds():
    """incrementalStreamProcessorTest47 (Aggregation2TestCase:62-131):
    minute buckets folded across out-of-order seconds."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, sum(price) as totalPrice, avg(price) as avgPrice "
        "group by symbol aggregate by timestamp every sec...year ;")
    rt.start()
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["IBM", 100.0, None, 200, 16, 1496289951011],
        ["IBM", 400.0, None, 200, 9, 1496289952000],
        ["IBM", 900.0, None, 200, 60, 1496289950000],
        ["WSO2", 500.0, None, 200, 7, 1496289951011],
        ["IBM", 100.0, None, 200, 26, 1496289953000],
        ["WSO2", 100.0, None, 200, 96, 1496289953000],
    ])
    events = rt.query("from stockAggregation within 0L, 1543664151000L per "
                      "'minutes' select AGG_TIMESTAMP, symbol, totalPrice, "
                      "avgPrice ")
    assert sorted(tuple(e.data) for e in events) == sorted([
        (1496289900000, "WSO2", 650.0, 216.66666666666666),
        (1496289900000, "IBM", 1500.0, 375.0),
    ])
    m.shutdown()


def test_seconds_granularity_long_bounds():
    """incrementalStreamProcessorTest48 (Aggregation2TestCase:132-199):
    seven second-buckets."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select symbol, sum(price) as totalPrice "
        "group by symbol aggregate by timestamp every sec...year ;")
    rt.start()
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["IBM", 100.0, None, 200, 16, 1496289951011],
        ["IBM", 400.0, None, 200, 9, 1496289952000],
        ["IBM", 900.0, None, 200, 60, 1496289950000],
        ["WSO2", 500.0, None, 200, 7, 1496289951011],
        ["IBM", 100.0, None, 200, 26, 1496289953000],
        ["WSO2", 100.0, None, 200, 96, 1496289953000],
    ])
    events = rt.query("from stockAggregation within 0L, 1543664151000L per "
                      "'seconds' select AGG_TIMESTAMP, symbol, totalPrice ")
    assert sorted(tuple(e.data) for e in events) == sorted([
        (1496289950000, "WSO2", 50.0),
        (1496289950000, "IBM", 900.0),
        (1496289951000, "IBM", 100.0),
        (1496289951000, "WSO2", 500.0),
        (1496289952000, "IBM", 400.0),
        (1496289953000, "IBM", 100.0),
        (1496289953000, "WSO2", 100.0),
    ])
    m.shutdown()


def test_single_dynamic_wildcard_bound():
    """incrementalStreamProcessorTest49 (Aggregation2TestCase:200-303):
    wall-clock aggregation read back through a join whose single within
    bound is a per-event year-wildcard pattern, per "years"."""
    from datetime import datetime, timezone

    m, rt, q = _join_collect(
        STOCK_STR_TS +
        " define aggregation stockAggregation from stockStream "
        "select avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue aggregate every sec...year; "
        + INPUT +
        " @info(name = 'query1') from inputStream join stockAggregation "
        "within startTime per perValue "
        "select avgPrice, totalPrice as sumPrice, lastTradeValue "
        "insert all events into outputStream; ")
    # timestamp attr is unused (`aggregate every` = arrival wall clock)
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, "x"],
        ["IBM", 50.0, 60.0, 90, 6, "x"],
        ["WSO2", 60.0, 44.0, 200, 56, "x"],
        ["WSO2", 100.0, None, 200, 16, "x"],
        ["WSO2", 70.0, None, 40, 10, "x"],
        ["IBM", 100.0, None, 200, 26, "x"],
        ["IBM", 100.0, None, 200, 96, "x"],
        ["IBM", 50.0, 60.0, 90, 6, "x"],
        ["IBM", 900.0, None, 200, 60, "x"],
        ["IBM", 500.0, None, 200, 7, "x"],
        ["IBM", 400.0, None, 200, 9, "x"],
        ["IBM", 600.0, None, 200, 6, "x"],
        ["IBM", 700.0, None, 200, 20, "x"],
    ])
    year = datetime.now(timezone.utc).year
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, f"{year}-**-** **:**:**", "unused", "years"])
    assert [tuple(e.data) for e in q.events] == [
        (283.0769230769231, 3680.0, 14000.0)]
    m.shutdown()


def test_on_demand_needs_per():
    """incrementalStreamProcessorTest50 (Aggregation2TestCase:304-329):
    a store query without within/per raises."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    rt.start()
    with pytest.raises(CompileError):
        rt.query("from stockAggregation  select * ")
    m.shutdown()


def test_repeated_identical_reads_match():
    """incrementalStreamProcessorTest51 (Aggregation2TestCase:330-444):
    the same read twice (join and on-demand) returns identical rows."""
    m, rt, q = _join_collect(
        "define stream stockStream (symbol string, price float, "
        "lastClosingPrice float, volume long, quantity int); "
        "define aggregation stockAggregation from stockStream "
        "select avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue aggregate every sec...year; "
        "define stream inputStream (symbol string, value int, "
        "startTime long, endTime long, perValue string); "
        "@info(name = 'query1') from inputStream join stockAggregation "
        "within startTime, endTime per perValue "
        "select AGG_TIMESTAMP, avgPrice, totalPrice as sumPrice "
        "insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6],
        ["WSO2", 50.0, 60.0, 90, 6],
        ["IBM", 50.0, 60.0, 90, 6],
        ["WSO2", 60.0, 44.0, 200, 56],
        ["WSO2", 100.0, None, 200, 16],
        ["IBM", 100.0, None, 200, 26],
        ["IBM", 100.0, None, 200, 96],
        ["IBM", 900.0, None, 200, 60],
        ["IBM", 500.0, None, 200, 7],
        ["IBM", 400.0, None, 200, 9],
        ["IBM", 600.0, None, 200, 6],
        ["IBM", 600.0, None, 200, 6],
        ["IBM", 700.0, None, 200, 20],
    ])
    import time as _time

    end = int(_time.time() * 1000) + 1_000_000
    hq = rt.get_input_handler("inputStream")
    hq.send(["IBM", 1, 0, end, "hours"])
    hq.send(["IBM", 1, 0, end, "hours"])
    e1 = rt.query(f"from stockAggregation within 0L, {end}L per 'hours' "
                  "select AGG_TIMESTAMP, avgPrice, totalPrice as sumPrice")
    e2 = rt.query(f"from stockAggregation within 0L, {end}L per 'hours' "
                  "select AGG_TIMESTAMP, avgPrice, totalPrice as sumPrice")
    assert len(q.events) == 2
    assert tuple(q.events[0].data) == tuple(q.events[1].data)
    assert [tuple(e.data) for e in e1] == [tuple(e.data) for e in e2]
    m.shutdown()


def test_partition_by_id_requires_shard_id():
    """incrementalStreamProcessorTest52/53 (Aggregation2TestCase:444-483):
    @PartitionById (bare or enable='true') without a configured shardId
    fails at creation."""
    base = ("define stream stockStream (symbol string, price float, "
            "lastClosingPrice float, volume long, quantity int);\n")
    agg = ("define aggregation stockAggregation from stockStream "
           "select avg(price) as avgPrice, sum(price) as totalPrice, "
           "(price * quantity) as lastTradeValue "
           "aggregate every sec...year; ")
    for ann in ("@PartitionById ", "@PartitionById(enable='true') "):
        m = SiddhiManager()
        with pytest.raises(CompileError):
            m.create_siddhi_app_runtime(base + ann + agg)
        m.shutdown()


def test_partition_by_id_disabled_ok():
    """incrementalStreamProcessorTest54 (Aggregation2TestCase:484-503):
    enable='false' needs no shardId."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream stockStream (symbol string, price float, "
        "lastClosingPrice float, volume long, quantity int);\n"
        "@PartitionById(enable='false') "
        "define aggregation stockAggregation from stockStream "
        "select avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "aggregate every sec...year; ")
    rt.start()
    m.shutdown()


def test_partition_by_id_system_property_overrides():
    """incrementalStreamProcessorTest55/56 (Aggregation2TestCase:504-553):
    the `partitionById` system property enables shard mode (even over
    enable='false') — without a shardId creation fails."""
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    base = ("define stream stockStream (symbol string, price float, "
            "lastClosingPrice float, volume long, quantity int);\n")
    agg = ("define aggregation stockAggregation from stockStream "
           "select avg(price) as avgPrice, sum(price) as totalPrice, "
           "(price * quantity) as lastTradeValue "
           "aggregate every sec...year; ")
    for ann in ("@PartitionById(enable='false') ", ""):
        m = SiddhiManager()
        m.set_config_manager(InMemoryConfigManager({"partitionById": "true"}))
        with pytest.raises(CompileError):
            m.create_siddhi_app_runtime(base + ann + agg)
        m.shutdown()


def test_shutdown_during_send_is_clean():
    """incrementalStreamProcessorTest57 (Aggregation2TestCase:554-630):
    shutting down while another thread sends batches must not error."""
    import threading

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK + AGG)
    h = rt.get_input_handler("stockStream")
    rt.start()
    batch = [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO2", 70.0, None, 40, 10, 1496289950000],
        ["WSO2", 60.0, 44.0, 200, 56, 1496289952000],
        ["WSO2", 100.0, None, 200, 16, 1496289952000],
        ["IBM", 100.0, None, 200, 96, 1496289954000],
        ["IBM", 100.0, None, 200, 26, 1496289954000],
    ]
    errors = []

    def sender():
        for _ in range(3):
            for r in batch:
                try:
                    h.send(list(r))
                except RuntimeError as e:
                    # the documented refusal once shutdown has landed
                    if "shut down" not in str(e):
                        errors.append(e)
                    return
                except Exception as e:  # anything else IS the bug under test
                    errors.append(e)
                    return

    t = threading.Thread(target=sender)
    t.start()
    rt.shutdown()
    t.join()
    assert errors == []
    m.shutdown()


# ------------------------- AggregationFilter / DistinctCount corpora


def test_join_on_condition_with_dynamic_per():
    """aggregationFilterTestCase1 (AggregationFilterTestCase:35-136): an
    `on i.symbol == s.symbol` filter composed with a per-event `per`."""
    m, rt, q = _join_collect(
        STOCK_STR_TS +
        " define aggregation stockAggregation from stockStream "
        "select symbol, avg(price) as avgPrice, sum(price) as totalPrice, "
        "(price * quantity) as lastTradeValue "
        "group by symbol aggregate by timestamp every sec...year; "
        + INPUT +
        " @info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        "on i.symbol == s.symbol "
        'within "2017-06-01 09:35:00 +05:30", "2017-06-01 10:37:57 +05:30" '
        "per i.perValue "
        "select s.symbol, avgPrice, totalPrice as sumPrice, lastTradeValue "
        "insert all events into outputStream; ")
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, "2017-06-01 04:05:50"],
        ["IBM", 50.0, 60.0, 90, 6, "2017-06-01 04:05:51"],
        ["WSO2", 60.0, 44.0, 200, 56, "2017-06-01 04:05:52"],
        ["WSO2", 100.0, None, 200, 16, "2017-06-01 04:05:52"],
        ["WSO2", 70.0, None, 40, 10, "2017-06-01 04:05:50"],
        ["IBM", 100.0, None, 200, 26, "2017-06-01 04:05:54"],
        ["IBM", 100.0, None, 200, 96, "2017-06-01 04:05:54"],
        ["IBM", 50.0, 60.0, 90, 6, "2017-06-01 04:05:50"],
        ["IBM", 900.0, None, 200, 60, "2017-06-01 04:05:56"],
        ["IBM", 500.0, None, 200, 7, "2017-06-01 04:05:56"],
        ["IBM", 400.0, None, 200, 9, "2017-06-01 04:06:56"],
        ["IBM", 600.0, None, 200, 6, "2017-06-01 04:07:56"],
        ["IBM", 700.0, None, 200, 20, "2017-06-01 05:07:56"],
    ])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 09:35:51 +05:30",
         "2017-06-01 09:35:52 +05:30", "minutes"])
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        ("IBM", 283.3333333333333, 1700.0, 3500.0),
        ("IBM", 400.0, 400.0, 3600.0),
        ("IBM", 700.0, 700.0, 14000.0),
        ("IBM", 600.0, 600.0, 3600.0),
    ])
    m.shutdown()


def test_distinct_count_aggregator_days():
    """DistinctCountAggregationTestCase test1 (:57-186): distinctCount
    per day bucket; remove events mirror in events."""
    got_removed = []

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " define aggregation stockAggregation from stockStream "
        "select distinctCount(symbol) as distinctCnt "
        "aggregate by timestamp every sec...year ;"
        "define stream inputStream (symbol string); "
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596535449000L per "days" '
        "select AGG_TIMESTAMP, s.distinctCnt order by AGG_TIMESTAMP "
        "insert all events into outputStream; ")

    class QC(QueryCallback):
        def __init__(self):
            self.events = []

        def receive(self, timestamp, in_events, remove_events):
            if in_events:
                self.events.extend(in_events)
            if remove_events:
                got_removed.extend(remove_events)

    q = QC()
    rt.add_callback("query1", q)
    rt.start()
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["WSO22", 70.0, None, 40, 10, 1496289950000],
        ["WSO23", 60.0, 44.0, 200, 56, 1496289952000],
        ["WSO24", 100.0, None, 200, 16, 1496289952000],
        ["IBM", 101.0, None, 200, 26, 1496289954000],
        ["IBM1", 102.0, None, 200, 96, 1496289954000],
        ["IBM", 900.0, None, 200, 60, 1496289956000],
        ["IBM1", 500.0, None, 200, 7, 1496289956000],
        ["IBM", 400.0, None, 200, 9, 1496290016000],
        ["IBM2", 600.0, None, 200, 6, 1496290076000],
        ["CISCO", 700.0, None, 200, 20, 1496293676000],
        ["WSO2", 61.0, 44.0, 200, 56, 1496297276000],
        ["CISCO", 801.0, None, 100, 10, 1496383676000],
        ["CISCO", 901.0, None, 100, 15, 1496470076000],
        ["IBM", 101.0, None, 200, 96, 1499062076000],
        ["IBM", 402.0, None, 200, 9, 1501740476000],
        ["WSO2", 63.0, 44.0, 200, 6, 1533276476000],
        ["WSO2", 260.0, 44.0, 200, 16, 1564812476000],
        ["CISCO", 26.0, 44.0, 200, 16, 1596434876000],
    ])
    rt.get_input_handler("inputStream").send(["IBM"])
    expected = [
        (1496275200000, 8),
        (1496361600000, 1),
        (1496448000000, 1),
        (1499040000000, 1),
        (1501718400000, 1),
        (1533254400000, 1),
        (1564790400000, 1),
        (1596412800000, 1),
    ]
    assert [tuple(e.data) for e in q.events] == expected
    assert [tuple(e.data) for e in got_removed] == expected
    m.shutdown()


# -------------------------------------- LatestAggregationTestCase corpus

LATEST_FEED = [
    ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
    ["WSO22", 75.0, None, 40, 10, 1496289950100],
    ["WSO23", 60.0, 44.0, 200, 56, 1496289952000],
    ["WSO24", 100.0, None, 200, 16, 1496289952000],
    ["WSO23", 70.0, None, 40, 10, 1496289950090],  # out of order: older ts
    ["IBM", 101.0, None, 200, 26, 1496289954000],
    ["IBM1", 102.0, None, 200, 100, 1496289954000],
    ["IBM", 900.0, None, 200, 60, 1496289956000],
    ["IBM1", 500.0, None, 200, 7, 1496289956000],
]
LATEST_AGG = (
    " define aggregation stockAggregation from stockStream "
    "select symbol, avg(price) as avgPrice, (price * quantity) as "
    "latestPrice aggregate by timestamp every sec...year ;"
    "define stream inputStream (symbol string); ")


def test_latest_value_ignores_older_out_of_order():
    """latestTestCase1 (LatestAggregationTestCase:57-153): bare selections
    keep the max-event-time value; an out-of-order OLDER arrival must not
    displace it."""
    m, rt, q = _join_collect(
        STOCK + LATEST_AGG +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596535449000L per "seconds" '
        "select AGG_TIMESTAMP, s.symbol, s.latestPrice "
        "order by AGG_TIMESTAMP insert all events into outputStream; ")
    _feed(rt, LATEST_FEED)
    rt.get_input_handler("inputStream").send(["IBM"])
    assert [tuple(e.data) for e in q.events] == [
        (1496289950000, "WSO22", 750.0),
        (1496289952000, "WSO24", 1600.0),
        (1496289954000, "IBM1", 10200.0),
        (1496289956000, "IBM1", 3500.0),
    ]
    m.shutdown()


def test_latest_value_join_group_by():
    """latestTestCase2 (:155-250): a join-side `group by s.symbol`
    collapses to the last row per symbol in the trigger chunk."""
    m, rt, q = _join_collect(
        STOCK + LATEST_AGG +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596535449000L per "seconds" '
        "select s.symbol, s.latestPrice group by s.symbol "
        "order by AGG_TIMESTAMP insert all events into outputStream; ")
    _feed(rt, LATEST_FEED)
    rt.get_input_handler("inputStream").send(["IBM"])
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        ("WSO22", 750.0),
        ("WSO24", 1600.0),
        ("IBM1", 3500.0),
    ])
    m.shutdown()


def test_latest_value_with_avg():
    """latestTestCase3 (:253-350): latest value and avg of the same bucket
    read together."""
    m, rt, q = _join_collect(
        STOCK + LATEST_AGG +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596535449000L per "seconds" '
        "select AGG_TIMESTAMP, s.symbol, s.latestPrice, s.avgPrice "
        "order by AGG_TIMESTAMP insert all events into outputStream; ")
    _feed(rt, LATEST_FEED)
    rt.get_input_handler("inputStream").send(["IBM"])
    assert [tuple(e.data) for e in q.events] == [
        (1496289950000, "WSO22", 750.0, 65.0),
        (1496289952000, "WSO24", 1600.0, 80.0),
        (1496289954000, "IBM1", 10200.0, 101.5),
        (1496289956000, "IBM1", 3500.0, 700.0),
    ]
    m.shutdown()


def test_latest_value_join_side_aggregation():
    """latestTestCase4 (:352-436): the join selector re-aggregates probe
    rows (`sum(s.avgPrice)` per symbol) around latest values."""
    m, rt, q = _join_collect(
        STOCK + LATEST_AGG +
        "@info(name = 'query1') "
        "from inputStream as i join stockAggregation as s "
        'within 1496200000000L, 1596535449000L per "seconds" '
        "select s.symbol, s.latestPrice, sum(s.avgPrice) as totalAvg "
        "group by s.symbol "
        "order by AGG_TIMESTAMP insert all events into outputStream; ")
    _feed(rt, LATEST_FEED)
    rt.get_input_handler("inputStream").send(["IBM"])
    assert sorted(tuple(e.data) for e in q.events) == sorted([
        ("WSO22", 750.0, 65.0),
        ("WSO24", 1600.0, 80.0),
        ("IBM1", 3500.0, 801.5),
    ])
    m.shutdown()


# ------------------------------------------------ PurgingTestCase corpus


def test_purge_annotation_creation():
    """incrementalPurgingTest1 (PurgingTestCase:42-53): @purge with
    @retentionPeriod parses at creation."""
    m = SiddhiManager()
    m.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, "
        "price float, volume int); "
        "@info(name = 'query1') "
        "@purge(enable='true',interval='1 min',"
        "@retentionPeriod(sec='120 sec',min='2 h',hours='25 h'))"
        " define aggregation stockAggregation from stockStream "
        "select sum(price) as sumPrice aggregate by arrival every sec...min")
    m.shutdown()


def test_purge_drops_expired_second_buckets():
    """incrementalPurgingTestCase3 (PurgingTestCase:106-174): second
    buckets older than the 120s retention vanish after a purge sweep
    (the reference waits 80 s of wall clock; the sweep is triggered
    directly here)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK + " @purge(enable='true',interval='10 sec',"
        "@retentionPeriod(sec='120 sec',min='all',hours='all',"
        "days='all',months='all',years='all')) "
        "define aggregation stockAggregation from stockStream "
        "select symbol, sum(price) as totalPrice "
        "group by symbol aggregate by timestamp every sec...year ;")
    rt.start()
    _feed(rt, [
        ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
        ["IBM", 100.0, None, 200, 16, 1496289951011],
        ["IBM", 400.0, None, 200, 9, 1496289952000],
        ["IBM", 900.0, None, 200, 60, 1496289950000],
        ["WSO2", 500.0, None, 200, 7, 1496289951011],
        ["IBM", 100.0, None, 200, 26, 1496289953000],
        ["WSO2", 100.0, None, 200, 96, 1496289953000],
    ])
    events = rt.query("from stockAggregation within 0L, 1543664151000L per "
                      "'seconds' select AGG_TIMESTAMP, symbol, totalPrice ")
    assert sorted(tuple(e.data) for e in events) == sorted([
        (1496289950000, "WSO2", 50.0),
        (1496289950000, "IBM", 900.0),
        (1496289951000, "IBM", 100.0),
        (1496289951000, "WSO2", 500.0),
        (1496289952000, "IBM", 400.0),
        (1496289953000, "IBM", 100.0),
        (1496289953000, "WSO2", 100.0),
    ])
    agg = rt.aggregations["stockAggregation"]
    # reference: Thread.sleep(80000) lets the 10s-interval purger run
    # with 'now' far past the 2017 event times; trigger the sweep directly
    agg.purge(now=1496289953000 + 200_000)
    events = rt.query("from stockAggregation within 0L, 1543664151000L per "
                      "'seconds' select AGG_TIMESTAMP, symbol, totalPrice ")
    assert list(events) == []
    m.shutdown()
