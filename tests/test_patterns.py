"""Pattern/sequence (NFA) tests — expectations mirror the reference corpus:
``query/pattern/{PatternTestCase,EveryPatternTestCase,CountPatternTestCase,
LogicalPatternTestCase}.java`` and ``query/sequence/*``.
"""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


STREAMS = """
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
"""


def test_simple_pattern_non_every():
    # PatternTestCase.testQuery1 style: e1 -> e2[price > e1.price], one match
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] -> e2=Stream2[price>e1.price]
        select e1.symbol as s1, e2.symbol as s2, e1.price as p1, e2.price as p2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 55.5, 100])
    s2.send(["IBM", 54.0, 100])     # not > 55.5
    s2.send(["IBM", 57.5, 100])     # match
    s1.send(["GOOG", 70.0, 100])    # non-every: no re-arm
    s2.send(["MSFT", 80.0, 100])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("WSO2", "IBM", 55.5, 57.5)]


def test_every_pattern_multiple_pending():
    # EveryPatternTestCase: every A -> B matches once per pending A
    m, rt, c = build(STREAMS + """
        from every e1=Stream1[price>20] -> e2=Stream2[price>20]
        select e1.price as p1, e2.price as p2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A", 25.0, 1])
    s1.send(["B", 35.0, 1])
    s2.send(["X", 45.0, 1])   # completes both pendings
    s1.send(["C", 26.0, 1])
    s2.send(["Y", 46.0, 1])   # completes only the new one
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [(25.0, 45.0), (26.0, 46.0), (35.0, 45.0)]


def test_pattern_within_expiry():
    m, rt, c = build("@app:playback " + STREAMS + """
        from every e1=Stream1[price>20] -> e2=Stream2[price>20] within 100 milliseconds
        select e1.price as p1, e2.price as p2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(1000, ["A", 25.0, 1])
    s2.send(1200, ["X", 45.0, 1])   # expired (200 > 100)
    s1.send(1300, ["B", 26.0, 1])
    s2.send(1350, ["Y", 46.0, 1])   # within
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(26.0, 46.0)]


def test_count_pattern_accumulates_single_match():
    # CountPatternTestCase.testQuery1: <2:5> accumulates into ONE match
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>20]
        select e1[0].price as p0, e1[1].price as p1, e1[2].price as p2,
               e1[3].price as p3, e2.price as pb
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 25.5, 100])
    s1.send(["GOOG", 47.5, 100])
    s1.send(["GOOG", 13.75, 100])    # fails filter, accumulation keeps going
    s1.send(["GOOG", 47.75, 100])
    s2.send(["IBM", 45.75, 100])     # one match with all 3 accumulated
    s2.send(["IBM", 55.75, 100])     # consumed: no second match
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(25.5, 47.5, 47.75, None, 45.75)]


def test_count_pattern_min_not_reached_keeps_accumulating():
    # CountPatternTestCase.testQuery3: B before min is ignored (pattern)
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>20]
        select e1[0].price as p0, e1[1].price as p1, e2.price as pb
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 25.5, 100])
    s2.send(["IBM", 45.75, 100])     # count=1 < 2: no match, pending kept
    s1.send(["GOOG", 47.75, 100])
    s2.send(["IBM", 55.75, 100])     # now matches
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(25.5, 47.75, 55.75)]


def test_count_pattern_min_zero_skippable():
    # CountPatternTestCase.testQuery7: <0:5> -> B matches on B alone
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] <0:5> -> e2=Stream2[price>20]
        select e1[0].price as p0, e1[1].price as p1, e2.price as pb
        insert into OutStream;
    """)
    s2 = rt.get_input_handler("Stream2")
    s2.send(["IBM", 45.75, 100])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(None, None, 45.75)]


def test_count_pattern_max_stops_absorbing():
    # CountPatternTestCase.testQuery5: only first 5 events absorbed
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>20]
        select e1[0].price as p0, e1[3].price as p3, e1[4].price as p4, e2.price as pb
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    for p in [25.5, 47.5, 23.75, 24.75, 25.75, 27.5]:
        s1.send(["G", p, 100])
    s2.send(["IBM", 45.75, 100])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(25.5, 24.75, 25.75, 45.75)]


def test_count_filter_referencing_indexed():
    # CountPatternTestCase.testQuery6: e2 filter uses e1[1].price
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>e1[1].price]
        select e1[0].price as p0, e1[1].price as p1, e2.price as pb
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 25.5, 100])
    s1.send(["GOOG", 47.5, 100])
    s2.send(["IBM", 45.75, 100])     # 45.75 < 47.5: no
    s2.send(["IBM", 55.75, 100])     # match
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(25.5, 47.5, 55.75)]


def test_logical_and_pattern():
    # LogicalPatternTestCase: e1=A and e2=B (either order) -> match
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] and e2=Stream2[price>20]
        select e1.price as p1, e2.price as p2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s2.send(["IBM", 45.0, 1])       # B first
    s1.send(["WSO2", 25.0, 1])      # A completes
    s1.send(["X", 30.0, 1])         # consumed: nothing more
    s2.send(["Y", 50.0, 1])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(25.0, 45.0)]


def test_logical_or_pattern():
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>100] or e2=Stream2[price>100]
        select e1.price as p1, e2.price as p2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A", 50.0, 1])         # fails filter
    s2.send(["B", 150.0, 1])        # or-side matches alone
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(None, 150.0)]


def test_sequence_kills_non_contiguous():
    # SequenceTestCase: e1, e2 requires immediate succession
    m, rt, c = build("""
        define stream S (symbol string, price float);
        from every e1=S[price>20], e2=S[price>e1.price]
        select e1.price as p1, e2.price as p2
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 30.0])
    h.send(["B", 25.0])   # fails e2 (not > 30); kills the pending; starts own
    h.send(["C", 40.0])   # completes (25, 40)
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(25.0, 40.0)]


def test_pattern_chain_three_steps():
    m, rt, c = build("""
        define stream S (k string, v int);
        from every e1=S[v==1] -> e2=S[v==2] -> e3=S[v==3]
        select e1.k as k1, e2.k as k2, e3.k as k3
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for k, v in [("a", 1), ("x", 5), ("b", 2), ("c", 3), ("d", 1), ("e", 2), ("f", 3)]:
        h.send([k, v])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("a", "b", "c"), ("d", "e", "f")]


def test_partitioned_pattern():
    # the benchmark shape: every A -> B within, partitioned by key
    m, rt, c = build("@app:playback " + """
        define stream A (k string, v int);
        define stream B (k string, v int);
        partition with (k of A, k of B)
        begin
            from every e1=A -> e2=B[v > e1.v] within 5 sec
            select e1.k as k, e1.v as v1, e2.v as v2
            insert into OutStream;
        end;
    """)
    ha = rt.get_input_handler("A")
    hb = rt.get_input_handler("B")
    ha.send(1000, ["k1", 10])
    ha.send(1001, ["k2", 20])
    hb.send(1002, ["k1", 15])       # k1 match
    hb.send(1003, ["k2", 5])        # fails condition
    hb.send(1004, ["k2", 25])       # k2 match
    hb.send(9000, ["k1", 99])       # within expired for any k1 pending
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [("k1", 10, 15), ("k2", 20, 25)]


def test_count_pattern_last_indexing():
    # e1[last] reads the final accumulated event; e1[last - 1] the one
    # before it (reference StateEvent LAST semantics)
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] <2:4> -> e2=Stream2[price>20]
        select e1[last].price as pl, e1[last - 1].price as pl1, e2.price as pb
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A", 25.5, 1])
    s1.send(["B", 47.5, 1])
    s1.send(["C", 48.75, 1])
    s2.send(["X", 55.0, 1])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(48.75, 47.5, 55.0)]
