"""Reference sequence-partition corpus — all 16 scenarios ported verbatim
from ``query/partition/SequencePartitionTestCase.java`` (feeds + expected
rows/counts; float32 prices compared rounded)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(query, streams=None, partition=None):
    streams = streams or """
        define stream Stream1 (symbol string, price float, volume int);
        define stream Stream2 (symbol string, price float, volume int);
    """
    partition = partition or "partition with (volume of Stream1, volume of Stream2)"
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        streams + partition + " begin @info(name = 'query1') "
        + query + " end;")
    c = Collector()
    rt.add_callback("OutputStream", c)
    return m, rt, c


def _rows(c):
    out = []
    for e in c.events:
        out.append(tuple(round(v, 4) if isinstance(v, float) else v
                         for v in e.data))
    return out


def test_seq_partition_1_basic_per_key():
    m, rt, c = build("""
        from e1=Stream1[price>20], e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 55.6, 100])
    s1.send(["BIRT", 55.6, 200])
    s2.send(["GOOG", 55.7, 200])
    s2.send(["IBM", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("BIRT", "GOOG"), ("WSO2", "IBM")]


def test_seq_partition_2_strict_continuity_per_key():
    """testSequencePartitionQuery2: in a SEQUENCE the second Stream1 event
    kills the first pending match per key — only the 300-volume instance
    (single e1 then e2) emits."""
    m, rt, c = build("""
        from every e1=Stream1[price>20], e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 55.6, 100])
    s1.send(["GOOG", 57.6, 100])
    s2.send(["IBM", 65.7, 100])
    s1.send(["WSO2", 55.6, 100])
    s1.send(["GOOG", 57.6, 200])
    s2.send(["IBM", 65.7, 300])
    m.shutdown()
    assert _rows(c) == [("GOOG", "IBM")]


def test_seq_partition_3_trailing_star_eager():
    m, rt, c = build("""
        from every e1=Stream1[price>20], e2=Stream2[price>e1.price]*
        select e1.symbol as symbol1, e2[0].symbol as symbol2,
               e2[1].symbol as symbol3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(["WSO2", 55.6, 100])
    s1.send(["IBM", 55.7, 100])
    s1.send(["BIRT", 55.6, 200])
    s1.send(["GOOG", 55.7, 200])
    m.shutdown()
    assert _rows(c) == [("WSO2", None, None), ("IBM", None, None),
                        ("BIRT", None, None), ("GOOG", None, None)]


def test_seq_partition_4_leading_star_per_key():
    m, rt, c = build("""
        from every e1=Stream2[price>20]*, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e1[1].price as price2,
               e2.price as price3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 59.6, 100])
    s2.send(["WSO2", 55.6, 100])
    s1.send(["BIRT", 69.6, 200])
    s2.send(["BIRT", 65.6, 200])
    s2.send(["IBM", 55.7, 100])
    s2.send(["GOOG", 75.7, 200])
    s1.send(["WSO2", 57.6, 100])
    s1.send(["BIRT", 87.6, 200])
    m.shutdown()
    assert _rows(c) == [(55.6, 55.7, 57.6), (65.6, 75.7, 87.6)]


def test_seq_partition_5_leading_star_two_rounds():
    m, rt, c = build("""
        from every e1=Stream2[price>20]*, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e1[1].price as price2,
               e2.price as price3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 59.6, 100])
    s2.send(["WSO2", 55.6, 100])
    s2.send(["IBM", 55.0, 100])
    s1.send(["WSO2", 57.6, 100])
    s2.send(["WSO2", 85.6, 1000])
    s2.send(["IBM", 85.0, 1000])
    s1.send(["WSO2", 87.6, 1000])
    m.shutdown()
    assert _rows(c) == [(55.6, 55.0, 57.6), (85.6, 85.0, 87.6)]


def test_seq_partition_6_optional_head_no_match():
    m, rt, c = build("""
        from every e1=Stream2[price>20]?, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e2.price as price3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 59.6, 100])
    s2.send(["WSO2", 55.6, 100])
    s2.send(["IBM", 55.7, 100])
    s1.send(["WSO2", 57.6, 200])
    m.shutdown()
    assert _rows(c) == []


_OR_SEQ = """
    from every e1=Stream2[price>20], e2=Stream2[price>e1.price]
         or e3=Stream2[symbol=='IBM']
    select e1.price as price1, e2.price as price2, e3.price as price3
    insert into OutputStream;
"""


def test_seq_partition_7_or_left_priority():
    m, rt, c = build(_OR_SEQ)
    s2 = rt.get_input_handler("Stream2")
    s2.send(["WSO2", 59.6, 100])
    s2.send(["WSO2", 55.6, 100])
    s2.send(["IBM", 55.7, 100])
    s2.send(["WSO2", 57.6, 100])
    s2.send(["WSO2", 599.6, 4100])
    s2.send(["WSO2", 55.6, 4100])
    s2.send(["IBM", 155.7, 4100])
    s2.send(["WSO2", 457.6, 4100])
    m.shutdown()
    assert _rows(c) == [(55.6, 55.7, None), (55.7, 57.6, None),
                        (55.6, 155.7, None), (155.7, 457.6, None)]


def test_seq_partition_8_or_right_fires():
    m, rt, c = build(_OR_SEQ)
    s2 = rt.get_input_handler("Stream2")
    s2.send(["WSO2", 59.6, 100])
    s2.send(["WSO2", 259.6, 200])
    s2.send(["WSO2", 55.6, 100])
    s2.send(["WSO2", 155.6, 200])
    s2.send(["IBM", 55.0, 100])
    s2.send(["IBM", 95.0, 200])
    s2.send(["WSO2", 57.6, 100])
    s2.send(["WSO2", 207.6, 200])
    m.shutdown()
    assert _rows(c) == [(55.6, None, 55.0), (155.6, None, 95.0),
                        (55.0, 57.6, None), (95.0, 207.6, None)]


def test_seq_partition_9_or_mixed():
    m, rt, c = build(_OR_SEQ)
    s2 = rt.get_input_handler("Stream2")
    s2.send(["WSO2", 59.6, 100])
    s2.send(["WSO2", 155.6, 200])
    s2.send(["WSO2", 55.6, 100])
    s2.send(["WSO2", 57.6, 100])
    s2.send(["IBM", 55.7, 100])
    s2.send(["WSO2", 207.6, 200])
    m.shutdown()
    assert _rows(c) == [(55.6, 57.6, None), (57.6, None, 55.7),
                        (155.6, 207.6, None)]


def test_seq_partition_10_plus_min_one():
    m, rt, c = build("""
        from every e1=Stream2[price>20]+, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e1[1].price as price2,
               e2.price as price3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 59.6, 100])
    s2.send(["WSO2", 55.6, 100])
    s1.send(["WSO2", 57.6, 100])
    s2.send(["WSO2", 55.6, 120])
    s1.send(["WSO2", 57.6, 150])
    m.shutdown()
    assert _rows(c) == [(55.6, None, 57.6)]


def test_seq_partition_11_rising_run_then_drop():
    """testSequencePartitionQuery11: collect a non-decreasing run with a
    self-referencing count condition, emit on the first drop — per key."""
    m, rt, c = build("""
        from every e1=Stream1[price>20],
             e2=Stream1[((e2[last].price is null) and price>=e1.price)
                  or ((not (e2[last].price is null))
                      and price>=e2[last].price)]+,
             e3=Stream1[price<e2[last].price]
        select e1.price as price1, e2[0].price as price2,
               e2[1].price as price3, e3.price as price4
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(["WSO2", 29.6, 100])
    s1.send(["WSO2", 35.6, 100])
    s1.send(["WSO2", 57.6, 100])
    s1.send(["IBM", 47.6, 100])
    s1.send(["WSO2", 129.6, 10])
    s1.send(["WSO2", 135.6, 10])
    s1.send(["WSO2", 157.6, 10])
    s1.send(["IBM", 147.6, 10])
    m.shutdown()
    assert _rows(c) == [(29.6, 35.6, 57.6, 47.6),
                        (129.6, 135.6, 157.6, 147.6)]


STOCK_TWITTER = """
    define stream StockStream (symbol string, price float, volume int,
                               name string);
    define stream TwitterStream (symbol string, count int, user string);
"""


def test_seq_partition_12_cross_stream_keys():
    m, rt, c = build("""
        from every e1=StockStream[price >= 50 and volume > 100],
             e2=TwitterStream[count > 10]
        select e1.price as price, e1.symbol as symbol, e2.count as count
        insert into OutputStream;
    """, streams=STOCK_TWITTER,
        partition="partition with (name of StockStream, user of TwitterStream)")
    stock = rt.get_input_handler("StockStream")
    tw = rt.get_input_handler("TwitterStream")
    stock.send(["IBM", 75.6, 105, "user"])
    stock.send(["GOOG", 51.0, 101, "user"])
    stock.send(["IBM", 76.6, 111, "user"])
    stock.send(["IBM", 76.6, 111, "user2"])
    tw.send(["IBM", 20, "user"])
    stock.send(["WSO2", 45.6, 100, "user"])
    tw.send(["GOOG", 20, "user"])
    m.shutdown()
    assert _rows(c) == [(76.6, "IBM", 20)]


def test_seq_partition_13_star_mid_chain():
    m, rt, c = build("""
        from every e1=StockStream[price >= 50 and volume > 100],
             e2=StockStream[price <= 40]*, e3=StockStream[volume <= 70]
        select e1.symbol as symbol1, e2[0].symbol as symbol2,
               e3.symbol as symbol3
        insert into OutputStream;
    """, streams=STOCK_TWITTER,
        partition="partition with (name of StockStream, user of TwitterStream)")
    stock = rt.get_input_handler("StockStream")
    stock.send(["IBM", 75.6, 105, "user"])
    stock.send(["GOOG", 21.0, 81, "user"])
    stock.send(["WSO2", 176.6, 65, "user"])
    stock.send(["GOOG", 75.6, 105, "user2"])
    stock.send(["BIRT", 21.0, 81, "user2"])
    stock.send(["DDD", 176.6, 65, "user2"])
    m.shutdown()
    assert _rows(c) == [("IBM", "GOOG", "WSO2"), ("GOOG", "BIRT", "DDD")]


STOCK12 = """
    define stream StockStream1 (symbol string, price float, volume int,
                                quantity int);
    define stream StockStream2 (symbol string, price float, volume int,
                                quantity int);
"""
_Q14_FEED_BLOCK = [
    ("StockStream1", ["IBM", 75.6, 105]),
    ("StockStream2", ["GOOG", 21.0, 81]),
    ("StockStream2", ["WSO2", 176.6, 65]),
    ("StockStream1", ["BIRT", 21.0, 81]),
    ("StockStream1", ["AMBA", 126.6, 165]),
    ("StockStream2", ["DDD", 23.0, 181]),
    ("StockStream2", ["BIRT", 21.0, 86]),
    ("StockStream2", ["BIRT", 21.0, 82]),
    ("StockStream2", ["WSO2", 176.6, 60]),
    ("StockStream1", ["AMBA", 126.6, 165]),
    ("StockStream2", ["DOX", 16.2, 25]),
]


def test_seq_partition_14_two_quantities():
    m, rt, c = build("""
        from every e1=StockStream1[price >= 50 and volume > 100],
             e2=StockStream2[price <= 40]*, e3=StockStream2[volume <= 70]
        select e3.symbol as symbol1, e2[0].symbol as symbol2,
               e3.volume as volume
        insert into OutputStream;
    """, streams=STOCK12,
        partition="partition with (quantity of StockStream1, quantity of StockStream2)")
    for q in (2, 22):
        for sid, row in _Q14_FEED_BLOCK:
            rt.get_input_handler(sid).send(row + [q])
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOG", 65), ("WSO2", "DDD", 60),
                        ("DOX", None, 25)] * 2


def test_seq_partition_15_cross_capture_filter():
    m, rt, c = build("""
        from every e1=StockStream1[price >= 50 and volume > 100],
             e2=StockStream2[e1.symbol != 'AMBA']*,
             e3=StockStream2[volume <= 70]
        select e3.symbol as symbol1, e2[0].symbol as symbol2,
               e3.volume as volume
        insert into OutputStream;
    """, streams=STOCK12,
        partition="partition with (quantity of StockStream1, quantity of StockStream2)")
    for q in (10, 100):
        for sid, row in _Q14_FEED_BLOCK:
            rt.get_input_handler(sid).send(row + [q])
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOG", 65), ("DOX", None, 25)] * 2


def test_seq_partition_16_interleaved_keys():
    """testSequencePartitionQuery16: three quantity instances interleaved
    mid-feed — per-key chains stay independent."""
    m, rt, c = build("""
        from every e1=StockStream1, e2=StockStream2[e1.symbol != 'AMBA']*,
             e3=StockStream2[volume <= 70]
        select e3.symbol as symbol1, e2[0].symbol as symbol2,
               e3.volume as volume, e1.quantity as quantity
        insert into OutputStream;
    """, streams=STOCK12,
        partition="partition with (quantity of StockStream1, quantity of StockStream2)")
    s1 = rt.get_input_handler("StockStream1")
    s2 = rt.get_input_handler("StockStream2")
    s1.send(["IBM", 75.6, 105, 5])
    s2.send(["GOOG", 21.0, 81, 5])
    s2.send(["WSO2", 176.6, 65, 5])
    s1.send(["BIRT", 21.0, 81, 5])
    s1.send(["AMBA", 126.6, 165, 5])
    s1.send(["IBM", 75.6, 105, 155])
    s2.send(["GOOG", 21.0, 81, 155])
    s2.send(["WSO2", 176.6, 65, 155])
    s1.send(["BIRT", 21.0, 81, 155])
    s2.send(["DDD", 23.0, 181, 5])
    s2.send(["BIRT", 21.0, 86, 5])
    s2.send(["BIRT", 21.0, 82, 5])
    s2.send(["WSO2", 176.6, 60, 5])
    s1.send(["AMBA", 126.6, 165, 5])
    s2.send(["DOX", 16.2, 25, 5])
    s1.send(["AMBA", 126.6, 165, 155])
    s2.send(["DDD", 23.0, 181, 155])
    s2.send(["BIRT", 21.0, 86, 155])
    s2.send(["BIRT", 21.0, 82, 155])
    s2.send(["WSO2", 176.6, 60, 155])
    s1.send(["IBM", 75.6, 105, 55])
    s2.send(["GOOG", 21.0, 81, 55])
    s2.send(["WSO2", 176.6, 65, 55])
    s1.send(["BIRT", 21.0, 81, 55])
    s1.send(["AMBA", 126.6, 165, 55])
    s2.send(["DDD", 23.0, 181, 55])
    s2.send(["BIRT", 21.0, 86, 55])
    s2.send(["BIRT", 21.0, 82, 55])
    s2.send(["WSO2", 176.6, 60, 55])
    s1.send(["AMBA", 126.6, 165, 55])
    s2.send(["DOX", 16.2, 25, 55])
    s1.send(["AMBA", 126.6, 165, 155])
    s2.send(["DOX", 16.2, 25, 155])
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOG", 65, 5), ("WSO2", "GOOG", 65, 155),
                        ("DOX", None, 25, 5), ("WSO2", "GOOG", 65, 55),
                        ("DOX", None, 25, 55), ("DOX", None, 25, 155)]
