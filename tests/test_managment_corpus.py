"""Reference management corpus — scenarios ported from
``managment/{Validate,StartStop,State,Async,Sandbox}TestCase.java``."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.compiler.errors import (SiddhiParserException,
                                        SiddhiAppValidationException)
from siddhi_tpu.ops.expressions import CompileError

CREATION_ERRORS = (CompileError, SiddhiParserException,
                   SiddhiAppValidationException)


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


# ------------------------------------------------------- ValidateTestCase


def test_validate_accepts_valid_app():
    """validateTest1 (:45-63): a valid app validates without being
    registered or started."""
    m = SiddhiManager()
    m.validate_siddhi_app("""
        @app:name('validateTest')
        define stream cseEventStream (symbol string, price float, volume long);
        @info(name = 'query1')
        from cseEventStream[symbol is null]
        select symbol, price insert into outputStream;
    """)
    assert m.get_siddhi_app_runtime("validateTest") is None
    m.shutdown()


def test_validate_rejects_unknown_stream():
    """validateTest2 (:64-84): a query over an undefined stream fails
    validation."""
    m = SiddhiManager()
    with pytest.raises(CREATION_ERRORS):
        m.validate_siddhi_app("""
            @app:name('validateTest')
            define stream cseEventStream (symbol string, price float, volume long);
            @info(name = 'query1')
            from cseEventStreamA[symbol is null]
            select symbol, price insert into outputStream;
        """)
    m.shutdown()


def test_validate_substitutes_variables():
    """validateTest3 (:85-107): `${var}` in definitions resolves from the
    environment before validation."""
    import os

    os.environ["stream"] = "cseEventStream"
    try:
        SiddhiManager().validate_siddhi_app("""
            @app:name('validateTest')
            define stream ${stream} (symbol string, price float, volume long);
            @info(name = 'query1')
            from cseEventStream select symbol, price insert into outputStream;
        """)
    finally:
        del os.environ["stream"]


def test_validate_unresolved_variable_fails():
    """validateTest4 (:108-129): an unresolvable `${stream}` placeholder
    fails parsing."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().validate_siddhi_app("""
            @app:name('validateTest')
            define stream ${stream} (symbol string, price float, volume long);
            @info(name = 'query1')
            from cseEventStream select symbol, price insert into outputStream;
        """)


# ------------------------------------------------------ StartStopTestCase


STARTSTOP_APP = """
    define stream cseEventStream (symbol string, price float, volume int);
    define stream cseEventStream2 (symbol string, price float, volume int);
    @info(name = 'query1')
    from cseEventStream select 1 as eventFrom insert into outputStream;
    @info(name = 'query2')
    from cseEventStream2 select 2 as eventFrom insert into outputStream;
"""


def test_send_after_shutdown_raises():
    """startStopTest1 (:46-75): sending through a handler after shutdown
    raises."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STARTSTOP_APP)
    h = rt.get_input_handler("cseEventStream2")
    rt.start()
    m.shutdown()
    with pytest.raises(Exception):
        h.send(["WSO2", 55.6, 100])


def test_two_queries_share_output_stream():
    """startStopTest2 (:77-...): both queries publish into one output
    stream; each source stream's constant marker arrives."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STARTSTOP_APP)
    c = Collector()
    rt.add_callback("outputStream", c)
    rt.get_input_handler("cseEventStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("cseEventStream2").send(["IBM", 75.6, 100])
    m.shutdown()
    assert sorted(e.data[0] for e in c.events) == [1, 2]


# ---------------------------------------------------------- StateTestCase


def test_query_statefulness_flags():
    """stateTest (:45-100): a plain projection is stateless; windowed,
    aggregating, and rate-limited queries are stateful."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream select * insert all events into outputStream;
        @info(name = 'query2')
        from cseEventStream#window.timeBatch(1 sec) select * insert all events into outputStream;
        @info(name = 'query3')
        from cseEventStream select sum(price) as total insert all events into outputStream1;
        @info(name = 'query4')
        from cseEventStream select * output every 5 min insert all events into outputStream;
    """)
    flags = [q.is_stateful() for q in rt.get_queries()]
    assert flags == [False, True, True, True]
    m.shutdown()


# ---------------------------------------------------------- AsyncTestCase


def test_app_level_async_rejected():
    """asyncTest1/2 (:48-95): @app:async (with or without parameters) is
    invalid — @Async belongs on streams."""
    for ann in ("@app:async", "@app:async(buffer.size='2')"):
        with pytest.raises(CREATION_ERRORS):
            SiddhiManager().create_siddhi_app_runtime(f"""
                {ann}
                define stream cseEventStream (symbol string, price float, volume int);
                @info(name = 'query1')
                from cseEventStream[70 > price] select * insert into outputStream;
            """)


def test_stream_level_async_delivers():
    """asyncTest3 (:97-160): @async buffering on a stream still delivers
    every event to a slow consumer."""
    import time

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @async(buffer.size='2')
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream[70 > price] select * insert into outputStream;
    """)
    c = Collector()
    rt.add_callback("outputStream", c)
    h = rt.get_input_handler("cseEventStream")
    for row in [["WSO2", 55.6, 100], ["IBM", 9.6, 100], ["FB", 7.6, 100],
                ["GOOG", 5.6, 100], ["WSO2", 15.6, 100]]:
        h.send(row)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(c.events) < 5:
        time.sleep(0.05)
    m.shutdown()
    assert len(c.events) == 5


# -------------------------------------------------------- SandboxTestCase


def test_sandbox_strips_external_transports():
    """sandboxTest1 (:54-120): createSandboxSiddhiAppRuntime keeps only the
    in-memory transports and drops @store, so the app runs fully
    in-process."""
    m = SiddhiManager()
    rt = m.create_sandbox_siddhi_app_runtime("""
        @source(type='foo')
        @source(type='foo1')
        @source(type='inMemory', topic='myTopic')
        define stream StockStream (symbol string, price float, vol long);
        @sink(type='foo1')
        @sink(type='inMemory', topic='myTopic1')
        define stream DeleteStockStream (symbol string, price float, vol long);
        @store(type='rdbms')
        define table StockTable (symbol string, price float, volume long);
        define stream CountStockStream (symbol string);
        @info(name = 'query1')
        from StockStream select symbol, price, vol as volume insert into StockTable;
        @info(name = 'query2')
        from DeleteStockStream[vol >= 100]
        delete StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CountStockStream join StockTable
        on CountStockStream.symbol == StockTable.symbol
        select CountStockStream.symbol as symbol
        insert into CountResultsStream;
    """)
    assert len(rt.source_runtimes) == 1
    assert len(rt.sink_runtimes) == 1
    # the rdbms @store was stripped: plain in-memory table CRUD works
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 100])
    rt.get_input_handler("DeleteStockStream").send(["IBM", 75.6, 100])
    rows = rt.query("from StockTable select *")
    assert [e.data[0] for e in rows] == ["WSO2"]
    m.shutdown()
