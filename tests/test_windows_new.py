"""hopping / cron / expression / expressionBatch windows + the dense keyed
session window — expectations mirror reference
``{Hoping,Cron,Expression,ExpressionBatch,Session}WindowProcessor`` tests.
"""

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.query.callback import QueryCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []
        self.expired = []

    def receive(self, events):
        for e in events:
            (self.expired if e.is_expired else self.events).append(e)


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []       # in_events
        self.expired = []      # remove_events

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


def build(app, out="OutStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


def build_q(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback("q", q)
    return m, rt, q


STREAM = "@app:playback define stream S (sym string, v int);\n"


def test_hopping_window_emits_trailing_window_every_hop():
    m, rt, c = build(STREAM + """
        from S#window.hopping(2 sec, 1 sec)
        select sym, v insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["a", 1])
    h.send(1400, ["b", 2])
    h.send(2100, ["c", 3])    # first hop boundary passed at 2000
    # the hop at ~2000 emits events within (0, 2000]: a, b
    got1 = [tuple(e.data) for e in c.events]
    h.send(3200, ["d", 4])    # hop at 3000: trailing 2s = (1200, 3200]: b, c
    got2 = [tuple(e.data) for e in c.events]
    m.shutdown()
    assert got1 == [("a", 1), ("b", 2)]
    assert got2 == got1 + [("b", 2), ("c", 3)]


def test_cron_window_flushes_on_schedule():
    m, rt, c = build(STREAM + """
        from S#window.cron('*/2 * * * * ?')
        select sym, v insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(500, ["a", 1])
    h.send(900, ["b", 2])
    h.send(2500, ["c", 3])    # the */2 fire at 2000 flushes {a, b}
    got1 = [tuple(e.data) for e in c.events]
    h.send(4500, ["d", 4])    # fire at 4000 flushes {c}
    got2 = [tuple(e.data) for e in c.events]
    m.shutdown()
    assert got1 == [("a", 1), ("b", 2)]
    assert got2 == got1 + [("c", 3)]


def test_expression_window_count_retention():
    # expression('count() <= 2') behaves as a sliding length(2) window
    m, rt, c = build_q(STREAM + """
        @info(name='q')
        from S#window.expression('count() <= 2')
        select sym, v insert all events into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["a", 1])
    h.send(1100, ["b", 2])
    h.send(1200, ["c", 3])    # evicts a
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("a", 1), ("b", 2), ("c", 3)]
    assert [tuple(e.data) for e in c.expired] == [("a", 1)]


def test_expression_window_timestamp_span():
    # retain while the window spans < 1 sec of event time
    m, rt, c = build_q(STREAM + """
        @info(name='q')
        from S#window.expression(
            'eventTimestamp(last) - eventTimestamp(first) < 1000')
        select sym, v insert all events into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["a", 1])
    h.send(1500, ["b", 2])
    h.send(2300, ["c", 3])    # span(a..c)=1300: a evicted; span(b..c)=800 ok
    m.shutdown()
    assert [tuple(e.data) for e in c.expired] == [("a", 1)]


def test_expression_batch_window():
    # flush the collected batch whenever it would exceed 2 rows
    m, rt, c = build(STREAM + """
        from S#window.expressionBatch('count() <= 2')
        select sym, v insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["a", 1])
    h.send(1100, ["b", 2])
    assert c.events == []     # still collecting
    h.send(1200, ["c", 3])    # breaks: flush {a, b}; window restarts at c
    got = [tuple(e.data) for e in c.events]
    m.shutdown()
    assert got == [("a", 1), ("b", 2)]


def test_keyed_session_window_in_partition():
    m, rt, c = build_q("""
        @app:playback
        define stream S (k string, v int);
        partition with (k of S)
        begin
          @info(name='q')
          from S#window.session(1 sec)
          select k, v insert all events into OutStream;
        end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["p1", 1])
    h.send(1500, ["p1", 2])    # same session (gap 500 < 1000)
    h.send(1600, ["p2", 3])
    h.send(3000, ["p1", 4])    # p1 idle 1500ms: session {1,2} expires first
    m.shutdown()
    cur = [tuple(e.data) for e in c.events]
    exp = sorted(tuple(e.data) for e in c.expired)
    assert cur == [("p1", 1), ("p1", 2), ("p2", 3), ("p1", 4)]
    # p1's first session expired (via the p1 gap break); p2 expires at
    # shutdown-time only if a timer fired — assert at least p1's rows
    assert ("p1", 1) in exp and ("p1", 2) in exp


def test_keyed_session_timer_sweep():
    m, rt, c = build_q("""
        @app:playback
        define stream S (k string, v int);
        define stream Tick (k string, v int);
        partition with (k of S, k of Tick)
        begin
          @info(name='q')
          from S#window.session(1 sec)
          select k, v insert all events into OutStream;
        end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["p1", 1])
    h.send(1100, ["p2", 2])
    # advancing the playback clock fires the scheduler's session timers
    h.send(2500, ["p3", 3])
    exp = sorted(tuple(e.data) for e in c.expired)
    m.shutdown()
    assert ("p1", 1) in exp and ("p2", 2) in exp


def test_keyed_length_batch_in_partition():
    m, rt, c = build_q("""
        define stream S (k string, v int);
        partition with (k of S)
        begin
          @info(name='q')
          from S#window.lengthBatch(3)
          select k, v insert all events into OutStream;
        end;
    """)
    h = rt.get_input_handler("S")
    for v in (1, 2):
        h.send(["p1", v])
    h.send(["p2", 10])
    assert c.events == []          # no key completed a batch yet
    h.send(["p1", 3])              # p1's 3rd event: flush {1,2,3}
    got1 = [tuple(e.data) for e in c.events]
    for v in (4, 5, 6):
        h.send(["p1", v])          # second p1 batch: prev {1,2,3} expires
    got2 = [tuple(e.data) for e in c.events]
    exp2 = [tuple(e.data) for e in c.expired]
    h.send(["p2", 11]); h.send(["p2", 12])   # p2 completes independently
    got3 = [tuple(e.data) for e in c.events]
    m.shutdown()
    assert got1 == [("p1", 1), ("p1", 2), ("p1", 3)]
    assert got2 == got1 + [("p1", 4), ("p1", 5), ("p1", 6)]
    assert exp2 == [("p1", 1), ("p1", 2), ("p1", 3)]
    assert got3 == got2 + [("p2", 10), ("p2", 11), ("p2", 12)]


def test_keyed_length_batch_multiple_flushes_one_chunk():
    import numpy as np

    m, rt, c = build_q("""
        define stream S (k string, v int);
        partition with (k of S)
        begin
          @info(name='q')
          from S#window.lengthBatch(2)
          select k, v insert all events into OutStream;
        end;
    """)
    h = rt.get_input_handler("S")
    # one columnar chunk completing TWO batches for p1 and one for p2
    h.send_columns(
        {"k": np.array(["p1", "p1", "p2", "p1", "p1", "p2"], dtype=object),
         "v": np.array([1, 2, 9, 3, 4, 8], np.int32)},
        timestamps=np.arange(6, dtype=np.int64))
    cur = [tuple(e.data) for e in c.events]
    exp = [tuple(e.data) for e in c.expired]
    m.shutdown()
    assert cur == [("p1", 1), ("p1", 2), ("p1", 3), ("p1", 4),
                   ("p2", 9), ("p2", 8)]
    # p1's second flush expires its first batch, all inside the chunk
    assert exp == [("p1", 1), ("p1", 2)]


def test_keyed_time_batch_in_partition():
    m, rt, c = build_q("""
        @app:playback
        define stream S (k string, v int);
        partition with (k of S)
        begin
          @info(name='q')
          from S#window.timeBatch(1 sec)
          select k, v insert all events into OutStream;
        end;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, ["p1", 1])      # p1's boundary: 2000
    h.send(1400, ["p1", 2])
    h.send(1800, ["p2", 9])      # p2's boundary: 2800
    assert c.events == []
    h.send(2100, ["p1", 3])      # clock passes p1's boundary: flush {1,2}
    got1 = [tuple(e.data) for e in c.events]
    h.send(3300, ["p1", 4])      # p1 flush {3}; prev {1,2} expires; p2 due too
    got2 = [tuple(e.data) for e in c.events]
    exp2 = [tuple(e.data) for e in c.expired]
    m.shutdown()
    assert got1 == [("p1", 1), ("p1", 2)]
    assert ("p1", 3) in got2 and ("p2", 9) in got2
    assert exp2 == [("p1", 1), ("p1", 2)]
