"""externalTimeBatch timeout and session allowedLatency — reference
ExternalTimeBatchWindowProcessor timer path (flush on idle, append on the
next crossing) and SessionWindowProcessor expired-session container."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.ops.expressions import CompileError


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


# ------------------------------------------------- externalTimeBatch timeout


ETB = """@app:playback define stream S (ets long, v int);
from S#window.externalTimeBatch(ets, 10 sec, 0, 1 sec)
select sum(v) as total insert into OutStream;
"""


def test_etb_timeout_flushes_idle_batch():
    m, rt, c = build(ETB)
    h = rt.get_input_handler("S")
    h.send(1000, [1000, 5])
    h.send(1200, [1200, 7])
    # no event-time crossing; playback clock advances past 1200+1000 via a
    # later event on another... use a timer: advance the clock by sending
    # an event far in wall-clock but same window? The playback clock drives
    # the scheduler; the scheduled 2200 timer fires when time passes it.
    h.send(2500, [1300, 0])       # arrival advances runtime clock past 2200
    m.shutdown()
    totals = [e.data[0] for e in c.events]
    # the timer (scheduled at first arrival +1s) flushed {5,7}; the third
    # event then joined the still-open window
    assert 12 in totals


def test_etb_event_crossing_appends_after_timeout_flush():
    m, rt, c = build(ETB)
    h = rt.get_input_handler("S")
    h.send(1000, [1000, 5])
    h.send(2500, [1200, 7])       # clock passed 2000: timeout flush {5}, 7 joins open window
    h.send(2600, [11000, 1])      # event-time crossing: appends {7}, new batch {1}
    m.shutdown()
    totals = [e.data[0] for e in c.events]
    # timeout flush outputs the partial batch (5); the append flush
    # continues the SAME batch without a RESET, so the running sum now
    # covers {5, 7} — the whole logical batch
    assert totals == [5, 12]


def test_etb_without_timeout_unchanged():
    m, rt, c = build("""@app:playback define stream S (ets long, v int);
        from S#window.externalTimeBatch(ets, 10 sec)
        select sum(v) as total insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, [1000, 5])
    h.send(1100, [1200, 7])
    h.send(1200, [12000, 9])      # crossing flushes {5,7}
    m.shutdown()
    assert [e.data[0] for e in c.events] == [12]


# --------------------------------------------------- session allowedLatency


SESSION = """@app:playback define stream S (user string, v int);
from S#window.session(2 sec, user, 1 sec)
select user, v insert all events into OutStream;
"""


def test_session_latency_delays_expiry_and_revives():
    m, rt, c = build(SESSION)
    h = rt.get_input_handler("S")
    h.send(1000, ["u1", 1])
    # gap passes at 3000; latency holds the session until 4000
    h.send(3500, ["u2", 9])     # advances clock: u1 parked, not emitted yet
    n_at_3500 = len(c.events)
    h.send(3700, ["u1", 2])     # ON-TIME past the gap: starts a NEW session
    h.send(8000, ["u2", 0])     # clock far ahead: everything expires
    m.shutdown()
    data = [tuple(e.data) for e in c.events]
    assert data.count(("u1", 1)) == 2 and data.count(("u1", 2)) == 2
    # at 3500 only pass-through currents had been emitted (no u1 expiry)
    assert n_at_3500 == 2
    # the parked session {1} expires at its due (4000) BEFORE the fresh
    # session {2} does (6700) — they must NOT fuse into one emission
    # (reference moveCurrentSessionToPreviousSession, not a revive)
    exp1 = max(i for i, d in enumerate(data) if d == ("u1", 1))
    exp2 = max(i for i, d in enumerate(data) if d == ("u1", 2))
    assert exp1 < exp2


def test_session_latency_timers_fire_at_scheduled_times():
    # a playback clock jump releases each pending session timer AT its
    # scheduled time (Scheduler.sendTimerEvents): parked {1} emits at its
    # due 4000, u2 at 6500, the fresh session {2} at 6700 — three distinct
    # expiry timestamps, never one fused sweep at the jumped-to clock
    m, rt, c = build(SESSION)
    h = rt.get_input_handler("S")
    h.send(1000, ["u1", 1])
    h.send(3500, ["u2", 9])
    h.send(3700, ["u1", 2])
    h.send(9000, ["u2", 0])
    m.shutdown()
    exp = [(e.timestamp, tuple(e.data)) for e in c.events[3:-1]]
    assert exp == [(4000, ("u1", 1)), (6500, ("u2", 9)), (6700, ("u1", 2))]


def test_session_latency_late_event_with_empty_current_starts_new():
    # reference processEventChunk: with current EMPTY (just parked), a late
    # event starts a NEW current session — it does NOT rejoin previous
    from siddhi_tpu.core.event import Event

    m, rt, c = build(SESSION)
    h = rt.get_input_handler("S")
    h.send(1000, ["u1", 1])
    h.send(3500, ["u2", 9])        # u1 {1} parks as previous (due 4000)
    h.send([Event(timestamp=2500, data=["u1", 2])])
    h.send(10000, ["u2", 0])
    m.shutdown()
    u1_exp = [e.timestamp for e in c.events if e.data[0] == "u1"][2:]
    # {1} at its due 4000; {2} (span 2500-4500, hold to 5500) at 5500
    assert u1_exp == [4000, 5500]


def test_session_latency_bridging_late_event_merges_all():
    # reference addLateEvent + mergeWindows: a late event landing within
    # gap of the live current session pulls its start back far enough to
    # bridge to the parked previous — all rows fuse into ONE emission
    from siddhi_tpu.core.event import Event

    m, rt, c = build(SESSION)
    h = rt.get_input_handler("S")
    h.send(1000, ["u1", 1])
    h.send(3500, ["u2", 9])        # u1 {1} parks (span 1000-3000, due 4000)
    h.send(3600, ["u1", 2])        # on-time: fresh current {start 3600}
    h.send([Event(timestamp=3400, data=["u1", 3])])   # late, bridges
    h.send(10000, ["u2", 0])
    m.shutdown()
    u1_exp = [e.timestamp for e in c.events if e.data[0] == "u1"][3:]
    assert len(u1_exp) == 3 and len(set(u1_exp)) == 1   # one merged chunk


def test_session_latency_expires_after_hold():
    m, rt, c = build(SESSION)
    h = rt.get_input_handler("S")
    h.send(1000, ["u1", 1])
    h.send(4500, ["u2", 9])     # clock past 4000: u1 expired after hold
    m.shutdown()
    u1 = [e for e in c.events if e.data[0] == "u1"]
    assert len(u1) == 2          # current + expired emission


def test_session_latency_revive_bounded_by_due_same_batch():
    # two same-key events far apart delivered in ONE batch must still
    # split into two sessions (the revive checks event time vs due)
    from siddhi_tpu.core.event import Event

    m, rt, c = build(SESSION)
    h = rt.get_input_handler("S")
    h.send([Event(timestamp=1000, data=["u1", 1]),
            Event(timestamp=10000, data=["u1", 2])])
    h.send(15000, ["u2", 0])    # drain timers
    m.shutdown()
    u1 = [tuple(e.data) for e in c.events if e.data[0] == "u1"]
    # each row appears twice: CURRENT on arrival + EXPIRED with its own
    # session (not one merged session)
    assert u1.count(("u1", 1)) == 2 and u1.count(("u1", 2)) == 2


def test_etb_timeout_flush_then_double_crossing_expires_prev():
    # rows flushed by the idle timer must still emit EXPIRED at the NEXT
    # actual flush — a crossing that jumps several window boundaries is
    # still ONE flush (the reference snaps endTime to cover the event,
    # ExternalTimeBatchWindowProcessor.java:285-297, and never synthesizes
    # empty intermediate batches)
    m, rt, c = build("""@app:playback define stream S (ets long, v int);
        from S#window.externalTimeBatch(ets, 10 sec, 0, 1 sec)
        select v insert all events into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(1000, [1000, 5])
    h.send(2500, [1100, 7])      # timer flush {5} happened; 7 appends
    h.send(2600, [25000, 9])     # single flush (append {7}); 9 accumulates
    fives_before = [e for e in c.events if e.data[0] == 5]
    h.send(4000, [26000, 1])     # clock passes 3600: timeout flush of {9}
    m.shutdown()
    # the 3600 timeout flush emits EXPIRED {5, 7} before CURRENT {9}
    fives = [e for e in c.events if e.data[0] == 5]
    assert len(fives_before) == 1          # no premature expiry at 2600
    assert len(fives) == 2
    assert [e.data[0] for e in c.events if e.data[0] == 7] == [7, 7]


def test_session_latency_validation():
    with pytest.raises(CompileError, match="allowedLatency"):
        build("""define stream S (user string, v int);
            from S#window.session(1 sec, user, 2 sec)
            select user insert into OutStream;
        """)


def test_external_time_uses_the_named_attribute():
    # the clock attribute, not the event timestamp, drives expiry
    m, rt, c = build("""@app:playback define stream S (ets long, v int);
        from S#window.externalTime(ets, 1 sec)
        select sum(v) as total insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(100, [1000, 1])
    h.send(200, [2500, 2])   # attr clock passed 2000: row 1 expires
    m.shutdown()
    assert [e.data[0] for e in c.events] == [1, 2]


def test_keyed_external_time_uses_the_named_attribute():
    m, rt, c = build("""@app:playback define stream S (sym string, ets long, v int);
        partition with (sym of S) begin
        from S#window.externalTime(ets, 1 sec)
        select sym, sum(v) as total insert into OutStream; end;
    """)
    h = rt.get_input_handler("S")
    h.send(100, ["A", 1000, 1])
    h.send(150, ["B", 1000, 5])
    h.send(200, ["A", 2500, 2])   # A's attr clock expires A's row 1
    h.send(250, ["B", 1100, 7])   # B's clock hasn't passed 2000
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("A", 1), ("B", 5), ("A", 2), ("B", 12)]


def test_external_time_attribute_clock_within_one_chunk():
    # both events in ONE chunk: in-batch expiry must use the clock attr
    from siddhi_tpu.core.event import Event

    m, rt, c = build("""@app:playback define stream S (ets long, v int);
        from S#window.externalTime(ets, 1 sec)
        select sum(v) as total insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send([Event(timestamp=100, data=[1000, 1]),
            Event(timestamp=200, data=[2500, 2])])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [1, 2]
