"""M2 golden tests: window processors.

Mirrors reference ``query/window/*TestCase.java`` behaviors: emission order
(EXPIRED-before-CURRENT for sliding, [expired, reset, current] flushes for
batch windows), batch-window single-output-per-flush with aggregators, and
playback-driven time windows (``PlaybackTestCase.java`` is the determinism
device).
"""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import Event
from siddhi_tpu.core.query.callback import QueryCallback
from siddhi_tpu.core.stream.output.stream_callback import StreamCallback


class Collect(StreamCallback):
    def __init__(self):
        self.events = []

    def receive(self, events):
        self.events.extend(events)


class QCollect(QueryCallback):
    def __init__(self):
        self.in_events = []
        self.remove_events = []
        self.chunks = []

    def receive(self, timestamp, in_events, remove_events):
        self.chunks.append((timestamp, in_events, remove_events))
        if in_events:
            self.in_events.extend(in_events)
        if remove_events:
            self.remove_events.extend(remove_events)


def test_length_window_sliding_expiry():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (symbol string, v int);
        @info(name='q')
        from S#window.length(2) select symbol, v insert all events into Out;
        """
    )
    q = QCollect()
    rt.add_callback("q", q)
    h = rt.get_input_handler("S")
    for i, sym in enumerate(["a", "b", "c", "d"]):
        h.send(100 + i, [sym, i])
    assert [e.data for e in q.in_events] == [["a", 0], ["b", 1], ["c", 2], ["d", 3]]
    # window of 2: c evicts a, d evicts b
    assert [e.data for e in q.remove_events] == [["a", 0], ["b", 1]]
    manager.shutdown()


def test_length_window_running_avg():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (price double);
        from S#window.length(3) select avg(price) as ap insert into Out;
        """
    )
    cb = Collect()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    for p in [10.0, 20.0, 30.0, 40.0]:
        h.send([p])
    # running avg over sliding window of 3:
    # 10; (10+20)/2; (10+20+30)/3; after expiry of 10: (20+30+40)/3
    assert [e.data[0] for e in cb.events] == [10.0, 15.0, 20.0, 30.0]
    manager.shutdown()


def test_length_window_batch_send_interleaving():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (v int);
        from S#window.length(2) select v insert all events into Out;
        """
    )
    cb = Collect()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send([Event(timestamp=i, data=[i]) for i in range(5)])
    # per-arrival interleave (EXPIRED re-published as CURRENT on the stream):
    # 0,1 fill; then [exp 0, cur 2], [exp 1, cur 3], [exp 2, cur 4]
    assert [e.data[0] for e in cb.events] == [0, 1, 0, 2, 1, 3, 2, 4]
    manager.shutdown()


def test_length_batch_window_flushes():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (v int);
        @info(name='q')
        from S#window.lengthBatch(3) select v insert all events into Out;
        """
    )
    q = QCollect()
    rt.add_callback("q", q)
    h = rt.get_input_handler("S")
    for i in range(7):
        h.send([i])
    # flush 1 after v=2: currents 0,1,2 ; flush 2 after v=5: expired 0,1,2 + currents 3,4,5
    assert [e.data[0] for e in q.in_events] == [0, 1, 2, 3, 4, 5]
    assert [e.data[0] for e in q.remove_events] == [0, 1, 2]
    manager.shutdown()


def test_length_batch_sum_single_output_per_flush():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (v int);
        from S#window.lengthBatch(3) select sum(v) as total insert into Out;
        """
    )
    cb = Collect()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    for i in range(1, 10):
        h.send([i])
    # one output per flush: 1+2+3, 4+5+6, 7+8+9
    assert [e.data[0] for e in cb.events] == [6, 15, 24]
    manager.shutdown()


def test_length_batch_multiple_flushes_in_one_chunk():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (v int);
        from S#window.lengthBatch(2) select sum(v) as total insert into Out;
        """
    )
    cb = Collect()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send([Event(timestamp=i, data=[i]) for i in [1, 2, 3, 4, 5]])
    # flushes [1,2] and [3,4] happen inside one device batch; 5 buffered
    assert [e.data[0] for e in cb.events] == [3, 7]
    h.send([Event(timestamp=9, data=[6])])
    assert [e.data[0] for e in cb.events] == [3, 7, 11]
    manager.shutdown()


def test_time_batch_playback():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        @app:playback
        define stream S (symbol string, v int);
        from S#window.timeBatch(1 sec) select symbol, sum(v) as total insert into Out;
        """
    )
    cb = Collect()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send(1000, ["A", 1])
    h.send(1100, ["A", 2])
    assert cb.events == []  # nothing until the boundary
    h.send(2100, ["B", 5])  # crossing 2000 flushes the first batch
    assert [e.data for e in cb.events] == [["A", 3]]
    h.send(3200, ["C", 7])  # crossing 3000 flushes [B,5]
    assert [e.data for e in cb.events] == [["A", 3], ["B", 5]]
    manager.shutdown()


def test_time_window_playback_expiry():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        @app:playback
        define stream S (symbol string, v int);
        @info(name='q')
        from S#window.time(1 sec) select symbol, v insert all events into Out;
        """
    )
    q = QCollect()
    rt.add_callback("q", q)
    h = rt.get_input_handler("S")
    h.send(1000, ["A", 1])
    assert [e.data for e in q.in_events] == [["A", 1]]
    assert q.remove_events == []
    h.send(2500, ["B", 2])  # timer at 2000 fires first, expiring A
    assert [e.data for e in q.remove_events] == [["A", 1]]
    assert [e.data for e in q.in_events] == [["A", 1], ["B", 2]]
    manager.shutdown()


def test_time_window_running_sum_with_expiry():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        @app:playback
        define stream S (v int);
        from S#window.time(1 sec) select sum(v) as s insert into Out;
        """
    )
    cb = Collect()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send(1000, [10])
    h.send(1500, [20])
    h.send(2200, [30])  # 10 expired at 2000 (before this event)
    assert [e.data[0] for e in cb.events] == [10, 30, 50]
    manager.shutdown()


def test_external_time_window():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (ts long, v int);
        @info(name='q')
        from S#window.externalTime(ts, 1 sec) select v insert all events into Out;
        """
    )
    q = QCollect()
    rt.add_callback("q", q)
    h = rt.get_input_handler("S")
    h.send(1000, [1000, 1])
    h.send(1500, [1500, 2])
    h.send(2100, [2100, 3])  # evicts the ts=1000 event (1000 + 1000 <= 2100)
    assert [e.data[-1] for e in q.in_events] == [1, 2, 3]
    assert [e.data[-1] for e in q.remove_events] == [1]
    manager.shutdown()


def test_batch_window():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (v int);
        @info(name='q')
        from S#window.batch() select v insert all events into Out;
        """
    )
    q = QCollect()
    rt.add_callback("q", q)
    h = rt.get_input_handler("S")
    h.send([Event(timestamp=1, data=[1]), Event(timestamp=1, data=[2])])
    h.send([Event(timestamp=2, data=[3])])
    assert [e.data[0] for e in q.in_events] == [1, 2, 3]
    # second chunk expires the first
    assert [e.data[0] for e in q.remove_events] == [1, 2]
    manager.shutdown()


def test_post_window_having_on_window_agg():
    manager = SiddhiManager()
    rt = manager.create_siddhi_app_runtime(
        """
        define stream S (symbol string, price double);
        from S#window.length(2)
        select symbol, avg(price) as ap
        group by symbol
        having ap > 10.0
        insert into Out;
        """
    )
    cb = Collect()
    rt.add_callback("Out", cb)
    h = rt.get_input_handler("S")
    h.send(["A", 5.0])     # avg 5 -> filtered
    h.send(["A", 30.0])    # avg 17.5 -> out
    h.send(["A", 40.0])    # 5 expires: avg (30+40)/2=35 -> expired row dropped (current only), current avg 35
    assert [e.data for e in cb.events] == [["A", 17.5], ["A", 35.0]]
    manager.shutdown()
