"""Reference named-window corpus — scenarios ported verbatim from
``window/WindowDefinitionTestCase.java`` (definition/validation surface)
and ``store/OnDemandQueryWindowTestCase.java`` (on-demand reads over
`define window` contents)."""

import pytest

from siddhi_tpu import SiddhiManager


@pytest.mark.parametrize("defn", [
    "define window CheckStockWindow(symbol string) length(1); ",
    "define window CheckStockWindow(symbol string) length(1) "
    "output all events; ",
    "define window CheckStockWindow(symbol string) length(1) "
    "output expired events; ",
    "define window CheckStockWindow(symbol string) length(1) "
    "output current events; ",
])
def test_window_definitions_compile(defn):
    """testEventWindow1-4 (WindowDefinitionTestCase:35-85)."""
    m = SiddhiManager()
    m.create_siddhi_app_runtime(defn)
    m.shutdown()


@pytest.mark.parametrize("defn", [
    # testEventWindow5/7: dangling `output`
    "define window CheckStockWindow(symbol string) length(1) output; ",
    "define window CheckStockWindow(symbol string) output; ",
    # testEventWindow6: sum(val) is not a window processor
    "define window CheckStockWindow(symbol string, val int) sum(val); ",
])
def test_window_definitions_rejected(defn):
    """testEventWindow5/6/7 (:86-121)."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(defn)
    m.shutdown()


def test_insert_into_window_schema_mismatch():
    """testEventWindow8 (:122-146): inserting (int, string) into a window
    defined (int, long, long) fails at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream InStream (meta_tenantId int, eventId string);\n"
            "define window countWindow (meta_tenantId int, "
            "batchEndTime long, timestamp long) "
            "externalTimeBatch(batchEndTime, 1 sec, 0, 10 sec, true);\n"
            "from InStream select meta_tenantId, eventId "
            "insert into countStream;\n"
            "from countStream select meta_tenantId, eventId "
            "insert into countWindow;")
    m.shutdown()


def _window_app(length):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream StockStream (symbol string, price float, "
        "volume long); "
        f"define window StockWindow (symbol string, price float, "
        f"volume long) length({length}); "
        "@info(name = 'query1') from StockStream insert into StockWindow ;")
    rt.start()
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 100])
    h.send(["WSO2", 57.6, 100])
    return m, rt


def test_on_demand_window_reads():
    """OnDemandQueryWindowTestCase test1 (:47-91): bare reads, constant
    and arithmetic `on` conditions over the retained rows."""
    m, rt = _window_app(2)
    events = rt.query("from StockWindow ")
    assert len(events) == 2           # length(2) retains the last two
    events = rt.query("from StockWindow on price > 75 ")
    assert len(events) == 1
    events = rt.query("from StockWindow on price > volume*3/4  ")
    assert len(events) == 1
    m.shutdown()


def test_on_demand_window_projection_and_group():
    """OnDemandQueryWindowTestCase test2 (:93-135): projections and
    group-by over window contents."""
    m, rt = _window_app(3)
    events = rt.query("from StockWindow on price > 75 "
                      "select symbol, volume ")
    assert len(events) == 1 and len(events[0].data) == 2
    events = rt.query("from StockWindow on price > 5 "
                      "select symbol, volume group by symbol ")
    assert len(events) == 2
    m.shutdown()
