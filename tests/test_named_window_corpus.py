"""Reference named-window corpus — scenarios ported verbatim from
``window/WindowDefinitionTestCase.java`` (definition/validation surface)
and ``store/OnDemandQueryWindowTestCase.java`` (on-demand reads over
`define window` contents)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback


@pytest.mark.parametrize("defn", [
    "define window CheckStockWindow(symbol string) length(1); ",
    "define window CheckStockWindow(symbol string) length(1) "
    "output all events; ",
    "define window CheckStockWindow(symbol string) length(1) "
    "output expired events; ",
    "define window CheckStockWindow(symbol string) length(1) "
    "output current events; ",
])
def test_window_definitions_compile(defn):
    """testEventWindow1-4 (WindowDefinitionTestCase:35-85)."""
    m = SiddhiManager()
    m.create_siddhi_app_runtime(defn)
    m.shutdown()


@pytest.mark.parametrize("defn", [
    # testEventWindow5/7: dangling `output`
    "define window CheckStockWindow(symbol string) length(1) output; ",
    "define window CheckStockWindow(symbol string) output; ",
    # testEventWindow6: sum(val) is not a window processor
    "define window CheckStockWindow(symbol string, val int) sum(val); ",
])
def test_window_definitions_rejected(defn):
    """testEventWindow5/6/7 (:86-121)."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(defn)
    m.shutdown()


def test_insert_into_window_schema_mismatch():
    """testEventWindow8 (:122-146): inserting (int, string) into a window
    defined (int, long, long) fails at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream InStream (meta_tenantId int, eventId string);\n"
            "define window countWindow (meta_tenantId int, "
            "batchEndTime long, timestamp long) "
            "externalTimeBatch(batchEndTime, 1 sec, 0, 10 sec, true);\n"
            "from InStream select meta_tenantId, eventId "
            "insert into countStream;\n"
            "from countStream select meta_tenantId, eventId "
            "insert into countWindow;")
    m.shutdown()


def _window_app(length):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream StockStream (symbol string, price float, "
        "volume long); "
        f"define window StockWindow (symbol string, price float, "
        f"volume long) length({length}); "
        "@info(name = 'query1') from StockStream insert into StockWindow ;")
    rt.start()
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 100])
    h.send(["WSO2", 57.6, 100])
    return m, rt


def test_on_demand_window_reads():
    """OnDemandQueryWindowTestCase test1 (:47-91): bare reads, constant
    and arithmetic `on` conditions over the retained rows."""
    m, rt = _window_app(2)
    events = rt.query("from StockWindow ")
    assert len(events) == 2           # length(2) retains the last two
    events = rt.query("from StockWindow on price > 75 ")
    assert len(events) == 1
    events = rt.query("from StockWindow on price > volume*3/4  ")
    assert len(events) == 1
    m.shutdown()


def test_on_demand_window_projection_and_group():
    """OnDemandQueryWindowTestCase test2 (:93-135): projections and
    group-by over window contents."""
    m, rt = _window_app(3)
    events = rt.query("from StockWindow on price > 75 "
                      "select symbol, volume ")
    assert len(events) == 1 and len(events[0].data) == 2
    events = rt.query("from StockWindow on price > 5 "
                      "select symbol, volume group by symbol ")
    assert len(events) == 2
    m.shutdown()


class _QC(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


def test_named_length_window_under_capacity():
    """testLengthWindow1 (window/LengthWindowTestCase:60-94): fewer events
    than the window size — only CURRENT emissions, in arrival order."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream cseEventStream (symbol string, price float, "
        "volume int); "
        "define window cseWindow (symbol string, price float, volume int) "
        "length(4) output all events; "
        "@info(name = 'query1') from cseEventStream "
        "select symbol,price,volume insert into cseWindow ;"
        "@info(name = 'query2') from cseWindow insert into outputStream ;")
    q = _QC()
    rt.add_callback("query2", q)
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    h.send(["IBM", 700.0, 0])
    h.send(["WSO2", 60.5, 1])
    m.shutdown()
    assert [e.data[2] for e in q.events] == [0, 1]
    assert q.expired == []


def test_named_length_window_over_capacity():
    """testLengthWindow2 (:96-145): past the window size each insert also
    expires the oldest — 6 current + 2 expired for 6 sends into
    length(4), expirations starting at the 5th event."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream cseEventStream (symbol string, price float, "
        "volume int); "
        "define window cseWindow (symbol string, price float, volume int) "
        "length(4) output all events; "
        "@info(name = 'query1') from cseEventStream "
        "select symbol,price,volume insert into cseWindow ;"
        "@info(name = 'query2') from cseWindow "
        "insert all events into outputStream ;")
    q = _QC()
    rt.add_callback("query2", q)
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    for i in range(1, 7):
        h.send(["IBM" if i % 2 else "WSO2", 700.0, i])
    m.shutdown()
    assert [e.data[2] for e in q.events] == [1, 2, 3, 4, 5, 6]
    assert [e.data[2] for e in q.expired] == [1, 2]
