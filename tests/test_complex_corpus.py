"""Reference ComplexPatternTestCase corpus — composed shapes: or-groups
under every with a continuation, every-group with a mid count, unbounded
min-2 counts with e[last], and a plain chain where a non-count step
follows a capture-referencing filter."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutputStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


TWO = """@app:playback
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
"""
ONE = ("@app:playback define stream Stream1 "
       "(symbol string, price float, volume int);\n")


def _rows(c):
    return [tuple(round(v, 4) if isinstance(v, float) else v
                  for v in e.data) for e in c.events]


def test_complex_q1_or_group_with_continuation():
    # ComplexPatternTestCase.testQuery1: every (e1 -> e2 or e3) -> e4
    m, rt, c = build(TWO + """
        from every ( e1=Stream1[price > 20]
          -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol])
          -> e4=Stream2[price > e1.price]
        select e1.price as p1, e2.price as p2, e3.price as p3,
               e4.price as p4
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["WSO2", 55.6, 100]); t += 100
    s2.send(t, ["WSO2", 55.7, 100]); t += 100
    s2.send(t, ["GOOG", 55.0, 100]); t += 100
    s1.send(t, ["GOOG", 54.0, 100]); t += 100
    s2.send(t, ["IBM", 57.7, 100]); t += 100
    s2.send(t, ["IBM", 59.7, 100]); t += 100
    m.shutdown()
    got = _rows(c)
    assert len(got) == 2
    assert (55.6, 55.7, None, 57.7) in got
    assert (54.0, 57.7, None, 59.7) in got


def test_complex_q2_every_group_with_mid_count():
    # testQuery2: every (e1 -> e2<1:2>) -> e3[price > e1.price]
    m, rt, c = build(ONE + """
        from every ( e1=Stream1[price > 20] -> e2=Stream1[price > 20]<1:2>)
          -> e3=Stream1[price > e1.price]
        select e1.price as p1, e2[0].price as p20, e2[1].price as p21,
               e3.price as p3
        insert into OutputStream;
    """)
    h = rt.get_input_handler("Stream1")
    t = 1000
    for sym, p in [("WSO2", 55.6), ("GOOG", 54.0), ("WSO2", 53.6),
                   ("GOOG", 57.0)]:
        h.send(t, [sym, p, 100]); t += 100
    m.shutdown()
    assert _rows(c) == [(55.6, 54.0, 53.6, 57.0)]


def test_complex_q3_min2_unbounded_count_with_last():
    # testQuery3: every e1 -> e2<2:> -> e3, three chained matches with
    # e2[last] reading the final collected occurrence
    m, rt, c = build(ONE + """
        from every e1 = Stream1[ price >= 50 and volume > 100 ]
          -> e2 = Stream1[price <= 40] <2:>
          -> e3 = Stream1[volume <= 70]
        select e1.symbol as s1, e2[last].symbol as s2, e3.symbol as s3
        insert into OutputStream;
    """)
    h = rt.get_input_handler("Stream1")
    t = 1000
    for sym, p, v in [("IBM", 75.6, 105), ("GOOG", 39.8, 91), ("FB", 35.0, 81),
                      ("WSO2", 21.0, 61), ("ADP", 50.0, 101),
                      ("GOOG", 41.2, 90), ("FB", 40.0, 100),
                      ("WSO2", 33.6, 85), ("AMZN", 23.5, 55),
                      ("WSO2", 51.7, 180), ("TXN", 34.0, 61),
                      ("QQQ", 24.6, 45), ("CSCO", 181.6, 40),
                      ("WSO2", 53.7, 200)]:
        h.send(t, [sym, p, v]); t += 100
    m.shutdown()
    assert _rows(c) == [("IBM", "FB", "WSO2"),
                        ("ADP", "WSO2", "AMZN"),
                        ("WSO2", "QQQ", "CSCO")]


def test_complex_q5_non_every_capture_ref_chain():
    # testQuery5 (non-every): e1 -> e2[e1.symbol != 'AMBA'] -> e3, one
    # match only, no re-arm for the plain stream head
    m, rt, c = build(TWO + """
        from e1 = Stream1[ price >= 50 and volume > 100 ]
          -> e2 = Stream2[e1.symbol != 'AMBA']
          -> e3 = Stream2[volume <= 70]
        select e3.symbol as s1, e2[0].symbol as s2, e3.volume as v3
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    feed = [(s1, ["IBM", 75.6, 105]), (s2, ["GOOG", 21.0, 81]),
            (s2, ["WSO2", 176.6, 65]), (s1, ["BIRT", 21.0, 81]),
            (s1, ["AMBA", 126.6, 165]), (s2, ["DDD", 23.0, 181]),
            (s2, ["BIRT", 21.0, 86]), (s2, ["BIRT", 21.0, 82]),
            (s2, ["WSO2", 176.6, 60]), (s1, ["AMBA", 126.6, 165]),
            (s2, ["DOX", 16.2, 25])]
    for h, row in feed:
        h.send(t, row); t += 100
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOG", 65)]
