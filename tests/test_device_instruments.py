"""Device telemetry plane (observability/instruments.py): instrument
slots ride the meta vector — zero extra pulls.

The load-bearing acceptance set: per-batch device truth (window ring
fill, join partition fill, NFA runs, routed-row skew) lands in
``device.<query>.<slot>`` telemetry off the meta pull that already
happens; a /metrics scrape performs ZERO device pulls
(transfer-guard-verified, including the join partition-occupancy gauges
that used to pull the directory per scrape); with the knob off the meta
layouts are bit-for-bit the pre-round-9 ones; and
``journey.critical_path_report()`` names the saturated device structure
for a PLANTED bottleneck (hot join partition at growth-off slack,
near-full keyed window)."""

import jax
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.util.config import InMemoryConfigManager
from siddhi_tpu.observability import export, instruments, journey


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


@pytest.fixture(autouse=True)
def _off_after():
    yield
    journey.disable(force=True)
    instruments.disable(force=True)


def _manager(extra=None):
    m = SiddhiManager()
    cfg = {"siddhi_tpu.pipeline_depth": "2"}
    cfg.update(extra or {})
    m.set_config_manager(InMemoryConfigManager(cfg))
    return m


JOIN_APP = """
define stream L (sym string, lv long);
define stream R (sym string, rv long);
@info(name='jq') from L#window.length(64) join R#window.length(64)
  on L.sym == R.sym
  select L.sym as sym, L.lv as lv, R.rv as rv insert into JOut;
"""


def _feed_join(rt, n=24, keys=5):
    hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
    for i in range(n):
        hl.send([f"S{i % keys}", i])
        hr.send([f"S{i % keys}", 100 + i])


# --------------------------------------------------- slots ride the meta


def test_join_fill_instrument_feeds_gauges_and_occupancy():
    """The per-partition directory fill rides the meta; the
    partition-occupancy gauges read the LAST DRAINED lanes — no device
    state is touched at scrape time."""
    m = _manager({"siddhi_tpu.join_partitions": "8"})
    rt = m.create_siddhi_app_runtime(JOIN_APP)
    rt.add_callback("JOut", Collector())
    _feed_join(rt)
    q = rt.query_runtimes["jq"]
    last = q._instr_last
    assert "fill.left" in last and "fill.right" in last
    assert last["fill.left"].shape == (8,)
    assert int(last["fill.left"].sum()) > 0
    # the occupancy gauge backend IS the drained lanes
    occ = q.engine.partition_occupancy("left")
    assert occ.tolist() == last["fill.left"].tolist()
    snap = rt.app_context.telemetry.snapshot()
    assert "device.jq.fill.left" in snap["gauges"]
    assert snap["gauges"]["device.jq.fill.left.capacity"] == \
        q.engine.plans["left"].Wp
    assert "device.jq.fill.right" in snap.get("histograms", {})
    m.shutdown()


def test_scrape_zero_device_pulls_under_transfer_guard():
    """A full /metrics scrape with live join + instrument gauges makes
    NO device pull: it completes under jax's transfer guard and the
    guarded families read real numbers, not the NaN a guarded gauge
    closure would produce."""
    m = _manager({"siddhi_tpu.join_partitions": "8"})
    rt = m.create_siddhi_app_runtime(JOIN_APP)
    rt.add_callback("JOut", Collector())
    _feed_join(rt)
    with jax.transfer_guard("disallow"):
        text = export.prometheus_text(m)
    assert "siddhi_join_partition_rows" in text
    assert "siddhi_device_instrument" in text
    values = []
    for line in text.splitlines():
        if line.startswith(("siddhi_join_partition_rows",
                            "siddhi_device_instrument{")):
            assert not line.endswith("NaN"), f"guarded gauge pulled: {line}"
            values.append(float(line.rsplit(" ", 1)[1]))
    assert values and sum(values) > 0
    m.shutdown()


def test_occupancy_host_mirror_fallback_with_knob_off():
    """Instruments off: partition_occupancy answers from the host ring
    mirror (still zero device pulls; exact for length rings)."""
    m = _manager({"siddhi_tpu.join_partitions": "8",
                  "siddhi_tpu.profile_device_instruments": "false"})
    rt = m.create_siddhi_app_runtime(JOIN_APP)
    rt.add_callback("JOut", Collector())
    _feed_join(rt)
    q = rt.query_runtimes["jq"]
    assert not q._instr_last        # nothing drained
    with jax.transfer_guard("disallow"):
        occ = q.engine.partition_occupancy("left")
    assert int(occ.sum()) == 24     # every inserted row is live (W=64)
    m.shutdown()


def test_knob_off_meta_layouts_bit_for_bit():
    """profile_device_instruments: false reproduces the pre-round-9
    layouts exactly — [3] plain, [4] engine join (prefix + seq)."""
    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import TS_KEY, TYPE_KEY, VALID_KEY

    m = _manager({"siddhi_tpu.join_partitions": "8",
                  "siddhi_tpu.profile_device_instruments": "false"})
    rt = m.create_siddhi_app_runtime(JOIN_APP + """
@info(name='pq') from L#window.length(8) select sym, lv insert into POut;
""")
    jq, pq = rt.query_runtimes["jq"], rt.query_runtimes["pq"]
    assert pq.instrument_slots() == []
    assert [s.name for s in jq.instrument_slots()] == ["seq"]
    B = 4
    cols = {TS_KEY: np.arange(B, dtype=np.int64),
            TYPE_KEY: np.zeros(B, np.int8), VALID_KEY: np.ones(B, bool),
            "sym": np.zeros(B, np.int64), "sym?": np.zeros(B, bool),
            "lv": np.arange(B, dtype=np.int64), "lv?": np.zeros(B, bool),
            GK_KEY: np.zeros(B, np.int32)}
    _st, out = jax.jit(pq.build_step_fn())(pq._init_state(), dict(cols),
                                           np.int64(0))
    assert np.asarray(out["__meta__"]).shape == (3,)
    import jax.numpy as jnp

    _st, out = jax.jit(jq.build_side_step_fn("left"))(
        jq._init_state(), {}, jnp.zeros((1,), bool), dict(cols),
        np.int64(0))
    assert np.asarray(out["__meta__"]).shape == (4,)
    m.shutdown()


def test_refcounted_process_collector():
    """The knob holds one refcount on the process collector for the
    app's lifetime, like profile_journeys."""
    m = _manager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (sym string, v long);\n"
        "@info(name='q') from S select sym, v insert into Out;")
    rt.start()
    assert instruments.enabled()
    m.shutdown()
    assert not instruments.enabled()


def test_fused_members_decode_their_own_rows():
    """A fused fan-out group stacks per-member suffixes (zero-padded);
    each member's drain decodes its own spec."""
    m = _manager()
    rt = m.create_siddhi_app_runtime("""
define stream S (sym string, v long);
@info(name='g1') from S#window.length(8) select sym, v insert into O1;
@info(name='g2') from S select sym, v insert into O2;
""")
    c1, c2 = Collector(), Collector()
    rt.add_callback("O1", c1)
    rt.add_callback("O2", c2)
    h = rt.get_input_handler("S")
    for i in range(20):
        h.send([f"K{i % 3}", i])
    assert rt.fused_fanout_groups, "shape did not fuse"
    g1 = rt.query_runtimes["g1"]
    assert g1._instr_last["win_fill"].tolist() == [8]
    assert len(c1.rows) and len(c2.rows) == 20
    m.shutdown()


# ------------------------------------------- planted saturated structures


def test_report_names_hot_join_partition_at_growth_off_slack():
    """Growth OFF + one hot key: the join directory's hot partition
    approaches Wp and critical_path_report names 'join right side
    partition fill' with the fill/Wp ratio."""
    m = _manager({"siddhi_tpu.join_partitions": "8",
                  "siddhi_tpu.join_partition_grow": "false",
                  "siddhi_tpu.join_partition_slack": "2"})
    rt = m.create_siddhi_app_runtime(JOIN_APP)
    rt.add_callback("JOut", Collector())
    hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
    hl.send(["HOT", 0])           # warm compile outside the measurement
    journey.enable()
    try:
        # Wp = pow2(64 * 2 / 8) = 16; 14 hot-key rows on the RIGHT side
        # fill one partition to 14/16 without tripping the static-slack
        # overflow
        for i in range(14):
            hr.send(["HOT", 100 + i])
        hl.send(["HOT", 1])       # trigger a probe so the left drains too
        rep = journey.critical_path_report(m)
        q = rep["apps"][rt.name]["queries"]["jq"]
        st = q.get("device_structure")
        assert st is not None, q
        assert st["slot"] == "fill.right", st
        assert st["ratio"] >= 0.8, st
        assert "join right side partition fill" in st["text"]
        assert "of Wp" in st["text"]
    finally:
        journey.disable(force=True)
    m.shutdown()


def test_report_names_near_full_keyed_window():
    """A keyed length window fed past W rows per key reports win_fill
    == W — the report names the window ring at ratio 1.0."""
    m = _manager()
    rt = m.create_siddhi_app_runtime("""
define stream S (k string, v double);
partition with (k of S)
begin
  @info(name='kq')
  from S#window.length(8) select k, v, sum(v) as s insert into Out;
end;
""")
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    h.send(["A", 0.0])            # warm
    journey.enable()
    try:
        for i in range(20):
            h.send(["A", float(i)])
        rep = journey.critical_path_report(m)
        q = rep["apps"][rt.name]["queries"]["kq"]
        st = q.get("device_structure")
        assert st is not None, q
        # log-bucket histogram p99 carries ~3.5% relative error
        assert st["slot"] == "win_fill" and st["ratio"] >= 0.95, st
        assert "window ring fill" in st["text"]
    finally:
        journey.disable(force=True)
    m.shutdown()


def test_device_bottleneck_verdict_carries_structure():
    """When the device stage IS the bottleneck, the verdict line names
    the saturated structure (unit-level: synthetic stage histograms +
    instrument signals through _query_report)."""
    dev = {"fill.right": {"snap": {"p99": 15.5, "count": 10, "sum": 150.0},
                          "capacity": 16.0}}
    stages = {
        "pack": {"service": {"sum": 5.0, "count": 10, "p99": 0.6}},
        "device": {"service": {"sum": 400.0, "count": 10, "p99": 45.0}},
        "emit": {"service": {"sum": 3.0, "count": 10, "p99": 0.4}},
    }
    rep = journey._query_report("app", "jq", stages, device_slots=dev)
    assert rep["bottleneck"]["stage"] == "device"
    assert "join right side partition fill" in rep["bottleneck"]["structure"]
    assert "0.97 of Wp" in rep["bottleneck"]["structure"]
    assert rep["device_structure"]["ratio"] == pytest.approx(15.5 / 16.0,
                                                             abs=1e-3)


# ----------------------------------------------- routed + NFA instruments


def test_routed_instruments_aggregate_across_shards():
    from siddhi_tpu.parallel.mesh import device_route_query_step, make_mesh

    m = _manager()
    rt = m.create_siddhi_app_runtime("""
define stream S (k string, v double);
partition with (k of S)
begin
  @info(name='rq')
  from S#window.length(4) select k, v, sum(v) as s insert into Out;
end;
""")
    rt.add_callback("Out", Collector())
    q = rt.query_runtimes["rq"]
    device_route_query_step(q, make_mesh(4), rows_per_shard=64)
    h = rt.get_input_handler("S")
    for i in range(80):
        h.send([f"P{i % 8}", float(i)])
    last = q._instr_last
    assert last["shard_rows"].shape == (4,)
    assert last["win_fill"].tolist() == [4]     # hottest key's ring full
    assert int(last["route_residual"][0]) <= 64
    assert int(last["groups"][0]) >= 1
    caps = q._instr_caps
    assert caps["win_fill"] == 4.0
    m.shutdown()


def test_nfa_runs_instrument():
    m = _manager()
    rt = m.create_siddhi_app_runtime("""
define stream A (sym string, p double);
@info(name='nq') from every e1=A[p > 10] -> e2=A[p > e1.p]
  select e1.sym as s1, e2.sym as s2 insert into Out;
""")
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("A")
    for i in range(10):
        h.send([f"N{i}", 11.0 + i])
    q = rt.query_runtimes["nq"]
    assert "nfa_runs" in q._instr_last
    assert int(q._instr_last["nfa_runs"][0]) >= 1
    assert q._instr_caps["nfa_runs"] > 0
    m.shutdown()
