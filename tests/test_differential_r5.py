"""Differential harness round 5: string casts, post-window transform
pipelines, and absent-sequence timing vs plain-Python models."""

import collections
import math

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback


class SCollect(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


def _run(app, sends, out="Out"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = SCollect()
    rt.add_callback(out, c)
    handlers = {}
    for ts, sid, row in sends:
        h = handlers.get(sid)
        if h is None:
            h = handlers[sid] = rt.get_input_handler(sid)
        if ts is None:
            h.send(row)
        else:
            h.send(ts, row)
    m.shutdown()
    return c.rows


def test_differential_string_cast_window_group():
    rng = np.random.default_rng(41)
    sends = []
    for _ in range(250):
        sends.append((None, "S", [f"k{int(rng.integers(0, 3))}",
                                  str(rng.choice(["1", "2", "bad", "10"]))]))
    app = """
        define stream S (sym string, num string);
        from S#window.length(6)
        select sym, sum(convert(num, 'long')) as t
        group by sym insert into Out;
    """
    got = _run(app, sends)
    dq = collections.deque()
    model = []
    for _, _, (sym, num) in sends:
        v = int(num) if num.isdigit() else None
        dq.append((sym, v))
        if len(dq) > 6:
            dq.popleft()
        vals = [x for s, x in dq if s == sym and x is not None]
        model.append((sym, sum(vals) if vals else None))
    assert got == model


def test_differential_post_window_transform_pipeline():
    rng = np.random.default_rng(43)
    sends = []
    for _ in range(200):
        theta = float(rng.choice([0.0, 45.0, 90.0, 225.0]))
        rho = float(rng.integers(1, 4))
        sends.append((None, "P", [theta, rho]))
    app = """
        define stream P (theta double, rho double);
        from P#window.length(3)#pol2Cart(theta, rho)[y > 0.0]
        select y insert all events into Out;
    """
    got = _run(app, sends)
    dq = collections.deque()
    model = []
    for _, _, (theta, rho) in sends:
        y = rho * math.sin(math.radians(theta))
        # StreamCallback sees the window's natural order: the evicted
        # (expired) row is emitted before the arriving current row
        if len(dq) == 3:
            ev = dq.popleft()
            if ev > 1e-12:
                model.append((ev,))
        dq.append(y)
        if y > 1e-12:
            model.append((y,))
    assert len(got) == len(model)
    for (g,), (mv,) in zip(got, model):
        assert abs(g - mv) < 1e-9


def test_differential_absent_sequence_random_timing():
    rng = np.random.default_rng(47)
    T = 500
    ts, sends, trace = 1000, [], []
    for _ in range(150):
        ts += int(rng.integers(50, 400))
        if rng.random() < 0.5:
            p = float(rng.integers(10, 60))
            sends.append((ts, "S1", ["a", p, 1]))
            trace.append((ts, "A", p))
        else:
            p = float(rng.integers(10, 60))
            sends.append((ts, "S2", ["b", p, 1]))
            trace.append((ts, "B", p))
    app = f"""@app:playback
        define stream S1 (symbol string, price double, v int);
        define stream S2 (symbol string, price double, v int);
        from e1=S1[price>30], not S2[price>e1.price] for {T} milliseconds
        select e1.price as p insert into Out;
    """
    got = _run(app, sends)
    # model: each qualifying A starts a wait; a LATER B with higher price
    # within T kills it; otherwise it emits at deadline. Sequence semantics
    # here: only one pending chain at a time (no head every) — the first
    # un-killed qualifying A wins, then the machine stops (every absent).
    model = []
    waiting = None   # (deadline, price)
    done = False
    for t_i, kind, p in trace:
        if done:
            break
        if waiting is not None and t_i >= waiting[0]:
            model.append((waiting[1],))
            done = True     # no head `every`: single match then stop
            waiting = None
        if done:
            break
        if kind == "A" and waiting is None and p > 30:
            waiting = (t_i + T, p)
        elif kind == "B" and waiting is not None and p > waiting[1]:
            waiting = None  # violated; chain dead (no every)
            done = True
    assert got == model
