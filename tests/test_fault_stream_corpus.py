"""Reference fault-stream corpus — scenarios ported verbatim from
``stream/FaultStreamTestCase.java``: default log-and-drop error handling,
@OnError(action='log'|'stream'), `!stream` fault routing with the
appended `_error` column, and sender-side non-propagation."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.query.callback import QueryCallback
from siddhi_tpu.extension import ScalarFunction
from siddhi_tpu.query_api.definitions import AttrType


class FaultFn(ScalarFunction):
    """The reference's FaultFunctionExtension: throws on every call."""

    return_type = AttrType.LONG

    @staticmethod
    def apply(xp, *args):
        raise RuntimeError("Error when running faultAdd()")


class QCount(QueryCallback):
    def __init__(self):
        self.events = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)


class SCollect(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def _mk(app):
    m = SiddhiManager()
    m.set_extension("function:custom:fault", FaultFn)
    rt = m.create_siddhi_app_runtime(app)
    return m, rt


FAULTY_QUERY = (
    "@info(name = 'query1') "
    "from cseEventStream[custom:fault() > volume] "
    "select symbol, price , symbol as sym1 "
    "insert into outputStream ;")


def test_default_logs_and_drops(caplog):
    """faultStreamTest1 (:61-106): without @OnError the error is logged,
    the event dropped, and send() does NOT raise."""
    m, rt = _mk(
        "define stream cseEventStream (symbol string, price float, "
        "volume long);" + FAULTY_QUERY)
    q = QCount()
    rt.add_callback("query1", q)
    rt.start()
    with caplog.at_level("ERROR"):
        rt.get_input_handler("cseEventStream").send(["IBM", 0.0, 100])
    m.shutdown()
    assert q.events == []
    assert any("faultAdd" in r.message or "faultAdd" in str(r.exc_info)
               or "error processing events" in r.message
               for r in caplog.records)


def test_onerror_log_action(caplog):
    """faultStreamTest2 (:109-155): @OnError(action='log') behaves like
    the default."""
    m, rt = _mk(
        "@OnError(action='log')"
        "define stream cseEventStream (symbol string, price float, "
        "volume long);" + FAULTY_QUERY)
    q = QCount()
    rt.add_callback("query1", q)
    rt.start()
    with caplog.at_level("ERROR"):
        rt.get_input_handler("cseEventStream").send(["IBM", 0.0, 100])
    m.shutdown()
    assert q.events == []
    assert any("error processing events" in r.message
               for r in caplog.records)


def test_onerror_stream_no_subscriber():
    """faultStreamTest3 (:157-203): @OnError(action='stream') with nobody
    on the fault stream — event vanishes quietly, nothing raises."""
    m, rt = _mk(
        "@OnError(action='stream')"
        "define stream cseEventStream (symbol string, price float, "
        "volume long);" + FAULTY_QUERY)
    q = QCount()
    rt.add_callback("query1", q)
    rt.start()
    rt.get_input_handler("cseEventStream").send(["IBM", 0.0, 100])
    m.shutdown()
    assert q.events == []


def test_fault_stream_query():
    """faultStreamTest4 (:206-255): a `from !cseEventStream` query sees
    the failing event with its original attributes."""
    m, rt = _mk(
        "@OnError(action='stream')"
        "define stream cseEventStream (symbol string, price float, "
        "volume long);" + FAULTY_QUERY +
        "@info(name = 'query2') from !cseEventStream select * "
        "insert into faultStream;")
    c = SCollect()
    rt.add_callback("faultStream", c)
    rt.start()
    rt.get_input_handler("cseEventStream").send(["IBM", 0.0, 100])
    m.shutdown()
    assert len(c.events) == 1
    assert c.events[0].data[0] == "IBM"
    assert c.events[0].data[3] is not None   # _error carries the cause


def test_fault_stream_direct_callback():
    """faultStreamTest5 (:258-293): subscribing to '!cseEventStream'
    directly delivers the failing event; data[3] is the error text."""
    m, rt = _mk(
        "@OnError(action='stream')"
        "define stream cseEventStream (symbol string, price float, "
        "volume long);" + FAULTY_QUERY)
    c = SCollect()
    rt.add_callback("!cseEventStream", c)
    rt.start()
    rt.get_input_handler("cseEventStream").send(["IBM", 0.0, 100])
    m.shutdown()
    assert len(c.events) == 1
    assert c.events[0].data[3] is not None
    assert "faultAdd" in c.events[0].data[3]


def test_capacity_overflow_still_raises():
    """Our framework-infrastructure failures (dense capacity knobs) keep
    propagating to the sender even under the log-and-drop default."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (v long);"
        "@info(name = 'q') from S#window.length(100) "
        "select distinctCount(v) as n insert into O;")
    q = next(iter(rt.query_runtimes.values()))
    for spec in q.selector_plan.specs:
        spec.distinct_capacity = 4
    rt.start()
    h = rt.get_input_handler("S")
    with pytest.raises(RuntimeError, match="distinct_values_capacity"):
        for i in range(10):
            h.send([i])
    m.shutdown()
