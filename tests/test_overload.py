"""Overload armor (resilience/overload.py): per-app quotas, shed-policy
backpressure, bounded blocking enqueue with supervisor escalation,
device-memory budgets at capacity-growth sites, and shed-vs-WAL replay
consistency. Default config (no quotas) must stay behavior-identical."""

import time

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.stream.junction import FatalQueryError
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore
from siddhi_tpu.resilience import FaultInjector, IngestWAL, OverloadManager
from siddhi_tpu.resilience.overload import FairScheduler


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)

    def rows(self):
        return [tuple(e.data) for e in self.events]


def _wait_for(predicate, timeout=15.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if predicate():
            return True
        time.sleep(0.01)
    return False


ASYNC_APP = """
@app:name('{name}')
@Async(buffer.size='64')
define stream S (sym string, v long);
@info(name='q') from S select sym, v insert into Out;
"""


def _mk(m, name, **overload_kwargs):
    rt = m.create_siddhi_app_runtime(ASYNC_APP.format(name=name))
    c = Collector()
    rt.add_callback("Out", c)
    ctl = rt.enable_overload(**overload_kwargs) if overload_kwargs else None
    return rt, c, ctl


# ------------------------------------------------------------ defaults


def test_no_quota_config_means_no_overload_control():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(ASYNC_APP.format(name="plain"))
    assert rt.app_context.overload is None
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    for i in range(50):
        h.send([f"K{i % 3}", i])
    assert _wait_for(lambda: len(c.events) == 50), len(c.events)
    assert [e.data[1] for e in c.events] == list(range(50))
    m.shutdown()


def test_registration_is_idempotent_and_unregisters_on_shutdown():
    m = SiddhiManager()
    rt, _c, ctl = _mk(m, "reg", queue_quota=8)
    assert rt.app_context.overload is ctl
    ctl2 = rt.enable_overload(queue_quota=16)     # replaces config
    assert ctl2 is ctl and ctl.config.queue_quota == 16
    assert OverloadManager.instance().control_of("reg") is ctl
    m.shutdown()
    assert OverloadManager.instance().control_of("reg") is None
    assert rt.app_context.overload is None


# --------------------------------------------------------- shed policies


def _wedge_and_flood(m, name, policy, n_flood=40, **kw):
    rt, c, ctl = _mk(m, name, queue_quota=4, shed_policy=policy, **kw)
    rt.start()
    inj = FaultInjector()
    j = rt.junctions["S"]
    inj.wedge_worker(j)
    h = rt.get_input_handler("S")
    h.send(["a", -1])
    assert inj.wait_wedged()
    sent = inj.flood_stream(j, ratio=1.0, base_events=n_flood, chunk=1)
    inj.release()
    assert _wait_for(lambda: len(c.events) + ctl.shed_events == sent + 1), (
        len(c.events), ctl.shed_events)
    return rt, c, ctl, sent + 1


def test_shed_newest_drops_incoming_with_exact_accounting():
    m = SiddhiManager()
    rt, c, ctl, total = _wedge_and_flood(m, "newest", "shed_newest")
    assert ctl.shed_events > 0
    assert len(c.events) + ctl.shed_events == total     # zero silent loss
    # shed_newest keeps the OLDEST queued units: the wedge-parked head
    # and the first few flood events survive
    assert c.events[0].data[1] == -1
    tel = rt.app_context.telemetry.snapshot()
    assert tel["counters"]["junction.S.shed_events"] == ctl.shed_events
    m.shutdown()


def test_shed_oldest_keeps_freshest_data():
    m = SiddhiManager()
    rt, c, ctl, total = _wedge_and_flood(m, "oldest", "shed_oldest")
    assert ctl.shed_events > 0
    assert len(c.events) + ctl.shed_events == total
    # the LAST flood event must have survived eviction (freshest wins);
    # flood_stream's default long column counts 0..n-1
    assert c.events[-1].data[1] == 39
    m.shutdown()


def test_flood_stream_respects_custom_data_and_base():
    m = SiddhiManager()
    rt, c, _ctl = _mk(m, "flood")
    rt.start()
    inj = FaultInjector()
    n = inj.flood_stream(rt.junctions["S"], ratio=0.5, base_events=20,
                         make_data=lambda i: ["X", i * 2])
    assert n == 10
    assert _wait_for(lambda: len(c.events) == 10)
    assert [e.data[1] for e in c.events] == [i * 2 for i in range(10)]
    m.shutdown()


# ----------------------------------------- block policy + escalation


def test_block_policy_escalates_to_supervisor_and_unblocks():
    """The bugfix satellite: a wedged consumer used to deadlock the
    producer forever. With policy 'block' the bounded wait escalates to
    the supervisor, which replaces the wedged worker — the producer's
    send COMPLETES."""
    m = SiddhiManager()
    rt, c, ctl = _mk(m, "blocker", queue_quota=2, shed_policy="block",
                     block_timeout_s=0.4)
    # huge interval: restarts can only come from the escalation path,
    # not from the supervisor's own periodic tick
    sup = rt.supervise(interval_s=60.0, wedge_timeout_s=0.3)
    rt.start()
    inj = FaultInjector()
    j = rt.junctions["S"]
    inj.wedge_worker(j)
    h = rt.get_input_handler("S")
    h.send(["a", 0])
    assert inj.wait_wedged()
    t0 = time.time()
    for i in range(1, 6):         # quota 2: these block until escalation
        h.send([f"K{i}", i])
    elapsed = time.time() - t0
    assert elapsed < 20.0          # finite — no deadlock
    assert ctl.enqueue_timeouts >= 1
    assert sup.worker_restarts >= 1
    assert _wait_for(lambda: len(c.events) == 6), len(c.events)
    assert [e.data[1] for e in c.events] == list(range(6))  # order kept
    inj.clear()
    m.shutdown()


def test_bounded_enqueue_escalates_without_overload_config(monkeypatch):
    """The blocking fallback is bounded in the DEFAULT configuration too:
    a full queue with a wedged worker escalates to the supervisor instead
    of parking the producer forever."""
    import siddhi_tpu.resilience.overload as ov

    monkeypatch.setattr(ov, "DEFAULT_BLOCK_TIMEOUT_S", 0.5)
    monkeypatch.setattr(ov, "BLOCK_PUT_SLICE_S", 0.1)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:name('defbound')
        @Async(buffer.size='2')
        define stream S (sym string, v long);
        @info(name='q') from S select sym, v insert into Out;
    """)
    assert rt.app_context.overload is None
    c = Collector()
    rt.add_callback("Out", c)
    sup = rt.supervise(interval_s=60.0, wedge_timeout_s=0.3)
    rt.start()
    inj = FaultInjector()
    j = rt.junctions["S"]
    inj.wedge_worker(j)
    h = rt.get_input_handler("S")
    h.send(["a", 0])
    assert inj.wait_wedged()
    t0 = time.time()
    for i in range(1, 5):          # buffer 2: producer must block
        h.send([f"K{i}", i])
    assert time.time() - t0 < 20.0
    assert sup.worker_restarts >= 1
    tel = rt.app_context.telemetry.snapshot()
    assert tel["counters"].get("junction.S.enqueue_timeouts", 0) >= 1
    assert _wait_for(lambda: len(c.events) == 5)
    inj.clear()
    m.shutdown()


# -------------------------------------------- shed-vs-WAL consistency


def test_wal_discard_removes_exactly_one_record():
    wal = IngestWAL(max_batches=16)
    from siddhi_tpu.core.event import Event

    s1 = wal.record_events("S", [Event(timestamp=1, data=[1])])
    s2 = wal.record_events("S", [Event(timestamp=2, data=[2])])
    s3 = wal.record_events("S", [Event(timestamp=3, data=[3])])
    assert (s1, s2, s3) == (1, 2, 3)
    assert wal.discard(s2) is True
    assert wal.discard(s2) is False           # already gone
    assert [r.seq for r in wal.records_after(0)] == [1, 3]
    assert wal.pending_events == 2
    assert wal.shed_records == 1


def test_shed_oldest_checkpoint_restore_replays_exactly_non_shed_suffix():
    """The satellite acceptance: under shed_oldest, a checkpoint/restore
    cycle replays exactly the non-shed suffix — shed events are never
    resurrected, and wal_replayed_batches counts only retained records."""
    store = InMemoryPersistenceStore()
    m1 = SiddhiManager()
    m1.set_persistence_store(store)
    rt1, c1, ctl = _mk(m1, "walshed", queue_quota=4,
                       shed_policy="shed_oldest")
    wal = rt1.enable_wal()
    rt1.start()
    h = rt1.get_input_handler("S")
    # prefix: fully delivered (waiting out each send keeps the queue
    # under the quota — the prefix must not shed), then checkpointed
    for i in range(6):
        h.send(1000 + i, [f"K{i % 3}", i])
        assert _wait_for(lambda n=i: len(c1.events) == n + 1)
    rt1.persist()
    assert len(wal) == 0
    assert ctl.shed_events == 0

    # suffix under overload: wedge the consumer, push past the quota
    inj = FaultInjector()
    j = rt1.junctions["S"]
    inj.wedge_worker(j)
    h.send(2000, ["w", 100])
    assert inj.wait_wedged()
    for i in range(1, 20):
        h.send(2000 + i, [f"K{i % 3}", 100 + i])
    inj.release()
    assert _wait_for(
        lambda: len(c1.events) + ctl.shed_events == 6 + 20), (
        len(c1.events), ctl.shed_events)
    assert ctl.shed_events > 0
    suffix_emitted = c1.rows()[6:]
    assert len(suffix_emitted) == 20 - ctl.shed_events
    # the WAL retains exactly the non-shed suffix
    assert len(wal) == len(suffix_emitted)
    m1.shutdown()

    # crash + restore: replay must reproduce exactly the emitted suffix
    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2, c2, _ctl2 = _mk(m2, "walshed", queue_quota=4,
                         shed_policy="shed_oldest")
    rt2.app_context.ingest_wal = wal
    replayed_before = wal.replayed_batches
    assert rt2.restore_last_revision() is not None
    assert _wait_for(lambda: len(c2.events) == len(suffix_emitted)), (
        len(c2.events), len(suffix_emitted))
    time.sleep(0.2)     # no stragglers: shed events must NOT resurrect
    assert c2.rows() == suffix_emitted
    assert wal.replayed_batches - replayed_before == len(suffix_emitted)
    m2.shutdown()


# ---------------------------------------------- device-memory budget


def test_memory_budget_denies_key_growth_naming_the_knob():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:name('membudget')
        define stream S (sym string, v long);
        @info(name='gq') from S select sym, sum(v) as t group by sym
          insert into Out;
    """)
    rt.enable_overload(memory_budget_mb=0.000001)   # ~1 byte: deny growth
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    # first batch fits the initial 16-key capacity — allowed (the budget
    # gates GROWTH, initial allocation is the baseline)
    h.send_columns({"sym": [f"g{i}" for i in range(10)],
                    "v": list(range(10))})
    with pytest.raises(FatalQueryError) as ei:
        h.send_columns({"sym": [f"h{i}" for i in range(40)],
                        "v": list(range(40))})
    assert "quota_memory_mb" in str(ei.value)
    assert "membudget" in str(ei.value)
    ctl = rt.app_context.overload
    assert ctl.quota_denials >= 1
    m.shutdown()


def test_memory_budget_denies_table_growth():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:name('tbudget')
        define stream S (sym string, v long);
        define table T (sym string, v long);
        @info(name='ins') from S select sym, v insert into T;
    """)
    rt.enable_overload(memory_budget_mb=0.000001)
    t = rt.tables["T"]
    with pytest.raises(FatalQueryError) as ei:
        t._ensure_room(5000)        # past the 1024 default capacity
    assert "quota_memory_mb" in str(ei.value)
    assert "table 'T'" in str(ei.value)
    m.shutdown()


def test_memory_budget_denies_aggregation_bucket_growth():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:name('abudget')
        define stream S (sym string, v long);
        define aggregation AggT
          from S select sym, sum(v) as total group by sym
          aggregate every sec ... hour;
    """)
    rt.enable_overload(memory_budget_mb=0.000001)
    h = rt.get_input_handler("S")
    with pytest.raises(FatalQueryError) as ei:
        h.send(1000, ["a", 1])
    assert "quota_memory_mb" in str(ei.value)
    assert "bucket-store" in str(ei.value)
    m.shutdown()


def test_generous_budget_charges_without_denying():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:name('genbudget')
        define stream S (sym string, v long);
        @info(name='gq') from S select sym, sum(v) as t group by sym
          insert into Out;
    """)
    rt.enable_overload(memory_budget_mb=256)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send_columns({"sym": [f"g{i}" for i in range(10)],
                    "v": list(range(10))})
    h.send_columns({"sym": [f"h{i}" for i in range(40)],
                    "v": list(range(40))})         # grows 16 -> 64 keys
    ctl = rt.app_context.overload
    assert ctl.charged_bytes() > 0                 # ledger records growth
    assert ctl.quota_denials == 0
    assert 0.0 < ctl.utilization()["memory"] < 1.0
    m.shutdown()


# ----------------------------------------------- pipeline quota


def test_pipeline_quota_outputs_identical_to_unbounded():
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    def run(quota):
        m = SiddhiManager()
        m.siddhi_context.config_manager = InMemoryConfigManager(
            {"siddhi_tpu.pipeline_depth": "8"})
        rt = m.create_siddhi_app_runtime(ASYNC_APP.format(name="pq"))
        c = Collector()
        rt.add_callback("Out", c)
        if quota is not None:
            rt.enable_overload(pipeline_quota=quota)
        h = rt.get_input_handler("S")
        for i in range(60):
            h.send([f"K{i % 5}", i])
        assert _wait_for(lambda: len(c.events) == 60), len(c.events)
        rows = c.rows()
        m.shutdown()
        return rows

    assert run(quota=1) == run(quota=None)


# ----------------------------------------------- fair scheduling


def test_fair_scheduler_throttles_only_the_over_share_app():
    fs = FairScheduler(tau_s=10.0)
    fs.register("hog", 1.0, lambda: 0)
    fs.register("victim", 1.0, lambda: 5)      # victim is backlogged
    for _ in range(5):
        hog_delay = fs.throttle("hog", 10_000)
    assert hog_delay > 0.0                     # over share + sibling starved
    fs.register("victim", 1.0, lambda: 5)
    assert fs.throttle("victim", 1) == 0.0     # under share: never sleeps
    # solo app never throttles, whatever its usage
    fs.unregister("victim")
    assert fs.throttle("hog", 10_000) == 0.0


def test_fair_scheduler_idle_siblings_do_not_throttle():
    fs = FairScheduler(tau_s=10.0)
    fs.register("hog", 1.0, lambda: 0)
    fs.register("idle", 1.0, lambda: 0)        # no backlog anywhere
    assert fs.throttle("hog", 10_000) == 0.0


# ----------------------------------------------------- observability


def test_quota_counters_predeclared_and_gauges_on_metrics():
    from siddhi_tpu.observability.export import prometheus_text

    m = SiddhiManager()
    rt, _c, _ctl = _mk(m, "metrics_app", queue_quota=8,
                       shed_policy="shed_newest", pipeline_quota=4,
                       memory_budget_mb=64)
    rt.start()
    text = prometheus_text(m)
    # the three new counters are pre-declared at 0 (dashboards first)
    for name in ("resilience.shed_events", "resilience.quota_denials",
                 "resilience.enqueue_timeouts"):
        assert f'siddhi_counter_total{{app="metrics_app",name="{name}"}} 0' \
            in text, name
    # per-app quota-utilization gauges
    assert ('siddhi_quota_utilization{app="metrics_app",resource="queue",'
            'stream="S"}') in text
    assert ('siddhi_quota_utilization{app="metrics_app",'
            'resource="pipeline"}') in text
    assert ('siddhi_quota_utilization{app="metrics_app",'
            'resource="memory"}') in text
    m.shutdown()


def test_shed_counter_exported_per_stream():
    from siddhi_tpu.observability.export import prometheus_text

    m = SiddhiManager()
    rt, c, ctl, total = _wedge_and_flood(m, "shedmetrics", "shed_newest")
    text = prometheus_text(m, "shedmetrics")
    assert "siddhi_junction_shed_events_total" in text
    assert f'stream="S"}} {ctl.shed_events}' in text
    m.shutdown()


def test_config_keys_register_overload():
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    m = SiddhiManager()
    m.siddhi_context.config_manager = InMemoryConfigManager({
        "siddhi_tpu.quota_queue_depth": "16",
        "siddhi_tpu.shed_policy": "shed_oldest",
        "siddhi_tpu.shed_policy.S": "shed_newest",
        "siddhi_tpu.quota_pipeline_depth": "8",
        "siddhi_tpu.quota_memory_mb": "128",
        "siddhi_tpu.fair_weight": "2.5",
        "siddhi_tpu.quota_query_cap": "32",
    })
    rt = m.create_siddhi_app_runtime(ASYNC_APP.format(name="cfg"))
    ctl = rt.app_context.overload
    assert ctl is not None
    assert ctl.config.queue_quota == 16
    assert ctl.config.shed_policy == "shed_oldest"
    assert ctl.policy_of(rt.junctions["S"]) == "shed_newest"
    assert ctl.config.pipeline_quota == 8
    assert ctl.config.memory_budget_bytes == 128 * 1024 * 1024
    assert ctl.config.fair_weight == 2.5
    assert ctl.query_cap == 32
    m.shutdown()


def test_old_runtime_shutdown_keeps_newer_same_named_registration():
    """Blue/green redeploys: shutting down the OLD runtime of a name must
    not strip the NEW runtime's quotas (unregister is identity-pinned)."""
    m_old = SiddhiManager()
    rt_old = m_old.create_siddhi_app_runtime(ASYNC_APP.format(name="bg"))
    ctl_old = rt_old.enable_overload(queue_quota=8)
    m_new = SiddhiManager()
    rt_new = m_new.create_siddhi_app_runtime(ASYNC_APP.format(name="bg"))
    ctl_new = rt_new.enable_overload(queue_quota=16)
    assert ctl_new is not ctl_old
    assert OverloadManager.instance().control_of("bg") is ctl_new
    m_old.shutdown()
    # the replacement keeps its registration and its control
    assert OverloadManager.instance().control_of("bg") is ctl_new
    assert rt_new.app_context.overload is ctl_new
    assert rt_old.app_context.overload is None
    m_new.shutdown()
    assert OverloadManager.instance().control_of("bg") is None


def test_bad_shed_policy_rejected():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(ASYNC_APP.format(name="badpolicy"))
    with pytest.raises(ValueError):
        rt.enable_overload(queue_quota=4, shed_policy="drop_everything")
    m.shutdown()
