"""Reference table-definition corpus — scenarios ported from
``query/table/DefineTableTestCase.java`` and
``query/table/InsertIntoTableTestCase.java``: duplicate/conflicting
definitions and insert-into schema equivalence."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.compiler.errors import (DuplicateDefinitionException,
                                        SiddhiAppValidationException,
                                        SiddhiParserException)
from siddhi_tpu.ops.expressions import CompileError

CREATION_ERRORS = (CompileError, SiddhiParserException,
                   SiddhiAppValidationException)


def build(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    return m, rt


def test_define_single_table():
    """testQuery1/2 (:45-68): plain table definitions compile."""
    m, _rt = build("define table TestTable(symbol string, price int, volume float);")
    m.shutdown()


def test_redefine_table_different_attribute_name():
    """testQuery3 (:70-79): same id, different attribute name — duplicate
    definition error."""
    with pytest.raises(DuplicateDefinitionException):
        build("""define table TestTable(symbol string, price int, volume float);
                 define table TestTable(symbols string, price int, volume float);""")


def test_redefine_table_different_arity():
    """testQuery4 (:81-90): same id, fewer attributes — duplicate
    definition error."""
    with pytest.raises(DuplicateDefinitionException):
        build("""define table TestTable(symbol string, volume float);
                 define table TestTable(symbols string, price int, volume float);""")


def test_redefine_table_identical_is_legal():
    """testQuery5 (:92-101): an identical re-definition is accepted."""
    m, _rt = build("""define table TestTable(symbol string, price int, volume float);
                      define table TestTable(symbol string, price int, volume float);""")
    m.shutdown()


def test_stream_then_table_same_id():
    """testQuery6 (:103-112): a table re-using a stream id conflicts."""
    with pytest.raises(DuplicateDefinitionException):
        build("""define stream TestTable(symbol string, price int, volume float);
                 define table TestTable(symbol string, price int, volume float);""")


def test_table_then_stream_same_id():
    """testQuery7 (:114-123): a stream re-using a table id conflicts."""
    with pytest.raises(DuplicateDefinitionException):
        build("""define table TestTable(symbol string, price int, volume float);
                 define stream TestTable(symbol string, price int, volume float);""")


def test_insert_into_table_type_conflict():
    """testQuery8/9 (:125-157): a query inserting (string,int,float) into a
    table defined (string,float,long) fails creation whichever side is
    declared first."""
    for app in [
        """define stream StockStream(symbol string, price int, volume float);
           from StockStream select symbol, price, volume insert into OutputStream;
           define table OutputStream (symbol string, price float, volume long);""",
        """define stream StockStream(symbol string, price int, volume float);
           define table OutputStream (symbol string, price float, volume long);
           from StockStream select symbol, price, volume insert into OutputStream;""",
    ]:
        with pytest.raises(CREATION_ERRORS):
            build(app)


def test_insert_into_table_arity_conflict():
    """testQuery10 (:159-173): inserting 2 columns into a 3-column table
    fails creation."""
    with pytest.raises(CREATION_ERRORS):
        build("""define stream StockStream(symbol string, price int, volume float);
                 define table OutputStream (symbol string, price float, volume long);
                 from StockStream select symbol, price insert into OutputStream;""")


def test_insert_into_matching_table():
    """testQuery11/12 (:175-205): schema-equivalent inserts (explicit and
    `select *`) compile and run."""
    for sel in ("symbol, price, volume", "*"):
        m, rt = build(f"""define stream StockStream(symbol string, price int, volume float);
            define table OutputStream (symbol string, price int, volume float);
            from StockStream select {sel} insert into OutputStream;""")
        rt.get_input_handler("StockStream").send(["IBM", 10, 1.5])
        assert len(rt.query("from OutputStream select *")) == 1
        m.shutdown()


def test_select_star_arity_conflicts():
    """testQuery13/14 (:207-237): `select *` into a wider table or a table
    with a different column type fails creation."""
    with pytest.raises(CREATION_ERRORS):
        build("""define stream StockStream(symbol string, price int, volume float);
                 define table OutputStream (symbol string, price int, volume float, time long);
                 from StockStream select * insert into OutputStream;""")
    with pytest.raises(CREATION_ERRORS):
        build("""define stream StockStream(symbol string, price int, volume float);
                 define table OutputStream (symbol string, price int, volume int);
                 from StockStream select * insert into OutputStream;""")


def test_query_from_table_as_stream_rejected():
    """testQuery15 (:239-253): `from <table>` as a plain stream source
    fails creation (tables are consumed via joins or on-demand queries)."""
    with pytest.raises(CREATION_ERRORS):
        build("""define stream StockStream(symbol string, price int, volume float);
                 define table OutputStream (symbol string, price int, volume float);
                 from OutputStream select symbol, price, volume insert into StockStream;""")


# ------------------------------------------- InsertIntoTableTestCase


def test_insert_then_join_sees_rows():
    """InsertIntoTableTestCase shape: inserted rows are visible to a
    subsequent join probe."""
    m, rt = build("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol == StockTable.symbol
        select StockTable.symbol, StockTable.price, StockTable.volume
        insert into OutStream;
    """)
    from siddhi_tpu.core.query.callback import QueryCallback

    class Q(QueryCallback):
        def __init__(self):
            self.events = []

        def receive(self, ts, ins, rms):
            if ins:
                self.events.extend(ins)

    q = Q()
    rt.add_callback("query2", q)
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 10])
    rt.get_input_handler("CheckStockStream").send(["IBM"])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("IBM", 75.5999984741211, 10)]


def test_insert_expired_events_from_window_into_table():
    """InsertIntoTableTestCase expired-mode shape: `insert expired events`
    from a length window lands the evicted rows in the table."""
    m, rt = build("""
        define stream StockStream (symbol string, price float, volume long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1')
        from StockStream#window.length(2)
        select symbol, price, volume
        insert expired events into StockTable;
    """)
    h = rt.get_input_handler("StockStream")
    h.send(["A", 1.0, 1])
    h.send(["B", 2.0, 2])
    h.send(["C", 3.0, 3])   # evicts A
    h.send(["D", 4.0, 4])   # evicts B
    got = sorted(e.data[0] for e in rt.query("from StockTable select *"))
    assert got == ["A", "B"]
    m.shutdown()
