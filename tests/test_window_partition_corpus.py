"""Reference window-partition corpus — scenarios ported verbatim from
``query/partition/WindowPartitionTestCase.java`` (feeds and expected
outputs; sleeps become playback clock jumps where timers must fire)."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStockStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


def _rows(c):
    return [tuple(e.data) for e in c.events]


def test_window_partition_q1_length_expired_events():
    """testWindowPartitionQuery1 (:49-92): per-key length(2) + sum,
    `insert expired events` — the expired row's aggregate DECREMENTS
    before the current event applies (chunk order expired-then-current,
    LengthWindowProcessor.java:124-137): IBM's third event expires 70
    when sum was 170 -> 100; WSO2's third expires 700 from 1700 -> 1000."""
    m, rt, c = build("""
        define stream cseEventStream (symbol string, price float, volume int);
        partition with (symbol of cseEventStream) begin
          @info(name = 'query1')
          from cseEventStream#window.length(2)
          select symbol, sum(price) as price, volume
          insert expired events into OutStockStream;
        end;
    """)
    h = rt.get_input_handler("cseEventStream")
    for row in [["IBM", 70.0, 100], ["WSO2", 700.0, 100], ["IBM", 100.0, 100],
                ["IBM", 200.0, 100], ["ORACLE", 75.6, 100],
                ["WSO2", 1000.0, 100], ["WSO2", 500.0, 100]]:
        h.send(row)
    m.shutdown()
    # stream-callback view: re-publish into the output junction flips
    # EXPIRED to CURRENT (InsertIntoStreamCallback.java:52-55) — the
    # reference test's counter name notwithstanding
    assert len(c.events) == 2
    assert _rows(c) == [("IBM", 100.0, 100), ("WSO2", 1000.0, 100)]


def test_window_partition_q2_length_batch_all_events():
    """testWindowPartitionQuery2 (:96-137): per-key lengthBatch(2) + sum,
    `insert all events` — one flush per completed per-key pair."""
    m, rt, c = build("""
        define stream cseEventStream (symbol string, price float, volume int);
        partition with (symbol of cseEventStream) begin
          @info(name = 'query1')
          from cseEventStream#window.lengthBatch(2)
          select symbol, sum(price) as price, volume
          insert all events into OutStockStream;
        end;
    """)
    h = rt.get_input_handler("cseEventStream")
    for row in [["IBM", 70.0, 100], ["WSO2", 700.0, 100], ["IBM", 100.0, 100],
                ["IBM", 200.0, 100], ["WSO2", 1000.0, 100]]:
        h.send(row)
    m.shutdown()
    current = [e for e in c.events if not e.is_expired]
    assert [tuple(e.data) for e in current] == [
        ("IBM", 170.0, 100), ("WSO2", 1700.0, 100)]


def test_window_partition_q3_time_window_default_sum():
    """testWindowPartitionQuery3 (:141-216): per-key time(1 sec) window,
    `default(sum(price), 0.0)` keeps expired-to-empty outputs at 0.0;
    per-key current/expired interleavings match the reference callback's
    asserted sequences."""
    m, rt, c = build("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int);
        define stream Tick (x int);
        partition with (symbol of cseEventStream) begin
          @info(name = 'query1')
          from cseEventStream#window.time(1 sec)
          select symbol, default(sum(price), 0.0) as price, volume
          insert all events into OutStockStream;
        end;
        from Tick select x insert into TickOut;
    """)
    h = rt.get_input_handler("cseEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 70.0, 100])
    h.send(1100, ["WSO2", 700.0, 100])
    h.send(1200, ["IBM", 100.0, 200])
    tick.send(4200, [0])                 # Thread.sleep(3000): all expire
    h.send(4300, ["IBM", 200.0, 300])
    h.send(4400, ["WSO2", 1000.0, 100])
    tick.send(6500, [0])                 # final drain past expiries
    m.shutdown()
    wso2 = [round(e.data[1], 4) for e in c.events if e.data[0] == "WSO2"]
    ibm = [round(e.data[1], 4) for e in c.events if e.data[0] == "IBM"]
    assert wso2 == [700.0, 0.0, 1000.0, 0.0]
    assert ibm == [70.0, 170.0, 100.0, 0.0, 200.0, 0.0]


def test_window_partition_q4_length_current_running_sums():
    """testWindowPartitionQuery4 (:223-...): per-key length(2) + sum,
    current events only — running per-key sums in arrival order."""
    m, rt, c = build("""
        define stream cseEventStream (symbol string, price float, volume int);
        partition with (symbol of cseEventStream) begin
          @info(name = 'query1')
          from cseEventStream#window.length(2)
          select symbol, sum(price) as price, volume
          insert into OutStockStream;
        end;
    """)
    h = rt.get_input_handler("cseEventStream")
    for row in [["IBM", 70.0, 100], ["WSO2", 700.0, 100], ["IBM", 100.0, 100],
                ["IBM", 200.0, 100], ["ORACLE", 75.6, 100],
                ["WSO2", 1000.0, 100], ["WSO2", 500.0, 100]]:
        h.send(row)
    m.shutdown()
    got = [round(e.data[1], 3) for e in c.events]
    assert got == [70.0, 700.0, 170.0, 300.0, 75.6, 1700.0, 1500.0], got
    assert not any(e.is_expired for e in c.events)


def test_window_partition_q5_time_batch():
    """testWindowPartitionQuery5: per-key timeBatch(5 sec) + sum — one
    aggregate row per key at the batch flush."""
    m, rt, c = build("""@app:playback
        define stream cseEventStream (symbol string, price double, volume int);
        define stream Tick (x int);
        partition with (symbol of cseEventStream) begin
          @info(name = 'query1')
          from cseEventStream#window.timeBatch(5 sec)
          select symbol, sum(price) as price, volume
          insert into OutStockStream;
        end;
        from Tick select x insert into TickOut;
    """)
    h = rt.get_input_handler("cseEventStream")
    for row in [["IBM", 70.0, 100], ["WSO2", 700.0, 100], ["IBM", 100.0, 100],
                ["IBM", 200.0, 100], ["ORACLE", 75.6, 100],
                ["WSO2", 1000.0, 100], ["WSO2", 500.0, 100]]:
        h.send(1000, row)
    rt.get_input_handler("Tick").send(7000, [0])   # Thread.sleep(7000)
    m.shutdown()
    by_sym = {e.data[0]: e.data[1] for e in c.events}
    assert by_sym == {"IBM": 370.0, "WSO2": 2200.0, "ORACLE": 75.6}
    assert not any(e.is_expired for e in c.events)


def test_window_partition_q6_length_batch_chained_query():
    """testWindowPartitionQuery6: partitioned lengthBatch(2) feeding a
    second pass-through query — both streams carry each key's flushed
    pair, 12 output events total."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream SensorStream (id string, sensorValue double);
        partition with (id of SensorStream) begin
          @info(name = 'query1')
          from SensorStream#window.lengthBatch(2)
          select id, sensorValue
          insert events into OutputStream;
          @info(name = 'query2')
          from OutputStream select * insert into TempStream;
        end;
    """)
    c1, c2 = Collector(), Collector()
    rt.add_callback("OutputStream", c1)
    rt.add_callback("TempStream", c2)
    h = rt.get_input_handler("SensorStream")
    for row in [["id1", 111.0], ["id1", 112.0], ["id2", 121.0],
                ["id2", 122.0], ["id3", 131.0], ["id3", 132.0]]:
        h.send(row)
    m.shutdown()
    expected = [("id1", 111.0), ("id1", 112.0), ("id2", 121.0),
                ("id2", 122.0), ("id3", 131.0), ("id3", 132.0)]
    assert _rows(c1) == expected
    assert [tuple(e.data) for e in c2.events] == expected
    assert len(c1.events) + len(c2.events) == 12
