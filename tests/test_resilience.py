"""Resilience subsystem (siddhi_tpu/resilience/): retry policy, ingest
WAL record/trim/replay, supervised worker restart, and the peer-death
recovery protocol — single-process coverage. The real 2-process
kill-a-peer recovery lives in tests/test_resilience_cluster.py; fault
injection soaks are @slow."""

import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore
from siddhi_tpu.resilience import (
    AppSupervisor,
    FaultInjector,
    IngestWAL,
    PeerRecovery,
    RetryPolicy,
)
from siddhi_tpu.resilience.retry import RetryExhausted


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)

    def rows(self):
        return [(e.timestamp, *e.data) for e in self.events]


def _wait_for(predicate, timeout=15.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if predicate():
            return True
        time.sleep(0.01)
    return False


# ------------------------------------------------------------ retry policy


def test_retry_schedule_exponential_with_cap():
    p = RetryPolicy(initial_ms=100, max_ms=1000, multiplier=2.0,
                    max_attempts=6)
    it = p.delays_ms()
    assert [next(it) for _ in range(6)] == [100, 200, 400, 800, 1000, 1000]


def test_retry_jitter_is_seeded_and_bounded():
    p1 = RetryPolicy(initial_ms=100, max_ms=1000, jitter=0.5, seed=7,
                     max_attempts=4)
    p2 = RetryPolicy(initial_ms=100, max_ms=1000, jitter=0.5, seed=7,
                     max_attempts=4)
    d1, d2 = list(p1.delays_ms()), list(p2.delays_ms())
    assert d1 == d2                      # deterministic under one seed
    for base, d in zip([100, 200, 400, 800], d1):
        assert base <= d <= base * 1.5   # jitter only ever ADDS, capped


def test_retry_run_absorbs_transient_failures():
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    p = RetryPolicy(initial_ms=10, max_ms=40)
    assert p.run(flaky, (OSError,), sleep=slept.append) == "ok"
    assert calls["n"] == 3
    assert slept == [0.01, 0.02]


def test_retry_run_exhausts_and_carries_cause():
    def always(
    ):
        raise OSError("down")

    p = RetryPolicy(initial_ms=1, max_ms=2, max_attempts=3)
    with pytest.raises(RetryExhausted, match="down"):
        p.run(always, (OSError,), sleep=lambda _s: None)


def test_retry_run_stop_aborts_cleanly():
    p = RetryPolicy(initial_ms=1, max_ms=2)
    calls = {"n": 0}

    def failing():
        calls["n"] += 1
        raise OSError("down")

    stop_after = lambda: calls["n"] >= 2  # noqa: E731
    assert p.run(failing, (OSError,), stop=stop_after,
                 sleep=lambda _s: None) is None
    assert calls["n"] == 2


# -------------------------------------------------------------- ingest WAL


def test_wal_bounds_drop_oldest_and_count():
    wal = IngestWAL(max_batches=3)
    from siddhi_tpu.core.event import Event

    for i in range(5):
        wal.record_events("S", [Event(timestamp=i, data=[i])])
    assert len(wal) == 3
    assert wal.dropped_batches == 2
    assert wal.recorded_batches == 5
    # the retained suffix is the NEWEST three
    assert [r.payload[0].timestamp for r in wal._log] == [2, 3, 4]


def test_wal_cut_trim_protocol_keeps_post_cut_batches():
    wal = IngestWAL(max_batches=100)
    from siddhi_tpu.core.event import Event

    wal.record_events("S", [Event(timestamp=1, data=[1])])
    cut = wal.cut()
    wal.record_events("S", [Event(timestamp=2, data=[2])])  # after capture
    assert wal.trim(cut) == 1
    assert len(wal) == 1               # the in-between batch survived
    assert wal._log[0].payload[0].timestamp == 2


APP_SUM = """
    @app:name('walApp')
    define stream S (sym string, v long);
    @info(name = 'q')
    from S#window.length(4)
    select sym, sum(v) as total
    group by sym
    insert into Out;
"""


def _uninterrupted_rows(sends):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP_SUM)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    for ts, data in sends:
        h.send(ts, list(data))
    m.shutdown()
    return c.rows()


SEG_A = [(1000 + i, [f"K{i % 3}", i]) for i in range(6)]
SEG_B = [(2000 + i, [f"K{i % 3}", 10 + i]) for i in range(5)]
SEG_C = [(3000 + i, [f"K{i % 3}", 100 + i]) for i in range(5)]


def test_checkpoint_trims_wal_and_restore_replays_suffix():
    """Effectively-once across runtimes: a restore of the checkpoint plus
    a WAL replay of the post-checkpoint suffix reproduces the exact output
    stream of an uninterrupted run — nothing lost, nothing doubled."""
    store = InMemoryPersistenceStore()
    m1 = SiddhiManager()
    m1.set_persistence_store(store)
    rt1 = m1.create_siddhi_app_runtime(APP_SUM)
    c1 = Collector()
    rt1.add_callback("Out", c1)
    wal = rt1.enable_wal()
    h = rt1.get_input_handler("S")
    for ts, data in SEG_A:
        h.send(ts, list(data))
    rt1.persist()
    assert len(wal) == 0               # checkpoint trimmed the prefix
    for ts, data in SEG_B:
        h.send(ts, list(data))
    assert len(wal) == len(SEG_B)      # the suffix is retained
    rows_before = c1.rows()
    m1.shutdown()

    # crash: a fresh process restores the revision, replays the suffix
    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP_SUM)
    c2 = Collector()
    rt2.add_callback("Out", c2)
    rt2.app_context.ingest_wal = wal   # survivor hands over its log
    assert rt2.restore_last_revision() is not None
    # replay already re-fed SEG_B; continue with SEG_C
    h2 = rt2.get_input_handler("S")
    for ts, data in SEG_C:
        h2.send(ts, list(data))
    m2.shutdown()

    expected = _uninterrupted_rows(SEG_A + SEG_B + SEG_C)
    assert rows_before[:len(SEG_A)] + c2.rows() == expected


def test_wal_records_columnar_batches_with_resolved_timestamps():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP_SUM)
    wal = rt.enable_wal()
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send_columns({"sym": np.array(["a", "b"], object),
                    "v": np.array([1, 2], np.int64)})
    assert len(wal) == 1
    rec = wal._log[0]
    assert rec.kind == "columns" and rec.size == 2
    # default-stamped batches record their RESOLVED ingest time so a
    # replay lands at the original position in event time
    assert rec.timestamps is not None and rec.timestamps.dtype == np.int64
    m.shutdown()


# --------------------------------------------------- supervised restart


APP_ASYNC = """
    @app:name('asyncApp')
    @Async(buffer.size='512', batch.size='32')
    define stream S (sym string, v long);
    @info(name = 'q')
    from S select sym, v insert into Out;
"""


def test_wedged_async_worker_is_replaced_without_loss_or_dup():
    """ISSUE acceptance: wedge an @Async junction worker via faults.py;
    the supervisor restarts it; every accepted batch is delivered exactly
    once (the stale worker retires on its generation token)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP_ASYNC)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send(1, ["warm", 0])
    assert _wait_for(lambda: len(c.events) == 1)

    sup = rt.supervise(interval_s=0.05, wedge_timeout_s=0.4)
    faults = FaultInjector()
    j = rt.junctions["S"]
    try:
        faults.wedge_worker(j)
        assert faults.wait_wedged(10.0)        # worker is stuck, alive
        for i in range(50):
            h.send(10 + i, [f"K{i % 4}", i])   # piles into the queue
        assert _wait_for(lambda: sup.worker_restarts >= 1)
        assert _wait_for(lambda: len(c.events) == 51), len(c.events)
        faults.release()                       # stale worker wakes, retires
        time.sleep(0.3)
        vs = [e.data[1] for e in c.events[1:]]
        assert vs == list(range(50))           # exactly once, in order
        assert sup.worker_restarts == 1
    finally:
        faults.clear()
        m.shutdown()


def test_killed_async_worker_is_restarted():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP_ASYNC)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send(1, ["warm", 0])
    assert _wait_for(lambda: len(c.events) == 1)

    rt.set_statistics_level("basic")
    sup = rt.supervise(interval_s=0.05, wedge_timeout_s=5.0)
    faults = FaultInjector()
    j = rt.junctions["S"]
    worker_before = j._worker
    try:
        faults.kill_worker(j)
        assert _wait_for(lambda: not worker_before.is_alive())
        assert _wait_for(lambda: sup.worker_restarts >= 1)
        for i in range(20):
            h.send(10 + i, [f"K{i % 4}", i])
        assert _wait_for(lambda: len(c.events) == 21), len(c.events)
        assert [e.data[1] for e in c.events[1:]] == list(range(20))
        counters = rt.statistics().get("counters", {})
        assert counters.get("resilience.worker_restarts", 0) >= 1
    finally:
        faults.clear()
        m.shutdown()


# ----------------------------------------------------- peer recovery


APP_SHARDED = """
    @app:name('peerApp')
    define stream S (sym string, v long);
    @info(name = 'q')
    from S#window.length(4)
    select sym, sum(v) as total
    group by sym
    insert into Out;
"""


def test_peer_death_triggers_full_recovery_protocol():
    """drop_peer makes the sharded step raise ClusterPeerError; the
    supervisor must run the whole distributed.py protocol: abandon the
    wedged runtime, rebuild, restore the last revision, replay the WAL
    suffix, resume — and the combined output stream must equal an
    uninterrupted run's."""
    from siddhi_tpu.parallel.mesh import make_mesh, shard_query_step

    store = InMemoryPersistenceStore()
    m1 = SiddhiManager()
    m1.set_persistence_store(store)
    rt1 = m1.create_siddhi_app_runtime(APP_SHARDED)
    c1 = Collector()
    rt1.add_callback("Out", c1)
    shard_query_step(rt1.query_runtimes["q"], make_mesh())
    rt1.app_context.cluster_step_timeout = 5.0
    wal = rt1.enable_wal()
    h1 = rt1.get_input_handler("S")

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    c2 = Collector()
    built = {}

    def rebuild():
        rt2 = m2.create_siddhi_app_runtime(APP_SHARDED)
        rt2.add_callback("Out", c2)
        shard_query_step(rt2.query_runtimes["q"], make_mesh())
        built["rt"] = rt2
        return rt2

    sup = rt1.supervise(interval_s=0.05,
                        peer_recovery=PeerRecovery(rebuild, wal=wal))
    assert isinstance(sup, AppSupervisor)

    faults = FaultInjector()
    try:
        for ts, data in SEG_A:
            h1.send(ts, list(data))
        rev = rt1.persist()
        for ts, data in SEG_B[:-1]:
            h1.send(ts, list(data))
        rows_before = c1.rows()

        faults.drop_peer()
        # the doomed batch IS accepted (WAL) before its step dies — it
        # must come back in the replay, not be lost
        h1.send(SEG_B[-1][0], list(SEG_B[-1][1]))
        result = sup.wait_recovered(60.0)
        assert result is not None, "recovery did not run"
        new_rt, restored = result
        assert restored == rev
        assert new_rt is built["rt"]
        faults.restore_peer()

        h2 = new_rt.get_input_handler("S")
        for ts, data in SEG_C:
            h2.send(ts, list(data))

        # the recovered stream must continue EXACTLY where the checkpoint
        # left off: replayed SEG_B then SEG_C, as an uninterrupted run
        # would have produced them on top of SEG_A's state — no batch
        # lost (the doomed one included), none doubled
        expected = _uninterrupted_rows(SEG_A + SEG_B + SEG_C)
        expected_a = _uninterrupted_rows(SEG_A)
        assert rows_before[:len(expected_a)] == expected_a
        assert c2.rows() == expected[len(expected_a):]
        counters = new_rt.statistics().get("counters", {}) \
            if new_rt.app_context.statistics_manager else {}
        # counters only exist when statistics are on; protocol result is
        # the real assertion above
        assert counters == {} or counters.get(
            "resilience.peer_recoveries", 0) >= 1
    finally:
        faults.clear()
        m2.shutdown()
        m1.shutdown()


# ------------------------------------------------------------ sink retry


def test_sink_publish_retries_through_transport_blips():
    from siddhi_tpu.core.util.transport import InMemoryBroker

    got = []

    class Sub:
        topic = "resil"

        def on_message(self, payload):
            got.append(payload)

    sub = Sub()
    InMemoryBroker.subscribe(sub)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:name('sinkApp')
        @sink(type='inMemory', topic='resil', @map(type='passthrough'))
        define stream S (sym string, v long);
    """)
    rt.set_statistics_level("basic")
    faults = FaultInjector()
    try:
        sr = rt.sink_runtimes[0]
        # fast policy so the test doesn't sit in backoff
        sr.retry_policy = RetryPolicy(initial_ms=1, max_ms=5, max_attempts=8)
        rt.start()
        faults.fail_publishes(sr.sinks[0], n=2)
        rt.get_input_handler("S").send(1000, ["a", 1])
        assert _wait_for(lambda: len(got) == 1), got
        counters = rt.statistics().get("counters", {})
        assert counters.get("resilience.sink_retries", 0) == 2
    finally:
        faults.clear()
        InMemoryBroker.unsubscribe(sub)
        m.shutdown()


def test_source_reconnect_uses_shared_retry_policy():
    """The source retry loop is driven by resilience.retry.RetryPolicy —
    stop() aborts it, and retries are counted on the app statistics."""
    from siddhi_tpu.core.stream.input.source import (
        ConnectionUnavailableException,
        SourceRuntime,
    )

    class FlakySource:
        def __init__(self):
            self.calls = 0

        def connect(self):
            self.calls += 1
            if self.calls < 4:
                raise ConnectionUnavailableException("not yet")

        def disconnect(self):
            pass

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (sym string, v long);")
    rt.set_statistics_level("basic")
    src = FlakySource()
    sr = SourceRuntime(src, mapper=None,
                       input_handler=rt.get_input_handler("S"),
                       app_context=rt.app_context,
                       retry_policy=RetryPolicy(initial_ms=1, max_ms=4))
    sr.connect_with_retry()
    assert src.calls == 4 and sr._connected
    counters = rt.statistics().get("counters", {})
    assert counters.get("resilience.source_retries", 0) == 3
    m.shutdown()


# ------------------------------------------------------------------ soak


@pytest.mark.slow
def test_soak_repeated_worker_faults_under_load():
    """Fault-injection soak (tier-2): alternate kills and wedges against
    an @Async junction under continuous load; every accepted event must
    come out exactly once, in order, across many supervised restarts."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP_ASYNC)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send(1, ["warm", -1])
    assert _wait_for(lambda: len(c.events) == 1)

    sup = rt.supervise(interval_s=0.05, wedge_timeout_s=0.3)
    faults = FaultInjector()
    j = rt.junctions["S"]
    sent = 0
    try:
        for cycle in range(10):
            if cycle % 2 == 0:
                faults.kill_worker(j)
            else:
                faults.wedge_worker(j)
            for i in range(200):
                h.send(10 + sent, [f"K{sent % 7}", sent])
                sent += 1
            if cycle % 2 == 1:
                assert faults.wait_wedged(15.0)
                assert _wait_for(
                    lambda n=sup.worker_restarts: sup.worker_restarts > n
                    or len(c.events) == sent + 1, 20.0)
                faults.release()
            assert _wait_for(lambda: len(c.events) == sent + 1, 30.0), (
                cycle, len(c.events), sent + 1)
        assert [e.data[1] for e in c.events[1:]] == list(range(sent))
        assert sup.worker_restarts >= 5
    finally:
        faults.clear()
        m.shutdown()


# --------------------------------------- checkpoint consistency (review)


def test_persist_drains_async_queue_before_wal_cut():
    """The WAL records at the InputHandler boundary, BEFORE the @Async
    queue: a persist racing queued-but-undelivered batches must drain
    them into the snapshot before cutting the log, or the trim drops
    events whose effects the snapshot never saw (silent loss)."""
    store = InMemoryPersistenceStore()
    m1 = SiddhiManager()
    m1.set_persistence_store(store)
    rt1 = m1.create_siddhi_app_runtime("""
        @app:name('asyncPersist')
        @Async(buffer.size='512', batch.size='32')
        define stream S (sym string, v long);
        @info(name = 'q')
        from S#window.length(4)
        select sym, sum(v) as total group by sym
        insert into Out;
    """)
    c1 = Collector()
    rt1.add_callback("Out", c1)
    wal = rt1.enable_wal()
    h = rt1.get_input_handler("S")
    for ts, data in SEG_A:
        h.send(ts, list(data))
    rt1.persist()          # queue may still hold every batch: must drain
    assert len(wal) == 0, "drained checkpoint should trim the whole log"
    for ts, data in SEG_B:
        h.send(ts, list(data))
    assert _wait_for(lambda: len(c1.events) == len(SEG_A) + len(SEG_B))
    m1.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime("""
        @app:name('asyncPersist')
        @Async(buffer.size='512', batch.size='32')
        define stream S (sym string, v long);
        @info(name = 'q')
        from S#window.length(4)
        select sym, sum(v) as total group by sym
        insert into Out;
    """)
    c2 = Collector()
    rt2.add_callback("Out", c2)
    rt2.app_context.ingest_wal = wal
    assert rt2.restore_last_revision() is not None
    assert _wait_for(lambda: len(c2.events) == len(SEG_B))
    m2.shutdown()
    # nothing lost at the cut, nothing doubled by the replay
    expected = _uninterrupted_rows(SEG_A + SEG_B)
    got = [(e.timestamp, *e.data) for e in c1.events[:len(SEG_A)]] + \
          [(e.timestamp, *e.data) for e in c2.events]
    assert got == expected


def test_wal_replay_bypasses_enforce_order_watermark():
    """An IN-PROCESS restore rewinds state but not the InputHandler's
    @app:enforceOrder watermark; the replayed suffix re-enters with its
    original (older) timestamps and must not be rejected against it."""
    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime("""
        @app:name('orderApp')
        @app:enforceOrder
        define stream S (sym string, v long);
        @info(name = 'q')
        from S#window.length(4)
        select sym, sum(v) as total group by sym
        insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    rt.enable_wal()
    h = rt.get_input_handler("S")
    for ts, data in SEG_A:
        h.send(ts, list(data))
    rev = rt.persist()
    for ts, data in SEG_B:
        h.send(ts, list(data))
    n_live = len(c.events)
    rt.restore_revision(rev)       # replays SEG_B behind the watermark
    assert len(c.events) == n_live + len(SEG_B)
    replayed = [(e.timestamp, *e.data) for e in c.events[n_live:]]
    expected = _uninterrupted_rows(SEG_A + SEG_B)[len(SEG_A):]
    assert replayed == expected
    # live ingest continues under the (kept) watermark
    with pytest.raises(ValueError, match="enforceOrder"):
        h.send(1, ["late", 0])
    m.shutdown()
