"""Reference table-join corpus — scenarios ported verbatim from
``query/table/JoinTableTestCase.java`` (feeds and expected outputs)."""

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.query.callback import QueryCallback


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


class Chunks(StreamCallback):
    def __init__(self):
        super().__init__()
        self.chunks = []

    def receive(self, events):
        self.chunks.append([tuple(e.data) for e in events])


def build_q(app, query):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback(query, q)
    return m, rt, q


STOCKS = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string);
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""


def test_table_join_unconditional():
    """testTableJoinQuery1 (:47-104): windowed stream joins every table
    row (no on-condition)."""
    m, rt, q = build_q("""
        define stream StockStream (symbol2 string, price2 float, volume2 long);
        define stream CheckStockStream (symbol1 string);
        define table StockTable (symbol2 string, price2 float, volume2 long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from CheckStockStream#window.length(1) join StockTable
        select symbol1, symbol2, volume2 insert into OutputStream;
    """, "query2")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 75.6, 10])
    check.send(["WSO2"])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("WSO2", "WSO2", 100), ("WSO2", "IBM", 10)]
    assert q.expired == []


def test_table_join_on_equality():
    """testTableJoinQuery2 (:106-171): on-condition narrows to the
    matching row."""
    m, rt, q = build_q(STOCKS + """
        @info(name = 'query2')
        from CheckStockStream#window.length(1) join StockTable
        on CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol as checkSymbol, StockTable.symbol as symbol,
               StockTable.volume as volume
        insert into OutputStream;
    """, "query2")
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 10])
    rt.get_input_handler("CheckStockStream").send(["WSO2"])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("WSO2", "WSO2", 100)]


def test_table_join_inequality_with_alias():
    """testTableJoinQuery3 (:173-238): `join StockTable as t` with a !=
    condition matches the other row."""
    m, rt, q = build_q(STOCKS + """
        @info(name = 'query2')
        from CheckStockStream#window.length(1) join StockTable as t
        on CheckStockStream.symbol != t.symbol
        select CheckStockStream.symbol as checkSymbol, t.symbol as symbol,
               t.volume as volume
        insert into OutputStream;
    """, "query2")
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 10])
    rt.get_input_handler("CheckStockStream").send(["WSO2"])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("WSO2", "IBM", 10)]


def test_table_join_windowless_stream():
    """testTableJoinQuery5 (:340-397): a bare (window-less) stream side
    joins the full table per arrival."""
    m, rt, q = build_q(STOCKS + """
        @info(name = 'query2')
        from CheckStockStream join StockTable
        select CheckStockStream.symbol as checkSymbol, StockTable.symbol as symbol,
               StockTable.volume as volume
        insert into OutputStream;
    """, "query2")
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 10])
    rt.get_input_handler("CheckStockStream").send(["WSO2"])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("WSO2", "WSO2", 100), ("WSO2", "IBM", 10)]


def test_table_join_recursive_route():
    """testTableJoinQuery6 (:399-394+): recursive routing — a request A→D
    walks the TimeTable hop by hop through a cyclic stream graph and the
    total elapsed time (25+10+60) reaches ResultStream."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream RequestStream (start string, end string);
        define stream TimeTableStream (start string, end string, elapsedTime int, startTime string);
        define stream ResultStream (totalElapsedTime int);
        define table TimeTable (start string, end string, elapsedTime int, startTime string);
        from TimeTableStream select * insert into TimeTable;
        from RequestStream join TimeTable
        on TimeTable.start == RequestStream.start
        select TimeTable.start as start, TimeTable.end as end,
               TimeTable.elapsedTime as elapsedTime, RequestStream.end as destination
        insert into intermediateResultStream;
        @info(name = 'query1')
        from intermediateResultStream[end == destination]
        select intermediateResultStream.elapsedTime as totalElapsedTime
        insert into ResultStream;
        from intermediateResultStream[end != destination]
        insert into intermediateResultStream2;
        from intermediateResultStream2 join TimeTable
        on TimeTable.start == intermediateResultStream2.end
        select TimeTable.start as start, TimeTable.end as end,
               (intermediateResultStream2.elapsedTime + TimeTable.elapsedTime) as elapsedTime,
               destination
        insert into intermediateResultStream;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    tt = rt.get_input_handler("TimeTableStream")
    req = rt.get_input_handler("RequestStream")
    tt.send(["A", "B", 25, "1.27PM"])
    tt.send(["B", "C", 10, "1.52PM"])
    tt.send(["C", "D", 60, "2.52PM"])
    req.send(["A", "D"])
    m.shutdown()
    assert [e.data[0] for e in q.events] == [95]


def test_table_join_unqualified_attribute_condition():
    """testTableJoinQuery7 (:470-530): bare attribute names in the
    on-condition resolve across sides (symbol1 == symbol2)."""
    m, rt, q = build_q("""
        define stream StockStream (symbol2 string, price2 float, volume2 long);
        define stream CheckStockStream (symbol1 string);
        define table StockTable (symbol2 string, price2 float, volume2 long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from CheckStockStream#window.length(1) join StockTable
        on symbol1 == symbol2
        select symbol1, symbol2, volume2 insert into OutputStream;
    """, "query2")
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 10])
    rt.get_input_handler("CheckStockStream").send(["WSO2"])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("WSO2", "WSO2", 100)]


def test_table_join_compound_condition():
    """testTableJoinQuery8 (:532-596): and-of-comparisons over string and
    long attributes (a.volume1 > b.volume1)."""
    m, rt, q = build_q("""
        define stream StockStream (symbol1 string, price1 string, volume1 long);
        define stream CheckStockStream (symbol1 string, price1 string, volume1 long);
        define table StockTable (symbol1 string, price1 string, volume1 long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from CheckStockStream as a join StockTable as b
        on a.symbol1 == b.symbol1 and a.price1 == b.price1 and a.volume1 > b.volume1
        select a.symbol1 insert into OutputStream;
    """, "query2")
    rt.get_input_handler("StockStream").send(["WSO2", "55.6f", 100])
    rt.get_input_handler("StockStream").send(["IBM", "75.6f", 10])
    rt.get_input_handler("CheckStockStream").send(["WSO2", "55.6f", 200])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [("WSO2",)]


def test_table_join_group_by_aggregate():
    """testTableJoinQuery9 (:598-670): group-by sum over the table side —
    each 2-event trigger chunk emits 4 rows (2 triggers × 2 groups) with
    running totals 120.0 / 4.0 repeated."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol1 string, price1 float, volume1 long);
        define stream CheckStockStream (symbol1 string, price1 float, volume1 long);
        define table StockTable (symbol1 string, price1 float, volume1 long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from CheckStockStream as a join StockTable as b
        select b.symbol1, sum(b.price1) as total
        group by b.symbol1
        insert into OutputStream;
    """)
    c = Chunks()
    rt.add_callback("OutputStream", c)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["IBM", 50.0, 100])
    stock.send(["IBM", 70.0, 10])
    stock.send(["WSO2", 1.0, 10])
    stock.send(["WSO2", 1.0, 10])
    stock.send(["WSO2", 2.0, 10])
    import numpy as np
    check.send_columns({"symbol1": np.array(["Foo", "Foo"]),
                        "price1": np.array([55.6, 55.6], np.float32),
                        "volume1": np.array([200, 200], np.int64)})
    check.send_columns({"symbol1": np.array(["Foo", "Foo"]),
                        "price1": np.array([55.6, 55.6], np.float32),
                        "volume1": np.array([200, 200], np.int64)})
    m.shutdown()
    assert len(c.chunks) == 2
    for chunk in c.chunks:
        assert [row[1] for row in chunk] == [120.0, 4.0, 120.0, 4.0]


def test_table_join_filtered_trigger():
    """testTableJoinQuery10 (:672-735): a filter on the trigger side gates
    the join."""
    m, rt, q = build_q(STOCKS + """
        @info(name = 'query2')
        from CheckStockStream[symbol == 'WSO2'] join StockTable
        select CheckStockStream.symbol as checkSymbol, StockTable.symbol as symbol,
               StockTable.volume as volume
        insert into OutputStream;
    """, "query2")
    rt.get_input_handler("StockStream").send(["WSO2", 55.6, 100])
    rt.get_input_handler("StockStream").send(["IBM", 75.6, 10])
    rt.get_input_handler("CheckStockStream").send(["IBM"])   # filtered out
    rt.get_input_handler("CheckStockStream").send(["WSO2"])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("WSO2", "WSO2", 100), ("WSO2", "IBM", 10)]
