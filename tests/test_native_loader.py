"""Native (C++) CSV ingest loader: parse correctness, dictionary sync,
null fields, and end-to-end through send_columns."""

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.native import CsvLoader


def test_csv_loader_parses_typed_columns():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (sym string, price double, volume long, ok bool);"
        "from S select sym insert into Out;")
    loader = CsvLoader(rt.stream_definitions["S"],
                       rt.app_context.string_dictionary)
    cols, n = loader.parse(b"IBM,55.5,100,true\nWSO2,7.25,42,false\n")
    m.shutdown()
    assert n == 2
    dic = rt.app_context.string_dictionary
    assert [dic.decode(int(i)) for i in cols["sym"]] == ["IBM", "WSO2"]
    assert cols["price"].tolist() == [55.5, 7.25]
    assert cols["volume"].tolist() == [100, 42]
    assert cols["ok"].tolist() == [True, False]


def test_csv_loader_nulls_and_dictionary_reuse():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (sym string, price double);"
        "from S select sym insert into Out;")
    loader = CsvLoader(rt.stream_definitions["S"],
                       rt.app_context.string_dictionary)
    cols, n = loader.parse(b"A,1.5\n,\nA,2.5\n")
    m.shutdown()
    assert n == 3
    assert cols["sym?"].tolist() == [False, True, False]
    assert cols["price?"].tolist() == [False, True, False]
    assert cols["sym"][0] == cols["sym"][2]     # same dictionary id


def test_csv_loader_end_to_end():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, price double);
        from S[price > 10.0] select sym, price insert into Out;
    """)
    seen = []

    class C(StreamCallback):
        def receive(self, events):
            seen.extend(tuple(e.data) for e in events)

    rt.add_callback("Out", C())
    loader = CsvLoader(rt.stream_definitions["S"],
                       rt.app_context.string_dictionary)
    cols, n = loader.parse(b"IBM,55.5\nWSO2,7.25\nGOOG,20.0\n")
    rt.get_input_handler("S").send_columns(
        cols, timestamps=np.arange(n, dtype=np.int64))
    m.shutdown()
    assert seen == [("IBM", 55.5), ("GOOG", 20.0)]
