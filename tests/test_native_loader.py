"""Native (C++) CSV ingest loader: parse correctness, dictionary sync,
null fields, and end-to-end through send_columns."""

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.native import CsvLoader


def test_csv_loader_parses_typed_columns():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (sym string, price double, volume long, ok bool);"
        "from S select sym insert into Out;")
    loader = CsvLoader(rt.stream_definitions["S"],
                       rt.app_context.string_dictionary)
    cols, n = loader.parse(b"IBM,55.5,100,true\nWSO2,7.25,42,false\n")
    m.shutdown()
    assert n == 2
    dic = rt.app_context.string_dictionary
    assert [dic.decode(int(i)) for i in cols["sym"]] == ["IBM", "WSO2"]
    assert cols["price"].tolist() == [55.5, 7.25]
    assert cols["volume"].tolist() == [100, 42]
    assert cols["ok"].tolist() == [True, False]


def test_csv_loader_nulls_and_dictionary_reuse():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (sym string, price double);"
        "from S select sym insert into Out;")
    loader = CsvLoader(rt.stream_definitions["S"],
                       rt.app_context.string_dictionary)
    cols, n = loader.parse(b"A,1.5\n,\nA,2.5\n")
    m.shutdown()
    assert n == 3
    assert cols["sym?"].tolist() == [False, True, False]
    assert cols["price?"].tolist() == [False, True, False]
    assert cols["sym"][0] == cols["sym"][2]     # same dictionary id


def test_csv_loader_end_to_end():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, price double);
        from S[price > 10.0] select sym, price insert into Out;
    """)
    seen = []

    class C(StreamCallback):
        def receive(self, events):
            seen.extend(tuple(e.data) for e in events)

    rt.add_callback("Out", C())
    loader = CsvLoader(rt.stream_definitions["S"],
                       rt.app_context.string_dictionary)
    cols, n = loader.parse(b"IBM,55.5\nWSO2,7.25\nGOOG,20.0\n")
    rt.get_input_handler("S").send_columns(
        cols, timestamps=np.arange(n, dtype=np.int64))
    m.shutdown()
    assert seen == [("IBM", 55.5), ("GOOG", 20.0)]


def test_jsonl_loader_parses_typed_columns():
    from siddhi_tpu.core.event import StringDictionary
    from siddhi_tpu.native import JsonlLoader
    from siddhi_tpu.query_api.definitions import (
        Attribute, AttrType, StreamDefinition,
    )

    d = StreamDefinition("S", [
        Attribute("sym", AttrType.STRING),
        Attribute("price", AttrType.DOUBLE),
        Attribute("vol", AttrType.LONG),
        Attribute("ok", AttrType.BOOL),
    ])
    dic = StringDictionary()
    loader = JsonlLoader(d, dic)
    data = (b'{"sym": "IBM", "price": 42.5, "vol": 100, "ok": true}\n'
            b'{"price": 1.25, "sym": "W\\"X", "vol": null, "ok": false}\n'
            b'{"sym": "IBM", "extra": 9, "price": 7, "vol": 3, "ok": true}\n')
    cols, n = loader.parse(data)
    assert n == 3
    assert [dic.decode(int(i)) for i in cols["sym"]] == ["IBM", 'W"X', "IBM"]
    assert list(cols["price"]) == [42.5, 1.25, 7.0]
    assert list(cols["vol"]) == [100, 0, 3]
    assert list(cols["vol?"]) == [False, True, False]
    assert list(cols["ok"]) == [True, False, True]


def test_jsonl_loader_end_to_end():
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.native import JsonlLoader

    class C(StreamCallback):
        def __init__(self):
            super().__init__()
            self.rows = []

        def receive(self, events):
            self.rows.extend(tuple(e.data) for e in events)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, price double);
        from S[price > 10.0] select sym, price insert into Out;
    """)
    c = C()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    loader = JsonlLoader(rt.app_context.definitions["S"]
                         if hasattr(rt.app_context, "definitions")
                         else rt.query_runtimes[
                             next(iter(rt.query_runtimes))].input_definition,
                         rt.app_context.string_dictionary)
    cols, n = loader.parse(b'{"sym": "A", "price": 50.0}\n'
                           b'{"sym": "B", "price": 5.0}\n')
    h.send_columns({k: v for k, v in cols.items()})
    m.shutdown()
    assert c.rows == [("A", 50.0)]


def test_jsonl_loader_unicode_escapes():
    import json as _json

    from siddhi_tpu.core.event import StringDictionary
    from siddhi_tpu.native import JsonlLoader
    from siddhi_tpu.query_api.definitions import (
        Attribute, AttrType, StreamDefinition,
    )

    d = StreamDefinition("S", [Attribute("sym", AttrType.STRING)])
    dic = StringDictionary()
    loader = JsonlLoader(d, dic)
    vals = ["café", "日本", "emoji 🎉", 'quote"inside']
    data = "".join(_json.dumps({"sym": v}) + "\n" for v in vals).encode()
    cols, n = loader.parse(data)
    assert n == len(vals)
    assert [dic.decode(int(i)) for i in cols["sym"]] == vals
