"""Partition @purge: idle keys retire, their dense ids recycle, and the
reused id's state rows start clean (reference PartitionRuntimeImpl purge)."""

import time

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def test_purge_frees_idle_keys_and_recycles_ids():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (k string, v int);
        @purge(enable='true', interval='10 sec', idle.period='1 hour')
        partition with (k of S)
        begin
          from S#window.length(4) select k, sum(v) as s insert into OutStream;
        end;
    """)
    c = Collector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("S")
    h.send(["p1", 10])
    h.send(["p1", 20])     # p1 running sum: 30
    h.send(["p2", 5])
    pctx = rt.partition_contexts[0]
    assert pctx.purge_interval_ms == 10_000 and pctx.purge_idle_ms == 3600_000
    ks = pctx.keyspace
    p1_id = ks._map[(rt.app_context.string_dictionary.encode("p1"),)]
    # make p1 look idle for > 1 hour; p2 stays fresh
    ks.last_seen[p1_id] = int(time.time() * 1000) - 2 * 3600_000
    freed = pctx.purge()
    assert freed == [p1_id]
    # a NEW key reuses p1's dense id with a CLEAN window/selector row
    h.send(["p3", 7])
    p3_id = ks._map[(rt.app_context.string_dictionary.encode("p3"),)]
    assert p3_id == p1_id
    got = [tuple(e.data) for e in c.events]
    m.shutdown()
    # p3's sum starts at 7 — no leakage from p1's 30
    assert got[-1] == ("p3", 7)
    # p2 untouched
    assert ("p2", 5) in got


def test_purge_survives_persistence_roundtrip():
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    APP = """
        define stream S (k string, v int);
        @purge(enable='true')
        partition with (k of S)
        begin
          from S#window.length(4) select k, sum(v) as s insert into OutStream;
        end;
    """
    rt = m.create_siddhi_app_runtime(APP)
    h = rt.get_input_handler("S")
    h.send(["p1", 1])
    pctx = rt.partition_contexts[0]
    p1_id = pctx.keyspace._map[(rt.app_context.string_dictionary.encode("p1"),)]
    pctx.keyspace.last_seen[p1_id] = 0
    pctx.purge(now_ms=int(time.time() * 1000))
    rt.persist()
    m.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    rt2.restore_last_revision()
    ks2 = rt2.partition_contexts[0].keyspace
    # the freed id survived the snapshot and is reusable
    assert len(ks2._free) == 1
    c = Collector()
    rt2.add_callback("OutStream", c)
    rt2.get_input_handler("S").send(["px", 9])
    got = [tuple(e.data) for e in c.events]
    m2.shutdown()
    assert got == [("px", 9)]
