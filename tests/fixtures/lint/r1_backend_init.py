"""Known-bad R1 fixture: module-level jax.numpy evaluation (the PR-7
force_host_devices breaker) plus an eager backend call at import."""

import jax
import jax.numpy as jnp

BIG = jnp.int64(2 ** 62)            # materializes a device array at import
N_DEV = len(jax.devices())          # initializes the backend at import


def ok_inside_function():
    # lazy: evaluating jnp here is fine
    return jnp.zeros(3)


def bad_default(x=jnp.ones(2)):     # default args evaluate at import
    return x
