"""Known-bad R2 fixture: ad-hoc siddhi_tpu.* knob reads around the typed
parser registry (the PR-9 'false'-crashes-the-int()-loop class)."""

import os


def read_knobs(cm, app_context):
    # generic untyped loop: int() crashes on 'false', names no key
    for knob in ("window_capacity", "pipeline_depth"):
        v = cm.get_property(f"siddhi_tpu.{knob}")
        if v is not None:
            setattr(app_context, knob, int(v))
    # one-off read with its own inline parser
    grow = cm.get_property("siddhi_tpu.join_partition_grow")
    # env spelling dodging the registry too
    depth = os.environ.get("SIDDHI_TPU_PIPELINE_DEPTH")
    return grow, depth
