"""Known-bad R5 fixture: host pulls inside a jitted step body — each
one a synchronous device->host round trip per batch."""

import jax
import numpy as np


def build_step_fn(plan):
    def step(state, cols, now):
        total = float(state["sum"])          # scalar pull
        count = state["count"].item()        # .item() pull
        host = np.asarray(cols["price"])     # whole-column pull
        if bool(state["overflow"]):          # control-flow pull
            total = 0.0
        return state, {"t": total, "c": count, "h": host}

    return jax.jit(step)
