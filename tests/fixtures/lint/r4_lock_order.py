"""Known-bad R4 fixture: acquiring an owner lock while holding the pump
lock — the inversion of the declared owner -> pump order that the
CompletionPump contract (PR-5) forbids."""

from siddhi_tpu.analysis.locks import make_lock


class BadPump:
    def __init__(self):
        self._lock = make_lock("pump")

    def drain_all(self, owners):
        with self._lock:                 # pump held...
            for owner in owners:
                with owner._lock:        # ...owner acquired: inversion
                    owner.flush()

    def barrier_under_owner(self, owner, app):
        with owner._lock:
            with app._barrier:           # barrier must wrap owner
                app.persist()
