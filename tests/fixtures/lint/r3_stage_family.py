"""Known-bad R3 fixture for the critical-path profiler families: a
``siddhi_stage_ms`` family literal outside export.py, and a ``stage.*``
GAUGE with no unregister path (journey.py itself registers only
histograms, which are exempt from the remove pairing — a gauge under
the prefix must pair or be declared process-lifetime)."""


def register(tel, query):
    # gauge under the declared 'stage' prefix, never removed and not in
    # PROCESS_LIFETIME_GAUGES
    tel.gauge(f"stage.{query}.dispatch.last_ms", lambda: 0.0)
    # family literal outside export.py
    return "siddhi_stage_ms"
