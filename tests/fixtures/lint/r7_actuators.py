"""Known-bad fixture for graftlint R7 (actuator parity).

Declares (a) an actuator driving a knob that no Knob(...) declaration
in core/util/knobs.py produces, (b) that same actuator referenced by no
PolicyRule (dead control surface), and (c) a policy rule naming an
actuator nobody declares (an actuation path that silently never fires)
— all three must be findings."""

from siddhi_tpu.autopilot.actuators import Actuator
from siddhi_tpu.autopilot.policy import PolicyRule


def _noop(rt, direction):
    return (0, 0)


GHOST = Actuator(name="ghost", knob="not_a_real_knob", lo=0, hi=1,
                 doc="drives an untyped knob and no rule references it",
                 apply=_noop)

PHANTOM_RULE = PolicyRule(name="phantom_pressure", actuator="phantom",
                          when=lambda sig: None)
