"""Known-bad fixture for graftlint R6 (device-instrument parity).

Declares instrument slots that (a) match no DEVICE_SLOTS declaration in
observability/export.py and (b) carry kind='check' with no
_consume_check_slot consumer anywhere — both must be findings."""

from siddhi_tpu.observability.instruments import Slot


class BadRuntime:
    def _step_instrument_slots(self):
        return [
            # undeclared data slot: its device.* telemetry would render
            # as an undeclared catch-all family
            Slot("ghost_fill"),
            # check slot nobody consumes at drain (also undeclared)
            Slot("phantom_check", kind="check"),
        ]
