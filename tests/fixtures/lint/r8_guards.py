"""Known-bad R8 fixture: every guarded-by failure mode in one file —
an unlocked read AND write of a declared field, a stale declaration
guarding nothing, and a thread-spawning class sharing a mutable dict
with no GUARDED_BY at all."""

import threading

from siddhi_tpu.analysis.locks import make_lock


class BadPendingTable:
    # '_pending' is declared pump-guarded but read and written outside
    # the lock; '_ghost' is declared but never used under any lock
    GUARDED_BY = {"_pending": "pump", "_ghost": "pump"}

    def __init__(self):
        self._lock = make_lock("pump")
        self._pending = {}
        self._ghost = 0

    def submit(self, key, value):
        self._pending[key] = value       # unlocked write: finding

    def oldest(self):
        if not self._pending:            # unlocked read: finding
            return None
        with self._lock:
            return min(self._pending)    # locked: fine


class BadWorkerPool:
    # spawns threads, mutates a shared dict from them, declares nothing
    def __init__(self):
        self._results = {}
        self._threads = []

    def start(self, n):
        for i in range(n):
            t = threading.Thread(target=self._work, args=(i,))
            self._threads.append(t)
            t.start()

    def _work(self, i):
        self._results[i] = i * i
