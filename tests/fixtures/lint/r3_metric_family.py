"""Known-bad R3 fixture: a telemetry family nobody declared in
export.py (renders as a generic catch-all), an out-of-band siddhi_*
family literal, and a gauge with no unregister path (the PR-6
registered-on-one-path-only class)."""


def register(tel, sid):
    # undeclared prefix: falls through to siddhi_gauge{name=...}
    tel.gauge(f"mystery.{sid}.depth", lambda: 0)
    # family literal outside export.py
    family = "siddhi_mystery_total"
    # counter under an undeclared prefix
    tel.count("mystery.events")
    return family
