"""Critical-path profiler (observability/journey.py + costmodel.py).

The load-bearing acceptance set: a PLANTED bottleneck (FaultInjector
delay in pack, and in an @Async queue) must be the stage the
critical-path report names, at pipeline depth 1 AND depth 4 — and
overlapped stages must be attributed by max, not sum (a slow host must
not make the device look busy for the full wall). Plus: the compiled-
program registry's fingerprint-duplicate clusters vs the fan-out
``unique_programs`` gauge on a 4-identical-query app, the new REST
endpoints, Prometheus label-value escaping under hostile names, and
scrape hygiene (no app barrier, wedged worker can't stall a scrape).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.util.config import InMemoryConfigManager
from siddhi_tpu.observability import costmodel, export, journey
from siddhi_tpu.resilience import FaultInjector


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


APP = """
define stream S (sym string, v long);
@info(name='pq')
from S#window.length(8)
  select sym, sum(v) as total group by sym
  insert into Out;
"""

ASYNC_APP = """
@Async(buffer.size='1024')
define stream S (sym string, v long);
@info(name='pq')
from S#window.length(8)
  select sym, sum(v) as total group by sym
  insert into Out;
"""


@pytest.fixture(autouse=True)
def _journey_off():
    yield
    journey.disable(force=True)
    journey.clear_delays()
    costmodel.disable(force=True)


def _manager(depth, extra=None):
    m = SiddhiManager()
    cfg = {"siddhi_tpu.pipeline_depth": str(depth)}
    cfg.update(extra or {})
    m.set_config_manager(InMemoryConfigManager(cfg))
    return m


def _warm(handler, n=3):
    """Sends BEFORE journeys are enabled: jit compiles land outside the
    measured window (a one-off 500 ms compile would otherwise drown a
    20 ms planted delay in the dispatch mean)."""
    for i in range(n):
        handler.send(["A", i])


def _bottleneck(m, rt, query="pq"):
    rep = journey.critical_path_report(m)
    q = rep["apps"][rt.name]["queries"][query]
    assert q["bottleneck"] is not None, q
    return q


# -------------------------------------------------- planted bottlenecks


@pytest.mark.parametrize("depth", [1, 4])
def test_pack_bottleneck_named(depth):
    """FaultInjector.delay_stage('pack'): the report must name pack —
    at depth 1 (synchronous) and depth 4 (pipelined submit path)."""
    m = _manager(depth)
    rt = m.create_siddhi_app_runtime(APP)
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    _warm(h)
    journey.enable()
    rt.app_context.telemetry.reset()
    inj = FaultInjector()
    inj.delay_stage("pack", 0.02)
    try:
        for i in range(8):
            h.send(["A", i])
    finally:
        inj.clear()
    q = _bottleneck(m, rt)
    assert q["bottleneck"]["stage"] == "pack", q["bottleneck"]
    assert q["stages"]["pack"]["mean_service_ms"] >= 15.0
    m.shutdown()


@pytest.mark.parametrize("depth", [1, 4])
def test_async_queue_bottleneck_named(depth):
    """A persistently delayed @Async worker makes the queue the place
    where the batch's latency goes: the report must attribute it to
    QUEUEING at the queue stage, not to any measured service."""
    m = _manager(depth)
    rt = m.create_siddhi_app_runtime(ASYNC_APP)
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    _warm(h)
    # warm the pad-16 batch shape too: the delayed worker coalesces the
    # measured sends into one unit, and a cold jit shape would charge a
    # one-off compile to the dispatch stage
    from siddhi_tpu.core.event import Event

    h.send([Event(timestamp=-1, data=["A", i]) for i in range(12)])
    time.sleep(0.3)         # async warmup batches fully drained
    journey.enable()
    rt.app_context.telemetry.reset()
    j = rt.junctions["S"]
    inj = FaultInjector()
    inj.delay_worker(j, 0.03, persistent=True)
    try:
        for i in range(12):
            h.send(["B", i])
            time.sleep(0.01)   # several worker iterations observe a wait
        # the worker may deliver the backlog as ONE coalesced unit (its
        # queue wait carries the first chunk's full residence) or as
        # several — either way at least one delivery with a recorded
        # queue wait must land and the queue must drain
        deadline = time.time() + 20
        while True:
            snap = rt.app_context.telemetry.snapshot().get("histograms", {})
            got = snap.get("stage.pq.queue.queue_ms", {}).get("count", 0)
            if got >= 1 and j._queue.qsize() == 0:
                break
            assert time.time() < deadline, \
                f"queue never drained ({got} deliveries observed)"
            time.sleep(0.05)
    finally:
        inj.clear()
    q = _bottleneck(m, rt)
    assert q["bottleneck"]["stage"] == "queue", q["bottleneck"]
    assert q["bottleneck"]["kind"] == "queueing"
    # the planted delay sits OUTSIDE every measured service window
    assert q["stages"]["queue"]["mean_queue_ms"] > 2 * max(
        q["stages"][s]["mean_service_ms"]
        for s in ("pack", "dispatch", "device"))
    m.shutdown()


def test_overlap_attributed_by_max_not_sum():
    """Depth 4, host-bound pipeline: outputs are READY at drain, so the
    ride must count as device slack (queue), NOT device service — the
    per-stage busy times must not each claim the wall."""
    m = _manager(4)
    rt = m.create_siddhi_app_runtime(APP)
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    _warm(h)
    journey.enable()
    rt.app_context.telemetry.reset()
    inj = FaultInjector()
    inj.delay_stage("pack", 0.02)
    try:
        for i in range(10):
            h.send(["A", i])
    finally:
        inj.clear()
    q = _bottleneck(m, rt)
    pack_busy = q["stages"]["pack"]["busy_ms"]
    dev_busy = q["stages"]["device"]["busy_ms"]
    assert q["bottleneck"]["stage"] == "pack"
    # max-not-sum: the device's attributed service is a small fraction
    # of the host bottleneck's busy time, and the total attributed busy
    # stays in the same ballpark as the wall (no double counting)
    assert dev_busy < 0.5 * pack_busy, (dev_busy, pack_busy)
    total_busy = sum(s["busy_ms"] for s in q["stages"].values())
    assert total_busy < 2.0 * q["wall_ms"], (total_busy, q["wall_ms"])
    m.shutdown()


CHAIN_APP = """
@Async(buffer.size='256')
define stream S (sym string, v long);
define stream Mid (sym string, v long);
@info(name='up')
from S select sym, v insert into Mid;
@info(name='down')
from Mid select sym, v insert into Out;
"""


def test_sync_cascade_does_not_inherit_queue_wait():
    """A downstream query fed SYNCHRONOUSLY by an upstream emit (inside
    the @Async worker's delivery) must not be charged the upstream
    queue's residence — the delivery scope masks the thread-local for
    nested deliveries."""
    m = _manager(1)
    rt = m.create_siddhi_app_runtime(CHAIN_APP)
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    _warm(h)
    time.sleep(0.3)
    journey.enable()
    rt.app_context.telemetry.reset()
    for i in range(6):
        h.send(["A", i])
        time.sleep(0.01)
    deadline = time.time() + 10
    while True:
        hists = rt.app_context.telemetry.snapshot().get("histograms", {})
        if hists.get("stage.down.dispatch.service_ms", {}).get("count", 0):
            break
        assert time.time() < deadline, "downstream query never ran"
        time.sleep(0.05)
    # the upstream query saw the @Async queue; the downstream one is a
    # sync cascade and must record NO queue residence
    assert hists.get("stage.up.queue.queue_ms", {}).get("count", 0) > 0
    assert "stage.down.queue.queue_ms" not in hists
    m.shutdown()


def test_journey_off_leaves_no_trace():
    """Default config: no Journey objects ride the batches and no stage
    histograms appear — the off path is one flag check."""
    m = _manager(2)
    rt = m.create_siddhi_app_runtime(APP)
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    for i in range(3):
        h.send(["A", i])
    hists = rt.app_context.telemetry.snapshot().get("histograms", {})
    assert not any(k.startswith("stage.") for k in hists)
    assert journey.critical_path_report(m)["apps"][rt.name]["queries"] == {}
    m.shutdown()


def test_profile_knobs_enable_collectors():
    """siddhi_tpu.profile_journeys / profile_costs ride the typed knob
    registry and flip the process collectors for the app's lifetime."""
    m = _manager(2, {"siddhi_tpu.profile_journeys": "true",
                     "siddhi_tpu.profile_costs": "on"})
    rt = m.create_siddhi_app_runtime(APP)
    rt.add_callback("Out", Collector())
    rt.start()
    assert journey.enabled() and costmodel.enabled()
    h = rt.get_input_handler("S")
    h.send(["A", 1])
    assert any(p.key == "query.pq.step"
               for p in costmodel.registry().programs())
    hists = rt.app_context.telemetry.snapshot().get("histograms", {})
    assert any(k.startswith("stage.pq.") for k in hists)
    m.shutdown()
    assert not journey.enabled()


# ---------------------------- routed + device-join coverage (ISSUE 12)


ROUTED_APP = """
define stream S (k string, v double);
partition with (k of S)
begin
  @info(name='rq')
  from S#window.length(4) select k, v, sum(v) as s insert into Out;
end;
"""

JOIN_APP = """
define stream L (sym string, lv long);
define stream R (sym string, rv long);
@info(name='jq') from L#window.length(32) join R#window.length(32)
  on L.sym == R.sym
  select L.sym as sym, L.lv as lv, R.rv as rv insert into JOut;
"""


@pytest.mark.parametrize("n_dev", [2, 4])
def test_routed_query_stage_attribution(n_dev):
    """A device-routed query at pipeline depth 4 produces correct stage
    attribution: every core stage populated, and its EXTENDED meta
    prefix (route slots + inner instrument lanes) rides the
    CompletionPump with output bit-identical to the unrouted run."""
    from siddhi_tpu.parallel.mesh import device_route_query_step, make_mesh

    m0 = _manager(4)
    rt0 = m0.create_siddhi_app_runtime(ROUTED_APP)
    ref = Collector()
    rt0.add_callback("Out", ref)
    h0 = rt0.get_input_handler("S")
    for i in range(160):
        h0.send([f"P{i % 16}", float(i)])
    m0.shutdown()
    # journey window: warm first (compiles outside the measurement)
    m = _manager(4)
    rt = m.create_siddhi_app_runtime(ROUTED_APP)
    c = Collector()
    rt.add_callback("Out", c)
    q = rt.query_runtimes["rq"]
    device_route_query_step(q, make_mesh(n_dev), rows_per_shard=256)
    h = rt.get_input_handler("S")
    for i in range(32):
        h.send([f"P{i % 16}", float(i)])
    journey.enable()
    rt.app_context.telemetry.reset()
    for i in range(32, 160):
        h.send([f"P{i % 16}", float(i)])
    qrep = _bottleneck(m, rt, query="rq")
    for stage in ("pack", "dispatch", "device", "emit"):
        assert qrep["stages"].get(stage, {}).get("batches", 0) > 0, \
            (stage, qrep["stages"].keys())
    # pump-compat: the routed run's full output equals the unrouted one
    assert c.rows == ref.rows
    # extended prefix decoded: shard-rows instrument drained per batch
    assert q._instr_last["shard_rows"].shape == (n_dev,)
    m.shutdown()


@pytest.mark.parametrize("n_parts", [2, 4])
def test_device_join_stage_attribution(n_parts):
    """Device-join batches (engine meta carries seq + partition fills)
    get stage attribution at depth 4, stay pump-compatible (no seq
    breaks), and both sides' journeys land under the join query."""
    m = _manager(4, {"siddhi_tpu.join_partitions": str(n_parts),
                     "siddhi_tpu.join_partition_slack": "8"})
    rt = m.create_siddhi_app_runtime(JOIN_APP)
    c = Collector()
    rt.add_callback("JOut", c)
    q = rt.query_runtimes["jq"]
    assert q.engine is not None, q.engine_reason
    hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
    hl.send(["S0", 0])
    hr.send(["S0", 100])   # warm both side steps
    journey.enable()
    rt.app_context.telemetry.reset()
    for i in range(24):
        hl.send([f"S{i % 3}", i])
        hr.send([f"S{i % 3}", 100 + i])
    qrep = _bottleneck(m, rt, query="jq")
    for stage in ("pack", "dispatch", "device", "emit"):
        assert qrep["stages"].get(stage, {}).get("batches", 0) > 0, \
            (stage, qrep["stages"].keys())
    assert len(c.rows) > 0
    # cross-stream order held through the pump: seq verified at drain
    counters = rt.app_context.telemetry.snapshot()["counters"]
    assert counters.get("join.seq_breaks", 0) == 0
    m.shutdown()


# ------------------------------------------- program registry vs fan-out


FOUR_Q = """
define stream S (sym string, v long);
@info(name='q1') from S#window.length(8) select sym, sum(v) as t group by sym insert into O1;
@info(name='q2') from S#window.length(8) select sym, sum(v) as t group by sym insert into O2;
@info(name='q3') from S#window.length(8) select sym, sum(v) as t group by sym insert into O3;
@info(name='q4') from S#window.length(8) select sym, sum(v) as t group by sym insert into O4;
"""


def test_programs_duplicate_clusters_agree_with_fanout_gauge():
    """Acceptance: on a 4-identical-query app the registry's duplicate-
    fingerprint clusters tell the same story as the fan-out dedup's
    ``unique_programs`` gauge — 4 compiled programs, ONE distinct
    computation."""
    # fusion ON (default): the fan-out dedup clusters the 4 members
    m1 = _manager(2)
    rt1 = m1.create_siddhi_app_runtime(FOUR_Q)
    rt1.get_input_handler("S").send(["A", 1])
    gauges = rt1.app_context.telemetry.read_gauges()
    unique = int(gauges["fanout.S.unique_programs"])
    assert unique == 1
    m1.shutdown()

    # fusion OFF + cost capture: 4 separate programs, equal fingerprints
    costmodel.registry().reset()
    costmodel.enable()
    m2 = _manager(2, {"siddhi_tpu.fuse_fanout": "false"})
    rt2 = m2.create_siddhi_app_runtime(FOUR_Q)
    rt2.get_input_handler("S").send(["A", 1])
    snap = costmodel.registry().snapshot()
    step_keys = [p["key"] for p in snap["programs"]
                 if p["key"].startswith("query.q")]
    assert len(step_keys) == 4
    step_clusters = [c for c in snap["clusters"]
                     if any(k.startswith("query.q") for k in c["keys"])]
    # every per-query step lands in ONE duplicate cluster — exactly the
    # unique_programs count the fused path reports
    assert len(step_clusters) == unique == 1
    assert step_clusters[0]["size"] == 4
    assert step_clusters[0]["duplicates"] == 3
    m2.shutdown()


def test_cost_capture_records_analysis_fields():
    costmodel.registry().reset()
    costmodel.enable()
    m = _manager(2)
    rt = m.create_siddhi_app_runtime(APP)
    rt.get_input_handler("S").send(["A", 1])
    recs = {p.key: p for p in costmodel.registry().programs()}
    rec = recs["query.pq.step"]
    assert rec.error is None
    assert rec.flops > 0
    assert rec.bytes_accessed > 0
    assert rec.arg_bytes > 0
    assert len(rec.fingerprint) == 16
    # bit-identity sanity: capture ran BEFORE the first (donating) call
    out = Collector()
    rt.add_callback("Out", out)
    rt.get_input_handler("S").send(["A", 2])
    assert out.rows == [("A", 3)]
    m.shutdown()


# ------------------------------------------------------------------ REST


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, json.loads(r.read().decode())


def _post(url, body=None):
    req = urllib.request.Request(
        url, data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, json.loads(r.read().decode())


def test_rest_profile_endpoints(tmp_path):
    from siddhi_tpu.service import SiddhiRestService

    costmodel.registry().reset()
    m = _manager(2)
    rt = m.create_siddhi_app_runtime(APP)
    rt.add_callback("Out", Collector())
    svc = SiddhiRestService(m, trace_base=str(tmp_path)).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        st, body = _post(f"{base}/profile/journeys/start")
        assert st == 200 and body["journeys"] is True
        st, body = _post(f"{base}/profile/costs/start")
        assert st == 200 and body["costs"] is True
        h = rt.get_input_handler("S")
        for i in range(4):
            h.send(["A", i])
        st, rep = _get(f"{base}/profile/critical_path/{rt.name}")
        assert st == 200
        q = rep["apps"][rt.name]["queries"]["pq"]
        assert set(q["stages"]) >= {"pack", "dispatch", "device", "emit"}
        assert q["bottleneck"]["stage"] in rep["stage_glossary"]
        st, progs = _get(f"{base}/programs")
        assert st == 200
        assert any(p["key"] == "query.pq.step" for p in progs["programs"])
        assert progs["unique_fingerprints"] >= 1
        st, body = _post(f"{base}/profile/journeys/stop")
        assert st == 200 and body["journeys"] is False
        _post(f"{base}/profile/costs/stop")
        # device profiler: path confinement mirrors /trace
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/profile/device/start", {"dir": "../escape"})
        assert e.value.code == 400
        st, body = _post(f"{base}/profile/device/start", {"dir": "prof1"})
        assert st == 200 and body["device_profile"].startswith(str(tmp_path))
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/profile/device/start", {"dir": "prof2"})
        assert e.value.code == 409
        st, body = _post(f"{base}/profile/device/stop")
        assert st == 200 and body["device_profile"] is None
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{base}/profile/device/stop")
        assert e.value.code == 409
    finally:
        svc.stop()
        m.shutdown()


# ------------------------------------------- exposition escaping (sat 1)


def _assert_valid_exposition(text):
    """Every sample line must match the text-format grammar: label
    values with backslash/quote/newline ESCAPED (a raw one breaks the
    line structure or the value quoting)."""
    import re

    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
        r'(\{([a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*",?)*\})?'
        r' (NaN|[-+0-9.e]+)$')
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert sample.match(line), f"malformed exposition line: {line!r}"


def test_prometheus_escaping_hostile_label_values():
    """Regression (satellite): backslash, double-quote and newline in
    label VALUES — stream/app/query names are user-controlled SiddhiQL
    identifiers and counter names are free-form — must be escaped per
    the exposition spec."""
    m = _manager(2)
    rt = m.create_siddhi_app_runtime(APP)
    hostile = 'ev"il\\str\neam'
    tel = rt.app_context.telemetry
    tel.gauge(f"junction.{hostile}.queue_depth", lambda: 7)
    tel.count(f"junction.{hostile}.backpressure_stalls", 3)
    tel.count(f'overload.{hostile}.events', 2)
    text = export.prometheus_text(m)
    _assert_valid_exposition(text)
    assert 'ev\\"il\\\\str\\neam' in text
    assert "\neam" not in text.replace("\\neam", "")  # no raw newline leak
    # JSON snapshot keeps the raw name (JSON handles its own escaping)
    snap = export.json_snapshot(m)
    tele = snap["apps"][rt.name]["telemetry"]
    assert tele["gauges"][f"junction.{hostile}.queue_depth"] == 7
    m.shutdown()


# ------------------------------------------------- scrape hygiene (sat 2)


def test_scrape_self_histogram_and_no_barrier():
    """A scrape must never take the app barrier OR the device: it
    completes while the barrier is HELD and an @Async worker is WEDGED,
    performs ZERO device pulls (the SIDDHI_TPU_SANITIZE transfer guard
    — asserted here with jax's transfer_guard directly, the same
    mechanism the sanitizer arms), and times itself into
    siddhi_scrape_ms (visible on the following scrape)."""
    import jax

    m = _manager(2)
    rt = m.create_siddhi_app_runtime(ASYNC_APP)
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    h.send(["A", 1])
    inj = FaultInjector()
    j = rt.junctions["S"]
    inj.wedge_worker(j)
    h.send(["A", 2])        # worker picks it up and wedges
    deadline = time.time() + 10
    while not inj._wedged.is_set():
        assert time.time() < deadline, "worker never wedged"
        time.sleep(0.01)
    result = {}

    def scrape():
        # device-instrument + pipeline + junction gauges all answer
        # host-side: a gauge pulling device state here would raise
        # under the guard and surface as NaN in its family
        with jax.transfer_guard("disallow"):
            result["text"] = export.prometheus_text(m)

    with rt._barrier:       # a checkpoint/ingest holding the barrier
        t = threading.Thread(target=scrape, daemon=True)
        t.start()
        t.join(timeout=10)
        assert not t.is_alive(), "scrape blocked on the app barrier"
    assert "siddhi_junction_queue_depth" in result["text"]
    for line in result["text"].splitlines():
        if line.startswith(("siddhi_device_instrument",
                            "siddhi_join_partition_rows")):
            assert not line.endswith("NaN"), \
                f"scrape gauge pulled device state: {line}"
    inj.release()
    inj.clear()
    # self-timing: the first scrape's duration shows on the second
    text2 = export.prometheus_text(m)
    assert "siddhi_scrape_ms" in text2
    assert 'siddhi_scrape_ms_count' in text2
    m.shutdown()
