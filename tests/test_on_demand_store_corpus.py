"""Reference on-demand (store) query corpus — scenarios ported verbatim
from ``store/OnDemandQueryTableTestCase.java`` (test3 lives in
tests/test_tables_extended.py; aggregation `within/per` on-demand reads in
tests/test_aggregation_corpus.py): find/CRUD on-demand queries over
tables, error paths included."""

import pytest

from siddhi_tpu import SiddhiManager

STOCK = """
    define stream StockStream (symbol string, price float, volume long);
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""

IDTBL = """
    define stream StockStream (id int, symbol string, volume int);
    define table StockTable (id int, symbol string, volume int);
    @info(name = 'query1') from StockStream insert into StockTable;
"""


def _stock_rt(pk: bool = False):
    """``pk=True`` declares ``@PrimaryKey('symbol')`` like the reference
    fixtures that rely on duplicate-symbol rows being dropped
    (IndexEventHolder.add putIfAbsent)."""
    m = SiddhiManager()
    app = STOCK if not pk else STOCK.replace(
        "define table", "@PrimaryKey('symbol') define table", 1)
    rt = m.create_siddhi_app_runtime(app)
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 100])
    h.send(["WSO2", 57.6, 100])
    return m, rt


def _id_rt():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(IDTBL)
    h = rt.get_input_handler("StockStream")
    h.send([1, "WSO2", 100])
    h.send([2, "IBM", 200])
    h.send([3, "GOOGLE", 300])
    return m, rt


def test_find_bare_and_conditions():
    """test1 (:40-84): bare reads, constant and arithmetic conditions."""
    m, rt = _stock_rt()
    assert len(rt.query("from StockTable")) == 3
    assert len(rt.query("from StockTable on price > 75")) == 1
    assert len(rt.query("from StockTable on price > volume*3/4")) == 1
    m.shutdown()


def test_find_projection_and_having():
    """test2 (:86-135): projections narrow the output row; having filters
    the selection."""
    m, rt = _stock_rt()
    ev = rt.query("from StockTable on price > 75 select symbol, volume")
    assert len(ev) == 1 and len(ev[0].data) == 2
    ev = rt.query("from StockTable select symbol, volume")
    assert len(ev) == 3 and len(ev[0].data) == 2
    ev = rt.query(
        "from StockTable on price > 5 select symbol, volume "
        "having symbol == 'WSO2'")
    assert len(ev) == 2
    m.shutdown()


def test_unknown_select_attribute_rejected():
    """test4 (:193-227, OnDemandQueryCreationException): selecting a
    non-existent attribute fails."""
    m, rt = _stock_rt()
    with pytest.raises(Exception):
        rt.query("from StockTable on price > 5 "
                 "select symbol1, sum(volume) as totalVolume group by symbol")
    m.shutdown()


def test_unknown_table_rejected():
    """test5 (:230-254, OnDemandQueryCreationException)."""
    m, rt = _stock_rt()
    with pytest.raises(Exception):
        rt.query("from StockTable1 on price > 5 "
                 "select symbol1, sum(volume) as totalVolume group by symbol")
    m.shutdown()


def test_malformed_query_rejected():
    """test6 (:257-281, SiddhiParserException): missing `as`."""
    m, rt = _stock_rt()
    with pytest.raises(Exception):
        rt.query("from StockTable1 on price > 5 "
                 "select symbol1, sum(volume)  totalVolume group by symbol")
    m.shutdown()


def test_find_on_primary_key():
    """test7 (:284-316): equality probe over a @PrimaryKey table."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STOCK.replace("define table",
                      "@PrimaryKey('symbol') define table", 1))
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 100])
    ev = rt.query("from StockTable on symbol == 'IBM' select symbol, volume")
    assert len(ev) == 1 and ev[0].data[0] == "IBM"
    m.shutdown()


def test_order_by_limit():
    """test9 (:319-355): order by price limit 2 — the reference table is
    @PrimaryKey('symbol'), so the duplicate WSO2 row (57.6) is dropped
    on insert and sort-then-limit yields {55.6, 75.6}."""
    m, rt = _stock_rt(pk=True)
    ev = rt.query("from StockTable on volume > 10 "
                  "select symbol, price, volume order by price limit 2")
    assert len(ev) == 2
    assert round(float(ev[0].data[1]), 4) == 55.6
    assert round(float(ev[1].data[1]), 4) == 75.6
    m.shutdown()


def test_order_by_limit_sorts_before_limiting():
    """QuerySelector orders the chunk BEFORE offset/limit
    (QuerySelector.java:192-198), store queries included: without a
    primary key all three rows survive, and limit 2 must return the two
    SMALLEST prices {55.6, 57.6}, not the first two by insertion order."""
    m, rt = _stock_rt()
    ev = rt.query("from StockTable on volume > 10 "
                  "select symbol, price, volume order by price limit 2")
    assert len(ev) == 2
    assert round(float(ev[0].data[1]), 4) == 55.6
    assert round(float(ev[1].data[1]), 4) == 57.6
    m.shutdown()


def test_ungrouped_aggregation():
    """test10 (:358-396): sum(volume) without group-by returns one row;
    repeated runs are stable (the 50-entry parsed-runtime cache)."""
    m, rt = _stock_rt()
    for _ in range(2):
        ev = rt.query("from StockTable on volume > 10 "
                      "select symbol, price, sum(volume) as totalVolume")
        assert len(ev) == 1 and ev[0].data[2] == 300
    m.shutdown()


def test_grouped_aggregation():
    """test11 (:399-440): group by symbol -> two rows of 100/200."""
    m, rt = _stock_rt()
    for _ in range(2):
        ev = rt.query("from StockTable on volume > 10 "
                      "select symbol, price, sum(volume) as totalVolume "
                      "group by symbol")
        assert len(ev) == 2
        assert sorted(e.data[2] for e in ev) == [100, 200]
    m.shutdown()


def test_select_star_and_aggregate_alternating():
    """test12 (:443-477): `select *` and an aggregate over the same table
    alternate without cache confusion."""
    m, rt = _stock_rt()
    assert len(rt.query("from StockTable select *")) == 3
    ev = rt.query("from StockTable select symbol, sum(volume) as totalVolume")
    assert len(ev) == 1 and ev[0].data[1] == 300
    assert len(rt.query("from StockTable select *")) == 3
    m.shutdown()


def test_update_or_insert_updates_matching_row():
    """test14 (:517-565): `update or insert ... set` rewrites the matched
    row's symbol/price, keeping its volume."""
    m, rt = _stock_rt()
    rt.query('select "newSymbol" as symbol, 123.45f as price, '
             "123L as volume update or insert into StockTable "
             "set StockTable.symbol = symbol, StockTable.price=price "
             "on StockTable.volume == 100L")
    ev = rt.query("from StockTable select * having volume == 100L")
    # all three rows have volume 100; the reference's set rewrites them
    # and asserts on the first — be strict about content, tolerant of count
    assert ev and ev[0].data[0] == "newSymbol"
    assert round(float(ev[0].data[1]), 4) == 123.45
    assert ev[0].data[2] == 100
    m.shutdown()


def test_update_or_insert_inserts_unmatched():
    """test15 (:568-608): nothing has volume 500 -> the projected row is
    INSERTED (volume 123)."""
    m, rt = _stock_rt()
    rt.query('select "newSymbol" as symbol, 123.45f as price, '
             "123L as volume update or insert into StockTable "
             "set StockTable.symbol = symbol, StockTable.price=price "
             "on StockTable.volume == 500L")
    assert len(rt.query("from StockTable select *")) == 4
    ev = rt.query("from StockTable select * having volume == 123L")
    assert len(ev) == 1 and ev[0].data[0] == "newSymbol"
    assert round(float(ev[0].data[1]), 4) == 123.45
    m.shutdown()


def test_delete_with_projected_condition_value():
    """test16 (:611-658): `select 100L as vol delete StockTable on
    StockTable.volume == vol` — one matching... ALL matching rows go."""
    m, rt = _stock_rt()
    assert len(rt.query("from StockTable select *")) == 3
    rt.query("select 100L as vol delete StockTable "
             "on StockTable.volume == vol")
    remaining = rt.query("from StockTable select *")
    assert len(remaining) == 0  # every seeded row has volume 100
    m.shutdown()


def test_delete_with_constant_condition():
    """test17 (:661-699): bare `delete StockTable on volume == 100L`."""
    m, rt = _stock_rt()
    assert len(rt.query("from StockTable select *")) == 3
    rt.query("delete StockTable on StockTable.volume == 100L")
    assert len(rt.query("from StockTable select *")) == 0
    m.shutdown()


def test_insert_on_demand():
    """test18 (:702-753): `select ... insert into StockTable` adds a row."""
    m, rt = _id_rt()
    assert len(rt.query("from StockTable select *")) == 3
    rt.query('select 10 as id, "YAHOO" as symbol, 400 as volume '
             "insert into StockTable")
    assert len(rt.query("from StockTable select *")) == 4
    ev = rt.query("from StockTable select * having id == 10")
    assert len(ev) == 1 and tuple(ev[0].data) == (10, "YAHOO", 400)
    m.shutdown()


def test_update_on_demand_with_set_constants():
    """test19 (:756-810): bare `update ... set` with literal values."""
    m, rt = _id_rt()
    rt.query('update StockTable set StockTable.symbol="MICROSOFT", '
             "StockTable.volume=2000 on StockTable.id==2")
    assert len(rt.query("from StockTable select *")) == 3
    ev = rt.query("from StockTable select * having id == 2")
    assert len(ev) == 1 and tuple(ev[0].data) == (2, "MICROSOFT", 2000)
    m.shutdown()


def test_update_on_demand_with_projected_values():
    """test20 (:813-856): `select ... update ... set` with projected
    values."""
    m, rt = _id_rt()
    rt.query('select "MICROSOFT" as newSymbol, 2000 as newVolume '
             "update StockTable "
             "set StockTable.symbol=newSymbol, StockTable.volume=newVolume "
             "on StockTable.id==2")
    assert len(rt.query("from StockTable select *")) == 3
    ev = rt.query("from StockTable select * having id == 2")
    assert len(ev) == 1 and tuple(ev[0].data) == (2, "MICROSOFT", 2000)
    m.shutdown()
