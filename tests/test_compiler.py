"""Compiler tests: SiddhiQL text -> IR.

Modeled on the reference's compiler test style
(``siddhi-query-compiler/src/test/``): parse app strings, assert IR shape.
"""

import pytest

from siddhi_tpu.compiler import SiddhiCompiler, SiddhiParserException
from siddhi_tpu.query_api import (
    AttrType,
    Compare,
    Constant,
    CountStateElement,
    EveryStateElement,
    EventOutputRate,
    Filter,
    InsertIntoStream,
    JoinInputStream,
    LogicalStateElement,
    NextStateElement,
    Partition,
    Query,
    SingleInputStream,
    StateInputStream,
    StreamStateElement,
    TimeOutputRate,
    ValuePartitionType,
    Variable,
    Window,
)
from siddhi_tpu.query_api.execution import AbsentStreamStateElement, StateInputStreamType


def test_define_stream():
    app = SiddhiCompiler.parse(
        "define stream StockStream (symbol string, price float, volume long);"
    )
    d = app.stream_definitions["StockStream"]
    assert [a.name for a in d.attributes] == ["symbol", "price", "volume"]
    assert [a.type for a in d.attributes] == [AttrType.STRING, AttrType.FLOAT, AttrType.LONG]


def test_app_name_annotation():
    app = SiddhiCompiler.parse(
        "@app:name('Test1') define stream S (a int);"
    )
    assert app.name == "Test1"


def test_filter_query():
    app = SiddhiCompiler.parse(
        """
        define stream StockStream (symbol string, price float, volume long);
        @info(name = 'query1')
        from StockStream[price > 100]
        select symbol, price
        insert into OutStream;
        """
    )
    q = app.queries[0]
    assert q.name == "query1"
    s = q.input_stream
    assert isinstance(s, SingleInputStream)
    assert s.stream_id == "StockStream"
    assert isinstance(s.handlers[0], Filter)
    cond = s.handlers[0].expression
    assert isinstance(cond, Compare) and cond.operator == ">"
    assert [oa.name for oa in q.selector.selection_list] == ["symbol", "price"]
    assert isinstance(q.output_stream, InsertIntoStream)
    assert q.output_stream.target_id == "OutStream"


def test_window_group_by_having():
    app = SiddhiCompiler.parse(
        """
        define stream StockStream (symbol string, price float, volume long);
        from StockStream#window.length(5)
        select symbol, avg(price) as avgPrice
        group by symbol
        having avgPrice > 50.0
        insert expired events into OutStream;
        """
    )
    q = app.queries[0]
    w = q.input_stream.handlers[0]
    assert isinstance(w, Window) and w.name == "length"
    assert isinstance(w.parameters[0], Constant) and w.parameters[0].value == 5
    assert q.selector.group_by_list[0].attribute_name == "symbol"
    assert q.selector.having is not None
    assert q.output_stream.output_event_type == "expired"


def test_time_windows_and_rates():
    app = SiddhiCompiler.parse(
        """
        define stream S (a string, b double);
        from S#window.timeBatch(1 sec)
        select a, count() as c
        group by a
        output all every 2 sec
        insert into Out;
        from S#window.time(1 min 30 sec)
        select a
        output first every 5 events
        insert into Out2;
        """
    )
    q0, q1 = app.queries
    assert q0.input_stream.handlers[0].parameters[0].value == 1000
    assert isinstance(q0.output_rate, TimeOutputRate) and q0.output_rate.value == 2000
    assert q1.input_stream.handlers[0].parameters[0].value == 90_000
    assert isinstance(q1.output_rate, EventOutputRate)
    assert q1.output_rate.type == "first" and q1.output_rate.value == 5


def test_join_query():
    app = SiddhiCompiler.parse(
        """
        define stream StockStream (symbol string, price float);
        define stream TwitterStream (symbol string, tweet string);
        from StockStream#window.time(10 sec) as S
          join TwitterStream#window.length(100) as T
          on S.symbol == T.symbol
        select S.symbol, T.tweet, S.price
        insert into OutStream;
        """
    )
    q = app.queries[0]
    j = q.input_stream
    assert isinstance(j, JoinInputStream)
    assert j.left.stream_id == "StockStream" and j.left.stream_reference_id == "S"
    assert j.right.stream_id == "TwitterStream"
    assert isinstance(j.on_compare, Compare)


def test_pattern_query():
    app = SiddhiCompiler.parse(
        """
        define stream A (v int); define stream B (v int);
        from every e1=A -> e2=B[e2.v > e1.v] within 5 sec
        select e1.v as v1, e2.v as v2
        insert into Out;
        """
    )
    q = app.queries[0]
    st = q.input_stream
    assert isinstance(st, StateInputStream)
    assert st.state_type == StateInputStreamType.PATTERN
    assert st.within == 5000
    root = st.state_element
    assert isinstance(root, NextStateElement)
    assert isinstance(root.state, EveryStateElement)
    first = root.state.state
    assert isinstance(first, StreamStateElement)
    assert first.stream.stream_reference_id == "e1"
    second = root.next
    assert isinstance(second, StreamStateElement)
    assert second.stream.stream_reference_id == "e2"
    assert isinstance(second.stream.handlers[0], Filter)


def test_sequence_and_count():
    app = SiddhiCompiler.parse(
        """
        define stream A (v int); define stream B (v int);
        from every e1=A, e2=B<2:5>
        select e1.v as v1
        insert into Out;
        """
    )
    st = app.queries[0].input_stream
    assert st.state_type == StateInputStreamType.SEQUENCE
    nxt = st.state_element
    assert isinstance(nxt, NextStateElement)
    cnt = nxt.next
    assert isinstance(cnt, CountStateElement)
    assert cnt.min_count == 2 and cnt.max_count == 5


def test_logical_and_absent_pattern():
    app = SiddhiCompiler.parse(
        """
        define stream A (v int); define stream B (v int); define stream C (v int);
        from e1=A and e2=B -> not C for 2 sec
        select e1.v as v1
        insert into Out;
        """
    )
    st = app.queries[0].input_stream
    root = st.state_element
    assert isinstance(root, NextStateElement)
    assert isinstance(root.state, LogicalStateElement)
    assert root.state.type == "and"
    absent = root.next
    assert isinstance(absent, AbsentStreamStateElement)
    assert absent.waiting_time == 2000


def test_partition():
    app = SiddhiCompiler.parse(
        """
        define stream StockStream (symbol string, price float);
        partition with (symbol of StockStream)
        begin
            from StockStream select symbol, price insert into #Inner;
            from #Inner select symbol insert into Out;
        end;
        """
    )
    p = app.partitions[0]
    assert isinstance(p, Partition)
    assert isinstance(p.partition_types[0], ValuePartitionType)
    assert len(p.queries) == 2
    assert p.queries[1].input_stream.is_inner_stream
    assert p.queries[0].output_stream.is_inner_stream


def test_table_and_trigger_and_window_defs():
    app = SiddhiCompiler.parse(
        """
        @primaryKey('symbol')
        define table StockTable (symbol string, price float);
        define trigger FiveSec at every 5 sec;
        define window SW (symbol string, price float) time(1 min) output all events;
        """
    )
    assert "StockTable" in app.table_definitions
    assert app.table_definitions["StockTable"].annotations[0].name == "primaryKey"
    assert app.trigger_definitions["FiveSec"].at_every == 5000
    w = app.window_definitions["SW"]
    assert w.window.name == "time" and w.window.parameters[0].value == 60_000


def test_aggregation_definition():
    app = SiddhiCompiler.parse(
        """
        define stream TradeStream (symbol string, price double, ts long);
        define aggregation TradeAgg
        from TradeStream
        select symbol, avg(price) as avgPrice, sum(price) as total
        group by symbol
        aggregate by ts every sec ... year;
        """
    )
    d = app.aggregation_definitions["TradeAgg"]
    assert d.aggregate_attribute.attribute_name == "ts"
    assert d.time_period.operator == "range"
    assert len(d.time_period.durations) == 2


def test_update_delete_outputs():
    app = SiddhiCompiler.parse(
        """
        define stream S (symbol string, price float);
        define table T (symbol string, price float);
        from S update T set T.price = S.price on T.symbol == S.symbol;
        from S delete T on T.symbol == S.symbol;
        from S update or insert into T on T.symbol == S.symbol;
        """
    )
    assert len(app.queries) == 3


def test_env_var_substitution(monkeypatch):
    monkeypatch.setenv("STREAM_NAME", "Foo")
    src = SiddhiCompiler.update_variables("define stream ${STREAM_NAME} (a int);")
    app = SiddhiCompiler.parse(src)
    assert "Foo" in app.stream_definitions


def test_parse_error_has_location():
    with pytest.raises(SiddhiParserException) as err:
        SiddhiCompiler.parse("define stream S (a int°);")
    assert "line" in str(err.value)


def test_math_and_bool_expressions():
    app = SiddhiCompiler.parse(
        """
        define stream S (a int, b int, c bool);
        from S[(a + b * 2 - 1) % 3 == 0 and (not c or b <= 4)]
        select a * 2 as a2, ifThenElse(c, 'y', 'n') as flag
        insert into Out;
        """
    )
    q = app.queries[0]
    assert len(q.selector.selection_list) == 2


def test_on_demand_query_parse():
    q = SiddhiCompiler.parse_on_demand_query(
        "from StockTable on price > 5.0 select symbol, price"
    )
    assert q.input_store.store_id == "StockTable"
    assert q.type == "find"
