"""Post-window filters: ``#window.x(...)[cond]`` masks the window's
emitted rows (CURRENT and EXPIRED) without affecting window retention —
the reference's FilterProcessor placed downstream of a WindowProcessor
(SingleInputStreamParser handler chains)."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


def test_post_window_filter_masks_current_rows():
    m, rt, c = build("""
        define stream S (price double);
        from S#window.length(2)[price > 10.0]
        select price insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send([5.0])
    h.send([100.0])
    h.send([20.0])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [100.0, 20.0]


def test_post_window_filter_masks_expired_rows_too():
    m, rt, c = build("""
        define stream S (price double);
        from S#window.length(1)[price > 10.0]
        select price insert all events into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send([5.0])     # filtered current
    h.send([100.0])   # current passes; expired 5.0 filtered
    h.send([20.0])    # current passes; expired 100.0 passes
    m.shutdown()
    assert [e.data[0] for e in c.events] == [100.0, 100.0, 20.0]


def test_post_window_filter_does_not_affect_retention():
    # the filtered row still occupies a window slot: with length(2), a
    # non-passing row still evicts the oldest row
    m, rt, c = build("""
        define stream S (price double);
        from S#window.length(2)[price > 10.0]
        select price insert all events into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send([100.0])
    h.send([200.0])
    h.send([5.0])     # filtered, but evicts 100.0 -> expired 100.0 emitted
    m.shutdown()
    assert [e.data[0] for e in c.events] == [100.0, 200.0, 100.0]


def test_post_window_filter_with_aggregation():
    # sum() sees only rows that pass the post-filter, symmetrically on
    # insert and expiry
    m, rt, c = build("""
        define stream S (v int);
        from S#window.length(2)[v > 0]
        select sum(v) as total insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send([3])     # total 3
    h.send([-1])    # filtered: no current emission
    h.send([4])     # total: +4, expired 3 passes -> -3 => 4... but -1 still in window
    h.send([5])     # +5, expired -1 filtered => 4 + 5 = 9
    m.shutdown()
    totals = [e.data[0] for e in c.events]
    assert totals == [3, 4, 9]


def test_post_window_filter_inside_partition():
    m, rt, c = build("""
        define stream S (sym string, v int);
        partition with (sym of S)
        begin
            from S#window.lengthBatch(2)[v > 10]
            select sym, v insert into OutStream;
        end;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 5])
    h.send(["A", 20])   # batch flush: 5 filtered, 20 passes
    h.send(["B", 30])
    h.send(["B", 40])   # batch flush: both pass
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("A", 20), ("B", 30), ("B", 40)]


def test_post_window_filter_on_join_side():
    # only passing window emissions trigger the join
    m, rt, c = build("""
        define stream L (sym string, v int);
        define stream R (sym string, w int);
        from L#window.length(5)[v > 10] join R#window.length(5)
             on L.sym == R.sym
        select L.sym as sym, L.v as v, R.w as w
        insert into OutStream;
    """)
    rt.get_input_handler("R").send(["A", 7])
    rt.get_input_handler("L").send(["A", 5])    # filtered: no trigger
    rt.get_input_handler("L").send(["A", 50])   # triggers
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("A", 50, 7)]


def test_filter_window_filter_combination():
    # pre-filter feeds the window; post-filter masks its emissions
    m, rt, c = build("""
        define stream S (v int);
        from S[v > 0]#window.length(3)[v < 100]
        select v insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for v in [-5, 1, 500, 7]:
        h.send([v])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [1, 7]
