"""Reference table CRUD corpus — scenarios ported verbatim from
``query/table/{DeleteFrom,UpdateFrom,UpdateOrInsert,Logical}TableTestCase
.java``. The reference's assert-free smoke tests additionally verify the
final table contents through on-demand queries (the observable surface the
reference checks via subsequent in-condition probes)."""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


def rows(rt, table="StockTable"):
    return sorted(tuple(e.data) for e in rt.query(f"from {table} select *"))


STOCK_DEFS = """
    define stream StockStream (symbol string, price float, volume long);
    define stream DeleteStockStream (symbol string, price float, volume long);
    define stream UpdateStockStream (symbol string, price float, volume long);
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
"""


def _feed3(rt):
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 100])
    h.send(["WSO2", 57.6, 100])


# --------------------------------------------- DeleteFromTableTestCase


def test_delete_on_unqualified_symbol_binds_to_stream():
    """deleteFromTableTest1/test3 (:76-...): bare `symbol` in the delete
    condition binds to the TRIGGER stream's attribute — a WSO2 trigger
    deletes nothing; an IBM trigger makes the condition row-independent
    true and empties the table."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK_DEFS + """
        @info(name = 'query2')
        from DeleteStockStream delete StockTable on symbol == 'IBM';
    """)
    _feed3(rt)
    rt.get_input_handler("DeleteStockStream").send(["WSO2", 57.6, 100])
    assert len(rows(rt)) == 3           # trigger symbol != 'IBM': no-op
    rt.get_input_handler("DeleteStockStream").send(["IBM", 57.6, 100])
    assert rows(rt) == []               # condition true: all rows deleted
    m.shutdown()


def test_delete_on_qualified_constant_condition():
    """deleteFromTableTest2: the table-qualified form
    `on StockTable.symbol=='IBM'` behaves identically."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK_DEFS + """
        @info(name = 'query2')
        from DeleteStockStream delete StockTable on StockTable.symbol == 'IBM';
    """)
    _feed3(rt)
    rt.get_input_handler("DeleteStockStream").send(["WSO2", 57.6, 100])
    assert [r[0] for r in rows(rt)] == ["WSO2", "WSO2"]
    m.shutdown()


def test_delete_on_stream_attribute():
    """deleteFromTableTest4/5 shape: `on StockTable.symbol == symbol`
    deletes the rows matching each delete-trigger event."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK_DEFS + """
        @info(name = 'query2')
        from DeleteStockStream delete StockTable on StockTable.symbol == symbol;
    """)
    _feed3(rt)
    rt.get_input_handler("DeleteStockStream").send(["WSO2", 0.0, 0])
    assert [r[0] for r in rows(rt)] == ["IBM"]
    m.shutdown()


# --------------------------------------------- UpdateFromTableTestCase


def test_update_on_qualified_constant():
    """updateFromTableTest1 (:46-81) with the table-qualified condition:
    `update ... on StockTable.symbol=='IBM'` rewrites the IBM row with the
    GOOG trigger's full values."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK_DEFS + """
        @info(name = 'query2')
        from UpdateStockStream update StockTable on StockTable.symbol == 'IBM';
    """)
    _feed3(rt)
    rt.get_input_handler("UpdateStockStream").send(["GOOG", 10.6, 100])
    got = rows(rt)
    # the matched IBM row took the update event's full values
    assert ("GOOG", 10.600000381469727, 100) in got
    assert len(got) == 3
    m.shutdown()


def test_update_in_condition_sees_new_values():
    """updateFromTableTest3 (:120-200): after `update ... on symbol==symbol`
    with (IBM, 77.6, 200), in-condition checks see IBM only at the OLD
    volume probe failing and the new row at 200 — the reference asserts
    IBM@100 matches before the update and fails after."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        define stream UpdateStockStream (symbol string, price float, volume long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from UpdateStockStream update StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol == StockTable.symbol and volume == StockTable.volume) in StockTable]
        insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query3", q)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    update.send(["IBM", 77.6, 200])
    check.send(["IBM", 100])       # no longer matches
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100), ("WSO2", 100), ("WSO2", 100)]


def test_update_with_projection():
    """updateFromTableTest4 (:203-280): `select comp as symbol, vol as
    volume update ...` — only the projected columns change."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        define stream UpdateStockStream (comp string, vol long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from UpdateStockStream
        select comp as symbol, vol as volume
        update StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol == StockTable.symbol and volume == StockTable.volume) in StockTable]
        insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query3", q)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    update.send(["IBM", 200])
    check.send(["IBM", 100])       # volume now 200
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100), ("WSO2", 100), ("WSO2", 100)]
    # the price column survived the partial update
    assert ("IBM", 55.599998474121094, 200) in rows(rt)


# ------------------------------------------ UpdateOrInsertTableTestCase


def test_update_or_insert_no_match_inserts():
    """updateOrInsertTableTest1 (:48-77): a GOOG trigger with a
    non-matching constant condition inserts a new row."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(STOCK_DEFS + """
        @info(name = 'query2')
        from UpdateStockStream
        update or insert into StockTable on StockTable.symbol == 'IBM';
    """)
    _feed3(rt)
    rt.get_input_handler("UpdateStockStream").send(["GOOG", 10.6, 100])
    got = rows(rt)
    assert len(got) == 3            # IBM row was REPLACED by GOOG
    assert ("GOOG", 10.600000381469727, 100) in got
    m.shutdown()


def test_update_or_insert_self_stream():
    """updateOrInsertTableTest2 (:79-105): the same stream upserts keyed on
    symbol — last write wins per symbol."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price float, volume long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query2')
        from StockStream
        update or insert into StockTable on StockTable.symbol == symbol;
    """)
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 100])
    h.send(["WSO2", 57.6, 100])
    h.send(["WSO2", 10.0, 100])
    got = rows(rt)
    assert len(got) == 2
    assert ("WSO2", 10.0, 100) in got
    m.shutdown()


def test_update_or_insert_then_in_condition():
    """updateOrInsertTableTest3 (:107-270): checks straddle an upsert —
    IBM@100 matches before, fails after the volume moves to 200."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        define stream UpdateStockStream (symbol string, price float, volume long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from UpdateStockStream
        update or insert into StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol == StockTable.symbol and volume == StockTable.volume) in StockTable]
        insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query3", q)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    update.send(["IBM", 77.6, 200])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100), ("WSO2", 100), ("WSO2", 100)]


def test_update_or_insert_partial_projection():
    """updateOrInsertTableTest6 (:338-...): partial `select comp as symbol,
    0f as price, vol as volume` upserts — the IBM update rewrites volume,
    the FB miss inserts a fresh row."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        define stream UpdateStockStream (comp string, vol long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from UpdateStockStream
        select comp as symbol, 0f as price, vol as volume
        update or insert into StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol == StockTable.symbol and volume == StockTable.volume) in StockTable]
        insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query3", q)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    update.send(["IBM", 200])
    update.send(["FB", 300])
    check.send(["IBM", 100])       # volume now 200: no match
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100), ("WSO2", 100), ("WSO2", 100)]
    got = rows(rt)
    assert ("FB", 0.0, 300) in got
    assert ("IBM", 0.0, 200) in got


def test_update_or_insert_updated_row_values():
    """updateOrInsertTableTest7 (:430-...): after the partial upsert the
    in-condition matching on all three columns sees (IBM, 200, 0f)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long, price float);
        define stream UpdateStockStream (comp string, vol long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from UpdateStockStream
        select comp as symbol, 0f as price, vol as volume
        update or insert into StockTable on StockTable.symbol == symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol == StockTable.symbol and volume == StockTable.volume
                               and price == StockTable.price) in StockTable]
        insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query3", q)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    update = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 155.6, 100])
    check.send(["IBM", 100, 155.6])
    check.send(["WSO2", 100, 155.6])
    update.send(["IBM", 200])
    check.send(["IBM", 200, 0.0])
    check.send(["WSO2", 100, 155.6])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100, 155.60000610351562), ("IBM", 200, 0.0)]


# ------------------------------------------------- LogicalTableTestCase


LOGICAL = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define table StockTable (symbol string, price float, volume long);
    @info(name = 'query1') from StockStream insert into StockTable;
    @info(name = 'query2')
    from CheckStockStream join StockTable
    on {cond}
    select CheckStockStream.symbol, StockTable.volume
    insert into OutStream;
"""


def _run_logical(cond, stock_rows, check_rows):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(LOGICAL.format(cond=cond))
    q = QCollect()
    rt.add_callback("query2", q)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    for r in stock_rows:
        stock.send(list(r))
    for r in check_rows:
        check.send(list(r))
    m.shutdown()
    return sorted(tuple(e.data) for e in q.events), q


STOCK3 = [("WSO2", 55.6, 100), ("IBM", 55.6, 300), ("GOOG", 55.6, 300)]


def test_logical_stream_side_constant_conjunct():
    """logicalTableTest1 (:56-120): `symbol match and CheckStockStream
    .volume==200` gates on the trigger's own attribute."""
    got, q = _run_logical(
        "CheckStockStream.symbol == StockTable.symbol and CheckStockStream.volume == 200",
        STOCK3, [("IBM", 200), ("WSO2", 200), ("GOOG", 100)])
    assert got == [("IBM", 300), ("WSO2", 100)]
    assert q.expired == []


def test_logical_table_side_constant_conjunct():
    """logicalTableTest2 (:123-187): `and StockTable.volume==300` filters
    the probed rows."""
    got, q = _run_logical(
        "CheckStockStream.symbol == StockTable.symbol and StockTable.volume == 300",
        STOCK3, [("IBM", 200), ("WSO2", 200), ("GOOG", 100)])
    assert got == [("GOOG", 300), ("IBM", 300)]


def test_logical_cross_side_equality_conjunct():
    """logicalTableTest3 (:190-255): two cross-side equalities."""
    got, q = _run_logical(
        "CheckStockStream.symbol == StockTable.symbol and StockTable.volume == CheckStockStream.volume",
        STOCK3, [("IBM", 300), ("WSO2", 100), ("GOOG", 100)])
    assert got == [("IBM", 300), ("WSO2", 100)]


def test_logical_relational_conjunct():
    """logicalTableTest4 (:258-320): `StockTable.volume <=
    CheckStockStream.volume`."""
    got, q = _run_logical(
        "CheckStockStream.symbol == StockTable.symbol and StockTable.volume <= CheckStockStream.volume",
        [("WSO2", 55.6, 100), ("IBM", 55.6, 50), ("GOOG", 55.6, 300)],
        [("IBM", 300), ("WSO2", 100), ("GOOG", 100)])
    assert got == [("IBM", 50), ("WSO2", 100)]


def test_logical_constant_left_operand():
    """logicalTableTest5 (:326-...): a literal on the LEFT of the compare
    (`55.6f == StockTable.price`) plus a relational conjunct — one trigger
    matches two rows."""
    got, q = _run_logical(
        "55.6f == StockTable.price and StockTable.volume <= CheckStockStream.volume",
        [("WSO2", 55.6, 100), ("IBM", 55.6, 50), ("GOOG", 55.6, 300)],
        [("IBM", 150)])
    assert got == [("IBM", 50), ("IBM", 100)]


def test_logical_three_conjuncts():
    """logicalTableTest6 (:393-460): three conjuncts spanning both sides."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, price float, volume long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol == StockTable.symbol
           and StockTable.volume == CheckStockStream.volume
           and StockTable.price <= CheckStockStream.price
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query2", q)
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    for r in [("WSO2", 55.6, 100), ("IBM", 55.6, 50), ("GOOG", 55.6, 300)]:
        stock.send(list(r))
    check.send(["IBM", 55.6, 50])
    check.send(["WSO2", 55.6, 100])
    m.shutdown()
    assert sorted(tuple(e.data) for e in q.events) == [
        ("IBM", 50), ("WSO2", 100)]


# ---------------------------------------------------------------- round 5:
# remaining UpdateOrInsertTableTestCase scenarios


def build_q(app, query="query2"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback(query, q)
    return m, rt, q


UOI_BASE = """
    define stream StockStream (symbol string, price float, volume long);
    define stream CheckStockStream (symbol string, volume long);
    define table StockTable (symbol string, price float, volume long);
"""


def test_upsert_then_composite_in_probe():
    """updateOrInsertTableTest4 (:254-319): upsert keyed on symbol; the
    (symbol, volume) `in` probe sees the post-upsert values."""
    m, rt, q = build_q(UOI_BASE + """
        @info(name = 'query2') from StockStream
        update or insert into StockTable on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol==StockTable.symbol and
                               volume==StockTable.volume) in StockTable]
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    check.send(["IBM", 100])
    check.send(["WSO2", 100])
    stock.send(["IBM", 77.6, 200])     # updates IBM's row
    check.send(["IBM", 100])           # stale volume: no match
    check.send(["WSO2", 100])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("IBM", 100), ("WSO2", 100), ("WSO2", 100)]


def test_upsert_with_aliases_no_output_query():
    """updateOrInsertTableTest5 (:322-372): aliased upsert
    (comp as symbol) compiles and runs; nothing listens on OutStream."""
    m, rt, q = build_q(UOI_BASE + """
        define stream UpdateStockStream (comp string, vol long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2') from UpdateStockStream
        select comp as symbol, vol as volume
        update or insert into StockTable on StockTable.symbol==symbol;
    """, query="query1")
    stock = rt.get_input_handler("StockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 55.6, 100])
    rt.get_input_handler("UpdateStockStream").send(["FB", 300])
    m.shutdown()
    # the reference only asserts the app runs (nothing listens on
    # OutStream); our callback sits on query1 and sees the two inserts
    assert len(q.events) == 2


def test_upsert_projected_then_triple_in_probe():
    """updateOrInsertTableTest8 (:508-570): projected upsert; 3-way
    composite probe before and after."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long, price float);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query2') from StockStream
        select symbol, price, volume
        update or insert into StockTable on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol==StockTable.symbol and
                               volume==StockTable.volume and
                               price==StockTable.price) in StockTable]
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 155.6, 100])
    check.send(["IBM", 100, 155.6])
    check.send(["WSO2", 100, 155.6])
    stock.send(["IBM", 155.6, 200])
    check.send(["IBM", 200, 155.6])
    check.send(["WSO2", 100, 155.6])
    m.shutdown()
    assert [(e.data[0], e.data[1]) for e in q.events] == [
        ("IBM", 100), ("IBM", 200)]


def test_upsert_left_outer_join_existing_row():
    """updateOrInsertTableTest9 (:573-641): left-outer enrichment upsert of
    an EXISTING row keeps its price (join side non-null)."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long, price float);
        define stream UpdateStockStream (comp string, vol long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2') from UpdateStockStream left outer join StockTable
        on UpdateStockStream.comp == StockTable.symbol
        select symbol, ifThenElse(price is null,0f,price) as price,
               vol as volume
        update or insert into StockTable on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol==StockTable.symbol and
                               volume==StockTable.volume and
                               price==StockTable.price) in StockTable]
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    stock.send(["WSO2", 55.6, 100])
    stock.send(["IBM", 155.6, 100])
    check.send(["IBM", 100, 155.6])
    check.send(["WSO2", 100, 155.6])
    rt.get_input_handler("UpdateStockStream").send(["IBM", 200])
    check.send(["IBM", 200, 155.6])
    check.send(["WSO2", 100, 155.6])
    m.shutdown()
    assert [(e.data[0], e.data[1]) for e in q.events] == [
        ("IBM", 100), ("IBM", 200)]


def test_upsert_left_outer_join_missing_row_null_fill():
    """updateOrInsertTableTest10 (:644-713): enrichment upsert of a row NOT
    in the table takes the ifThenElse null fill (price 0)."""
    m, rt, q = build_q("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long, price float);
        define stream UpdateStockStream (comp string, vol long);
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable;
        @info(name = 'query2') from UpdateStockStream left outer join StockTable
        on UpdateStockStream.comp == StockTable.symbol
        select comp as symbol, ifThenElse(price is null,0f,price) as price,
               vol as volume
        update or insert into StockTable on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol==StockTable.symbol and
                               volume==StockTable.volume and
                               price==StockTable.price) in StockTable]
        insert into OutStream;
    """, query="query3")
    stock = rt.get_input_handler("StockStream")
    check = rt.get_input_handler("CheckStockStream")
    upd = rt.get_input_handler("UpdateStockStream")
    stock.send(["WSO2", 55.6, 100])
    check.send(["IBM", 100, 155.6])
    check.send(["WSO2", 100, 155.6])
    upd.send(["IBM", 200])
    upd.send(["WSO2", 300])
    check.send(["IBM", 200, 0.0])
    check.send(["WSO2", 300, 55.6])
    m.shutdown()
    assert [(e.data[0], e.data[1]) for e in q.events] == [
        ("IBM", 200), ("WSO2", 300)]


def test_upsert_chunk_sequential_visibility():
    """updateOrInsertTableTest11 (:716-780): one 4-event chunk whose later
    events update rows the earlier events of the SAME chunk inserted."""
    m, rt, q = build_q("""
        define stream UpdateStockStream (symbol string, price int, volume long);
        define stream SearchStream (symbol string);
        define table StockTable (symbol string, price int, volume long);
        @info(name = 'query1') from UpdateStockStream
        update or insert into StockTable on StockTable.symbol == symbol;
        @info(name = 'query2') from SearchStream#window.length(1) join StockTable
        on StockTable.symbol == SearchStream.symbol
        select StockTable.symbol as symbol, price, volume
        insert into OutStream;
    """)
    import numpy as np

    upd = rt.get_input_handler("UpdateStockStream")
    upd.send_columns(
        {"symbol": np.array(["WSO2", "IBM", "WSO2", "IBM"], object),
         "price": np.array([55, 55, 155, 155], np.int32),
         "volume": np.array([100, 100, 200, 200], np.int64)})
    rt.get_input_handler("SearchStream").send(["WSO2"])
    rt.get_input_handler("SearchStream").send(["IBM"])
    m.shutdown()
    assert [tuple(e.data) for e in q.events] == [
        ("WSO2", 155, 200), ("IBM", 155, 200)]
