"""Every example under examples/ must run clean (user-facing quick start)."""

import os
import pathlib
import subprocess
import sys

import pytest

EXAMPLES = sorted(
    (pathlib.Path(__file__).parent.parent / "examples").glob("*.py"))


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: p.name)
def test_example_runs(path):
    env = dict(os.environ)
    root = str(path.parent.parent)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, str(path)], capture_output=True, text=True,
        timeout=240, cwd=root, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
