"""Every example under examples/ must run clean (user-facing quick start)."""

import os
import pathlib
import subprocess
import sys

import pytest

EXAMPLES = sorted(
    (pathlib.Path(__file__).parent.parent / "examples").glob("*.py"))


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: p.name)
def test_example_runs(path):
    env = dict(os.environ)
    root = str(path.parent.parent)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    # examples are correctness smoke tests: force the CPU platform at the
    # jax.config level (plugin platforms override the env var at
    # interpreter start — same defense as conftest.force_host_devices),
    # keeping them off the single-client TPU tunnel
    wrapper = (
        "import sys; "
        "from siddhi_tpu.parallel.mesh import force_host_devices; "
        "force_host_devices(1); "
        "import runpy; runpy.run_path(sys.argv[1], run_name='__main__')")
    r = subprocess.run(
        [sys.executable, "-c", wrapper, str(path)], capture_output=True,
        text=True, timeout=240, cwd=root, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
