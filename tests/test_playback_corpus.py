"""Reference playback corpus — scenarios ported from
``managment/PlaybackTestCase.java``: the event-time clock drives timers,
and the ``@app:playback(idle.time, increment)`` heartbeat advances the
clock through quiet wall-time periods (TimestampGeneratorImpl idle task).
Heartbeat tests use real (short) wall sleeps, as the reference does."""

import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.compiler.errors import (SiddhiParserException,
                                        SiddhiAppValidationException)
from siddhi_tpu.core.query.callback import QueryCallback


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


def build_q(app, query="query1"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback(query, q)
    return m, rt, q


def wait_for(cond, timeout=10.0):
    """SiddhiTestHelper.waitForEvents: poll until cond() or timeout."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


def test_playback_time_batch_event_driven():
    """playbackTest1 (:48-106): timeBatch(1 sec) driven purely by event
    timestamps — 3 in, 2 remove."""
    m, rt, q = build_q("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.timeBatch(1 sec)
        select * insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    ts = 1700000000000
    h.send(ts, ["IBM", 700.0, 0])
    h.send(ts + 500, ["WSO2", 60.5, 1])
    h.send(ts + 1000, ["GOOGLE", 85.0, 1])
    h.send(ts + 2000, ["ORACLE", 90.5, 1])
    m.shutdown()
    assert len(q.events) == 3
    assert len(q.expired) == 2


def test_playback_time_batch_start_time():
    """playbackTest2 (:109-168): timeBatch(2 sec, 0) + sum — three
    non-empty batches collapse to 3 in rows."""
    m, rt, q = build_q("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.timeBatch(2 sec, 0)
        select symbol, sum(price) as sumPrice, volume insert into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    h.send(0, ["IBM", 700.0, 0])
    h.send(0, ["WSO2", 60.5, 1])
    h.send(8500, ["WSO2", 60.5, 1])
    h.send(8500, ["II", 60.5, 1])
    h.send(21500, ["TT", 60.5, 1])
    h.send(21500, ["YY", 60.5, 1])
    h.send(26500, ["ZZ", 0.0, 0])
    m.shutdown()
    assert len(q.events) == 3
    assert q.expired == []


def test_playback_heartbeat_flushes_last_batch():
    """playbackTest3 (:171-228): the heartbeat drains the final timeBatch
    batch with no trailing event. idle.time is scaled to 1 sec (reference:
    100 ms) so first-compile pauses between sends cannot fire it
    mid-feed — the JVM's sends are microseconds apart."""
    m, rt, q = build_q("""
        @app:playback(idle.time = '1 sec', increment = '2 sec')
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.timeBatch(2 sec, 0)
        select symbol, sum(price) as sumPrice, volume insert into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    h.send(0, ["IBM", 700.0, 0])
    h.send(0, ["WSO2", 60.5, 1])
    h.send(8500, ["WSO2", 60.5, 1])
    h.send(8500, ["II", 60.5, 1])
    h.send(21500, ["TT", 60.5, 1])
    h.send(21500, ["YY", 60.5, 1])
    assert wait_for(lambda: len(q.events) >= 3)
    m.shutdown()
    assert len(q.events) == 3
    assert q.expired == []


def test_playback_heartbeat_join():
    """playbackTest4 (:230-279): joined timeBatch(1 sec) sides drained by
    the heartbeat — 2 in events, none removed. idle.time scaled to 10 sec
    (reference: 100 ms): each new runtime re-traces the join step for
    seconds per side, and under a loaded xdist run even a warm-cache feed
    can stall past shorter idle windows, firing the heartbeat mid-feed;
    the app is also built and fed once first to warm the jit caches."""
    APP = """
        @app:playback(idle.time = '10 sec', increment = '1 sec')
        define stream cseEventStream (symbol string, price float, volume int);
        define stream twitterStream (user string, tweet string, company string);
        @info(name = 'query1')
        from cseEventStream#window.timeBatch(1 sec) join twitterStream#window.timeBatch(1 sec)
        on cseEventStream.symbol == twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert into OutStream;
    """

    def run():
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(APP)
        q = QCollect()
        rt.add_callback("query1", q)
        cse = rt.get_input_handler("cseEventStream")
        twitter = rt.get_input_handler("twitterStream")
        ts = 1700000000000
        cse.send(ts, ["WSO2", 55.6, 100])
        twitter.send(ts, ["User1", "Hello World", "WSO2"])
        cse.send(ts, ["IBM", 75.6, 100])
        cse.send(ts + 1100, ["WSO2", 57.6, 100])
        ok = wait_for(lambda: len(q.events) >= 2, timeout=40.0)
        m.shutdown()
        return ok, q

    run()                        # warm the jit caches
    ok, q = run()
    assert ok
    assert len(q.events) == 2
    assert q.expired == []


def test_playback_time_length_event_driven():
    """playbackTest5 (:281-330): timeLength(4 sec, 10) — the 5 sec jump
    expires the first four; 5 in, 4 remove."""
    m, rt, q = build_q("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.timeLength(4 sec, 10)
        select symbol, price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    ts = 1700000000000
    for i, (sym, p, v) in enumerate([("IBM", 700.0, 1), ("WSO2", 60.5, 2),
                                     ("IBM", 700.0, 3), ("WSO2", 60.5, 4)]):
        h.send(ts + 500 * i, [sym, p, v])
    h.send(ts + 1500 + 5000, ["GOOGLE", 90.5, 5])
    m.shutdown()
    assert len(q.events) == 5
    assert [e.data[2] for e in q.expired] == [1, 2, 3, 4]


def test_playback_heartbeat_time_length():
    """playbackTest6 (:332-381): heartbeat increment 4 sec expires all four
    timeLength rows with no trailing event — 4 in, 4 remove."""
    m, rt, q = build_q("""
        @app:playback(idle.time = '100 millisecond', increment = '4 sec')
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.timeLength(4 sec, 10)
        select symbol, price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    ts = 1700000000000
    for i, (sym, p, v) in enumerate([("IBM", 700.0, 1), ("WSO2", 60.5, 2),
                                     ("IBM", 700.0, 3), ("WSO2", 60.5, 4)]):
        h.send(ts + 500 * i, [sym, p, v])
    assert wait_for(lambda: len(q.expired) >= 4)
    m.shutdown()
    assert len(q.events) == 4
    assert len(q.expired) == 4


def test_playback_time_window_event_driven():
    """playbackTest7 (:383-432): time(2 sec) — the 2 sec jump expires the
    first two; 3 in, 2 remove."""
    m, rt, q = build_q("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.time(2 sec)
        select symbol, price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    ts = 1700000000000
    h.send(ts, ["IBM", 700.0, 0])
    h.send(ts, ["WSO2", 60.5, 1])
    h.send(ts + 2000, ["GOOGLE", 0.0, 1])
    m.shutdown()
    assert len(q.events) == 3
    assert len(q.expired) == 2


def test_playback_heartbeat_time_window():
    """playbackTest8 (:434-481): heartbeat increment 2 sec expires both
    rows with no trailing event."""
    m, rt, q = build_q("""
        @app:playback(idle.time = '100 millisecond', increment = '2 sec')
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.time(2 sec)
        select symbol, price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    ts = 1700000000000
    h.send(ts, ["IBM", 700.0, 0])
    h.send(ts, ["WSO2", 60.5, 1])
    assert wait_for(lambda: len(q.expired) >= 2)
    m.shutdown()
    assert len(q.events) == 2
    assert len(q.expired) == 2


def test_playback_rejects_unitless_increment():
    """playbackTest9 (:483-499): increment '2' (no unit) fails creation."""
    with pytest.raises(SiddhiParserException):
        SiddhiManager().create_siddhi_app_runtime("""
            @app:playback(idle.time = '100 millisecond', increment = '2')
            define stream S (symbol string, price float, volume int);
            from S#window.time(2 sec) select symbol insert all events into OutStream;
        """)


def test_playback_rejects_empty_idle_time():
    """playbackTest10 (:501-517): idle.time '' fails creation."""
    with pytest.raises(SiddhiParserException):
        SiddhiManager().create_siddhi_app_runtime("""
            @app:playback(idle.time = '', increment = '2 sec')
            define stream S (symbol string, price float, volume int);
            from S#window.time(2 sec) select symbol insert all events into OutStream;
        """)


def test_playback_requires_both_heartbeat_elements():
    """SiddhiAppParser.java:191-197: idle.time without increment (and vice
    versa) fails creation."""
    with pytest.raises(SiddhiAppValidationException):
        SiddhiManager().create_siddhi_app_runtime("""
            @app:playback(idle.time = '100 millisecond')
            define stream S (symbol string, price float, volume int);
            from S select symbol insert into OutStream;
        """)


def test_playback_heartbeat_out_of_order_event():
    """playbackTest11 (:519-570): an out-of-order event below the advanced
    clock joins the open batch without moving the clock backward — 3 in,
    3 remove once the heartbeat drains the batches."""
    m, rt, q = build_q("""
        @app:playback(idle.time = '100 millisecond', increment = '1 sec')
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.timeBatch(2 sec)
        select symbol, price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    h.send(100, ["IBM", 700.0, 0])
    h.send(200, ["WSO2", 600.5, 1])
    time.sleep(0.15)
    h.send(1150, ["ORACLE", 500.0, 2])
    assert wait_for(lambda: len(q.events) >= 3 and len(q.expired) >= 3)
    m.shutdown()
    assert len(q.events) == 3
    assert len(q.expired) == 3


def test_playback_heartbeat_ahead_of_clock_event():
    """playbackTest12 (:573-625): an event ahead of the heartbeat-advanced
    clock re-anchors it — 3 in, 3 remove."""
    m, rt, q = build_q("""
        @app:playback(idle.time = '100 millisecond', increment = '1 sec')
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.timeBatch(2 sec)
        select symbol, price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    h.send(100, ["IBM", 700.0, 0])
    h.send(200, ["WSO2", 600.5, 1])
    time.sleep(0.15)
    h.send(1900, ["ORACLE", 500.0, 2])
    assert wait_for(lambda: len(q.events) >= 3 and len(q.expired) >= 3)
    m.shutdown()
    assert len(q.events) == 3
    assert len(q.expired) == 3
