"""Serving tier tests: mesh-sharded incremental aggregation, scatter-
gather on-demand queries, per-shard WAL rebuild, admission control
(``siddhi_tpu/serving/``)."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.util.config import InMemoryConfigManager

APP = """
@app:name('ServeApp')
define stream TradeStream (symbol string, price double, volume long, ts long);
define aggregation TradeAgg
from TradeStream
select symbol, sum(price) as total, avg(price) as avgPrice, count() as n,
       min(price) as lo, max(price) as hi, distinctCount(volume) as dv,
       price * 2.0 as lastDouble
group by symbol
aggregate by ts every sec ... year;
"""

QUERY = ("from TradeAgg within 0L, 100000000000L per '{per}' "
         "select AGG_TIMESTAMP, symbol, total, avgPrice, n, lo, hi, dv, "
         "lastDouble")


def _mk(shards: int, app: str = APP):
    m = SiddhiManager()
    cfg = {"siddhi_tpu.agg_shards": str(shards)}
    m.set_config_manager(InMemoryConfigManager(cfg))
    rt = m.create_siddhi_app_runtime(app)
    return m, rt


def _pump(rt, seed=0, n=300, keys=23):
    h = rt.get_input_handler("TradeStream")
    rng = np.random.default_rng(seed)
    for i in range(n):
        h.send([f"S{rng.integers(0, keys)}", float(rng.random() * 100.0),
                int(rng.integers(1, 5)), int(rng.integers(0, 50_000))])


def _rows(rt, per="seconds", q=QUERY):
    return sorted(tuple(e.data) for e in rt.query(q.format(per=per)))


def test_sharded_equals_unsharded_all_granularities():
    m1, rt1 = _mk(1)
    m4, rt4 = _mk(4)
    _pump(rt1)
    _pump(rt4)
    try:
        agg = rt4.aggregations["TradeAgg"]
        from siddhi_tpu.serving import ShardedIncrementalAggregation

        assert isinstance(agg, ShardedIncrementalAggregation)
        assert agg.n_shards == 4
        # every shard owns a non-empty slice of the key space
        assert all(s.store[agg.durations[0]] for s in agg.shards)
        for per in ("seconds", "minutes", "hours", "days"):
            assert _rows(rt1, per) == _rows(rt4, per), per
    finally:
        m1.shutdown()
        m4.shutdown()


def test_within_straddles_granularity_boundaries():
    """A `within` range that starts/ends mid-bucket must truncate its
    start down to the queried granularity's bucket start identically on
    both paths (the reference IncrementalTimeConverterUtil rule)."""
    m1, rt1 = _mk(1)
    m3, rt3 = _mk(3)
    try:
        for rt in (rt1, rt3):
            h = rt.get_input_handler("TradeStream")
            for ts in (500, 1500, 59_500, 60_500, 3_599_500, 3_600_500):
                h.send(["A", 1.0, 1, ts])
                h.send(["B", 2.0, 1, ts])
        for q in (
            "from TradeAgg within 1500L, 3500L per 'seconds' "
            "select AGG_TIMESTAMP, symbol, total, n",
            # straddles the minute boundary mid-minute on both ends
            "from TradeAgg within 30000L, 90000L per 'minutes' "
            "select AGG_TIMESTAMP, symbol, total, n",
            # one-bucket hour range expressed inside the bucket
            "from TradeAgg within 3599000L, 3599900L per 'hours' "
            "select AGG_TIMESTAMP, symbol, total, n",
        ):
            a = sorted(tuple(e.data) for e in rt1.query(q))
            b = sorted(tuple(e.data) for e in rt3.query(q))
            assert a == b and a, q
    finally:
        m1.shutdown()
        m3.shutdown()


def test_out_of_order_near_bucket_flip():
    """Out-of-order arrivals just after a bucket flip fold into their own
    (older) bucket, and bare-selection last-value semantics keep the
    latest EVENT-TIME value — identically sharded and unsharded."""
    m1, rt1 = _mk(1)
    m2, rt2 = _mk(2)
    try:
        seq = [("A", 10.0, 1999), ("A", 20.0, 2000), ("B", 5.0, 2001),
               ("A", 7.0, 1998),   # late: lands in bucket 1000
               ("B", 9.0, 1999),   # late for B too
               ("A", 30.0, 2999), ("A", 1.0, 2500)]  # older within bucket 2000
        for rt in (rt1, rt2):
            h = rt.get_input_handler("TradeStream")
            for sym, price, ts in seq:
                h.send([sym, price, 1, ts])
        a = _rows(rt1)
        b = _rows(rt2)
        assert a == b
        by_key = {(r[0], r[1]): r for r in a}
        # bucket 1000/A sums the on-time and the late arrival
        assert by_key[(1000, "A")][2] == 17.0
        # bucket 2000/A: lastDouble keeps ts=2999's value (60.0), not the
        # later-ARRIVING ts=2500 one
        assert by_key[(2000, "A")][8] == 60.0
    finally:
        m1.shutdown()
        m2.shutdown()


def test_shard_kill_rebuild_effectively_once():
    m, rt = _mk(3)
    try:
        _pump(rt, seed=7, n=120)
        agg = rt.aggregations["TradeAgg"]
        blobs = agg.checkpoint_shards()
        _pump(rt, seed=8, n=80)       # suffix lives in the shard WALs
        ref = _rows(rt)
        agg.kill_shard(1)
        assert _rows(rt) != ref       # the shard's slice is gone
        replayed = agg.rebuild_shard(1, blobs[1])
        assert replayed >= 1
        assert _rows(rt) == ref       # zero lost, zero duplicated
    finally:
        m.shutdown()


def test_rebuild_skips_wal_suffix_predating_revision():
    """A shard blob whose cut predates the WAL's last checkpoint trim
    restores WITHOUT replay: the retained suffix follows a newer base and
    grafting it would silently lose the gap (PR-1 stale-revision rule)."""
    m, rt = _mk(2)
    try:
        _pump(rt, seed=1, n=60)
        agg = rt.aggregations["TradeAgg"]
        old = agg.checkpoint_shards()
        _pump(rt, seed=2, n=60)
        agg.checkpoint_shards()       # trims WALs past old's cut
        _pump(rt, seed=3, n=40)       # fresh suffix follows the NEW base
        agg.kill_shard(0)
        assert agg.rebuild_shard(0, old[0]) == 0   # replay skipped
        # the shard holds exactly the old blob's state (stale by design,
        # visibly so — not silently wrong)
        expect = agg._deser_store(old[0]["store"])
        assert agg.shards[0].store == expect
    finally:
        m.shutdown()


def test_rebuild_reports_wal_overflow_gap():
    """A shard WAL bounded too small for the post-checkpoint suffix must
    SAY so at rebuild (gap counter + error log), not silently restore a
    hole — sequence numbers are contiguous, so the drop is detectable."""
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.agg_shards": "2", "siddhi_tpu.agg_shard_wal": "2"}))
    rt = m.create_siddhi_app_runtime(APP)
    rt.set_statistics_level("basic")
    try:
        h = rt.get_input_handler("TradeStream")
        agg = rt.aggregations["TradeAgg"]
        blobs = agg.checkpoint_shards()
        for i in range(5):     # 5 single-event batches > bound of 2
            h.send(["A", 1.0, 1, 1000 * i])
        victim = agg._owner_of(
            (rt.app_context.string_dictionary.encode("A"),))
        agg.kill_shard(victim)
        agg.rebuild_shard(victim, blobs[victim])
        counters = rt.app_context.statistics_manager.counters
        assert counters.get("resilience.shard_replay_gaps") == 1
        # the retained tail IS replayed (visible partial state, counted)
        assert _rows(rt)
    finally:
        m.shutdown()


def test_cross_restore_with_foreign_durations():
    """Restoring a snapshot that keeps MORE granularities than the app
    declares (sec...day snap into a sec...hour sharded app) must follow
    the restored state, both ways — and querying a granularity neither
    kept raises a clean CompileError, not KeyError."""
    from siddhi_tpu.ops.expressions import CompileError

    small = APP.replace("every sec ... year", "every sec ... hour")
    m1, rt1 = _mk(1)                 # sec...year, unsharded
    m2, rt2 = _mk(2, app=small)      # sec...hour, sharded
    try:
        _pump(rt1, seed=61, n=60)
        ref = _rows(rt1)
        rt2.restore(rt1.snapshot())  # brings sec...year buckets along
        assert _rows(rt2) == ref
        assert _rows(rt2, per="days") == _rows(rt1, per="days")
        # ingest after the cross-restore folds into DECLARED durations
        rt2.get_input_handler("TradeStream").send(["S0", 1.0, 1, 5])
        rt1.get_input_handler("TradeStream").send(["S0", 1.0, 1, 5])
        assert _rows(rt2) == _rows(rt1)
    finally:
        m1.shutdown()
        m2.shutdown()

    # shrinking direction: a sec...hour snapshot into a sec...year
    # sharded app — the un-restored granularity reads as a clean
    # CompileError (not KeyError), and reappears once ingest re-folds it
    m3, rt3 = _mk(1, app=small)
    m4, rt4 = _mk(3)
    try:
        _pump(rt3, seed=62, n=40)
        rt4.restore(rt3.snapshot())
        assert _rows(rt4) == _rows(rt3)
        with pytest.raises(CompileError):
            rt4.query("from TradeAgg within 0L, 1L per 'months' select n")
        rt4.get_input_handler("TradeStream").send(["S0", 1.0, 1, 5])
        assert rt4.query(
            "from TradeAgg within 0L, 100000L per 'months' select n")
    finally:
        m3.shutdown()
        m4.shutdown()


def test_full_snapshot_cross_restores_sharded_and_unsharded():
    m4, rt4 = _mk(4)
    m1, rt1 = _mk(1)
    m2, rt2 = _mk(2)
    try:
        _pump(rt4, seed=5, n=150)
        ref = _rows(rt4)
        blob = rt4.snapshot()
        rt1.restore(blob)             # sharded -> unsharded
        assert _rows(rt1) == ref
        rt2.restore(rt1.snapshot())   # unsharded -> sharded(2)
        assert _rows(rt2) == ref
        # ingest keeps folding correctly after the re-route
        rt2.get_input_handler("TradeStream").send(["S0", 1.5, 1, 123])
        rt1.get_input_handler("TradeStream").send(["S0", 1.5, 1, 123])
        assert _rows(rt2) == _rows(rt1)
    finally:
        m4.shutdown()
        m1.shutdown()
        m2.shutdown()


def test_incremental_snapshot_cross_layout():
    """persist_incremental/restore chains work across the sharded layout:
    op-logs capture per shard and apply back per shard."""
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    m, rt = _mk(3)
    m.set_persistence_store(InMemoryPersistenceStore())
    try:
        _pump(rt, seed=11, n=60)
        rt.persist()
        _pump(rt, seed=12, n=60)
        ref = _rows(rt)
        rev = rt.persist_incremental()
        _pump(rt, seed=13, n=30)      # diverge past the checkpoint
        rt.restore_revision(rev)
        assert _rows(rt) == ref
    finally:
        m.shutdown()


def test_device_views_epoch_cached_on_shard_devices():
    import jax

    m, rt = _mk(4)
    try:
        _pump(rt, seed=21, n=80)
        agg = rt.aggregations["TradeAgg"]
        d = agg.durations[0]
        views = [agg.shard_device_contents(i, d) for i in range(4)]
        for i, (defn, cols, valid) in enumerate(views):
            arr = cols["total"]
            assert isinstance(arr, jax.Array)
            assert arr.devices() == {agg.shards[i].device}
        # cached until the next fold bumps the epoch
        assert agg.shard_device_contents(0, d) is views[0]
        rt.get_input_handler("TradeStream").send(["S0", 1.0, 1, 1])
        owner = agg._owner_of(
            (rt.app_context.string_dictionary.encode("S0"),))
        assert agg.shard_device_contents(owner, d) is not views[owner]
    finally:
        m.shutdown()


def test_queries_do_not_hold_the_app_barrier():
    """An aggregation store-query mid-flight must not block ingest: the
    serving read path takes per-shard locks only."""
    m, rt = _mk(2)
    try:
        _pump(rt, seed=31, n=50)
        agg = rt.aggregations["TradeAgg"]
        release = threading.Event()
        in_query = threading.Event()
        orig = agg.shards[0].partials

        def slow_partials(duration):
            in_query.set()
            release.wait(5)
            return orig(duration)

        agg.shards[0].partials = slow_partials
        result = {}

        def query():
            result["rows"] = _rows(rt)

        t = threading.Thread(target=query)
        t.start()
        assert in_query.wait(5)
        # the query is parked inside shard 0's read; ingest must proceed
        rt.get_input_handler("TradeStream").send(["S1", 2.0, 1, 77])
        release.set()
        t.join(5)
        assert not t.is_alive() and result["rows"]
    finally:
        release.set()
        m.shutdown()


def test_admission_pool_caps_and_counters():
    from siddhi_tpu.observability.telemetry import TelemetryRegistry
    from siddhi_tpu.serving import AdmissionPool, QueryShedError

    tel = TelemetryRegistry()
    pool = AdmissionPool(max_workers=2, default_cap=3, telemetry=tel)
    gate = threading.Event()
    futs = [pool.try_submit("/query", gate.wait, 10) for _ in range(3)]
    with pytest.raises(QueryShedError):
        pool.try_submit("/query", gate.wait, 10)
    # a different endpoint has its own budget (admitted, queued behind
    # the gated workers)
    f = pool.try_submit("/stats", lambda: 42)
    gate.set()
    assert f.result(10) == 42
    for fu in futs:
        fu.result(10)
    snap = tel.snapshot()
    assert snap["counters"]["serving.queries"] == 4
    assert snap["counters"]["serving.sheds"] == 1
    assert snap["gauges"]["serving.pool.pending"] == 0
    # capacity freed after completion
    pool.try_submit("/query", lambda: None).result(5)
    pool.shutdown()


def _req(port, method, path, body=None, text=False):
    data = None
    headers = {}
    if body is not None:
        data = body.encode() if text else json.dumps(body).encode()
        headers["Content-Type"] = "text/plain" if text else "application/json"
    r = urllib.request.Request(f"http://127.0.0.1:{port}{path}", data=data,
                               method=method, headers=headers)
    with urllib.request.urlopen(r) as resp:
        return json.loads(resp.read())


def test_rest_storm_sheds_503_and_query_during_rebuild():
    from siddhi_tpu.service import SiddhiRestService

    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.agg_shards": "3"}))
    svc = SiddhiRestService(m, query_workers=2, query_queue_cap=4).start()
    app = APP.replace("@app:name('ServeApp')",
                      "@app:name('ServeApp')\n@app:statistics('true')")
    try:
        _req(svc.port, "POST", "/apps", app, text=True)
        rt = m.get_siddhi_app_runtime("ServeApp")
        _pump(rt, seed=41, n=100)
        agg = rt.aggregations["TradeAgg"]
        blobs = agg.checkpoint_shards()
        _pump(rt, seed=42, n=50)
        q = {"app": "ServeApp",
             "query": QUERY.format(per="seconds") + ";"}
        ref = _req(svc.port, "POST", "/query", q)["rows"]

        # store queries keep answering (200 or a clean 503, never a 500)
        # while a shard is killed and rebuilt
        codes = []

        def client():
            for _ in range(10):
                try:
                    _req(svc.port, "POST", "/query", q)
                    codes.append(200)
                except urllib.error.HTTPError as e:
                    codes.append(e.code)

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        agg.kill_shard(2)
        agg.rebuild_shard(2, blobs[2])
        for t in threads:
            t.join(30)
        assert set(codes) <= {200, 503} and 200 in codes
        # after the rebuild the stitched result is exact again
        assert _req(svc.port, "POST", "/query", q)["rows"] == ref

        # storm past the cap: 503 with the shed marker + counters
        gate = threading.Event()
        orig = agg.shards[0].partials
        agg.shards[0].partials = lambda d: (gate.wait(10), orig(d))[1]
        storm_codes = []

        def storm():
            try:
                _req(svc.port, "POST", "/query", q)
                storm_codes.append(200)
            except urllib.error.HTTPError as e:
                storm_codes.append(e.code)

        threads = [threading.Thread(target=storm) for _ in range(10)]
        for t in threads:
            t.start()
        while storm_codes.count(503) == 0 and any(
                t.is_alive() for t in threads):
            pass
        gate.set()
        for t in threads:
            t.join(30)
        assert 503 in storm_codes
        metrics = _req(svc.port, "GET", "/metrics?format=json")
        proc = metrics["process"]["counters"]
        assert proc["serving.sheds"] >= 1
        stats = metrics["apps"]["ServeApp"]["statistics"]["counters"]
        assert stats["resilience.query_sheds"] >= 1
        assert stats["resilience.shard_rebuilds"] == 1
    finally:
        svc.stop()
        m.shutdown()


def test_metrics_families_for_both_aggregation_paths():
    """The /metrics satellite fix: per-granularity bucket gauges and
    flush-latency histograms are scraped for the legacy single-store
    runtime AND the sharded serving runtime."""
    from siddhi_tpu.observability import export

    for shards in (1, 3):
        m, rt = _mk(shards)
        try:
            _pump(rt, seed=51, n=40)
            _rows(rt)
            text = export.prometheus_text(m)
            assert ('siddhi_aggregation_buckets{app="ServeApp",'
                    'name="TradeAgg",duration="sec"}') in text
            assert 'siddhi_aggregation_flush_ms{app="ServeApp"' in text
            assert 'siddhi_aggregation_flush_ms_count{' in text
            if shards > 1:
                assert ('siddhi_aggregation_shards{app="ServeApp",'
                        'name="TradeAgg"} 3') in text
                assert "siddhi_serving_fanout_ms{" in text
                assert "siddhi_serving_merge_ms{" in text
                assert ('siddhi_serving_query_ms{app="ServeApp",'
                        'granularity="sec",quantile="0.99"}') in text
                assert "siddhi_aggregation_shard_wal_batches{" in text
        finally:
            m.shutdown()


def test_partition_by_id_keeps_legacy_runtime():
    """@PartitionById (DB shard-stitch) is subsumed but NOT broken: it
    keeps the legacy runtime even when agg_shards is configured."""
    from siddhi_tpu.core.aggregation import IncrementalAggregationRuntime
    from siddhi_tpu.serving import ShardedIncrementalAggregation

    app = APP.replace("define aggregation TradeAgg",
                      "@PartitionById\ndefine aggregation TradeAgg")
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.agg_shards": "4", "shardId": "node-1"}))
    try:
        rt = m.create_siddhi_app_runtime(app)
        agg = rt.aggregations["TradeAgg"]
        assert isinstance(agg, IncrementalAggregationRuntime)
        assert not isinstance(agg, ShardedIncrementalAggregation)
        assert agg.shard_mode and agg.shard_id == "node-1"
    finally:
        m.shutdown()
