"""@Async junction behavior: re-batching, max.delay coalescing, and the
latency-target adaptive batch cap (SURVEY §7 hard part 6 — the knob the
reference's Disruptor ring does not have; its analog is StreamHandler
re-batching up to batch.size, StreamHandler.java:57-71)."""

import time

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.event import Event
from siddhi_tpu.core.stream.junction import Receiver, StreamJunction
from siddhi_tpu.query_api.definitions import Attribute, AttrType, StreamDefinition


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def _wait_for(predicate, timeout=10.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if predicate():
            return True
        time.sleep(0.01)
    return False


def test_async_app_delivers_all_events_with_max_delay():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @Async(buffer.size='256', batch.size='64', max.delay='5 ms')
        define stream S (sym string, v long);
        @info(name = 'q')
        from S select sym, v insert into Out;
    """)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    for i in range(40):          # trickle: one event per send
        h.send([f"K{i % 4}", i])
    assert _wait_for(lambda: len(c.events) == 40), len(c.events)
    assert [e.data[1] for e in c.events] == list(range(40))  # order kept
    m.shutdown()


def _mk_junction(app_context):
    sdef = StreamDefinition(id="S", attributes=[
        Attribute("v", AttrType.LONG)])
    return StreamJunction(sdef, app_context)


class _SlowReceiver(Receiver):
    def __init__(self, sleep_s):
        self.sleep_s = sleep_s
        self.batches = []

    def receive(self, events):
        time.sleep(self.sleep_s)
        self.batches.append(len(events))


def test_latency_target_shrinks_then_regrows_batch_cap():
    from siddhi_tpu.core.context import SiddhiAppContext, SiddhiContext

    ctx = SiddhiAppContext(SiddhiContext(), "t")
    j = _mk_junction(ctx)
    j.enable_async(buffer_size=4096, batch_size=256,
                   latency_target_ms=5.0)
    slow = _SlowReceiver(0.02)   # 20 ms per delivery >> 5 ms target
    j.subscribe(slow)
    j.start_processing()
    for i in range(600):
        j.send_events([Event(timestamp=i, data=[i])])
    assert _wait_for(lambda: sum(slow.batches) == 600), sum(slow.batches)
    assert j._cur_batch < 256, j._cur_batch   # overshoot shrank the cap
    shrunk = j._cur_batch
    # receiver turns fast: sustained headroom regrows the cap
    slow.sleep_s = 0.0
    for i in range(600):
        j.send_events([Event(timestamp=i, data=[i])])
    assert _wait_for(lambda: sum(slow.batches) == 1200), sum(slow.batches)
    assert j._cur_batch > shrunk, (j._cur_batch, shrunk)
    j.stop_processing()


def test_max_delay_coalesces_trickled_events():
    from siddhi_tpu.core.context import SiddhiAppContext, SiddhiContext

    ctx = SiddhiAppContext(SiddhiContext(), "t")
    j = _mk_junction(ctx)
    j.enable_async(buffer_size=4096, batch_size=1024, max_delay_ms=50.0)
    rec = _SlowReceiver(0.0)
    j.subscribe(rec)
    j.start_processing()
    # 20 events arriving faster than max.delay coalesce into FEW batches
    # (without max.delay, an empty queue flushes 1-event batches)
    for i in range(20):
        j.send_events([Event(timestamp=i, data=[i])])
        time.sleep(0.002)
    assert _wait_for(lambda: sum(rec.batches) == 20), sum(rec.batches)
    assert len(rec.batches) <= 5, rec.batches
    j.stop_processing()
