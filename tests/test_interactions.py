"""Cross-feature interaction differentials (VERDICT round-2 gaps): host
windows under checkpoint/restore, and per-group rate limiters inside
partitions — each vs a plain-Python model over the same trace."""

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


SORT_APP = """@app:playback
define stream S (sym string, v int);
from S#window.sort(3, v, 'asc')
select sym, v insert all events into Out;
"""


def _drive(rt, c, sends):
    h = rt.get_input_handler("S")
    for ts, data in sends:
        h.send(ts, data)
    return [(e.timestamp, tuple(e.data), e.is_expired) for e in c.events]


def test_host_window_survives_restore_mid_trace():
    # a host-mode window (sort keeps the 3 smallest) checkpointed mid
    # trace must produce the SAME continuation as an uninterrupted run
    rng = np.random.default_rng(11)
    trace = [(1000 + i * 50, [f"s{i}", int(rng.integers(0, 100))])
             for i in range(40)]
    cut = 25

    # uninterrupted reference run
    m1 = SiddhiManager()
    rt1 = m1.create_siddhi_app_runtime(SORT_APP)
    c1 = Collector()
    rt1.add_callback("Out", c1)
    full = _drive(rt1, c1, trace)
    m1.shutdown()

    # checkpointed run: persist after `cut` sends, restore in a FRESH
    # manager, continue with the rest
    store = InMemoryPersistenceStore()
    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(SORT_APP)
    c2 = Collector()
    rt2.add_callback("Out", c2)
    head = _drive(rt2, c2, trace[:cut])
    rt2.persist()
    m2.shutdown()

    m3 = SiddhiManager()
    m3.set_persistence_store(store)
    rt3 = m3.create_siddhi_app_runtime(SORT_APP)
    c3 = Collector()
    rt3.add_callback("Out", c3)
    rt3.restore_last_revision()
    tail = _drive(rt3, c3, trace[cut:])
    m3.shutdown()

    assert head + tail == full


def test_session_window_survives_restore_mid_hold():
    # session with allowedLatency restored while a session is PARKED in
    # the previous container: the hold must still emit at its due time
    app = """@app:playback
    define stream S (user string, v int);
    from S#window.session(2 sec, user, 1 sec)
    select user, v insert all events into Out;
    """
    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    h.send(1000, ["u1", 1])
    h.send(3500, ["u2", 9])     # u1 {1} parks (due 4000)
    rt.persist()
    m.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(app)
    c2 = Collector()
    rt2.add_callback("Out", c2)
    rt2.restore_last_revision()
    h2 = rt2.get_input_handler("S")
    h2.send(8000, ["u2", 0])    # clock jump releases both holds
    m2.shutdown()
    exp = [(e.timestamp, tuple(e.data)) for e in c2.events
           if e.is_expired or e.data[0] == "u1"]
    # u1's parked session emits at its restored due time, not at 8000
    assert (4000, ("u1", 1)) in exp


def test_per_group_rate_limiter_inside_partition():
    # `output last every 3 events` with group-by inside a partition: the
    # reference clones the limiter per partition key, so the 3-event
    # counter runs per USER, flushing the latest event of each SYM group
    # seen in that user's window (LastGroupByPerEventOutputRateLimiter
    # inside PartitionInstanceRuntime)
    app = """
    define stream S (user string, sym string, v int);
    partition with (user of S) begin
      from S select user, sym, v group by sym
      output last every 3 events
      insert into Out;
    end;
    """
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    rng = np.random.default_rng(5)
    counters = {}
    lasts = {}
    model_out = []
    for i in range(60):
        user = f"u{int(rng.integers(0, 2))}"
        sym = f"A{int(rng.integers(0, 2))}"
        h.send([user, sym, i])
        counters[user] = counters.get(user, 0) + 1
        lasts.setdefault(user, {})[sym] = (user, sym, i)
        if counters[user] % 3 == 0:
            model_out.extend(lasts[user].values())
            lasts[user] = {}
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert sorted(got) == sorted(model_out)
    # per-user windows never mix: each user's emissions appear in order
    for u in ("u0", "u1"):
        seq = [g for g in got if g[0] == u]
        model_seq = [g for g in model_out if g[0] == u]
        assert seq == model_seq
