"""Differential tests: the fused sliding-window aggregation stage
(ops/fused_agg.py) must produce exactly what the generic
window->selector pipeline produces for CURRENT outputs (exact mode).
"""

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback


class Collect(StreamCallback):
    def __init__(self):
        self.events = []

    def receive(self, events):
        self.events.extend(events)


APP = """
    define stream S (symbol string, price double, volume long);
    @info(name = 'q')
    from S#window.length({W})
    select symbol, sum(price) as total, avg(price) as avgP, count() as n,
           stdDev(price) as sd
    group by symbol
    insert into Out;
"""


def _run_planned(app, rows, fusion: bool, batches=None):
    """Plan with fusion on/off by flipping the flag BEFORE runtime build."""
    from siddhi_tpu.core import context as ctx_mod

    orig = ctx_mod.SiddhiAppContext.__init__

    def patched(self, siddhi_context, name):
        orig(self, siddhi_context, name)
        self.enable_fusion = fusion

    ctx_mod.SiddhiAppContext.__init__ = patched
    try:
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        q = rt.query_runtimes["q"]
        from siddhi_tpu.ops.fused_agg import FusedSlidingAggStage

        assert isinstance(q.window_stage, FusedSlidingAggStage) == fusion
        cb = Collect()
        rt.add_callback("Out", cb)
        h = rt.get_input_handler("S")
        if batches is None:
            for r in rows:
                h.send(r)
        else:
            from siddhi_tpu.core.event import Event

            i = 0
            for sz in batches:
                h.send([Event(timestamp=1000 + i + j, data=rows[i + j])
                        for j in range(sz)])
                i += sz
        m.shutdown()
        return [e.data for e in cb.events]
    finally:
        ctx_mod.SiddhiAppContext.__init__ = orig


def test_fused_matches_generic_small_window():
    # window smaller than the batch: same-batch evictions exercised
    rng = np.random.default_rng(7)
    rows = [[f"S{rng.integers(0, 3)}", float(rng.integers(1, 20)), int(rng.integers(1, 9))]
            for _ in range(40)]
    app = APP.format(W=5)
    fused = _run_planned(app, rows, fusion=True, batches=[13, 1, 26])
    generic = _run_planned(app, rows, fusion=False, batches=[13, 1, 26])
    assert len(fused) == len(generic) == 40
    for f, g in zip(fused, generic):
        assert f[0] == g[0] and f[3] == g[3]
        np.testing.assert_allclose(f[1], g[1], rtol=1e-12)
        np.testing.assert_allclose(f[2], g[2], rtol=1e-12)
        np.testing.assert_allclose(f[4], g[4], rtol=1e-9, atol=1e-9)


def test_fused_matches_generic_many_keys():
    rng = np.random.default_rng(11)
    rows = [[f"K{rng.integers(0, 40)}", float(rng.standard_normal() * 10), 1]
            for _ in range(120)]
    app = APP.format(W=50)
    fused = _run_planned(app, rows, fusion=True, batches=[64, 56])
    generic = _run_planned(app, rows, fusion=False, batches=[64, 56])
    assert len(fused) == len(generic)
    for f, g in zip(fused, generic):
        assert f[0] == g[0] and f[3] == g[3]
        np.testing.assert_allclose(f[1], g[1], rtol=1e-9, atol=1e-9)
        np.testing.assert_allclose(f[2], g[2], rtol=1e-9, atol=1e-9)


def test_fused_null_args_and_having():
    app = """
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.length(3)
        select symbol, sum(price) as total, avg(price) as avgP
        group by symbol
        having total > 5.0
        insert into Out;
    """
    rows = [["A", 10.0, 1], ["A", None, 1], ["A", 30.0, 1], ["A", 2.0, 1],
            ["A", 1.0, 1]]
    fused = _run_planned(app, rows, fusion=True)
    generic = _run_planned(app, rows, fusion=False)
    assert fused == generic


def test_fused_no_group_by():
    app = """
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.length(4)
        select sum(price) as total, count() as n
        insert into Out;
    """
    rows = [["A", float(v), 1] for v in [1, 2, 3, 4, 5, 6, 7]]
    fused = _run_planned(app, rows, fusion=True, batches=[7])
    generic = _run_planned(app, rows, fusion=False, batches=[7])
    assert fused == generic
    assert fused[-1] == [4.0 + 5 + 6 + 7, 4]


def test_min_max_not_fused():
    # min/max are not invertible — the generic ring path must stay in place
    from siddhi_tpu.ops.fused_agg import FusedSlidingAggStage

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price double);
        @info(name = 'q')
        from S#window.length(3) select symbol, min(price) as mn
        group by symbol insert into Out;
    """)
    q = rt.query_runtimes["q"]
    assert not isinstance(q.window_stage, FusedSlidingAggStage)
    m.shutdown()


def test_expired_consumers_not_fused():
    from siddhi_tpu.ops.fused_agg import FusedSlidingAggStage

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price double);
        @info(name = 'q')
        from S#window.length(3) select symbol, sum(price) as s
        group by symbol insert all events into Out;
    """)
    q = rt.query_runtimes["q"]
    assert not isinstance(q.window_stage, FusedSlidingAggStage)
    m.shutdown()
