"""Property: outputs are invariant to how a trace is chunked into batches
(per-event sends vs multi-event chunks) — the engine's batch processing
must not change window/aggregation semantics. Hypothesis shrinks failing
chunkings to minimal counterexamples."""

import pytest

pytest.importorskip("hypothesis")   # absent in some images: skip, don't
#                                     fail collection
from hypothesis import given, settings, strategies as st  # noqa: E402

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.event import Event


class C(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


def run_chunked(app, rows, chunks):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = C()
    rt.add_callback("Out", c)
    h = rt.get_input_handler("S")
    i = 0
    for size in chunks:
        batch = [Event(timestamp=1000 + j, data=list(rows[j]))
                 for j in range(i, min(i + size, len(rows)))]
        if batch:
            h.send(batch)
        i += size
        if i >= len(rows):
            break
    while i < len(rows):
        h.send(1000 + i, list(rows[i]))
        i += 1
    m.shutdown()
    return c.rows


APP = """
    define stream S (sym string, v long);
    from S#window.length(3)
    select sym, sum(v) as total, count() as n
    group by sym insert into Out;
"""

trace = st.lists(
    st.tuples(st.sampled_from(["a", "b", "c"]), st.integers(1, 9)),
    min_size=1, max_size=24)
chunking = st.lists(st.integers(1, 7), min_size=1, max_size=24)


@settings(max_examples=12, deadline=None)
@given(trace, chunking)
def test_chunking_invariance_windowed_groupby(rows, chunks):
    per_event = run_chunked(APP, rows, [1] * len(rows))
    chunked = run_chunked(APP, rows, chunks)
    assert chunked == per_event


APP_BATCH = """
    define stream S (sym string, v long);
    from S#window.lengthBatch(4)
    select sum(v) as total insert into Out;
"""


@settings(max_examples=8, deadline=None)
@given(trace, chunking)
def test_chunking_invariance_tumbling(rows, chunks):
    per_event = run_chunked(APP_BATCH, rows, [1] * len(rows))
    chunked = run_chunked(APP_BATCH, rows, chunks)
    assert chunked == per_event


NFA_APP = """
    define stream S (sym string, v long);
    from every e1=S[v > 5] -> e2=S[v > e1.v]
    select e1.v as a, e2.v as b insert into Out;
"""


@settings(max_examples=8, deadline=None)
@given(trace, chunking)
def test_chunking_invariance_nfa(rows, chunks):
    per_event = run_chunked(NFA_APP, rows, [1] * len(rows))
    chunked = run_chunked(NFA_APP, rows, chunks)
    assert chunked == per_event


PART_APP = """
    define stream S (sym string, v long);
    partition with (sym of S) begin
    from S#window.length(2)
    select sym, sum(v) as total insert into Out; end;
"""


@settings(max_examples=8, deadline=None)
@given(trace, chunking)
def test_chunking_invariance_partitioned(rows, chunks):
    per_event = run_chunked(PART_APP, rows, [1] * len(rows))
    chunked = run_chunked(PART_APP, rows, chunks)
    assert chunked == per_event
