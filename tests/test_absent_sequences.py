"""Absent states in sequences — reference
query/sequence/absent/{AbsentSequenceTestCase,LogicalAbsentSequenceTestCase}."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


STREAMS = """@app:playback
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""

TAIL = STREAMS + """
from e1=Stream1[price>20], not Stream2[price>e1.price] for 1 sec
select e1.symbol as symbol1
insert into OutStream;
"""


def test_seq_tail_absent_emits_at_deadline():
    # AbsentSequenceTestCase.testQueryAbsent1
    m, rt, c = build(TAIL)
    s1 = rt.get_input_handler("Stream1")
    s1.send(1000, ["WSO2", 55.6, 100])
    s1.send(2500, ["LATE", 5.0, 100])   # advances the clock past 2000
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("WSO2",)]


def test_seq_tail_absent_late_event_after_deadline_ok():
    # testQueryAbsent2: a matching B after the deadline changes nothing
    m, rt, c = build(TAIL)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(2200, ["IBM", 58.7, 100])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("WSO2",)]


def test_seq_tail_absent_violated_within_wait():
    # testQueryAbsent3: a matching B inside the window kills the match
    m, rt, c = build(TAIL)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["IBM", 58.7, 100])
    s1.send(2500, ["X", 5.0, 100])
    m.shutdown()
    assert c.events == []


def test_seq_tail_absent_nonmatching_event_does_not_kill():
    # testQueryAbsent4 family: a NON-matching Stream2 event during the
    # wait neither violates nor breaks the sequence
    m, rt, c = build(TAIL)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["IBM", 10.0, 100])    # price <= e1.price: no violation
    s1.send(2500, ["X", 5.0, 100])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("WSO2",)]


def test_seq_head_absent_then_event():
    # testQueryAbsent from the head-absent family:
    # `not Stream1 for 1 sec, e2=Stream2[price>30]`
    m, rt, c = build(STREAMS + """
        from not Stream1[price>20] for 1 sec, e2=Stream2[price>30]
        select e2.symbol as symbol
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    # head waits anchor at the clock's first value: start the timeline
    # with a non-violating Stream1 event (price <= 20)
    s1.send(0, ["start", 5.0, 100])
    s2.send(2500, ["IBM", 45.0, 100])   # quiet first second passed
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("IBM",)]


def test_seq_head_absent_violated():
    m, rt, c = build(STREAMS + """
        from not Stream1[price>20] for 1 sec, e2=Stream2[price>30]
        select e2.symbol as symbol
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(1100, ["WSO2", 55.0, 100])   # matching A inside the quiet window
    s2.send(1500, ["IBM", 45.0, 100])
    m.shutdown()
    assert c.events == []


def test_seq_logical_absent_or_present():
    # LogicalAbsentSequenceTestCase shape: (not A for 1 sec) or e2 present
    m, rt, c = build(STREAMS + """
        define stream Stream3 (symbol string, price float, volume int);
        from e1=Stream1[price>20], not Stream2[price>e1.price] for 1 sec or e3=Stream3[price>e1.price]
        select e1.symbol as symbol1
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s3 = rt.get_input_handler("Stream3")
    s1.send(1000, ["WSO2", 55.6, 100])
    s3.send(1200, ["HIGH", 60.0, 100])   # present side completes first
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("WSO2",)]


EVERY_HEAD = STREAMS + """
from every not Stream1[price>20] for 1 sec, e2=Stream2[price>30]
select e2.symbol as symbol
insert into OutStream;
"""


def test_seq_every_head_absent_rearms():
    # EveryAbsentSequenceTestCase testQueryAbsent2 shape: each event after
    # its own quiet window matches
    m, rt, c = build(EVERY_HEAD)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(0, ["start", 5.0, 100])     # clock start (non-violating)
    s2.send(2200, ["IBM", 58.7, 100])
    s2.send(3300, ["WSO2", 68.7, 100])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("IBM",), ("WSO2",)]


def test_seq_every_head_absent_single_pending():
    # a long quiet stretch yields ONE pending state, not one per second
    m, rt, c = build(EVERY_HEAD)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(0, ["start", 5.0, 100])     # clock start (non-violating)
    s2.send(5100, ["IBM", 58.7, 100])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("IBM",)]


def test_seq_every_head_absent_violated_window():
    m, rt, c = build(EVERY_HEAD)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(600, ["WSO2", 55.0, 100])     # breaks the first quiet window
    s2.send(900, ["IBM", 58.7, 100])      # no quiet window elapsed yet
    s2.send(2000, ["GOOG", 58.7, 100])    # quiet [600+,1600+] passed
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert ("IBM",) not in got and ("GOOG",) in got


def test_seq_mid_chain_every():
    m, rt, c = build("""@app:playback
        define stream A (v int); define stream B (v int);
        from e1=A, every e2=B[v > e1.v]
        select e1.v as a, e2.v as b insert into OutStream;
    """)
    rt.get_input_handler("A").send(1000, [1])
    hb = rt.get_input_handler("B")
    hb.send(1100, [5])
    hb.send(1200, [7])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [(1, 5), (1, 7)]


def test_seq_mid_absent_then_stream():
    m, rt, c = build("""@app:playback
        define stream A (v int); define stream B (v int);
        define stream Cs (v int);
        from e1=A, not B[v > e1.v] for 1 sec, e3=Cs
        select e1.v as a, e3.v as c insert into OutStream;
    """)
    rt.get_input_handler("A").send(1000, [1])
    rt.get_input_handler("Cs").send(2500, [9])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [(1, 9)]


def test_seq_every_logical_absent_head_rearms():
    # every (not A for 1 sec and not B for 1 sec), e3=C — re-arms per
    # quiet window like the plain absent head
    m, rt, c = build("""@app:playback
        define stream A (v int); define stream B (v int);
        define stream Cs (v int);
        from every not A[v > 0] for 1 sec and not B[v > 0] for 1 sec, e3=Cs
        select e3.v as c insert into OutStream;
    """)
    ha = rt.get_input_handler("A")
    h = rt.get_input_handler("Cs")
    ha.send(0, [0])                     # clock start (v=0: non-violating)
    h.send(2500, [1])
    h.send(4000, [2])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [(1,), (2,)]
