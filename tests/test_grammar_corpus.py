"""Grammar corpus: parse inputs ported from the reference compiler's own
test suite (``siddhi-query-compiler/src/test/java/io/siddhi/query/test/``:
DefineStream/DefineTable/DefineAggregation/DefinePartition/SimpleQuery/
QueryStore/AbsentPattern test cases), with structural spot-checks and
parse-error POSITION assertions (reference ``SiddhiErrorListener`` line/
column context — SURVEY §C3 queryContextStartIndex parity)."""

import pytest

from siddhi_tpu.compiler import SiddhiCompiler
from siddhi_tpu.compiler.errors import SiddhiParserException
from siddhi_tpu.query_api.definitions import AttrType

parse = SiddhiCompiler.parse
parse_query = SiddhiCompiler.parse_query


# ------------------------------------------------- DefineStreamTestCase

def test_stream_definition_types():
    app = parse("define stream cseStream (symbol string, price int, "
                "volume float, data Object);")
    d = app.stream_definitions["cseStream"]
    assert [a.type for a in d.attributes] == [
        AttrType.STRING, AttrType.INT, AttrType.FLOAT, AttrType.OBJECT]


def test_stream_definition_backtick_quoted_ids():
    # DefineStreamTestCase.testCreatingStreamDefinition2: keywords as
    # identifiers via backticks
    app = parse("define stream `define` (`string` string, price int, "
                "volume float, data Object);")
    d = app.stream_definitions["define"]
    assert d.attributes[0].name == "string"


def test_stream_definition_annotation():
    app = parse("@Foo(name='bar','Custom')"
                "define stream StockStream (symbol string, price int);")
    d = app.stream_definitions["StockStream"]
    ann = d.annotations[0]
    assert ann.name == "Foo"
    assert ("name", "bar") in ann.elements
    assert (None, "Custom") in ann.elements


def test_malformed_stream_definition_rejected_with_position():
    # DefineStreamTestCase error cases carry line/col context
    with pytest.raises(SiddhiParserException) as ei:
        parse("define stream StockStream ( symbol, price int )")
    assert ei.value.line >= 1 and ei.value.col >= 1


# -------------------------------------------------- DefineTableTestCase

def test_table_definition_backticks_and_types():
    app = parse("define table `define` (`string` string, price int, "
                "volume float);")
    assert "define" in app.table_definitions


# -------------------------------------------- DefineAggregationTestCase

def test_aggregation_definition_parses():
    app = parse("""
        define stream StockStream (symbol string, price float, volume long);
        define aggregation StockAggregation
        from StockStream
        select symbol, avg(price) as avgPrice, sum(price) as total
        group by symbol
        aggregate by price every seconds ... days;
    """)
    assert "StockAggregation" in app.aggregation_definitions


# ---------------------------------------------- DefinePartitionTestCase

def test_partition_range_keyer_parses():
    app = parse("""
        define stream cseEventStream (symbol string, price float, volume int);
        partition with (200 > volume as 'LessValue' or 200 <= volume as
        'HighValue' of cseEventStream)
        begin
          from cseEventStream select symbol insert into OutStream;
        end;
    """)
    from siddhi_tpu.query_api.execution import Partition

    parts = [e for e in app.execution_elements if isinstance(e, Partition)]
    assert len(parts) == 1


# -------------------------------------------------- SimpleQueryTestCase

@pytest.mark.parametrize("src", [
    # testQuery1/2: filters + windows + group by + having
    "from StockStream[price>3]#window.length(50) "
    "select symbol, avg(price) as avgPrice group by symbol "
    "having (price >= 20) insert all events into StockQuote;",
    "from StockStream [price >= 20]#window.lengthBatch(50) "
    "select symbol, avg(price) as avgPrice group by symbol "
    "having avgPrice>50 insert into StockQuote;",
    # testQuery3: expressions in having
    "from AllStockQuotes#window.time(10 min) "
    "select symbol as symbol, price, avg(price) as averagePrice "
    "group by symbol "
    "having ( price > ( averagePrice*1.02) ) or ( averagePrice > price ) "
    "insert into MovingAverageStream;",
    # arithmetic in filters
    "from StockStream[7+9.5 > price and 100 >= volume] "
    "select symbol, avg(price) as avgPrice group by symbol "
    "having avgPrice>= 50 insert into StockQuote;",
    "from StockStream[7+9.5 < price or 100 <= volume]#window.length(50) "
    "select symbol, avg(price) as avgPrice group by symbol "
    "having avgPrice!= 50 insert into StockQuote;",
    # post-window filter handler
    "from StockStream[7-9.5 > price and 100 >= volume]#window.length(50)"
    "#[symbol=='WSO2'] "
    "select symbol, avg(price) as avgPrice group by symbol "
    "having avgPrice >= 50 insert into StockQuote;",
    # output rate limiting forms
    "from StockStream select symbol output every 5 events "
    "insert into Out;",
    "from StockStream select symbol output snapshot every 1 sec "
    "insert into Out;",
    "from StockStream select symbol output last every 500 milliseconds "
    "insert into Out;",
    # joins
    "from StockStream#window.length(10) as a join OtherStream#window.time(1 sec) as b "
    "on a.symbol == b.symbol "
    "select a.symbol, b.price insert into JoinOut;",
    "from StockStream#window.length(10) left outer join "
    "OtherStream#window.length(5) on StockStream.symbol == OtherStream.symbol "
    "select StockStream.symbol, OtherStream.price insert into JoinOut;",
])
def test_simple_query_corpus_parses(src):
    q = parse_query(src)
    assert q.selector is not None and q.output_stream is not None


# -------------------------------------------------- AbsentPatternTestCase

@pytest.mark.parametrize("src", [
    "from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 2 sec "
    "select e1.symbol as symbol insert into OutputStream;",
    "from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30] "
    "select e2.symbol as symbol insert into OutputStream;",
    "from every (e1=Stream1[price>20]) -> e2=Stream2[price>e1.price] "
    "within 5 min select e1.price as p1 insert into OutputStream;",
])
def test_absent_pattern_corpus_parses(src):
    from siddhi_tpu.query_api.execution import StateInputStream

    q = parse_query(src)
    assert isinstance(q.input_stream, StateInputStream)


def test_absent_capture_rejected():
    # AbsentPatternTestCase.testQueryAbsent2: `not e2=...` is invalid
    with pytest.raises(Exception):
        parse_query(
            "from e1=Stream1[price>20] -> not e2=Stream2[price>e1.price] "
            "for 1 sec select e1.symbol insert into OutputStream;")


# ------------------------------------------------- error position parity

def test_error_positions_are_exact():
    # the reference's SiddhiErrorListener reports line:col of the
    # offending token; pin ours to exact positions
    src = ("define stream S (a int);\n"
           "from S seletc a insert into Out;")
    with pytest.raises(SiddhiParserException) as ei:
        parse(src)
    assert ei.value.line == 2          # error on the second line
    assert ei.value.col > 5            # past 'from S '


def test_error_position_mid_expression():
    with pytest.raises(SiddhiParserException) as ei:
        parse("define stream S (a int);\n"
              "from S[a >] select a insert into Out;")
    assert ei.value.line == 2


def test_error_context_snippet():
    with pytest.raises(SiddhiParserException) as ei:
        parse("define stream S (a int;")
    msg = str(ei.value)
    assert "line" in msg
