"""Reference filter corpus — scenario table extracted verbatim from
``query/FilterTestCase1.java`` and ``query/FilterTestCase2.java`` (the
SiddhiQL string tests plus the programmatic query-API tests expressed as
their SiddhiQL equivalents): comparison operators over every numeric
type pairing, bool/string equality, and/or/not compositions, and
constant-vs-attribute orderings. Each entry is (name, stream attrs,
filter, select, feed rows, expected pass count)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.query.callback import QueryCallback

SCENARIOS = [
    ('filterTest1',
     'symbol string, price float, volume long',
     '70 > price',
     'symbol, price',
     [['IBM', 700.0, 100], ['WSO2', 60.5, 200]],
     1),
    ('filterTest2',
     'symbol string, price float, volume long',
     '150 > volume',
     'symbol,price',
     [['IBM', 700.0, 100], ['WSO2', 60.5, 200]],
     1),
    ('testFilterQuery3',
     'symbol string, price float, volume int',
     '70 > price',
     'symbol,price',
     [['WSO2', 55.6, 100], ['IBM', 75.6, 100], ['WSO2', 57.6, 200]],
     2),
    ('testFilterQuery4',
     'symbol string, price float, volume long',
     'volume > 50f',
     'symbol,price,volume',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),
    ('testFilterQuery5',
     'symbol string, price float, volume long',
     'volume > 50L',
     'symbol,price,volume',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),
    ('testFilterQuery6',
     'symbol string, price float, volume int',
     'volume > 50L',
     'symbol,price,volume',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),
    ('testFilterQuery7',
     'symbol string, price float, volume double',
     'volume > 50L',
     'symbol,price,volume',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery8',
     'symbol string, price float, volume float',
     'volume > 50L',
     'symbol,price,volume',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery9',
     'symbol string, price float, volume float',
     'volume > 50f',
     'symbol,price',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery10',
     'symbol string, price float, volume double',
     'volume > 50d',
     'symbol,price',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery11',
     'symbol string, price float, volume double',
     'volume > 50f',
     'symbol,price',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery12',
     'symbol string, price float, volume double',
     'volume > 45',
     'symbol,price',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery13',
     'symbol string, price float, volume float',
     'volume > 50d',
     'symbol,price',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery14',
     'symbol string, price float, volume float',
     'volume > 45',
     'symbol,price',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery15',
     'symbol string, price float, volume float, quantity int',
     'quantity > 4d',
     'symbol,price,quantity',
     [['WSO2', 50.0, 60.0, 5], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 200.0, 4]],
     1),
    ('testFilterQuery16',
     'symbol string, price float, volume long',
     'volume > 50d',
     'symbol,price,volume',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),
    ('testFilterQuery17',
     'symbol string, price float, volume long',
     'volume > 45',
     'symbol, volume',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),
    ('testFilterQuery18',
     'symbol string, price float, volume int',
     '70 > volume',
     'symbol, price',
     [['WSO2', 55.6, 50], ['IBM', 75.6, 100], ['WSO2', 57.6, 30]],
     2),
    ('testFilterQuery20',
     'symbol string, price float, volume long',
     'volume < 100',
     'symbol, price, volume',
     [['WSO2', 55.6, 103], ['WSO2', 57.6, 10]],
     1),
    ('testFilterQuery21',
     'symbol string, price float, volume long',
     'volume != 100',
     'symbol,price,volume',
     [['WSO2', 55.6, 100], ['WSO2', 57.6, 10]],
     1),
    ('testFilterQuery22',
     'symbol string, price float, volume double',
     'volume > 12L and price < 56',
     'symbol,price,volume',
     [['WSO2', 55.6, 100.0], ['WSO2', 57.6, 10.0]],
     1),
    ('testFilterQuery23',
     'symbol string, price float, volume long',
     "symbol != 'WSO2' and volume != 55L and price != 45f ",
     'symbol,price,volume',
     [['WSO2', 45.0, 100], ['IBM', 35.0, 50]],
     1),
    ('testFilterQuery24',
     'symbol string, price float, volume long',
     'volume != 50f',
     'symbol,price',
     [['WSO2', 45.0, 100], ['IBM', 35.0, 50]],
     1),
    ('testFilterQuery25',
     'symbol string, price float, volume long',
     'price != 35L',
     'symbol,price',
     [['WSO2', 45.0, 100], ['IBM', 35.0, 50]],
     1),
    ('testFilterQuery26',
     'symbol string, price float, volume long',
     'volume != 100 and volume != 70d',
     'symbol,price,volume',
     [['WSO2', 55.6, 100], ['IBM', 57.6, 10]],
     1),
    ('testFilterQuery27',
     'symbol string, price float, volume long',
     'price != 53.6d or price != 87',
     'symbol,price,volume',
     [['WSO2', 55.6, 100], ['IBM', 57.6, 10]],
     2),
    ('testFilterQuery28',
     'symbol string, price float, volume int',
     'volume != 40f and volume != 400',
     'symbol,price,volume',
     [['WSO2', 55.5, 40], ['WSO2', 53.5, 50], ['WSO2', 50.5, 400]],
     1),
    ('testFilterQuery29',
     'symbol string, price float, volume int',
     'volume != 40d and volume != 400d',
     'symbol,price,volume',
     [['WSO2', 55.5, 40], ['WSO2', 53.5, 50], ['WSO2', 50.5, 400]],
     1),
    ('testFilterQuery30',
     'symbol string, price float, available bool',
     'available != true ',
     'symbol,price,available',
     [['IBM', 55.6, True], ['WSO2', 57.6, False]],
     1),
    ('testFilterQuery31',
     'symbol string, price float, available bool',
     'available != true',
     'symbol, price, available',
     [['IBM', 55.6, True], ['WSO2', 57.6, False]],
     1),
    ('testFilterQuery32',
     'symbol string, price float, volume int',
     'price != 50 and volume != 50L',
     'symbol,price,volume',
     [['WSO2', 55.5, 40], ['WSO2', 53.5, 50]],
     1),
    ('testFilterQuery33',
     'symbol string, price float, volume double',
     'volume != 50d',
     'symbol,price,volume',
     [['WSO2', 55.5, 40.0], ['WSO2', 53.5, 50.0]],
     1),
    ('testFilterQuery34',
     'symbol string, price float, volume double',
     'volume != 50f  or volume != 50',
     'symbol,price,volume',
     [['WSO2', 55.5, 40.0], ['WSO2', 53.5, 50.0]],
     1),
    ('testFilterQuery35',
     'symbol string, price float, volume double',
     'volume != 50L',
     'symbol,price,volume',
     [['WSO2', 55.5, 40.0], ['WSO2', 53.5, 50.0]],
     1),
    ('testFilterQuery36',
     'symbol string, price float, available bool',
     'available == true',
     'symbol, price, available',
     [['IBM', 55.6, True], ['WSO2', 57.6, False]],
     1),
    ('testFilterQuery37',
     'symbol string, price float, volume double',
     'volume == 50d',
     'symbol,price,volume',
     [['WSO2', 55.5, 40.0], ['WSO2', 53.5, 50.0]],
     1),
    ('testFilterQuery38',
     'symbol string, price float, volume double',
     "symbol == 'IBM'",
     'symbol,price,volume',
     [['WSO2', 55.5, 40.0], ['IBM', 53.5, 50.0]],
     1),
    ('testFilterQuery39',
     'symbol string, price float, volume double',
     'price <= 53.5f',
     'symbol,price,volume',
     [['WSO2', 55.5, 40.0], ['WSO2', 53.5, 50.0]],
     1),
    ('testFilterQuery40',
     'symbol string, price float, volume double',
     'price <= 54',
     'symbol,price,volume',
     [['WSO2', 55.5, 40.0], ['WSO2', 53.5, 50.0]],
     1),
    ('testFilterQuery41',
     'symbol string, price float, volume int',
     'volume <= 40',
     'symbol,price,volume',
     [['WSO2', 55.5, 40], ['WSO2', 53.5, 50]],
     1),
    ('testFilterQuery42',
     'symbol string, price float, volume int',
     'price >= 54',
     'symbol,price,volume',
     [['WSO2', 55.5, 40], ['WSO2', 53.5, 50]],
     1),
    ('testFilterQuery43',
     'symbol string, price float, volume long',
     'volume >= 50',
     'symbol,price,volume',
     [['WSO2', 55.5, 40], ['WSO2', 53.5, 50]],
     1),
    ('testFilterQuery51',
     'symbol string, price float, volume double',
     'volume == 60f',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery52',
     'symbol string, price float, volume double',
     'volume == 60',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery53',
     'symbol string, price float, volume double',
     'volume == 60L',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 60.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery54',
     'symbol string, price float, volume double',
     'price == 50.0',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery55',
     'symbol string, price float, volume double',
     'price == 50f',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery56',
     'symbol string, price float, volume double',
     'price == 70',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery57',
     'symbol string, price float, volume double',
     'price == 60L',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 60.0], ['WSO2', 60.0, 200.0]],
     1),
    ('testFilterQuery58',
     'symbol string, price float, volume double, quantity int',
     'quantity == 5.0',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 5], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 200.0, 4]],
     1),
    ('testFilterQuery59',
     'symbol string, price float, volume double, quantity int',
     'quantity == 5f',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 5], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 200.0, 4]],
     1),
    ('testFilterQuery60',
     'symbol string, price float, volume double, quantity int',
     'quantity == 2',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 5], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 200.0, 4]],
     1),
    ('testFilterQuery61',
     'symbol string, price float, volume double, quantity int',
     'quantity == 4L',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 5], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 200.0, 4]],
     1),
    ('testFilterQuery62',
     'symbol string, price float, volume long, quantity int',
     'volume == 200L',
     'symbol, quantity',
     [['WSO2', 50.0, 60, 5], ['WSO2', 70.0, 60, 2], ['WSO2', 60.0, 200, 4]],
     1),
    ('testFilterQuery63',
     'symbol string, price float, volume long',
     'volume == 40.0',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     1),
    ('testFilterQuery64',
     'symbol string, price float, volume long',
     'volume == 40f',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     1),
    ('testFilterQuery65',
     'symbol string, price float, volume long',
     'volume == 40',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     1),
    ('testFilterQuery67',
     'symbol string, price double, volume long',
     'price <= 60.0',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),
    ('testFilterQuery68',
     'symbol string, price double, volume long',
     'price <= 100f',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     3),
    ('testFilterQuery69',
     'symbol string, price double, volume long',
     'price <= 50',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),
    ('testFilterQuery70',
     'symbol string, price float, volume double, quantity int',
     'volume <= 200L',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 5], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 300.0, 4]],
     2),
    ('testFilterQuery71',
     'symbol string, price float, volume long',
     'price <= 50.0',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),
    ('testFilterQuery72',
     'symbol string, price float, volume double, quantity int',
     'price <= 200L',
     'symbol, quantity',
     [['WSO2', 500.0, 60.0, 5], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 300.0, 4]],
     2),
    ('testFilterQuery73',
     'symbol string, price float, volume double, quantity int',
     'quantity <= 5.0',
     'symbol, quantity',
     [['WSO2', 500.0, 60.0, 6], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 300.0, 4]],
     2),
    ('testFilterQuery74',
     'symbol string, price float, volume double, quantity int',
     'quantity <= 5f',
     'symbol, quantity',
     [['WSO2', 500.0, 60.0, 6], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 300.0, 4]],
     2),
    ('testFilterQuery75',
     'symbol string, price float, volume double, quantity int',
     'quantity <= 3L',
     'symbol, quantity',
     [['WSO2', 500.0, 60.0, 6], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 300.0, 4]],
     1),
    ('testFilterQuery76',
     'symbol string, price float, volume long',
     'volume <= 50.0',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     1),
    ('testFilterQuery77',
     'symbol string, price float, volume long',
     'volume <= 50f',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     1),
    ('testFilterQuery78',
     'symbol string, price float, volume long',
     'volume <= 50',
     'symbol',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     1),
    ('testFilterQuery79',
     'symbol string, price float, volume long, quantity int',
     'volume <= 60L',
     'symbol, quantity',
     [['WSO2', 500.0, 60, 6], ['WSO2', 70.0, 60, 2], ['WSO2', 60.0, 300, 4]],
     2),
    ('testFilterQuery80',
     'symbol string, price float, volume double',
     'volume < 50.0',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery81',
     'symbol string, price float, volume double',
     'volume < 70f',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery82',
     'symbol string, price double, volume double',
     'price < 50',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery83',
     'symbol string, price float, volume long',
     'volume > 45',
     'symbol, volume',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),
    ('testFilterQuery83',
     'symbol string, price float, volume double, quantity int',
     'volume < 60L',
     'symbol, quantity',
     [['WSO2', 500.0, 50.0, 6], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 300.0, 4]],
     1),
    ('testFilterQuery84',
     'symbol string, price float, volume double, quantity int',
     'price < 60L',
     'symbol, quantity',
     [['WSO2', 500.0, 50.0, 6], ['WSO2', 70.0, 60.0, 2], ['WSO2', 50.0, 300.0, 4]],
     1),
    ('testFilterQuery85',
     'symbol string, price float, volume double, quantity int',
     'quantity < 4L',
     'symbol, quantity',
     [['WSO2', 500.0, 50.0, 6], ['WSO2', 70.0, 60.0, 2], ['WSO2', 50.0, 300.0, 4]],
     1),
    ('testFilterQuery86',
     'symbol string, price float, volume long, quantity int',
     'volume < 40L',
     'symbol, quantity',
     [['WSO2', 500.0, 50, 6], ['WSO2', 70.0, 20, 2], ['WSO2', 50.0, 300, 4]],
     1),
    ('testFilterQuery87',
     'symbol string, price float, volume double',
     'price < 50.0',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery88',
     'symbol string, price float, volume double',
     'price < 55f',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery89',
     'symbol string, price float, volume double, quantity int',
     'quantity < 50.0',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 6], ['WSO2', 70.0, 40.0, 10], ['WSO2', 44.0, 200.0, 56]],
     2),
    ('testFilterQuery90',
     'symbol string, price float, volume double, quantity int',
     'quantity < 10f',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 6], ['WSO2', 70.0, 40.0, 10], ['WSO2', 44.0, 200.0, 56]],
     1),
    ('testFilterQuery91',
     'symbol string, price float, volume double, quantity int',
     'quantity < 15',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 6], ['WSO2', 70.0, 40.0, 10], ['WSO2', 44.0, 200.0, 56]],
     2),
    ('testFilterQuery92',
     'symbol string, price float, volume long, quantity int',
     'volume < 100.0',
     'symbol, quantity',
     [['WSO2', 50.0, 60, 6], ['WSO2', 70.0, 40, 10], ['WSO2', 44.0, 200, 56]],
     2),
    ('testFilterQuery93',
     'symbol string, price float, volume long, quantity int',
     'volume < 100f',
     'symbol, quantity',
     [['WSO2', 50.0, 60, 6], ['WSO2', 70.0, 40, 10], ['WSO2', 44.0, 200, 56]],
     2),
    ('testFilterQuery94',
     'symbol string, price float, volume double',
     'volume >= 50.0',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery95',
     'symbol string, price float, volume double',
     'volume >= 70f',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery96',
     'symbol string, price double, volume double',
     'price >= 50',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery97',
     'symbol string, price float, volume double, quantity int',
     'volume >= 60L',
     'symbol, quantity',
     [['WSO2', 500.0, 50.0, 6], ['WSO2', 70.0, 60.0, 2], ['WSO2', 60.0, 300.0, 4]],
     2),
    ('testFilterQuery98',
     'symbol string, price float, volume double, quantity int',
     'price >= 60L',
     'symbol, quantity',
     [['WSO2', 500.0, 50.0, 6], ['WSO2', 70.0, 60.0, 2], ['WSO2', 50.0, 300.0, 4]],
     2),
    ('testFilterQuery99',
     'symbol string, price float, volume double, quantity int',
     'quantity >= 4L',
     'symbol, quantity',
     [['WSO2', 500.0, 50.0, 6], ['WSO2', 70.0, 60.0, 2], ['WSO2', 50.0, 300.0, 4]],
     2),
    ('testFilterQuery100',
     'symbol string, price float, volume long, quantity int',
     'volume >= 40L',
     'symbol, quantity',
     [['WSO2', 500.0, 50, 6], ['WSO2', 70.0, 20, 2], ['WSO2', 50.0, 300, 4]],
     2),
    ('testFilterQuery101',
     'symbol string, price float, volume double',
     'price >= 50.0',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     2),
    ('testFilterQuery102',
     'symbol string, price float, volume double',
     'price >= 55f',
     'symbol',
     [['WSO2', 50.0, 60.0], ['WSO2', 70.0, 40.0], ['WSO2', 44.0, 200.0]],
     1),
    ('testFilterQuery103',
     'symbol string, price float, volume double, quantity int',
     'quantity >= 50.0',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 6], ['WSO2', 70.0, 40.0, 10], ['WSO2', 44.0, 200.0, 56]],
     1),
    ('testFilterQuery104',
     'symbol string, price float, volume double, quantity int',
     'quantity >= 10f',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 6], ['WSO2', 70.0, 40.0, 10], ['WSO2', 44.0, 200.0, 56]],
     2),
    ('testFilterQuery105',
     'symbol string, price float, volume double, quantity int',
     'quantity >= 15',
     'symbol, quantity',
     [['WSO2', 50.0, 60.0, 6], ['WSO2', 70.0, 40.0, 10], ['WSO2', 44.0, 200.0, 56]],
     1),
    ('testFilterQuery106',
     'symbol string, price float, volume long, quantity int',
     'volume >= 100.0',
     'symbol, quantity',
     [['WSO2', 50.0, 60, 6], ['WSO2', 70.0, 40, 10], ['WSO2', 44.0, 200, 56]],
     1),
    ('testFilterQuery107',
     'symbol string, price float, volume long, quantity int',
     'volume >= 100f',
     'symbol, quantity',
     [['WSO2', 50.0, 60, 6], ['WSO2', 70.0, 40, 10], ['WSO2', 44.0, 200, 56]],
     1),
    ('filterTest121',
     'symbol string, price float, volume long',
     '150 > volume',
     'symbol,price , symbol as sym1',
     [['IBM', 700.0, 100], ['WSO2', 60.5, 200]],
     1),
    ('testFilterQuery66',
     'symbol string, price float, volume long',
     'not (volume == 40)',
     'symbol, price',
     [['WSO2', 50.0, 60], ['WSO2', 70.0, 40], ['WSO2', 44.0, 200]],
     2),]


@pytest.mark.parametrize(
    "name,stream,filt,sel,feed,expected", SCENARIOS,
    ids=[s[0] for s in SCENARIOS])
def test_filter_scenario(name, stream, filt, sel, feed, expected):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        f"define stream cseEventStream ({stream});"
        f"@info(name = 'query1') from cseEventStream[{filt}] "
        f"select {sel} insert into outputStream ;")

    events = []

    class QC(QueryCallback):
        def receive(self, ts, in_events, remove_events):
            if in_events:
                events.extend(in_events)

    rt.add_callback("query1", QC())
    h = rt.get_input_handler("cseEventStream")
    rt.start()
    for row in feed:
        h.send(list(row))
    m.shutdown()
    assert len(events) == expected, (
        f"{name}: [{filt}] passed {len(events)} of {len(feed)} rows, "
        f"expected {expected}")


def _collect(app, query="query1"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    events = []

    class QC(QueryCallback):
        def receive(self, ts, in_events, remove_events):
            if in_events:
                events.extend(in_events)

    rt.add_callback(query, QC())
    rt.start()
    return m, rt, events


@pytest.mark.parametrize("filt", [
    "volume >= 50 and volume",   # testFilterQuery44 (:1505-1517)
    "price and volume >= 50",    # testFilterQuery45 (:1519-1530)
    "volume >= 50 or volume",    # testFilterQuery46 (:1532-1543)
    "price or volume >= 50",     # testFilterQuery47 (:1545-1556)
])
def test_non_boolean_logical_operand_rejected(filt):
    """testFilterQuery44-47 (FilterTestCase1.java:1505-1556): and/or over
    a non-boolean operand fails at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream cseEventStream (symbol string, price float, "
            "volume long);"
            f"@info(name = 'query1') from cseEventStream[{filt}] "
            "select symbol,price,volume insert into outputStream ;")
    m.shutdown()


def test_not_over_non_boolean_rejected():
    """testFilterQuery48 (FilterTestCase1.java:1558-1587): not(price) on a
    float attribute fails at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            "define stream cseEventStream (symbol string, price float, "
            "available bool);"
            "@info(name = 'query1') from cseEventStream[not (price)] "
            "select symbol, price insert into outputStream ;")
    m.shutdown()


def test_arithmetic_add_mixed_types():
    """testFilterQuery109 (FilterTestCase2.java:1102-1160): constant +
    float/double/int/long keeps each side's promoted type."""
    m, rt, events = _collect(
        "define stream cseEventStream (symbol string, price float, "
        "volume double, quantity int, awards long);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, 100 + price as increasedPrice, "
        "50 + volume as increasedVolume, 4 + quantity as increasedQuantity, "
        "10 + awards as increasedAwards insert into outputStream ;")
    rt.get_input_handler("cseEventStream").send(["WSO2", 55.5, 100.0, 5, 10])
    m.shutdown()
    assert len(events) == 1
    d = events[0].data
    assert d[1:] == [155.5, 150.0, 9, 20]
    assert isinstance(d[3], int) and isinstance(d[4], int)


def test_arithmetic_subtract_mixed_types():
    """testFilterQuery110 (FilterTestCase2.java:1162-1222)."""
    m, rt, events = _collect(
        "define stream cseEventStream (symbol string, price float, "
        "volume double, quantity int, awards long);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, price - 20 as decreasedPrice, "
        "volume - 50 as decreasedVolume, quantity - 4 as decreasedQuantity, "
        "awards - 10 as decreasedAwards insert into outputStream ;")
    rt.get_input_handler("cseEventStream").send(["WSO2", 55.5, 100.0, 5, 10])
    m.shutdown()
    assert len(events) == 1
    assert events[0].data[1:] == [35.5, 50.0, 1, 0]


def test_arithmetic_divide_mixed_types():
    """testFilterQuery111 (FilterTestCase2.java:1224-1283): int/int and
    long/int divisions stay integral (Java semantics)."""
    m, rt, events = _collect(
        "define stream cseEventStream (symbol string, price float, "
        "volume double, quantity int, awards long);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, price / 2 as dividedPrice, "
        "volume / 2 as dividedVolume, quantity / 5 as dividedQuantity, "
        "awards / 10 as dividedAwards insert into outputStream ;")
    rt.get_input_handler("cseEventStream").send(["WSO2", 60.0, 100.0, 100, 70])
    m.shutdown()
    assert len(events) == 1
    d = events[0].data
    assert d[1:] == [30.0, 50.0, 20, 7]
    assert isinstance(d[3], int) and isinstance(d[4], int)


def test_arithmetic_multiply_mixed_types():
    """testFilterQuery112 (FilterTestCase2.java:1285-1345)."""
    m, rt, events = _collect(
        "define stream cseEventStream (symbol string, price float, "
        "volume double, quantity int, awards long);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, quantity * 4 as multipliedQuantity, "
        "price * 2 as multipliedPrice, volume * 3 as multipliedVolume, "
        "awards * 5 as multipliedAwards insert into outputStream ;")
    rt.get_input_handler("cseEventStream").send(["WSO2", 55.5, 100.0, 5, 3])
    m.shutdown()
    assert len(events) == 1
    assert events[0].data[1:] == [20, 111.0, 300.0, 15]


def test_arithmetic_mod_mixed_types():
    """testFilterQuery113 (FilterTestCase2.java:1347-1407)."""
    m, rt, events = _collect(
        "define stream cseEventStream (symbol string, price float, "
        "volume double, quantity int, awards long);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, price % 2 as modPrice, volume % 2 as modVolume, "
        "quantity % 2 as modQuantity, awards % 2 as modAwards "
        "insert into outputStream ;")
    rt.get_input_handler("cseEventStream").send(["WSO2", 55.5, 101.0, 5, 7])
    m.shutdown()
    assert len(events) == 1
    assert events[0].data[1:] == [1.5, 1.0, 1, 1]


def test_select_arithmetic_windowless():
    """filterTest116 (FilterTestCase2.java:1455-1490): `price+5 as price`
    passes every event through."""
    m, rt, events = _collect(
        "define stream cseEventStream (symbol string, price float, "
        "volume long);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, price+5 as price insert into outputStream ;")
    h = rt.get_input_handler("cseEventStream")
    h.send(["IBM", 700.0, 100])
    h.send(["WSO2", 60.5, 200])
    h.send(["IBM", 700.0, 100])
    m.shutdown()
    assert [e.data[1] for e in events] == [705.0, 65.5, 705.0]


def test_sum_plus_constant_time_batch():
    """filterTest117 (FilterTestCase2.java:1492-1529): `sum(price)+5` over
    a timeBatch flush (playback clock instead of a 500 ms sleep)."""
    m, rt, events = _collect(
        "@app:playback "
        "define stream cseEventStream (symbol string, price float, "
        "volume long);"
        "@info(name = 'query1') from cseEventStream#window.timeBatch(500) "
        "select symbol, sum(price)+5 as price insert into outputStream ;")
    h = rt.get_input_handler("cseEventStream")
    h.send(1000, ["IBM", 700.0, 100])
    h.send(1000, ["WSO2", 60.5, 200])
    h.send(1000, ["IBM", 700.0, 100])
    h.send(1600, ["IBM", 1.0, 100])  # advances the clock past the flush
    m.shutdown()
    assert len(events) >= 1
    assert events[0].data[1] == 1465.5
