"""Device-side repartitioning (parallel/mesh.device_route_query_step).

Round-6 contract: a keyed query's batch routing happens INSIDE the jitted
step (dense all_to_all under shard_map), the group-by key rides a dense-id
space SEPARATE from the partition key (the old host router's GK == PK
restriction is lifted), and emitted rows re-merge across shards into the
exact unsharded emission order — every test here asserts bit-identity
against an unsharded run of the same feed, through the full engine path
(junction -> process_batch -> CompletionPump -> callbacks).
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.stream.junction import FatalQueryError
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore
from siddhi_tpu.parallel.mesh import device_route_query_step, make_mesh

DISTINCT_GK_APP = """
    @app:name('routeapp')
    define stream S (symbol string, side string, price double, volume long);
    partition with (symbol of S)
    begin
      @info(name = 'q')
      from S#window.length(8)
      select symbol, side, avg(price) as ap, sum(volume) as tv
      group by side
      insert into Out;
    end;
"""


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


def _build(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("Out", c)
    return m, rt, c


def _feed(rt, lo, hi, n_sym=13, n_side=5):
    rng = np.random.default_rng(42)
    syms = rng.integers(0, n_sym, 2000)
    sides = rng.integers(0, n_side, 2000)
    h = rt.get_input_handler("S")
    for i in range(lo, hi):
        h.send([f"SYM{syms[i]}", f"SIDE{sides[i]}",
                float(i % 17) + 0.25, int(i)])


def _run_unsharded(app, lo=0, hi=400):
    m, rt, c = _build(app)
    _feed(rt, lo, hi)
    m.shutdown()
    return c.rows


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_distinct_group_key_bit_identical(n_dev):
    """The case the host router hard-rejected: a partitioned query whose
    group-by key differs from the partition key runs sharded and yields
    output bit-identical to the unsharded run."""
    ref = _run_unsharded(DISTINCT_GK_APP)
    m, rt, c = _build(DISTINCT_GK_APP)
    q = rt.query_runtimes["q"]
    device_route_query_step(q, make_mesh(n_dev), rows_per_shard=256)
    assert q._route_layout.n == n_dev   # conftest pins an 8-device mesh
    _feed(rt, 0, 400)
    m.shutdown()
    assert len(ref) == 400
    assert c.rows == ref


def test_out_of_order_emission_remerges():
    """Keys are fed in an order that makes consecutive rows land on
    DIFFERENT shards every time (round-robin over the shard owners), so
    any merge that concatenates per-shard output instead of re-merging by
    the global emission-order key would interleave wrongly. Window
    evictions (EXPIRED rows) must also stay glued before the CURRENT row
    that displaced them."""
    app = """
        define stream S (k string, v double);
        partition with (k of S)
        begin
          @info(name = 'q')
          from S#window.length(2) select k, v, sum(v) as s insert into Out;
        end;
    """
    def feed(rt):
        h = rt.get_input_handler("S")
        # 16 keys; adjacent sends always hit different shards at n=4
        for i in range(240):
            h.send([f"P{i % 16}", float(i)])

    m1, rt1, c1 = _build(app)
    feed(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app)
    device_route_query_step(rt2.query_runtimes["q"], make_mesh(4),
                            rows_per_shard=256)
    feed(rt2)
    m2.shutdown()
    assert len(c1.rows) > 0
    assert c2.rows == c1.rows


def test_oversized_batches_split_not_die():
    """Key skew past the per-pair exchange quota splits the batch
    host-side (prepare_routed_batches) instead of overflowing — output
    stays bit-identical."""
    app = """
        define stream S (k string, v long);
        partition with (k of S)
        begin
          @info(name = 'q')
          from S#window.length(4) select k, sum(v) as s insert into Out;
        end;
    """
    def feed(rt):
        h = rt.get_input_handler("S")
        for i in range(200):           # 80% of rows on one key/shard
            h.send([f"K{0 if i % 5 else i % 7}", i])

    m1, rt1, c1 = _build(app)
    feed(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app)
    device_route_query_step(rt2.query_runtimes["q"], make_mesh(4),
                            rows_per_shard=8)   # quota 2 rows per pair
    feed(rt2)
    m2.shutdown()
    assert c2.rows == c1.rows


def test_exchange_overflow_attribution():
    """A direct step call that bypasses the host precheck trips the
    device-side overflow flag; the meta check surfaces it as
    FatalQueryError naming rows_per_shard (the overflow_knob_msg
    convention), and the per-shard routed-row counts ride the meta."""
    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import PK_KEY, TS_KEY, TYPE_KEY, VALID_KEY

    app = """
        define stream S (k string, v long);
        partition with (k of S)
        begin
          @info(name = 'q')
          from S#window.length(4) select k, sum(v) as s insert into Out;
        end;
    """
    m, rt, _c = _build(app)
    q = rt.query_runtimes["q"]
    device_route_query_step(q, make_mesh(4), rows_per_shard=8)
    h = rt.get_input_handler("S")
    for i in range(20):
        h.send([f"K{i % 6}", i])
    B = 32
    pk = np.zeros(B, np.int32)   # every row on one shard: pair count 8 > 2
    cols = {TS_KEY: np.arange(B, dtype=np.int64),
            TYPE_KEY: np.zeros(B, np.int8), VALID_KEY: np.ones(B, bool),
            "k": pk.astype(np.int64), "k?": np.zeros(B, bool),
            "v": np.arange(B, dtype=np.int64), "v?": np.zeros(B, bool),
            GK_KEY: pk, PK_KEY: pk}
    _st, out = q._step(q._state, cols, np.int64(99))
    meta = np.asarray(out["__meta__"])
    # layout = [ov, notify, count] + the runtime's declared instrument
    # spec (route_overflow, rows_0..3, residual, win_fill, groups —
    # observability/instruments.py); route overflow stays at lane 3
    spec = q.instrument_slots()
    assert [s.name for s in spec][:2] == ["route_overflow", "shard_rows"]
    assert meta.shape[0] == 3 + sum(s.width for s in spec)
    assert int(meta[3]) > 0                # route overflow flag
    with pytest.raises(FatalQueryError, match="rows_per_shard"):
        q.decode_meta_suffix(meta)
    m.shutdown()


def test_snapshot_cross_restore_between_layouts():
    """A revision persisted by a 2-shard routed runtime restores into
    4- and 8-shard routed runtimes AND into an unsharded one, and every
    continuation matches the continuous unsharded reference exactly —
    snapshots store canonical (unsharded) layout."""
    ref = _run_unsharded(DISTINCT_GK_APP, 0, 500)

    store = InMemoryPersistenceStore()
    m1, rt1, c1 = _build(DISTINCT_GK_APP)
    m1.set_persistence_store(store)
    device_route_query_step(rt1.query_runtimes["q"], make_mesh(2),
                            rows_per_shard=128)
    _feed(rt1, 0, 250)
    rt1.persist()
    m1.shutdown()
    head = len(c1.rows)

    for n_dev in (4, 8, None):
        m2, rt2, c2 = _build(DISTINCT_GK_APP)
        m2.set_persistence_store(store)
        if n_dev is not None:
            device_route_query_step(rt2.query_runtimes["q"], make_mesh(n_dev),
                                    rows_per_shard=128)
        rt2.restore_last_revision()
        _feed(rt2, 250, 500)
        m2.shutdown()
        assert c2.rows == ref[head:], f"restore into {n_dev or 'unsharded'}"


def test_grouped_no_window_routes_by_group_key():
    """Non-partitioned grouped aggregation (no window): rows route by the
    group key itself; no partition-key column exists at all."""
    app = """
        define stream S (k string, v long);
        @info(name = 'q')
        from S select k, sum(v) as s, count() as c group by k insert into Out;
    """
    def feed(rt):
        rng = np.random.default_rng(3)
        h = rt.get_input_handler("S")
        for i in range(300):
            h.send([f"G{int(rng.integers(0, 40))}", i])

    m1, rt1, c1 = _build(app)
    feed(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app)
    device_route_query_step(rt2.query_runtimes["q"], make_mesh(8),
                            rows_per_shard=256)
    feed(rt2)
    m2.shutdown()
    assert len(c1.rows) == 300
    assert c2.rows == c1.rows


def test_ineligible_runtimes_raise_cleanly():
    from siddhi_tpu.ops.expressions import CompileError

    app = """
        define stream S (k string, v double);
        @info(name = 'q')
        from S#window.length(4) select k, sum(v) as s insert into Out;
    """
    m, rt, _c = _build(app)
    with pytest.raises(CompileError, match="device routing"):
        # global (unpartitioned) window: ring semantics need every row
        device_route_query_step(rt.query_runtimes["q"], make_mesh(2),
                                rows_per_shard=64)
    m.shutdown()


def test_purged_groups_do_not_leak_into_new_ones():
    """Regression (round-6 review): after reset_partition_keys prunes the
    keyer map, a LUT rebuild (re-install / growth / restore) compacts
    local gk ids — the freed slots are what NEW groups allocate next, and
    the relayout must NOT pour the purged groups' stale aggregate rows
    into them."""
    app = """
        define stream S (k string, g string, v long);
        partition with (k of S)
        begin
          @info(name = 'q')
          from S select k, g, sum(v) as s group by g insert into Out;
        end;
    """
    def feed_phase1(rt):
        h = rt.get_input_handler("S")
        for i in range(24):
            h.send([f"K{i % 12}", f"G{i % 12}", 7])

    def feed_phase2(rt):
        h = rt.get_input_handler("S")
        for i in range(8):
            h.send([f"KN{i}", f"GN{i}", 100])

    def run(routed):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        c = Collector()
        rt.add_callback("Out", c)
        q = rt.query_runtimes["q"]
        if routed:
            device_route_query_step(q, make_mesh(2), rows_per_shard=64)
        feed_phase1(rt)
        # purge a few partition keys, then force a re-layout (the
        # re-install path exercises rebuild_gk + _canonical_to_routed)
        q.reset_partition_keys([0, 1])
        if routed:
            device_route_query_step(q, make_mesh(2), rows_per_shard=64)
        feed_phase2(rt)
        m.shutdown()
        return c.rows

    ref = run(False)
    got = run(True)
    # fresh groups must start from init (sum == 100), not inherit a
    # purged group's leftovers
    assert [r for r in got if r[0].startswith("KN")] == \
        [r for r in ref if r[0].startswith("KN")]
    assert got == ref


def test_gk_equals_pk_reinstall_and_cross_restore():
    """Regression (round-6 review follow-up): a partitioned query WITHOUT
    a distinct group-by (gk == pk, no LUT) must survive the relayout
    paths too — re-install onto a larger mesh mid-run, and snapshot
    cross-restore — translating its window-buffered key ids by the
    round-robin formula."""
    app = """
        @app:name('gkpk')
        define stream S (k string, v double);
        partition with (k of S)
        begin
          @info(name = 'q')
          from S#window.length(4) select k, sum(v) as s insert into Out;
        end;
    """
    def feed(rt, lo, hi):
        h = rt.get_input_handler("S")
        for i in range(lo, hi):
            h.send([f"P{i % 24}", float(i % 9)])

    m1, rt1, c1 = _build(app)
    feed(rt1, 0, 300)
    m1.shutdown()

    store = InMemoryPersistenceStore()
    m2, rt2, c2 = _build(app)
    m2.set_persistence_store(store)
    q = rt2.query_runtimes["q"]
    device_route_query_step(q, make_mesh(2), rows_per_shard=64)
    feed(rt2, 0, 100)
    device_route_query_step(q, make_mesh(8), rows_per_shard=64)  # re-install
    feed(rt2, 100, 200)
    rt2.persist()
    m2.shutdown()
    assert c2.rows == c1.rows[:len(c2.rows)]

    m3, rt3, c3 = _build(app)
    m3.set_persistence_store(store)
    device_route_query_step(rt3.query_runtimes["q"], make_mesh(4),
                            rows_per_shard=64)
    rt3.restore_last_revision()
    feed(rt3, 200, 300)
    m3.shutdown()
    assert c3.rows == c1.rows[len(c2.rows):]


def test_capacity_growth_relayouts_live_state():
    """Key dictionaries outgrowing n * localK mid-run force a routed
    relayout (canonical round trip) without output divergence."""
    app = """
        define stream S (k string, g string, v long);
        partition with (k of S)
        begin
          @info(name = 'q')
          from S#window.length(4)
          select k, g, sum(v) as s group by g insert into Out;
        end;
    """
    def feed(rt):
        h = rt.get_input_handler("S")
        for i in range(600):           # 60 pks x composite groups >> 16*n
            h.send([f"K{i % 60}", f"G{i % 7}", i])

    m1, rt1, c1 = _build(app)
    feed(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app)
    q = rt2.query_runtimes["q"]
    device_route_query_step(q, make_mesh(4), rows_per_shard=256)
    k0 = q.selector_plan.num_keys
    feed(rt2)
    m2.shutdown()
    assert q.selector_plan.num_keys > k0    # growth actually happened
    assert c2.rows == c1.rows
