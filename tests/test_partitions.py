"""Partition tests — modeled on reference
``siddhi-core/src/test/java/io/siddhi/core/query/partition/PartitionTestCase1.java``
(value partitions, range partitions, inner streams, partitioned windows).
"""

import threading

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []
        self.lock = threading.Lock()

    def receive(self, events):
        with self.lock:
            self.events.extend(events)


def run_app(app, sends, out_stream="OutStream"):
    """sends: list of (stream_id, [event rows])"""
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out_stream, collector)
    handlers = {}
    for sid, rows in sends:
        if sid not in handlers:
            handlers[sid] = runtime.get_input_handler(sid)
        for row in rows:
            handlers[sid].send(row)
    manager.shutdown()
    return collector.events


def test_value_partition_count_per_key():
    # separate aggregator state per partition key (PartitionTestCase1 style)
    app = """
        define stream StockStream (symbol string, price float, volume int);
        partition with (symbol of StockStream)
        begin
            @info(name = 'query1')
            from StockStream
            select symbol, count() as cnt
            insert into OutStream;
        end;
    """
    events = run_app(app, [("StockStream", [
        ["IBM", 10.0, 100],
        ["WSO2", 20.0, 100],
        ["IBM", 30.0, 100],
        ["IBM", 40.0, 100],
        ["WSO2", 50.0, 100],
    ])])
    got = [(e.data[0], e.data[1]) for e in events]
    assert got == [("IBM", 1), ("WSO2", 1), ("IBM", 2), ("IBM", 3), ("WSO2", 2)]


def test_value_partition_sum_independent_state():
    app = """
        define stream StockStream (symbol string, price float);
        partition with (symbol of StockStream)
        begin
            from StockStream
            select symbol, sum(price) as total
            insert into OutStream;
        end;
    """
    events = run_app(app, [("StockStream", [
        ["A", 1.0], ["B", 10.0], ["A", 2.0], ["B", 20.0],
    ])])
    got = [(e.data[0], e.data[1]) for e in events]
    assert got == [("A", 1.0), ("B", 10.0), ("A", 3.0), ("B", 30.0)]


def test_partitioned_length_window_avg():
    # per-key sliding window: each key's window evicts independently
    app = """
        define stream StockStream (symbol string, price float);
        partition with (symbol of StockStream)
        begin
            from StockStream#window.length(2)
            select symbol, avg(price) as avgPrice
            insert into OutStream;
        end;
    """
    events = run_app(app, [("StockStream", [
        ["A", 1.0], ["A", 3.0], ["B", 100.0], ["A", 5.0], ["B", 200.0],
    ])])
    got = [(e.data[0], e.data[1]) for e in events]
    # A: avg(1)=1, avg(1,3)=2, avg(3,5)=4 (1 evicted); B: avg(100)=100, avg(100,200)=150
    assert got == [("A", 1.0), ("A", 2.0), ("B", 100.0), ("A", 4.0), ("B", 150.0)]


def test_partition_group_by_combined_keys():
    # group by inside a partition: state per (partition key, group key)
    app = """
        define stream TradeStream (symbol string, side string, qty int);
        partition with (symbol of TradeStream)
        begin
            from TradeStream
            select symbol, side, sum(qty) as total
            group by side
            insert into OutStream;
        end;
    """
    events = run_app(app, [("TradeStream", [
        ["A", "buy", 1], ["A", "sell", 2], ["B", "buy", 10], ["A", "buy", 4], ["B", "buy", 20],
    ])])
    got = [(e.data[0], e.data[1], e.data[2]) for e in events]
    assert got == [("A", "buy", 1), ("A", "sell", 2), ("B", "buy", 10),
                   ("A", "buy", 5), ("B", "buy", 30)]


def test_range_partition():
    # reference PartitionTestCase1.testPartitionQuery range style:
    # copies per matching range, drop non-matching
    app = """
        define stream StockStream (symbol string, price float);
        partition with (price < 100 as 'cheap' or price >= 100 as 'pricey' of StockStream)
        begin
            from StockStream
            select symbol, count() as cnt
            insert into OutStream;
        end;
    """
    events = run_app(app, [("StockStream", [
        ["A", 50.0], ["B", 150.0], ["C", 60.0],
    ])])
    got = [(e.data[0], e.data[1]) for e in events]
    assert got == [("A", 1), ("B", 1), ("C", 2)]


def test_inner_stream_carries_partition():
    # reference testPartitionQuery11-ish: chained queries over '#inner'
    app = """
        define stream StockStream (symbol string, price float);
        partition with (symbol of StockStream)
        begin
            from StockStream
            select symbol, price * 2 as doubled
            insert into #Mid;

            from #Mid
            select symbol, sum(doubled) as total
            insert into OutStream;
        end;
    """
    events = run_app(app, [("StockStream", [
        ["A", 1.0], ["B", 10.0], ["A", 2.0],
    ])])
    got = [(e.data[0], e.data[1]) for e in events]
    assert got == [("A", 2.0), ("B", 20.0), ("A", 6.0)]


def test_partitioned_time_window(monkeypatch):
    # playback-driven keyed time window: per-key expiry
    app = """
        @app:playback
        define stream S (symbol string, v int);
        partition with (symbol of S)
        begin
            from S#window.time(100)
            select symbol, sum(v) as total
            insert into OutStream;
        end;
    """
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback("OutStream", collector)
    h = runtime.get_input_handler("S")
    h.send(1000, ["A", 1])
    h.send(1010, ["B", 10])
    h.send(1050, ["A", 2])
    # at 1200 both of A's events and B's are expired; new arrival sums alone
    h.send(1200, ["A", 4])
    h.send(1210, ["B", 40])
    manager.shutdown()
    got = [(e.data[0], e.data[1]) for e in collector.events]
    assert got[:3] == [("A", 1), ("B", 10), ("A", 3)]
    # after expiry, running sums drop back
    assert ("A", 4) in got[3:]
    assert ("B", 40) in got[3:]
