"""Reference core-query corpus — scenarios ported verbatim from the
top-level ``query/`` test classes: IsNullTestCase, StringCompareTestCase,
BooleanCompareTestCase, GroupByTestCase, CallbackTestCase,
PassThroughTestCase, and SimpleQueryValidatorTestCase."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.query.callback import QueryCallback


class QC(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


def _collect(app, query="query1"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QC()
    rt.add_callback(query, q)
    rt.start()
    return m, rt, q


# ------------------------------------------------------ IsNullTestCase


def test_is_null_filter():
    """isNullTest1 (IsNullTestCase:43-96): `symbol is null` passes only
    the null-symbol row."""
    m, rt, q = _collect(
        "define stream cseEventStream (symbol string, price float, "
        "volume long);"
        "@info(name = 'query1') from cseEventStream[symbol is null] "
        "select symbol, price insert into outputStream ;")
    h = rt.get_input_handler("cseEventStream")
    h.send(["IBM", 700.0, 100])
    h.send([None, 60.5, 200])
    h.send(["WSO2", 60.5, 200])
    m.shutdown()
    assert len(q.events) == 1
    assert q.events[0].data == [None, 60.5]


def test_is_null_on_kleene_captures():
    """isNullTest2 (IsNullTestCase:97-165): `e2[last-k] is null` inside a
    Kleene condition and the select; exact captured row asserted."""
    m, rt, q = _collect(
        "define stream Stream1 (symbol string, price float, volume int); "
        "define stream Stream2 (symbol string, price float, volume int); "
        "@info(name = 'query1') "
        "from every e1=Stream1[price>20], "
        "   e2=Stream1[(price>=e2[last].price and not e2[last-1] is null "
        "and price>=e2[last-1].price+5)  or ("
        " e2[last-1] is null and price>=e1.price+5 )]+, "
        "   e3=Stream1[price<e2[last].price]"
        "select e1.price as price1, e2[0].price as price2, "
        "e2[last-2] is null as check1, e2[last-1].price as price3, "
        "e2[last].price as price4, e3.price as price5, "
        "e2 is null as check2 "
        "insert into OutputStream ;")
    h = rt.get_input_handler("Stream1")
    for row in [
        ["WSO2", 29.6, 100], ["WSO2", 25.0, 100], ["WSO2", 35.6, 100],
        ["WSO2", 41.5, 100], ["WSO2", 42.6, 100], ["WSO2", 43.6, 100],
        ["IBM", 58.7, 100], ["IBM", 45.6, 100],
    ]:
        h.send(row)
    m.shutdown()
    assert len(q.events) == 1
    d = q.events[0].data
    assert d[2] is True and d[3] is None and d[6] is False
    assert [round(x, 4) for x in (d[0], d[1], d[4], d[5])] == [
        43.6, 58.7, 58.7, 45.6]
    assert q.expired == []


# -------------------------- String/Boolean compare validation batteries

_OPS = ["x > y", "x < y", "x >= y", "x <= y", "x == y", "x != y"]
_STRING_DEFS = ["x string, y int", "x int, y string", "x long, y string",
                "x float, y string", "x double, y string"]
_BOOL_DEFS = ["x bool, y int", "x int, y bool", "x long, y bool",
              "x float, y bool", "x double, y bool"]


@pytest.mark.parametrize("cond", _OPS)
@pytest.mark.parametrize("defs", _STRING_DEFS)
def test_string_numeric_compare_rejected(cond, defs):
    """StringCompareTestCase test1-30 (:40-225): every comparison between
    a string and a numeric attribute fails at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            f"define stream cseEventStream ({defs}, symbol string, "
            f"price float);"
            f"@info(name = 'query1') from cseEventStream[{cond}] "
            f"select symbol, price insert into outputStream;")
    m.shutdown()


@pytest.mark.parametrize("cond", _OPS)
@pytest.mark.parametrize("defs", _BOOL_DEFS)
def test_bool_numeric_compare_rejected(cond, defs):
    """BooleanCompareTestCase test1-30: every comparison between a bool
    and a numeric attribute fails at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(
            f"define stream cseEventStream ({defs}, symbol string, "
            f"price float);"
            f"@info(name = 'query1') from cseEventStream[{cond}] "
            f"select symbol, price insert into outputStream;")
    m.shutdown()


# ------------------------------------------------------ GroupByTestCase


def test_group_by_sliding_time_window():
    """testGroupByQuery1 (GroupByTestCase:50-95): sliding time(1 sec)
    group-by emits one output per arriving event (playback clock replaces
    the reference's sleeps)."""
    m, rt, q = _collect(
        "@app:playback "
        "define stream cseEventStream (symbol string, price float, "
        "volume long);"
        "@info(name = 'query1') from cseEventStream#window.time(1 sec) "
        "select symbol, sum(volume) as totalVolume, avg(price) as avgPrice "
        "group by symbol insert into outputStream;")
    h = rt.get_input_handler("cseEventStream")
    h.send(100, ["IBM", 50.0, 200])
    h.send(100, ["WSO2", 50.0, 200])
    h.send(300, ["WSO2", 50.0, 200])
    h.send(300, ["IBM", 50.0, 200])
    h.send(4500, ["WSO2", 50.0, 200])
    h.send(4500, ["WSO2", 50.0, 200])
    m.shutdown()
    assert len(q.events) == 6


def test_group_by_time_batch_window():
    """testGroupByQuery2 (GroupByTestCase:97-147): timeBatch(1 sec)
    group-by flushes one output per group per batch (4 events -> 2
    groups, then 2 WSO2 -> 1)."""
    m, rt, q = _collect(
        "@app:playback "
        "define stream cseEventStream (symbol string, price float, "
        "volume long);"
        "@info(name = 'query1') from cseEventStream#window.timeBatch(1 sec) "
        "select symbol, sum(volume) as totalVolume, avg(price) as avgPrice "
        "group by symbol insert into outputStream;")
    h = rt.get_input_handler("cseEventStream")
    h.send(100, ["IBM", 50.0, 200])
    h.send(100, ["WSO2", 50.0, 200])
    h.send(300, ["WSO2", 50.0, 200])
    h.send(300, ["IBM", 50.0, 200])
    h.send(3500, ["WSO2", 50.0, 200])
    h.send(3500, ["WSO2", 50.0, 200])
    h.send(5000, ["XYZ", 1.0, 1])   # advances the clock past the flush
    m.shutdown()
    assert len(q.events) == 3
    got = {tuple(e.data) for e in q.events[:2]}
    assert got == {("IBM", 400, 50.0), ("WSO2", 400, 50.0)}
    assert tuple(q.events[2].data) == ("WSO2", 400, 50.0)


# ----------------------------------------------------- CallbackTestCase


def test_remove_query_callback():
    """testCallback1 (CallbackTestCase:44-85): a removed QueryCallback
    stops receiving."""
    m, rt, q = _collect(
        "define stream cseEventStream (symbol string, price float, "
        "volume long);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, price , symbol as sym1 insert into outputStream ;")
    h = rt.get_input_handler("cseEventStream")
    h.send(["IBM", 0.0, 100])
    rt.remove_callback(q)
    h.send(["WSO2", 0.0, 100])
    m.shutdown()
    assert len(q.events) == 1


def test_remove_stream_callback():
    """removeCallback also detaches StreamCallbacks."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream S (symbol string);"
        "@info(name = 'query1') from S select symbol insert into O ;")
    got = []

    class SC(StreamCallback):
        def receive(self, events):
            got.extend(events)

    sc = SC()
    rt.add_callback("O", sc)
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["a"])
    rt.remove_callback(sc)
    h.send(["b"])
    m.shutdown()
    assert len(got) == 1


# -------------------------------------------------- PassThroughTestCase


def test_passthrough_simple():
    """testPassThroughQuery1 (PassThroughTestCase:50-96)."""
    m, rt, q = _collect(
        "define stream cseEventStream (symbol string, price int);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, price insert into StockQuote ;")
    h = rt.get_input_handler("cseEventStream")
    h.send(["IBM", 100])
    h.send(["WSO2", 100])
    m.shutdown()
    assert len(q.events) == 2


def test_passthrough_other_stream_gets_nothing():
    """testPassThroughQuery2 (:98-143): events sent to an unrelated
    stream produce no query output."""
    m, rt, q = _collect(
        "define stream cseEventStream (symbol string, price int);"
        "define stream cseEventStream1 (symbol string, price int);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, price insert into StockQuote ;")
    h1 = rt.get_input_handler("cseEventStream1")
    h1.send(["IBM", 100])
    h1.send(["WSO2", 100])
    m.shutdown()
    assert q.events == []


def test_passthrough_duplicate_projection():
    """testPassThroughQuery3 (:145-196): the same attribute projected
    under two names; the unrelated stream's events don't count."""
    m, rt, q = _collect(
        "define stream cseEventStream (symbol string, price int);"
        "define stream cseEventStream1 (symbol string, price int);"
        "@info(name = 'query1') from cseEventStream "
        "select symbol, symbol as price2 insert into StockQuote ;")
    rt.get_input_handler("cseEventStream").send(["IBM", 100])
    rt.get_input_handler("cseEventStream").send(["WSO2", 100])
    rt.get_input_handler("cseEventStream1").send(["ORACLE", 100])
    rt.get_input_handler("cseEventStream1").send(["ABC", 100])
    m.shutdown()
    assert len(q.events) == 2
    assert [e.data for e in q.events] == [["IBM", "IBM"], ["WSO2", "WSO2"]]


def test_passthrough_chained_select_star():
    """testPassThroughQuery4 (:198-247): `select *` chained through two
    streams."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream cseEventStream (symbol string, price float, "
        "volume long);"
        "@info(name = 'query1') from cseEventStream "
        "insert into outputStream;"
        "@info(name = 'query2') from outputStream select * "
        "insert into outputStream2 ;")
    q = QC()
    rt.add_callback("query2", q)
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    h.send(["WSO2", 700.0, 100])
    h.send(["WSO2", 60.5, 200])
    m.shutdown()
    assert len(q.events) == 2
    assert q.events[0].data == ["WSO2", 700.0, 100]


# ------------------------------------------- SimpleQueryValidatorTestCase


@pytest.mark.parametrize("app", [
    # testQueryWithNotExistingAttributes (:38-47)
    ("define stream cseEventStream (symbol string, price float, "
     "volume long);"
     "@info(name = 'query1') from cseEventStream[volume >= 50] "
     "select symbol1,price,volume insert into outputStream ;"),
    # testQueryWithDuplicateDefinition (:49-58): outputStream already
    # defined with an incompatible schema
    ("define stream \n cseEventStream (symbol string, price float, "
     "volume long);"
     "define stream outputStream (symbol string, price float);"
     "@info(name = 'query1') from cseEventStream[volume >= 50] "
     "select symbol,price,volume insert into outputStream ;"),
    # testInvalidFilterCondition1/2 (:60-78)
    ("define stream cseEventStream (symbol string, price float, "
     "volume long);"
     "@info(name = 'query1') from cseEventStream[volume >= 50 and volume] "
     "select symbol,price,volume insert into outputStream ;"),
    ("define stream cseEventStream (symbol string, price float, "
     "volume long);"
     "@info(name = 'query1') from cseEventStream[not(price)] "
     "select symbol,price,volume insert into outputStream ;"),
    # testQueryWithTable / testQueryWithEveryTable (:102-112, :131-141)
    ("define table TestTable(symbol string, volume float); "
     "from TestTable select * insert into OutputStream; "),
    ("define table TestTable(symbol string, volume float);\n"
     "from every TestTable select * insert into OutputStream; "),
    # testQueryWithAggregation / testQueryWithEveryAggregation (:114-158)
    ("define stream TradeStream (symbol string, price double, "
     "volume long, timestamp long);\n"
     "define aggregation TradeAggregation\n"
     "  from TradeStream\n"
     "  select symbol, avg(price) as avgPrice, sum(price) as total\n"
     "    group by symbol\n"
     "    aggregate by timestamp every sec ... year; "
     "from every TradeAggregation \nselect * \ninsert into OutputStream; "),
    ("define stream TradeStream (symbol string, price double, "
     "volume long, timestamp long);\n"
     "define aggregation TradeAggregation\n"
     "  from TradeStream\n"
     "  select symbol, avg(price) as avgPrice, sum(price) as total\n"
     "    group by symbol\n"
     "    aggregate by timestamp every sec ... year; "
     "from every TradeAggregation select * insert into OutputStream; "),
])
def test_invalid_apps_rejected(app):
    """SimpleQueryValidatorTestCase error battery: undefined attributes,
    incompatible duplicate definitions, non-boolean logical operands, and
    tables/aggregations as plain stream sources all fail at creation."""
    m = SiddhiManager()
    with pytest.raises(Exception):
        m.create_siddhi_app_runtime(app)
    m.shutdown()


@pytest.mark.parametrize("filt", ["available", "available and price>50"])
def test_bool_attribute_filters_compile(filt):
    """testComplexFilterQuery1/2 (:80-99): a bare bool attribute is a
    valid filter condition."""
    m = SiddhiManager()
    m.create_siddhi_app_runtime(
        "define stream cseEventStream (symbol string, price float, "
        "volume long, available bool);"
        f"@info(name = 'query1') from cseEventStream[{filt}] "
        "select symbol,price,volume insert into outputStream ;")
    m.shutdown()
