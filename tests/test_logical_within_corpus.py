"""Reference Logical/Within pattern corpus — scenarios ported verbatim
from ``query/pattern/LogicalPatternTestCase.java`` (or/and tails and
heads, three-stream logical joins) and ``WithinPatternTestCase.java``
(grouped every chains under `within`, sleeps -> playback clock jumps)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutputStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


TWO = """@app:playback
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
"""


def _rows(c):
    return [tuple(round(v, 4) if isinstance(v, float) else v
                  for v in e.data) for e in c.events]


def test_logical_q1_or_tail_present_side():
    # LogicalPatternTestCase.testQuery1
    m, rt, c = build(TWO + """
        from e1=Stream1[price > 20]
          -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]
        select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["GOOG", 59.6, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOG")]


def test_logical_q4_and_tail_completes_on_both():
    # testQuery4: e2 and e3 — sides fill in any order, emit when both do
    m, rt, c = build(TWO + """
        from e1=Stream1[price > 20]
          -> e2=Stream2[price > e1.price] and e3=Stream2['IBM' == symbol]
        select e1.symbol as s1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["GOOG", 72.7, 100])
    s2.send(1200, ["IBM", 4.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", 72.7, 4.7)]


def test_logical_q7_and_head_then_tail():
    # testQuery7: the AND is the HEAD state
    m, rt, c = build(TWO + """
        from e1=Stream1[price > 20] and e2=Stream2[price > 30]
          -> e3=Stream2['IBM' == symbol]
        select e1.symbol as s1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["GOOG", 72.7, 100])
    s2.send(1200, ["IBM", 4.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", 72.7, 4.7)]


def test_logical_q8_or_head_unmatched_side_null():
    # testQuery8: OR head completes on e1 alone; e2 stays null
    m, rt, c = build(TWO + """
        from e1=Stream1[price > 20] or e2=Stream2[price > 30]
          -> e3=Stream2['IBM' == symbol]
        select e1.symbol as s1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["GOOG", 72.7, 100])
    s2.send(1200, ["IBM", 4.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", None, 4.7)]


def test_logical_q13_three_stream_and_tail_two_chains():
    # testQuery13: every e1 -> e2=S2 and e3=S3 over THREE streams; one
    # e2/e3 pair closes BOTH pending chains
    m, rt, c = build("""@app:playback
        define stream Stream1 (symbol string, price float, volume int);
        define stream Stream2 (symbol string, price float, volume int);
        define stream Stream3 (symbol string, price float, volume int);
        from every e1=Stream1[price > 20]
          -> e2=Stream2['IBM' == symbol] and e3=Stream3['WSO2' == symbol]
        select e1.price as p1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s3 = rt.get_input_handler("Stream3")
    s1.send(1000, ["IBM", 25.5, 100])
    s1.send(1100, ["IBM", 59.65, 100])
    s2.send(1200, ["IBM", 45.5, 100])
    s3.send(1300, ["WSO2", 46.56, 100])
    m.shutdown()
    got = sorted(_rows(c))
    assert got == sorted([(25.5, 45.5, 46.56), (59.65, 45.5, 46.56)])


ONE = "@app:playback define stream Stream1 (symbol string, price float, volume int);\n"


def test_within_q4_grouped_every_pair_expiry():
    # WithinPatternTestCase.testQuery4: every (e1 -> e2[same symbol])
    # within 5 sec; a 6-second gap expires the first chain
    m, rt, c = build(ONE + """
        from every (e1=Stream1 -> e2=Stream1[symbol == e1.symbol])
        within 5 sec
        select e1.symbol as s1, e1.volume as v1, e2.symbol as s2,
               e2.volume as v2
        insert into OutputStream;
    """)
    h = rt.get_input_handler("Stream1")
    t = 1000
    h.send(t, ["WSO2", 55.6, 100])
    t += 6000                              # Thread.sleep(6000): expires
    h.send(t, ["WSO2", 55.7, 150]); t += 500
    h.send(t, ["WSO2", 58.7, 200]); t += 10
    h.send(t, ["WSO2", 58.7, 250]); t += 500
    m.shutdown()
    assert _rows(c) == [("WSO2", 150, "WSO2", 200)]


def test_within_q5_grouped_every_triples_non_overlapping():
    # testQuery5: every (e1 -> e2 -> e3) within 5 sec over one stream —
    # sequential non-overlapping triples
    m, rt, c = build(ONE + """
        from every (e1=Stream1 -> e2=Stream1[symbol == e1.symbol]
          -> e3=Stream1[symbol == e2.symbol]) within 5 sec
        select e1.volume as v1, e2.volume as v2, e3.volume as v3
        insert into OutputStream;
    """)
    h = rt.get_input_handler("Stream1")
    t = 1000
    for v in (100, 150, 200, 210):
        h.send(t, ["WSO2", 55.6, v]); t += 10
    t += 500
    for v in (250, 260, 270):
        h.send(t, ["WSO2", 58.7, v]); t += 10
    m.shutdown()
    assert _rows(c) == [(100, 150, 200), (210, 250, 260)]


# ---------------------------------------------------------------- round 5:
# LogicalPatternTestCase.java and/or tail+head permutations (2-16)

TWO_STREAMS = """
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
"""

THREE_STREAMS = TWO_STREAMS + """
    define stream Stream3 (symbol string, price float, volume int);
"""


def _run(defs, query, feeds):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        defs + f"@info(name = 'query1') {query}")
    got = []

    class C(StreamCallback):
        def receive(self, events):
            got.extend(tuple(e.data) for e in events)

    rt.add_callback("OutputStream", C())
    hs = {s: rt.get_input_handler(s)
          for s in ("Stream1", "Stream2", "Stream3") if s in defs}
    for stream, data in feeds:
        hs[stream].send(list(data))
    m.shutdown()
    return [tuple(round(float(x), 4) if isinstance(x, float) else x
                  for x in row) for row in got]


def test_logical_q2_or_tail_second_side_fires():
    """testQuery2 (:98-146): `e2 or e3` tail — the e3 side ('IBM') fires;
    e2's projection is null."""
    got = _run(TWO_STREAMS,
               "from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] "
               "or e3=Stream2['IBM' == symbol] "
               "select e1.symbol as symbol1, e2.symbol as symbol2 "
               "insert into OutputStream;",
               [("Stream1", ["WSO2", 55.6, 100]),
                ("Stream2", ["IBM", 10.7, 100])])
    assert got == [("WSO2", None)]


def test_logical_q3_or_tail_first_side_fires():
    """testQuery3 (:149-199): the e2 side (price > e1.price) fires first;
    e3 stays null; the second qualifying event does not re-fire."""
    got = _run(TWO_STREAMS,
               "from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] "
               "or e3=Stream2['IBM' == symbol] "
               "select e1.symbol as symbol1, e2.price as price2, "
               "e3.price as price3 insert into OutputStream;",
               [("Stream1", ["WSO2", 55.6, 100]),
                ("Stream2", ["IBM", 72.7, 100]),
                ("Stream2", ["IBM", 75.7, 100])])
    assert got == [("WSO2", 72.7, None)]


def test_logical_q5_and_tail_one_event_matches_both_sides():
    """testQuery5 (:255-305): ONE event matching both `and` sides fills
    both captures (LogicalPreStateProcessor side-1-first)."""
    got = _run(TWO_STREAMS,
               "from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] "
               "and e3=Stream2['IBM' == symbol] "
               "select e1.symbol as symbol1, e2.price as price2, "
               "e3.price as price3 insert into OutputStream;",
               [("Stream1", ["WSO2", 55.6, 100]),
                ("Stream2", ["IBM", 72.7, 100]),
                ("Stream2", ["IBM", 75.7, 100])])
    assert got == [("WSO2", 72.7, 72.7)]


def test_logical_q6_and_tail_cross_stream_sides():
    """testQuery6 (:308-358): `and` sides on DIFFERENT streams complete
    from separate events."""
    got = _run(TWO_STREAMS,
               "from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] "
               "and e3=Stream1['IBM' == symbol] "
               "select e1.symbol as symbol1, e2.price as price2, "
               "e3.price as price3 insert into OutputStream;",
               [("Stream1", ["WSO2", 55.6, 100]),
                ("Stream2", ["IBM", 72.7, 100]),
                ("Stream1", ["IBM", 75.7, 100])])
    assert got == [("WSO2", 72.7, 75.7)]


def test_logical_q9_or_head_second_side_arms():
    """testQuery9 (:467-514): `or` HEAD — the e2 side arms (GOOG 72.7 >
    30); e1 stays null in the emission."""
    got = _run(TWO_STREAMS,
               "from e1=Stream1[price > 20] or e2=Stream2[price >30] "
               "-> e3=Stream2['IBM' == symbol] "
               "select e1.symbol as symbol1, e2.price as price2, "
               "e3.price as price3 insert into OutputStream;",
               [("Stream2", ["GOOG", 72.7, 100]),
                ("Stream2", ["IBM", 4.7, 100])])
    assert got == [(None, 72.7, 4.7)]


def test_logical_q10_or_head_first_side_arms():
    """testQuery10 (:517-565)."""
    got = _run(TWO_STREAMS,
               "from e1=Stream1[price > 20] or e2=Stream2[price >30] "
               "-> e3=Stream2['IBM' == symbol] "
               "select e1.symbol as symbol1, e2.price as price2, "
               "e3.price as price3 insert into OutputStream;",
               [("Stream1", ["WSO2", 55.6, 100]),
                ("Stream2", ["IBM", 4.7, 100])])
    assert got == [("WSO2", None, 4.7)]


def test_logical_q11_every_head_and_tail_two_matches():
    """testQuery11 (:568-633): `every e1 -> e2 and e3` — both armed
    iterations complete when the and-pair fills."""
    got = _run(THREE_STREAMS,
               "from every e1=Stream1[price >20] -> e2=Stream2['IBM' == symbol] "
               "and e3=Stream3['WSO2' == symbol]"
               "select e1.price as price1, e2.price as price2, "
               "e3.price as price3 insert into OutputStream;",
               [("Stream1", ["IBM", 25.5, 100]),
                ("Stream1", ["IBM", 59.65, 100]),
                ("Stream2", ["IBM", 45.5, 100]),
                ("Stream3", ["WSO2", 46.56, 100])])
    assert sorted(got) == [(25.5, 45.5, 46.56), (59.65, 45.5, 46.56)]


def test_logical_q12_every_head_or_tail_two_matches():
    """testQuery12 (:636-699): or-tail completes on its first side for
    both armed iterations."""
    got = _run(THREE_STREAMS,
               "from every e1=Stream1[price >20] -> e2=Stream2['IBM' == symbol] "
               "or e3=Stream3['WSO2' == symbol]"
               "select e1.price as price1, e2.price as price2, "
               "e3.price as price3 insert into OutputStream;",
               [("Stream1", ["IBM", 25.5, 100]),
                ("Stream1", ["IBM", 59.65, 100]),
                ("Stream2", ["IBM", 45.5, 100])])
    assert sorted(got) == [(25.5, 45.5, None), (59.65, 45.5, None)]


def test_logical_q13_bare_and():
    """testQuery13 (:702-754): a bare `e1 and e2` pattern completes once
    and never re-arms."""
    got = _run(TWO_STREAMS,
               "from e1=Stream1[price > 20] and e2=Stream2[price >30] "
               "select e1.symbol as symbol1, e2.price as price2 "
               "insert into OutputStream;",
               [("Stream1", ["WSO2", 25.0, 100]),
                ("Stream2", ["IBM", 35.0, 100]),
                ("Stream1", ["GOOGLE", 45.0, 100]),
                ("Stream2", ["ORACLE", 55.0, 100])])
    assert got == [("WSO2", 35.0)]


def test_logical_q14_bare_or():
    """testQuery14 (:757-807): a bare `e1 or e2` fires on the first
    matching side only."""
    got = _run(TWO_STREAMS,
               "from e1=Stream1[price > 20] or e2=Stream2[price >30] "
               "select e1.symbol as symbol1, e2.price as price2 "
               "insert into OutputStream;",
               [("Stream1", ["WSO2", 25.0, 100]),
                ("Stream2", ["IBM", 35.0, 100]),
                ("Stream2", ["ORACLE", 45.0, 100])])
    assert got == [("WSO2", None)]


def test_logical_q15_every_and_group():
    """testQuery15 (:810-868): `every (e1 and e2)` restarts after each
    completion — two pairs, two matches."""
    got = _run(TWO_STREAMS,
               "from every (e1=Stream1[price > 20] and e2=Stream2[price >30]) "
               "select e1.symbol as symbol1, e2.price as price2 "
               "insert into OutputStream;",
               [("Stream1", ["WSO2", 25.0, 100]),
                ("Stream2", ["IBM", 35.0, 100]),
                ("Stream1", ["GOOGLE", 45.0, 100]),
                ("Stream2", ["ORACLE", 55.0, 100])])
    assert got == [("WSO2", 35.0), ("GOOGLE", 55.0)]


def test_logical_q16_every_or_group():
    """testQuery16 (:871-931): `every (e1 or e2)` fires per matching event,
    re-arming each time."""
    got = _run(TWO_STREAMS,
               "from every (e1=Stream1[price > 20] or e2=Stream2[price >30]) "
               "select e1.symbol as symbol1, e2.price as price2 "
               "insert into OutputStream;",
               [("Stream1", ["WSO2", 25.0, 100]),
                ("Stream2", ["IBM", 35.0, 100]),
                ("Stream2", ["ORACLE", 45.0, 100])])
    assert got == [("WSO2", None), (None, 35.0), (None, 45.0)]
