"""Reference Logical/Within pattern corpus — scenarios ported verbatim
from ``query/pattern/LogicalPatternTestCase.java`` (or/and tails and
heads, three-stream logical joins) and ``WithinPatternTestCase.java``
(grouped every chains under `within`, sleeps -> playback clock jumps)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutputStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


TWO = """@app:playback
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
"""


def _rows(c):
    return [tuple(round(v, 4) if isinstance(v, float) else v
                  for v in e.data) for e in c.events]


def test_logical_q1_or_tail_present_side():
    # LogicalPatternTestCase.testQuery1
    m, rt, c = build(TWO + """
        from e1=Stream1[price > 20]
          -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]
        select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["GOOG", 59.6, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOG")]


def test_logical_q4_and_tail_completes_on_both():
    # testQuery4: e2 and e3 — sides fill in any order, emit when both do
    m, rt, c = build(TWO + """
        from e1=Stream1[price > 20]
          -> e2=Stream2[price > e1.price] and e3=Stream2['IBM' == symbol]
        select e1.symbol as s1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["GOOG", 72.7, 100])
    s2.send(1200, ["IBM", 4.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", 72.7, 4.7)]


def test_logical_q7_and_head_then_tail():
    # testQuery7: the AND is the HEAD state
    m, rt, c = build(TWO + """
        from e1=Stream1[price > 20] and e2=Stream2[price > 30]
          -> e3=Stream2['IBM' == symbol]
        select e1.symbol as s1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["GOOG", 72.7, 100])
    s2.send(1200, ["IBM", 4.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", 72.7, 4.7)]


def test_logical_q8_or_head_unmatched_side_null():
    # testQuery8: OR head completes on e1 alone; e2 stays null
    m, rt, c = build(TWO + """
        from e1=Stream1[price > 20] or e2=Stream2[price > 30]
          -> e3=Stream2['IBM' == symbol]
        select e1.symbol as s1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.6, 100])
    s2.send(1100, ["GOOG", 72.7, 100])
    s2.send(1200, ["IBM", 4.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", None, 4.7)]


def test_logical_q13_three_stream_and_tail_two_chains():
    # testQuery13: every e1 -> e2=S2 and e3=S3 over THREE streams; one
    # e2/e3 pair closes BOTH pending chains
    m, rt, c = build("""@app:playback
        define stream Stream1 (symbol string, price float, volume int);
        define stream Stream2 (symbol string, price float, volume int);
        define stream Stream3 (symbol string, price float, volume int);
        from every e1=Stream1[price > 20]
          -> e2=Stream2['IBM' == symbol] and e3=Stream3['WSO2' == symbol]
        select e1.price as p1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s3 = rt.get_input_handler("Stream3")
    s1.send(1000, ["IBM", 25.5, 100])
    s1.send(1100, ["IBM", 59.65, 100])
    s2.send(1200, ["IBM", 45.5, 100])
    s3.send(1300, ["WSO2", 46.56, 100])
    m.shutdown()
    got = sorted(_rows(c))
    assert got == sorted([(25.5, 45.5, 46.56), (59.65, 45.5, 46.56)])


ONE = "@app:playback define stream Stream1 (symbol string, price float, volume int);\n"


def test_within_q4_grouped_every_pair_expiry():
    # WithinPatternTestCase.testQuery4: every (e1 -> e2[same symbol])
    # within 5 sec; a 6-second gap expires the first chain
    m, rt, c = build(ONE + """
        from every (e1=Stream1 -> e2=Stream1[symbol == e1.symbol])
        within 5 sec
        select e1.symbol as s1, e1.volume as v1, e2.symbol as s2,
               e2.volume as v2
        insert into OutputStream;
    """)
    h = rt.get_input_handler("Stream1")
    t = 1000
    h.send(t, ["WSO2", 55.6, 100])
    t += 6000                              # Thread.sleep(6000): expires
    h.send(t, ["WSO2", 55.7, 150]); t += 500
    h.send(t, ["WSO2", 58.7, 200]); t += 10
    h.send(t, ["WSO2", 58.7, 250]); t += 500
    m.shutdown()
    assert _rows(c) == [("WSO2", 150, "WSO2", 200)]


def test_within_q5_grouped_every_triples_non_overlapping():
    # testQuery5: every (e1 -> e2 -> e3) within 5 sec over one stream —
    # sequential non-overlapping triples
    m, rt, c = build(ONE + """
        from every (e1=Stream1 -> e2=Stream1[symbol == e1.symbol]
          -> e3=Stream1[symbol == e2.symbol]) within 5 sec
        select e1.volume as v1, e2.volume as v2, e3.volume as v3
        insert into OutputStream;
    """)
    h = rt.get_input_handler("Stream1")
    t = 1000
    for v in (100, 150, 200, 210):
        h.send(t, ["WSO2", 55.6, v]); t += 10
    t += 500
    for v in (250, 260, 270):
        h.send(t, ["WSO2", 58.7, v]); t += 10
    m.shutdown()
    assert _rows(c) == [(100, 150, 200), (210, 250, 260)]
