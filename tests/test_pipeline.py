"""Dispatch pipeline (core/query/completion.py — CompletionPump).

Covers the ISSUE-5 acceptance set: per-query dispatch-order emission with
depth-bounded in-flight batches, overflow surfacing as FatalQueryError on
the producer's next send with the capacity knob named, checkpoint/restore
with a NON-empty pipeline (no lost and no doubled emission), and @Async
worker death with in-flight pipelined batches (the supervisor's
replacement drains them in order — the pipeline belongs to the pump, not
the worker thread).

Direct ``receive_batch`` calls are the deterministic way to park batches
in the pipeline: junction sends flush the pump before returning (that's
the synchronous-semantics contract), so a test that needs entries IN
FLIGHT feeds the receiver below the junction.
"""

import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.event import HostBatch
from siddhi_tpu.core.stream.junction import FatalQueryError
from siddhi_tpu.core.util.config import InMemoryConfigManager
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore


class Collector(StreamCallback):
    def __init__(self):
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


APP = """
define stream S (sym string, v long);
@info(name='pq')
from S#window.length(8)
  select sym, sum(v) as total group by sym
  insert into Out;
"""


def _manager(depth, extra=None):
    m = SiddhiManager()
    cfg = {"siddhi_tpu.pipeline_depth": str(depth)}
    cfg.update(extra or {})
    m.set_config_manager(InMemoryConfigManager(cfg))
    return m


def _batch(rt, vals, ts0=0):
    defn = rt.junctions["S"].definition
    n = len(vals)
    return HostBatch.from_columns(
        {"sym": np.array(["A"] * n, dtype=object),
         "v": np.asarray(vals, np.int64)},
        defn, rt.app_context.string_dictionary,
        timestamps=np.arange(ts0, ts0 + n, dtype=np.int64))


def test_sync_sends_keep_synchronous_semantics():
    """A junction send flushes the pump before returning: callers observe
    their outputs immediately, at any depth."""
    m = _manager(4)
    rt = m.create_siddhi_app_runtime(APP)
    out = Collector()
    rt.add_callback("Out", out)
    h = rt.get_input_handler("S")
    h.send(["A", 1])
    assert out.rows == [("A", 1)]
    h.send(["A", 2])
    assert out.rows == [("A", 1), ("A", 3)]
    pump = rt.app_context.completion_pump
    assert not pump.has_pending
    m.shutdown()


def test_inflight_batches_emit_in_dispatch_order():
    m = _manager(4)
    rt = m.create_siddhi_app_runtime(APP)
    out = Collector()
    rt.add_callback("Out", out)
    qr = rt.query_runtimes["pq"]
    pump = rt.app_context.completion_pump
    for i in range(3):
        qr.receive_batch(_batch(rt, [i + 1], ts0=i))
    # three batches ride in flight, nothing emitted yet
    assert pump.inflight(qr) == 3
    assert out.rows == []
    pump.flush()
    assert pump.inflight(qr) == 0
    # strict per-query dispatch order: running sums 1, 3, 6
    assert out.rows == [("A", 1), ("A", 3), ("A", 6)]
    m.shutdown()


def test_depth_bound_forces_batched_drain():
    m = _manager(2)
    rt = m.create_siddhi_app_runtime(APP)
    out = Collector()
    rt.add_callback("Out", out)
    qr = rt.query_runtimes["pq"]
    pump = rt.app_context.completion_pump
    for i in range(5):
        qr.receive_batch(_batch(rt, [1], ts0=i))
        assert pump.inflight(qr) <= 2
    # at least the older batches drained along the way, in order
    assert out.rows == [("A", k) for k in range(1, len(out.rows) + 1)]
    pump.flush()
    assert out.rows == [("A", 1), ("A", 2), ("A", 3), ("A", 4), ("A", 5)]
    tel = rt.app_context.telemetry.snapshot()
    assert tel["counters"]["pipeline.pulls"] >= 1
    assert tel["counters"]["pipeline.metas"] == 5
    assert tel["gauges"]["pipeline.pq.inflight"] == 0
    m.shutdown()


def test_overflow_reaches_producer_as_fatal_with_knob_named():
    """An overflow riding a pipelined meta surfaces on the producer's
    NEXT interaction as FatalQueryError naming the capacity knob, and the
    overflowed batch's clamped rows do not emit."""
    m = _manager(4, {"siddhi_tpu.window_capacity": "8"})
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v long, ts long);
        @info(name='ovq')
        from S#window.externalTime(ts, 10 sec)
          select sym, sum(v) as sv insert into Out;
    """)
    out = Collector()
    rt.add_callback("Out", out)
    qr = rt.query_runtimes["ovq"]
    defn = rt.junctions["S"].definition
    n = 16    # > capacity 8, all within the horizon -> overflow
    big = HostBatch.from_columns(
        {"sym": np.array(["A"] * n, dtype=object),
         "v": np.arange(n, dtype=np.int64),
         "ts": np.full(n, 1000, np.int64)},
        defn, rt.app_context.string_dictionary,
        timestamps=np.full(n, 1000, np.int64))
    qr.receive_batch(big)          # dispatched; overflow rides the meta
    pump = rt.app_context.completion_pump
    assert pump.inflight(qr) == 1
    with pytest.raises(FatalQueryError, match=r"ovq.*window_capacity"):
        pump.flush()
    assert out.rows == []          # the overflowed batch did not emit
    m.shutdown()


def test_checkpoint_drains_pipeline_and_restore_discards_it():
    """persist() drains the pump inside the barrier (its state updates
    are already in the captured pytrees, so its outputs must emit exactly
    once); restore discards pre-restore in-flight outputs — nothing is
    lost, nothing doubles across the cycle."""
    store = InMemoryPersistenceStore()
    m = _manager(4)
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    out = Collector()
    rt.add_callback("Out", out)
    qr = rt.query_runtimes["pq"]
    pump = rt.app_context.completion_pump

    qr.receive_batch(_batch(rt, [1], ts0=0))
    qr.receive_batch(_batch(rt, [2], ts0=1))
    assert pump.inflight(qr) == 2 and out.rows == []
    rev = rt.persist()
    # the two in-flight batches emitted exactly once, in order, and the
    # snapshot covers their state (sum == 3)
    assert out.rows == [("A", 1), ("A", 3)]
    assert pump.inflight(qr) == 0

    # new in-flight work AFTER the checkpoint, then roll back: the
    # pending outputs belong to the abandoned timeline and must vanish
    qr.receive_batch(_batch(rt, [10], ts0=2))
    assert pump.inflight(qr) == 1
    rt.restore_revision(rev)
    assert pump.inflight(qr) == 0
    assert out.rows == [("A", 1), ("A", 3)]   # no doubled emission
    h = rt.get_input_handler("S")
    h.send(["A", 4])
    # restored window holds 1,2 -> 1+2+4
    assert out.rows[-1] == ("A", 7)
    m.shutdown()


def test_worker_replacement_adopts_inflight_pipeline():
    """@Async worker dies with batches riding the pipeline: the pump's
    entries are worker-independent, so the supervisor's replacement
    worker drains them in order — no loss, no double-emit. The worker is
    first WEDGED (parked inside the fault hook, so its idle flush cannot
    run) to make the in-flight window deterministic."""
    from siddhi_tpu.resilience.faults import FaultInjector

    m = _manager(8)
    rt = m.create_siddhi_app_runtime("""
        @Async(buffer.size='64')
        define stream S (sym string, v long);
        @info(name='pq')
        from S#window.length(8) select sym, sum(v) as total group by sym
          insert into Out;
    """)
    out = Collector()
    rt.add_callback("Out", out)
    rt.start()
    sup = rt.supervise(interval_s=0.05, wedge_timeout_s=1.0)
    inj = FaultInjector()
    sj = rt.junctions["S"]
    try:
        qr = rt.query_runtimes["pq"]
        pump = rt.app_context.completion_pump
        inj.wedge_worker(sj)
        assert inj.wait_wedged(10.0)      # worker parked, cannot flush
        for i in range(2):
            qr.receive_batch(_batch(rt, [i + 1], ts0=i))
        assert pump.inflight(qr) == 2 and out.rows == []
        h = rt.get_input_handler("S")
        h.send(["A", 4])                  # queued past the stuck worker
        deadline = time.time() + 10.0
        while len(out.rows) < 3 and time.time() < deadline:
            time.sleep(0.02)
        # the replacement drained the adopted pipeline in dispatch order,
        # then delivered (and flushed) the queued batch
        assert out.rows == [("A", 1), ("A", 3), ("A", 7)]
        assert sup.worker_restarts >= 1
        inj.release()                     # stale worker wakes, retires
    finally:
        inj.clear()
        sup.stop()
        m.shutdown()


def test_async_idle_flush_bounds_trickle_lag():
    """Under trickle load the worker flushes the pipeline when its queue
    goes idle — a single send's outputs appear without further sends."""
    m = _manager(8)
    rt = m.create_siddhi_app_runtime("""
        @Async(buffer.size='64')
        define stream S (sym string, v long);
        @info(name='pq')
        from S#window.length(8) select sym, sum(v) as total group by sym
          insert into Out;
    """)
    out = Collector()
    rt.add_callback("Out", out)
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["A", 5])
    deadline = time.time() + 5.0
    while not out.rows and time.time() < deadline:
        time.sleep(0.01)
    assert out.rows == [("A", 5)]
    m.shutdown()


def test_fused_group_rides_pipeline_and_drains_in_member_order():
    m = _manager(4, {"siddhi_tpu.fuse_fanout": "1"})
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v long);
        @info(name='q0') from S select sym, v insert into A;
        @info(name='q1') from S select sym, v * 2 as v insert into B;
    """)
    outs = {s: Collector() for s in ("A", "B")}
    for s, c in outs.items():
        rt.add_callback(s, c)
    (group,) = rt.fused_fanout_groups
    pump = rt.app_context.completion_pump
    defn = rt.junctions["S"].definition
    for i in range(2):
        b = HostBatch.from_columns(
            {"sym": np.array(["A"], dtype=object),
             "v": np.array([i + 1], np.int64)},
            defn, rt.app_context.string_dictionary,
            timestamps=np.array([i], np.int64))
        group.receive_batch(b)
    assert pump.inflight(group) == 2
    assert outs["A"].rows == [] and outs["B"].rows == []
    pump.flush()
    assert outs["A"].rows == [("A", 1), ("A", 2)]
    assert outs["B"].rows == [("A", 2), ("A", 4)]
    tel = rt.app_context.telemetry.snapshot()
    assert tel["counters"]["fanout.S.dispatches"] == 2
    assert tel["counters"]["fanout.S.meta_pulls"] == 2
    m.shutdown()


def test_drain_error_routes_to_fault_stream_with_events():
    """A NON-fatal error that escapes ``_emit`` at drain time (a raising
    QueryCallback — invoked directly, not behind a downstream junction)
    must reach the @OnError(action='stream') fault junction WITH the
    failing input events, exactly like the synchronous path — the entry
    retains its input batch when the junction routes faults. (A raising
    StreamCallback is different: the OUTPUT junction catches and logs it,
    at any depth.)"""
    from siddhi_tpu import QueryCallback

    m = _manager(4)
    rt = m.create_siddhi_app_runtime("""
        @OnError(action='stream')
        define stream S (sym string, v long);
        @info(name='pq') from S select sym, v insert into Out;
    """)

    class Boom(QueryCallback):
        def receive(self, timestamp, in_events, remove_events):
            raise ValueError("callback exploded")

    faults = Collector()
    rt.add_callback("pq", Boom())
    rt.add_callback("!S", faults)
    h = rt.get_input_handler("S")
    h.send(["A", 1])      # sync send -> dispatch -> flush -> emit raises
    assert len(faults.rows) == 1
    sym, v, err = faults.rows[0]
    assert (sym, v) == ("A", 1) and "callback exploded" in err
    m.shutdown()


def test_defer_meta_maps_onto_pipeline_depth():
    """Deprecation shim: defer_meta>1 becomes pipeline_depth (MIGRATION
    note); the legacy hold-N queue no longer engages."""
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.defer_meta": "4"}))
    with pytest.warns(DeprecationWarning, match="defer_meta"):
        rt = m.create_siddhi_app_runtime(APP)
    assert rt.app_context.pipeline_depth == 4
    assert rt.app_context.defer_meta == 1
    out = Collector()
    rt.add_callback("Out", out)
    h = rt.get_input_handler("S")
    h.send(["A", 1])
    assert out.rows == [("A", 1)]   # sync semantics, no defer lag
    m.shutdown()


def test_depth_one_bypasses_pump():
    m = _manager(1)
    rt = m.create_siddhi_app_runtime(APP)
    out = Collector()
    rt.add_callback("Out", out)
    qr = rt.query_runtimes["pq"]
    pump = rt.app_context.completion_pump
    qr.receive_batch(_batch(rt, [1]))
    # synchronous: emitted inline, nothing ever rode the pipeline
    assert out.rows == [("A", 1)]
    assert not pump.has_pending
    m.shutdown()
