"""Autopilot (siddhi_tpu/autopilot/): closed-loop controller contracts.

The load-bearing claims, each tested here:

- default OFF is bit-identical and registers nothing;
- dry_run logs full decisions WITHOUT actuating;
- hysteresis: cooldown blocks repeat moves, oscillation damping blocks
  direction reversals, compile-storm backoff freezes every knob;
- LIVE actuation safety — depth / ingest-pool / fusion / shard knobs
  flipped at batch boundaries under live ingest stay bit-identical, and
  a persist/restore straddling a reshard actuation is exactly-once;
- device-join Wp shrink releases over-provisioned sub-windows after a
  skew burst, bit-identically;
- the decision log, ``siddhi_autopilot_*`` metric families and
  ``GET /autopilot`` agree about what happened.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.autopilot import ACTUATORS, AutopilotController
from siddhi_tpu.autopilot.actuators import DOWN, UP
from siddhi_tpu.autopilot.policy import Policy, RULES
from siddhi_tpu.autopilot.signals import SignalSnapshot, collect
from siddhi_tpu.core.util.config import InMemoryConfigManager
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


MULTI_APP = """
@app:name('apapp')
define stream S (sym string, v long);
@info(name='q1') from S select sym, v * 2 as d insert into Out;
@info(name='q2') from S select sym, v + 7 as p insert into Out2;
@info(name='q3') from S select sym, sum(v) as s group by sym insert into Out3;
"""


def _build(app=MULTI_APP, extra=None):
    m = SiddhiManager()
    cfg = {"siddhi_tpu.ingest_split": "8"}
    cfg.update(extra or {})
    m.set_config_manager(InMemoryConfigManager(cfg))
    rt = m.create_siddhi_app_runtime(app)
    sinks = {}
    for s in ("Out", "Out2", "Out3"):
        sinks[s] = Collector()
        rt.add_callback(s, sinks[s])
    rt.start()
    return m, rt, sinks


def _chunks(n_chunks=10, rows=24, seed=11):
    rng = np.random.default_rng(seed)
    out = []
    t = 0
    for _ in range(n_chunks):
        syms = rng.integers(0, 6, rows)
        vals = rng.integers(0, 100, rows)
        out.append((
            {"sym": np.array([f"S{s}" for s in syms], dtype=object),
             "v": vals.astype(np.int64)},
            np.arange(t, t + rows, dtype=np.int64)))
        t += rows
    return out


def _rows(sinks):
    return {s: list(c.rows) for s, c in sinks.items()}


# -------------------------------------------------------- default off


def test_default_off_registers_nothing_and_is_bit_identical():
    ctl = AutopilotController.instance()

    def run(extra):
        m, rt, sinks = _build(extra=extra)
        assert rt.name not in ctl.report()["apps"]
        for data, ts in _chunks():
            rt.get_input_handler("S").send_columns(data, timestamps=ts)
        out = _rows(sinks)
        m.shutdown()
        return out

    assert run(None) == run({"siddhi_tpu.autopilot": "off"})


def test_autopilot_knob_parses_and_registers_on_start():
    ctl = AutopilotController.instance()
    m, rt, _sinks = _build(extra={
        "siddhi_tpu.autopilot": "dry_run",
        "siddhi_tpu.autopilot_interval_s": "30",
        "siddhi_tpu.autopilot_cooldown_s": "1.5",
    })
    try:
        assert rt.app_context.autopilot == "dry_run"
        rep = ctl.report(rt.name)["apps"][rt.name]
        assert rep["mode"] == "dry_run"
        assert rep["interval_s"] == 30.0
        assert rep["cooldown_s"] == 1.5
    finally:
        m.shutdown()
    # shutdown unregistered it
    assert rt.name not in ctl.report()["apps"]


def test_bad_autopilot_mode_rejected():
    from siddhi_tpu.core.util.knobs import KNOBS

    with pytest.raises(Exception, match="autopilot"):
        KNOBS["autopilot"].parse("sideways")
    m, rt, _sinks = _build()
    try:
        with pytest.raises(ValueError):
            rt.enable_autopilot(mode="off")
    finally:
        m.shutdown()


# ---------------------------------------------- dry_run vs on, per tick


def _device_bound_collect(rt):
    """Real signals with a synthetic device-bound bottleneck planted —
    deterministic rule trigger without having to manufacture load."""
    sig = collect(rt)
    sig.bottlenecks = {"q1": {"stage": "device", "kind": "service",
                              "utilization": 0.9}}
    sig.jit_compiles = 0
    return sig


def test_dry_run_logs_decisions_without_actuating(monkeypatch):
    from siddhi_tpu.autopilot import signals as sigmod

    monkeypatch.setattr(sigmod, "collect", _device_bound_collect)
    ctl = AutopilotController.instance()
    m, rt, _sinks = _build()
    try:
        rt.enable_autopilot(mode="dry_run", interval_s=3600, cooldown_s=0.1)
        depth0 = rt.app_context.pipeline_depth
        entries = ctl.tick(rt.name, now=1000.0)
        dec = [e for e in entries if e["actuator"] == "pipeline_depth"]
        assert dec and dec[0]["applied"] is False
        assert dec[0]["mode"] == "dry_run"
        assert dec[0]["direction"] == "up"
        assert dec[0]["reason"] == "device_bound"
        assert rt.app_context.pipeline_depth == depth0   # untouched
        # the decision rode the log and the counter
        log = ctl.report(rt.name)["apps"][rt.name]["decisions"]
        assert any(e["actuator"] == "pipeline_depth" for e in log)
        counters = rt.app_context.telemetry.snapshot()["counters"]
        assert counters[
            "autopilot.decisions.pipeline_depth.up.device_bound"] >= 1
    finally:
        m.shutdown()


def test_on_mode_actuates_and_cooldown_blocks_repeat(monkeypatch):
    from siddhi_tpu.autopilot import signals as sigmod

    monkeypatch.setattr(sigmod, "collect", _device_bound_collect)
    ctl = AutopilotController.instance()
    m, rt, _sinks = _build()
    try:
        rt.enable_autopilot(mode="on", interval_s=3600, cooldown_s=5.0)
        depth0 = rt.app_context.pipeline_depth
        entries = ctl.tick(rt.name, now=1000.0)
        dec = [e for e in entries if e["actuator"] == "pipeline_depth"]
        assert dec and dec[0]["applied"] is True
        assert dec[0]["old"] == depth0 and dec[0]["new"] == depth0 + 1
        assert rt.app_context.pipeline_depth == depth0 + 1
        # inside the cooldown window the same rule is logged, blocked
        entries = ctl.tick(rt.name, now=1001.0)
        dec = [e for e in entries if e["actuator"] == "pipeline_depth"]
        assert dec and dec[0]["applied"] is False
        assert dec[0]["blocked"] == "cooldown"
        assert rt.app_context.pipeline_depth == depth0 + 1
        # past the cooldown it moves again
        ctl.tick(rt.name, now=1006.0)
        assert rt.app_context.pipeline_depth == depth0 + 2
    finally:
        m.shutdown()


def test_compile_storm_freezes_actuation(monkeypatch):
    from siddhi_tpu.autopilot import signals as sigmod

    compiles = {"n": 0}

    def storm_collect(rt):
        sig = _device_bound_collect(rt)
        sig.jit_compiles = compiles["n"]
        return sig

    monkeypatch.setattr(sigmod, "collect", storm_collect)
    ctl = AutopilotController.instance()
    m, rt, _sinks = _build()
    try:
        rt.enable_autopilot(mode="on", interval_s=3600, cooldown_s=0.1)
        depth0 = rt.app_context.pipeline_depth
        ctl.tick(rt.name, now=1000.0)      # baseline compile count
        compiles["n"] = 5                  # storm: count climbing
        assert ctl.tick(rt.name, now=1001.0) == []
        compiles["n"] = 9
        assert ctl.tick(rt.name, now=1002.0) == []
        rep = ctl.report(rt.name)["apps"][rt.name]
        assert rep["freezes"] >= 2 and rep["frozen"] is True
        # count stops climbing -> actuation resumes next tick
        entries = ctl.tick(rt.name, now=1003.0)
        assert any(e["applied"] for e in entries)
        assert rt.app_context.pipeline_depth > depth0
        counters = rt.app_context.telemetry.snapshot()["counters"]
        assert counters["autopilot.freezes"] >= 2
    finally:
        m.shutdown()


def test_oscillation_damping_suppresses_reversal():
    pol = Policy(cooldown_s=5.0)
    up_sig = SignalSnapshot(
        app="a", bottlenecks={"q": {"stage": "device", "utilization": 0.9}},
        pipeline_depth=2)
    down_sig = SignalSnapshot(
        app="a", bottlenecks={"q": {"stage": "device", "utilization": 0.05}},
        pipeline_depth=4)
    v = [x for x in pol.decide(up_sig, 100.0)
         if x["rule"].actuator == "pipeline_depth"]
    assert v and v[0]["blocked"] is None
    pol.applied("pipeline_depth", UP, 100.0)
    # a reversal within 2x cooldown is damped, not applied
    v = [x for x in pol.decide(down_sig, 107.0)
         if x["rule"].actuator == "pipeline_depth"]
    assert v and v[0]["blocked"] == "damped"
    # past the damping horizon the reversal is free to run
    v = [x for x in pol.decide(down_sig, 111.0)
         if x["rule"].actuator == "pipeline_depth"]
    assert v and v[0]["blocked"] is None


def test_every_actuator_reachable_and_bounded():
    reached = {r.actuator for r in RULES}
    assert reached == set(ACTUATORS)
    for a in ACTUATORS.values():
        assert a.lo <= a.hi
        assert a.apply is not None


# ------------------------------------------- live re-actuation safety


def test_live_actuations_at_batch_boundaries_bit_identical():
    """Depth / ingest-pool / fusion knobs flipped between live batches
    (seeded schedule) leave every output stream bit-identical to an
    untouched run of the same feed."""
    feed = _chunks(n_chunks=12, rows=24)

    def run(actuate):
        m, rt, sinks = _build()
        schedule = {
            2: ("pipeline_depth", UP),
            4: ("ingest_pool", UP),
            5: ("fuse_fanout", DOWN),
            7: ("pipeline_depth", DOWN),
            8: ("fuse_fanout", UP),
            9: ("ingest_pool", UP),
            10: ("ingest_pool", DOWN),
        }
        h = rt.get_input_handler("S")
        for i, (data, ts) in enumerate(feed):
            h.send_columns({k: v.copy() for k, v in data.items()},
                           timestamps=ts.copy())
            if actuate and i in schedule:
                name, direction = schedule[i]
                ACTUATORS[name].apply(rt, direction)
        out = _rows(sinks)
        m.shutdown()
        return out

    assert run(True) == run(False)


def test_controller_on_under_live_ingest_bit_identical():
    """The real loop: controller ON with an aggressive cadence, manual
    ticks between every chunk — whatever it decides to actuate, outputs
    match the autopilot-off run exactly."""
    feed = _chunks(n_chunks=10, rows=24, seed=23)
    ctl = AutopilotController.instance()

    def run(autopilot):
        extra = {"siddhi_tpu.autopilot": "on",
                 "siddhi_tpu.autopilot_interval_s": "3600",
                 "siddhi_tpu.autopilot_cooldown_s": "0.0"} if autopilot \
            else None
        m, rt, sinks = _build(extra=extra)
        h = rt.get_input_handler("S")
        for data, ts in feed:
            h.send_columns({k: v.copy() for k, v in data.items()},
                           timestamps=ts.copy())
            if autopilot:
                ctl.tick(rt.name)
        out = _rows(sinks)
        m.shutdown()
        return out

    assert run(True) == run(False)


ROUTED_APP = """
@app:name('aproute')
define stream S (sym string, side string, price double, volume long);
partition with (sym of S)
begin
  @info(name = 'q')
  from S#window.length(8)
  select sym, side, avg(price) as ap, sum(volume) as tv
  group by side
  insert into Out;
end;
"""


def _route_feed(rt, lo, hi):
    rng = np.random.default_rng(42)
    syms = rng.integers(0, 13, 1000)
    sides = rng.integers(0, 5, 1000)
    h = rt.get_input_handler("S")
    for i in range(lo, hi):
        h.send([f"SYM{syms[i]}", f"SIDE{sides[i]}",
                float(i % 17) + 0.25, int(i)])


def _build_routed(store=None, shards=None):
    from siddhi_tpu.parallel.mesh import device_route_query_step, make_mesh

    m = SiddhiManager()
    if store is not None:
        m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(ROUTED_APP)
    c = Collector()
    rt.add_callback("Out", c)
    if shards:
        device_route_query_step(rt.query_runtimes["q"], make_mesh(shards),
                                rows_per_shard=128)
    return m, rt, c


def test_route_shards_actuation_and_straddling_restore_exactly_once():
    """A reshard actuation mid-feed is bit-identical, and a persist
    taken AFTER the actuation restores exactly-once into a
    differently-sharded continuation (the canonical-snapshot
    contract straddles the actuation)."""
    m0, rt0, c0 = _build_routed()
    _route_feed(rt0, 0, 400)
    m0.shutdown()
    ref = list(c0.rows)

    store = InMemoryPersistenceStore()
    m1, rt1, c1 = _build_routed(store=store, shards=2)
    _route_feed(rt1, 0, 100)
    changed = ACTUATORS["route_shards"].apply(rt1, UP)
    assert changed == (2, 4)
    assert rt1.query_runtimes["q"]._route_layout.n == 4
    _route_feed(rt1, 100, 200)
    rt1.persist()
    m1.shutdown()
    head = len(c1.rows)
    assert c1.rows == ref[:head]

    m2, rt2, c2 = _build_routed(store=store, shards=2)
    rt2.restore_last_revision()
    _route_feed(rt2, 200, 300)
    # and actuate DOWN in the restored world too
    changed = ACTUATORS["route_shards"].apply(rt2, DOWN)
    # restored install re-lands at its configured 2 shards: nothing to halve
    assert changed is None or changed[1] >= 2
    _route_feed(rt2, 300, 400)
    m2.shutdown()
    assert c2.rows == ref[head:]


JOIN_SKEW_APP = """
@app:name('apjoin')
define stream L (sym string, lv long);
define stream R (sym string, rv long);
@info(name='jq') from L#window.length(32) join R#window.length(32)
  on L.sym == R.sym
  select L.sym as sym, L.lv as lv, R.rv as rv insert into Out;
"""


def test_join_partition_shrink_after_skew_bit_identical():
    """A hot-key burst grows Wp (the engine's own pre-dispatch growth);
    once diverse traffic evicts the hot rows, the autopilot's shrink
    actuation releases the over-provisioned sub-windows — outputs stay
    bit-identical to a never-shrunk run."""
    def run(actuate):
        m = SiddhiManager()
        m.set_config_manager(InMemoryConfigManager({
            "siddhi_tpu.join_engine": "device",
            "siddhi_tpu.join_partitions": "8",
            "siddhi_tpu.join_partition_slack": "1",
        }))
        rt = m.create_siddhi_app_runtime(JOIN_SKEW_APP)
        c = Collector()
        rt.add_callback("Out", c)
        rt.start()
        hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
        rng = np.random.default_rng(17)
        for i in range(120):                     # ~70% one hot key
            sym = "HOT" if rng.random() < .7 else f"S{rng.integers(0, 4)}"
            (hl if rng.random() < .5 else hr).send([sym, int(i)])
        eng = rt.query_runtimes["jq"].engine
        grown = max(p.Wp for p in eng.plans.values())
        assert grown > 4, f"sub-windows never grew (Wp={grown})"
        for i in range(120, 280):                # diverse: hot rows evict
            # 16 distinct keys spread the ring across every partition,
            # so per-partition occupancy falls well under the grown Wp
            sym = f"S{rng.integers(0, 16)}"
            (hl if rng.random() < .5 else hr).send([sym, int(i)])
        shrunk = None
        if actuate:
            shrunk = ACTUATORS["join_partitions"].apply(rt, DOWN)
            assert shrunk is not None, "nothing shrank after the burst"
            assert shrunk[1] < shrunk[0]
            # at least one side released sub-windows; a side whose live
            # occupancy still demands the grown Wp legitimately holds
            assert any(p.Wp < grown for p in eng.plans.values())
        for i in range(280, 400):
            sym = "HOT" if rng.random() < .8 else f"S{rng.integers(0, 4)}"
            (hl if rng.random() < .5 else hr).send([sym, int(i)])
        rows = list(c.rows)
        m.shutdown()
        return rows

    assert run(True) == run(False)


# --------------------------------------------------- export + REST


def test_autopilot_metric_families_render(monkeypatch):
    from siddhi_tpu.autopilot import signals as sigmod
    from siddhi_tpu.observability import export

    monkeypatch.setattr(sigmod, "collect", _device_bound_collect)
    ctl = AutopilotController.instance()
    m, rt, _sinks = _build()
    try:
        rt.enable_autopilot(mode="on", interval_s=3600, cooldown_s=0.1)
        ctl.tick(rt.name, now=1000.0)
        text = export.prometheus_text(m)
        assert ('siddhi_autopilot_mode{app="apapp"} 2') in text
        assert "siddhi_autopilot_ticks_total" in text
        assert ('siddhi_autopilot_decisions_total{app="apapp",'
                'knob="pipeline_depth",direction="up",'
                'reason="device_bound"}') in text
        # dotted autopilot.* names never leak as generic families
        assert 'name="autopilot' not in text
    finally:
        m.shutdown()
    # the gauge dies with the registration (remove_gauge paired)
    assert "autopilot.mode" not in \
        rt.app_context.telemetry.snapshot()["gauges"]


def _http_get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_rest_autopilot_endpoint():
    from siddhi_tpu.service import SiddhiRestService

    m, rt, _sinks = _build()
    svc = SiddhiRestService(m).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        # deployed but not under autopilot control -> 404
        st, body = _http_get(f"{base}/autopilot/{rt.name}")
        assert st == 404 and "autopilot" in body["error"]
        st, body = _http_get(f"{base}/autopilot/nosuchapp")
        assert st == 404
        rt.enable_autopilot(mode="dry_run", interval_s=3600)
        st, body = _http_get(f"{base}/autopilot")
        assert st == 200
        assert set(body["actuators"]) == set(ACTUATORS)
        assert body["decision_log_capacity"] == 256
        st, body = _http_get(f"{base}/autopilot/{rt.name}")
        assert st == 200
        assert body["apps"][rt.name]["mode"] == "dry_run"
    finally:
        svc.stop()
        m.shutdown()


def test_decision_log_is_bounded(monkeypatch):
    from siddhi_tpu.autopilot import controller as ctlmod
    from siddhi_tpu.autopilot import signals as sigmod

    monkeypatch.setattr(sigmod, "collect", _device_bound_collect)
    ctl = AutopilotController.instance()
    m, rt, _sinks = _build()
    try:
        rt.enable_autopilot(mode="dry_run", interval_s=3600, cooldown_s=0.0)
        for i in range(ctlmod.DECISION_LOG_CAPACITY + 40):
            ctl.tick(rt.name, now=1000.0 + i)
        log = ctl.report(rt.name)["apps"][rt.name]["decisions"]
        assert len(log) == ctlmod.DECISION_LOG_CAPACITY
        # oldest entries fell off; seq numbers stay monotonic
        seqs = [e["seq"] for e in log]
        assert seqs == sorted(seqs) and seqs[0] > 1
    finally:
        m.shutdown()
