"""Fan-out fusion: multi-query-per-stream semantics (ISSUE 4).

Covers the fused group's contract against the unfused reference path:
subscription-order delivery, per-receiver column-mutation isolation (the
``_deliver_batch`` per-receiver dict wrapper), fused == unfused outputs
(exact precision on CPU), the one-dispatch/one-meta-pull amortization
asserted via telemetry, per-member overflow attribution and fault-stream
routing, and snapshot/restore round trips across a fusion-config change.
"""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.stream.junction import FatalQueryError, Receiver
from siddhi_tpu.core.util.config import InMemoryConfigManager


class Collector(StreamCallback):
    def __init__(self, log=None, tag=None):
        self.events = []
        self._log = log
        self._tag = tag

    def receive(self, events):
        self.events.extend(events)
        if self._log is not None:
            self._log.extend((self._tag, tuple(e.data)) for e in events)


def _manager(fused: bool) -> SiddhiManager:
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.fuse_fanout": "1" if fused else "0"}))
    return m


_FOUR_QUERY_APP = """
define stream S (symbol string, price float, volume long);
@info(name='q0') from S[price > 10.0] select symbol, price insert into O0;
@info(name='q1') from S#window.length(4)
  select symbol, sum(volume) as tv group by symbol insert into O1;
@info(name='q2') from S select symbol, volume * 2 as v2 insert into O2;
@info(name='q3') from S#window.lengthBatch(2)
  select symbol, avg(price) as ap group by symbol insert into O3;
"""


def _drive(rt):
    h = rt.get_input_handler("S")
    h.send(100, ["IBM", 15.0, 10])
    h.send(101, ["WSO2", 5.0, 20])
    h.send_columns(
        {"symbol": np.array(["IBM", "GOOG", "WSO2", "IBM"], dtype=object),
         "price": np.array([30.0, 11.0, 2.0, 7.5], np.float32),
         "volume": np.array([1, 2, 3, 4], np.int64)},
        timestamps=np.array([102, 103, 104, 105], np.int64))
    h.send(106, ["GOOG", 50.0, 7])


def _collect_all(rt, streams):
    outs = {}
    for s in streams:
        outs[s] = Collector()
        rt.add_callback(s, outs[s])
    return outs


def test_fused_equals_unfused_outputs():
    results = {}
    for fused in (True, False):
        m = _manager(fused)
        rt = m.create_siddhi_app_runtime(_FOUR_QUERY_APP)
        outs = _collect_all(rt, ["O0", "O1", "O2", "O3"])
        if fused:
            assert [(g.stream_id, len(g.members))
                    for g in rt.fused_fanout_groups] == [("S", 4)]
        else:
            assert rt.fused_fanout_groups == []
        _drive(rt)
        results[fused] = {
            s: [(e.timestamp, tuple(e.data)) for e in c.events]
            for s, c in outs.items()}
        m.shutdown()
    assert results[True] == results[False]


def test_single_dispatch_and_meta_pull_per_batch():
    m = _manager(True)
    rt = m.create_siddhi_app_runtime(_FOUR_QUERY_APP)
    _collect_all(rt, ["O0", "O1", "O2", "O3"])
    h = rt.get_input_handler("S")
    h.send(100, ["IBM", 15.0, 10])      # warm: builds + compiles the step
    tel = rt.app_context.telemetry
    base = tel.snapshot()
    for i in range(3):
        h.send(101 + i, ["IBM", 15.0, 10])
    snap = tel.snapshot()
    # exactly ONE jitted dispatch and ONE meta pull per junction batch
    assert snap["counters"]["fanout.S.dispatches"] \
        - base["counters"]["fanout.S.dispatches"] == 3
    assert snap["counters"]["fanout.S.meta_pulls"] \
        - base["counters"]["fanout.S.meta_pulls"] == 3
    rec = snap["jit"]["fanout.S.step"]
    assert rec["compiles"] == 1
    # member hit-counting: 4 query-batches amortized per dispatch
    assert rec["hits"] - base["jit"]["fanout.S.step"]["hits"] == 3 * 4
    # no member compiled (or dispatched) its own step
    assert not any(k.startswith("query.") for k in snap["jit"])
    assert snap["gauges"]["fanout.S.group_size"] == 4
    m.shutdown()


def test_subscription_order_delivery():
    for fused in (True, False):
        m = _manager(fused)
        rt = m.create_siddhi_app_runtime("""
            define stream S (v long);
            @info(name='qa') from S select v insert into OA;
            @info(name='qb') from S select v + 1 as v insert into OB;
            @info(name='qc') from S select v + 2 as v insert into OC;
        """)
        log = []
        for tag, s in (("a", "OA"), ("b", "OB"), ("c", "OC")):
            rt.add_callback(s, Collector(log=log, tag=tag))
        h = rt.get_input_handler("S")
        h.send(1, [10])
        h.send(2, [20])
        assert [t for t, _d in log] == ["a", "b", "c", "a", "b", "c"], fused
        m.shutdown()


def test_receiver_column_mutation_isolation():
    """Regression for the ``_deliver_batch`` per-receiver dict wrapper: a
    receiver rebinding a column in its batch dict must not leak the
    mutation into later receivers' deliveries."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("define stream S (v long);")

    seen = []

    class Mutator(Receiver):
        def receive(self, events):  # pragma: no cover — batch path only
            raise AssertionError("columnar path expected")

        def receive_batch(self, batch, junction):
            batch.cols["v"] = np.zeros_like(np.asarray(batch.cols["v"]))
            batch.cols["__extra__"] = np.ones(1)

    class Witness(Receiver):
        def receive_batch(self, batch, junction):
            seen.append((np.asarray(batch.cols["v"]).copy(),
                         "__extra__" in batch.cols))

    j = rt.junctions["S"]
    j.subscribe(Mutator())
    j.subscribe(Witness())
    h = rt.get_input_handler("S")
    h.send_columns({"v": np.array([7, 8, 9], np.int64)},
                   timestamps=np.array([1, 2, 3], np.int64))
    assert len(seen) == 1
    vals, extra_leaked = seen[0]
    assert vals[:3].tolist() == [7, 8, 9]
    assert not extra_leaked
    m.shutdown()


def test_mixed_eligibility_groups_contiguous_run():
    m = _manager(True)
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, v long, ts long);
        @info(name='q0') from S select symbol, v insert into O0;
        @info(name='q1') from S#window.time(1 sec)
          select symbol, sum(v) as sv insert into O1;
        @info(name='q2') from S select symbol, v + 1 as v insert into O2;
        @info(name='q3') from S[v > 0] select symbol, v insert into O3;
    """)
    # q1's time window is scheduler-driven -> ineligible; it splits the
    # receiver list into [q0] (too short) and [q2, q3] (fused)
    groups = rt.fused_fanout_groups
    assert len(groups) == 1
    assert [q.name for q in groups[0].members] == ["q2", "q3"]
    outs = _collect_all(rt, ["O0", "O1", "O2", "O3"])
    h = rt.get_input_handler("S")
    h.send(1000, ["IBM", 5, 1000])
    assert [tuple(e.data) for e in outs["O0"].events] == [("IBM", 5)]
    assert [tuple(e.data) for e in outs["O2"].events] == [("IBM", 6)]
    assert [tuple(e.data) for e in outs["O3"].events] == [("IBM", 5)]
    m.shutdown()


def test_fuse_fanout_opt_out_knob():
    m = _manager(False)
    rt = m.create_siddhi_app_runtime(_FOUR_QUERY_APP)
    assert rt.fused_fanout_groups == []
    m.shutdown()


_OVERFLOW_APP = """
@OnError(action='stream')
define stream S (symbol string, v long, ts long);
@info(name='q_ok') from S select symbol, v insert into OK;
@info(name='q_over') from S#window.externalTime(ts, 10 sec)
  select symbol, sum(v) as sv insert into OV;
@info(name='q_ok2') from S select symbol, v + 1 as v insert into OK2;
"""


def _overflow_manager():
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.fuse_fanout": "1", "siddhi_tpu.window_capacity": "8"}))
    return m


def test_fused_overflow_names_query_and_routes_fault_stream():
    m = _overflow_manager()
    rt = m.create_siddhi_app_runtime(_OVERFLOW_APP)
    assert len(rt.fused_fanout_groups[0].members) == 3
    ok, ok2, faults = Collector(), Collector(), Collector()
    rt.add_callback("OK", ok)
    rt.add_callback("OK2", ok2)
    rt.add_callback("!S", faults)
    h = rt.get_input_handler("S")
    n = 16   # > capacity 8, all within the 10 s horizon: q_over overflows
    h.send_columns(
        {"symbol": np.array(["A"] * n, dtype=object),
         "v": np.arange(n, dtype=np.int64),
         "ts": np.full(n, 1000, np.int64)},
        timestamps=np.full(n, 1000, np.int64))
    # only q_over's failure routed to the fault stream, naming its knob
    assert len(faults.events) == n
    err = faults.events[0].data[-1]
    assert "q_over" in err and "window_capacity" in err
    # the sibling members' outputs for the SAME batch are unaffected
    assert len(ok.events) == n
    assert len(ok2.events) == n
    assert [e.data[1] for e in ok2.events] == list(range(1, n + 1))
    m.shutdown()


def test_fused_overflow_propagates_without_fault_stream():
    m = _overflow_manager()
    rt = m.create_siddhi_app_runtime(
        _OVERFLOW_APP.replace("@OnError(action='stream')\n", ""))
    ok = Collector()
    rt.add_callback("OK", ok)
    h = rt.get_input_handler("S")
    n = 16
    with pytest.raises(FatalQueryError, match=r"q_over.*window_capacity"):
        h.send_columns(
            {"symbol": np.array(["A"] * n, dtype=object),
             "v": np.arange(n, dtype=np.int64),
             "ts": np.full(n, 1000, np.int64)},
            timestamps=np.full(n, 1000, np.int64))
    # siblings emitted before the fatal surfaced to the sender
    assert len(ok.events) == n
    m.shutdown()


_SNAP_APP = """
@app:name('FanSnap')
define stream S (symbol string, v long);
@info(name='qs0') from S#window.length(4)
  select symbol, sum(v) as sv group by symbol insert into OS0;
@info(name='qs1') from S#window.length(2)
  select symbol, max(v) as mv group by symbol insert into OS1;
"""


def _feed(h, lo, hi):
    for i in range(lo, hi):
        h.send(1000 + i, [f"K{i % 3}", i])


@pytest.mark.parametrize("fused_before,fused_after",
                         [(True, False), (False, True), (True, True)])
def test_snapshot_restores_across_fusion_config_change(fused_before,
                                                       fused_after):
    # reference run: uninterrupted, unfused
    m_ref = _manager(False)
    rt_ref = m_ref.create_siddhi_app_runtime(_SNAP_APP)
    ref = _collect_all(rt_ref, ["OS0", "OS1"])
    h = rt_ref.get_input_handler("S")
    _feed(h, 0, 6)
    _feed(h, 6, 12)
    expect = {s: [(e.timestamp, tuple(e.data)) for e in c.events]
              for s, c in ref.items()}
    m_ref.shutdown()

    m1 = _manager(fused_before)
    rt1 = m1.create_siddhi_app_runtime(_SNAP_APP)
    outs1 = _collect_all(rt1, ["OS0", "OS1"])
    _feed(rt1.get_input_handler("S"), 0, 6)
    head = {s: [(e.timestamp, tuple(e.data)) for e in c.events]
            for s, c in outs1.items()}
    snap = rt1.snapshot()
    m1.shutdown()

    m2 = _manager(fused_after)
    rt2 = m2.create_siddhi_app_runtime(_SNAP_APP)
    outs2 = _collect_all(rt2, ["OS0", "OS1"])
    rt2.restore(snap)
    _feed(rt2.get_input_handler("S"), 6, 12)
    tail = {s: [(e.timestamp, tuple(e.data)) for e in c.events]
            for s, c in outs2.items()}
    m2.shutdown()

    for s in expect:
        assert head[s] + tail[s] == expect[s], (s, fused_before, fused_after)


def test_identical_program_dedup_cluster():
    """Members with provably identical step programs (and states) run as
    ONE computation in the fused module; a differing sibling keeps its
    own — outputs stay per-member and match the unfused path."""
    app = """
    define stream S (symbol string, v long);
    @info(name='t0') from S#window.length(4)
      select symbol, sum(v) as sv group by symbol insert into T0;
    @info(name='t1') from S#window.length(4)
      select symbol, sum(v) as sv group by symbol insert into T1;
    @info(name='t2') from S#window.length(4)
      select symbol, sum(v) as sv group by symbol insert into T2;
    @info(name='t3') from S[v > 2]
      select symbol, v insert into T3;
    """
    results = {}
    for fused in (True, False):
        m = _manager(fused)
        rt = m.create_siddhi_app_runtime(app)
        outs = _collect_all(rt, ["T0", "T1", "T2", "T3"])
        h = rt.get_input_handler("S")
        _feed(h, 0, 8)
        if fused:
            (group,) = rt.fused_fanout_groups
            # t0/t1/t2 dedup into one cluster; t3 is its own
            assert [len(c) for c in group._clusters] == [3, 1]
            # cluster members share the (immutable) state arrays
            q0, q1 = rt.query_runtimes["t0"], rt.query_runtimes["t1"]
            assert q0._state is q1._state
            # snapshot keys stay per-query; restore round-trips
            snap = rt.snapshot()
            rt.restore(snap)
            _feed(h, 8, 12)
        else:
            _feed(h, 8, 12)
        results[fused] = {
            s: [(e.timestamp, tuple(e.data)) for e in c.events]
            for s, c in outs.items()}
        m.shutdown()
    assert results[True] == results[False]
    # sanity: the three identical queries really got identical outputs
    assert results[True]["T0"] == results[True]["T1"]


def test_release_middle_member_preserves_subscription_order():
    """Releasing a MIDDLE member dissolves the group: the fused slot
    cannot keep the released member between its former siblings, and
    subscription-order delivery outranks keeping the fusion."""
    m = _manager(True)
    rt = m.create_siddhi_app_runtime("""
        define stream S (v long);
        @info(name='r0') from S select v insert into R0;
        @info(name='r1') from S select v + 1 as v insert into R1;
        @info(name='r2') from S select v + 2 as v insert into R2;
    """)
    (group,) = rt.fused_fanout_groups
    log = []
    for tag, s in (("r0", "R0"), ("r1", "R1"), ("r2", "R2")):
        rt.add_callback(s, Collector(log=log, tag=tag))
    group.release(rt.query_runtimes["r1"])
    assert group.members == []          # dissolved, not reordered
    j = rt.junctions["S"]
    names = [getattr(r, "name", None) for r in j.receivers]
    assert names[:3] == ["r0", "r1", "r2"]
    rt.get_input_handler("S").send(1, [10])
    assert [t for t, _d in log] == ["r0", "r1", "r2"]
    m.shutdown()


def test_two_groups_one_stream_gauges_aggregate():
    """An ineligible receiver mid-run splits one stream into two fused
    groups; the per-stream gauges aggregate over both, and dissolving
    one group must not delete the survivor's metric surface."""
    m = _manager(True)
    rt = m.create_siddhi_app_runtime("""
        define stream S (v long, ts long);
        @info(name='g0') from S select v insert into A0;
        @info(name='g1') from S select v + 1 as v insert into A1;
        @info(name='mid') from S#window.time(1 sec)
          select sum(v) as sv insert into AM;
        @info(name='g2') from S select v + 2 as v insert into A2;
        @info(name='g3') from S select v + 3 as v insert into A3;
    """)
    groups = rt.fused_fanout_groups
    assert [[q.name for q in g.members] for g in groups] == \
        [["g0", "g1"], ["g2", "g3"]]
    tel = rt.app_context.telemetry
    assert tel.read_gauges()["fanout.S.group_size"] == 4
    groups[0].dissolve()
    gauges = tel.read_gauges()
    assert gauges["fanout.S.group_size"] == 2      # survivor still scraped
    groups[1].dissolve()
    assert "fanout.S.group_size" not in tel.read_gauges()
    m.shutdown()


def test_group_release_and_dissolve():
    m = _manager(True)
    rt = m.create_siddhi_app_runtime("""
        define stream S (v long);
        @info(name='qa') from S select v insert into OA;
        @info(name='qb') from S select v + 1 as v insert into OB;
    """)
    (group,) = rt.fused_fanout_groups
    qa = rt.query_runtimes["qa"]
    outs = _collect_all(rt, ["OA", "OB"])
    h = rt.get_input_handler("S")
    h.send(1, [10])
    group.release(qa)      # drops below two members -> dissolves entirely
    assert group.members == []
    assert qa._fanout_group is None
    j = rt.junctions["S"]
    assert group not in j.receivers
    h.send(2, [20])        # both members back on their own subscriptions
    assert [e.data[0] for e in outs["OA"].events] == [10, 20]
    assert [e.data[0] for e in outs["OB"].events] == [11, 21]
    m.shutdown()
