"""Fast two-step NFA kernel == generic round-loop engine, differentially.

The loop-free kernel (ops/nfa.py ``_apply_stream_fast``) replaces the
per-round ``lax.while_loop`` for ``e1=A -> e2=B`` / ``e1=A, e2=B`` chains.
These tests drive identical randomized MULTI-ROW batches (same-key
duplicates, within-expiry straddles, filter failures) through a fast-path
runtime and a generic-path runtime (``stage.fast_enabled = False``) and
require byte-identical output sequences — emission order included
(reference semantics: StreamPreStateProcessor.java:364-403).
"""

import zlib

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.util.config import InMemoryConfigManager


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend((e.timestamp, tuple(e.data)) for e in events)


def _drive(app, feeds, fast: bool, slots: int):
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.nfa_slots": str(slots)}))
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("M", c)
    q = rt.query_runtimes[list(rt.query_runtimes)[0]]
    q.stage.fast_enabled = fast
    hs = {s: rt.get_input_handler(s) for s in ("A", "B")}
    err = None
    try:
        for stream, cols, ts in feeds:
            hs[stream].send_columns(cols, timestamps=ts)
    except Exception as ex:  # overflow parity counts too
        err = type(ex).__name__
    m.shutdown()
    return c.rows, err


def _random_feeds(rng, n_batches, max_rows, n_keys, ts_jump_ms):
    """Interleaved multi-row A/B batches with same-key duplicates."""
    feeds = []
    t = 1_000
    for _ in range(n_batches):
        stream = "A" if rng.random() < 0.55 else "B"
        n = int(rng.integers(1, max_rows + 1))
        keys = np.array([f"K{int(i)}" for i in rng.integers(0, n_keys, n)],
                        dtype=object)
        vals = np.round(rng.random(n) * 10.0, 1)
        # occasional in-batch ts spread, sometimes straddling `within`
        spread = rng.choice([0, 1, ts_jump_ms])
        ts = t + np.sort(rng.integers(0, spread + 1, n)).astype(np.int64)
        feeds.append((stream, {"k": keys, "v": vals}, ts))
        t += int(rng.integers(1, ts_jump_ms))
    return feeds


PATTERNS = {
    "every-pattern-within": """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        from every e1=A -> e2=B[e2.v > e1.v] within 2 sec
        select e1.v as v1, e2.v as v2, e1.k as k insert into M;
    """,
    "every-pattern-nowithin": """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        from every e1=A -> e2=B[e2.v > e1.v]
        select e1.v as v1, e2.v as v2 insert into M;
    """,
    "nonevery-pattern": """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        from e1=A -> e2=B[e2.v > e1.v] within 2 sec
        select e1.v as v1, e2.v as v2 insert into M;
    """,
    "every-pattern-headfilter": """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        from every e1=A[v > 3.0] -> e2=B[e2.v > e1.v] within 2 sec
        select e1.v as v1, e2.v as v2 insert into M;
    """,
    "every-sequence": """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        from every e1=A, e2=B[e2.v > e1.v]
        select e1.v as v1, e2.v as v2 insert into M;
    """,
    "nonevery-sequence": """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        from e1=A, e2=B[e2.v > e1.v]
        select e1.v as v1, e2.v as v2 insert into M;
    """,
    "every-sequence-within": """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        from every e1=A, e2=B[e2.v > e1.v] within 2 sec
        select e1.v as v1, e2.v as v2 insert into M;
    """,
}


@pytest.mark.parametrize("name", sorted(PATTERNS))
def test_fast_matches_generic_unpartitioned(name):
    app = PATTERNS[name]
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    feeds = _random_feeds(rng, n_batches=30, max_rows=6, n_keys=1,
                          ts_jump_ms=900)
    fast, ef = _drive(app, feeds, fast=True, slots=16)
    slow, es = _drive(app, feeds, fast=False, slots=16)
    assert ef == es
    assert fast == slow


@pytest.mark.parametrize("name", sorted(PATTERNS))
def test_fast_matches_generic_partitioned(name):
    app = PATTERNS[name].replace(
        "from ", "partition with (k of A, k of B) begin @info(name='q') from ", 1
    ).replace("insert into M;", "insert into M; end;")
    rng = np.random.default_rng(zlib.crc32(name.encode()) // 2)
    feeds = _random_feeds(rng, n_batches=40, max_rows=8, n_keys=5,
                          ts_jump_ms=700)
    fast, ef = _drive(app, feeds, fast=True, slots=16)
    slow, es = _drive(app, feeds, fast=False, slots=16)
    assert ef == es
    assert fast == slow


def test_fast_hard_fallback_expiry_straddle():
    """An in-batch ts spread straddling `within` for one key forces the
    lax.cond fallback to the generic engine; outputs must still agree."""
    app = PATTERNS["every-pattern-within"]
    feeds = []
    # pre-arm two pendings whose deadlines fall INSIDE the next batch span
    feeds.append(("A", {"k": np.array(["K0", "K0"], object),
                        "v": np.array([1.0, 2.0])},
                  np.array([1_000, 1_400], np.int64)))
    # arming batch: same key, rows straddling both deadlines (3000, 3400)
    feeds.append(("A", {"k": np.array(["K0", "K0", "K0"], object),
                        "v": np.array([3.0, 4.0, 5.0])},
                  np.array([2_900, 3_200, 3_600], np.int64)))
    feeds.append(("B", {"k": np.array(["K0"], object),
                        "v": np.array([9.9])},
                  np.array([3_700], np.int64)))
    fast, ef = _drive(app, feeds, fast=True, slots=16)
    slow, es = _drive(app, feeds, fast=False, slots=16)
    assert ef == es is None
    assert fast == slow
    assert len(fast) > 0


def test_fast_overflow_parity():
    app = PATTERNS["every-pattern-nowithin"]
    rows = 10
    feeds = [("A", {"k": np.array(["K0"] * rows, object),
                    "v": np.arange(rows, dtype=float)},
              np.arange(1_000, 1_000 + rows, dtype=np.int64))]
    fast, ef = _drive(app, feeds, fast=True, slots=4)
    slow, es = _drive(app, feeds, fast=False, slots=4)
    assert ef == es == "FatalQueryError"


def test_ineligible_plans_take_generic_path():
    """3-step, logical, count, and same-stream chains must not dispatch to
    the fast kernel."""
    from siddhi_tpu.core.manager import SiddhiManager as SM

    cases = [
        "from every e1=A -> e2=B -> e3=A select e1.v as v1 insert into M;",
        "from every e1=A -> not B for 1 sec select e1.v as v1 insert into M;",
        "from every e1=A<1:3> -> e2=B select e2.v as v2 insert into M;",
        "from every e1=A -> e2=A[e2.v > e1.v] select e1.v as v1 insert into M;",
    ]
    for q in cases:
        m = SM()
        rt = m.create_siddhi_app_runtime(
            "@app:playback define stream A (k string, v double); "
            "define stream B (k string, v double); " + q)
        rtq = rt.query_runtimes[list(rt.query_runtimes)[0]]
        assert rtq.stage._fast_side("A") is None, q
        assert rtq.stage._fast_side("B") is None, q
        m.shutdown()


def test_out_of_order_timestamp_cannot_resurrect_expired_pending():
    """Minimized from a randomized divergence: the generic engine expires
    pendings PHYSICALLY at each event's ts (`_expire` clears persist), so
    an out-of-order earlier-ts event must not match a pending that a
    later-ts event already expired. Playback feeds can go backwards."""
    app = PATTERNS["every-pattern-headfilter"].replace(
        "from ", "partition with (k of A, k of B) begin @info(name='q') from ", 1
    ).replace("insert into M;", "insert into M; end;")
    feeds = [
        ("A", {"k": np.array(["K1"], object), "v": np.array([3.5])},
         np.array([8_762], np.int64)),
        ("B", {"k": np.array(["K1"], object), "v": np.array([2.6])},
         np.array([11_015], np.int64)),   # expires the pending (dl 10762)
        ("B", {"k": np.array(["K1"], object), "v": np.array([6.2])},
         np.array([10_684], np.int64)),   # out-of-order: must NOT match
    ]
    fast, ef = _drive(app, feeds, fast=True, slots=16)
    slow, es = _drive(app, feeds, fast=False, slots=16)
    assert ef == es is None
    assert fast == slow == []
