"""Windowed distinctCount attribute aggregator — reference
DistinctCountAttributeAggregatorExecutor: +1 when a value's count goes
0->1, -1 when it returns to 0 (via window expiry), exact per-event."""

import collections

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


def test_distinct_count_sliding_window():
    m, rt, c = build("""
        define stream S (sym string);
        from S#window.length(3)
        select distinctCount(sym) as d insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for s in ["a", "a", "b", "c", "c", "a"]:
        h.send([s])
    m.shutdown()
    got = [e.data[0] for e in c.events]
    # window contents after each arrival: [a] [aa] [aab] [abc] [bcc] [cca]
    assert got == [1, 1, 2, 3, 2, 2]


def test_distinct_count_group_by():
    m, rt, c = build("""
        define stream S (user string, page string);
        from S#window.length(4)
        select user, distinctCount(page) as d
        group by user insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["u1", "home"])
    h.send(["u1", "cart"])
    h.send(["u2", "home"])
    h.send(["u1", "home"])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("u1", 1), ("u1", 2), ("u2", 1), ("u1", 2)]


def test_distinct_count_batch_window_resets():
    m, rt, c = build("""
        define stream S (sym string);
        from S#window.lengthBatch(3)
        select distinctCount(sym) as d insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for s in ["a", "b", "a", "c", "c", "c"]:
        h.send([s])
    m.shutdown()
    got = [e.data[0] for e in c.events]
    # per tumbling batch of 3: {a,b,a} -> 2 ; {c,c,c} -> 1
    assert got == [2, 1]


def test_distinct_count_numeric_values():
    m, rt, c = build("""
        define stream S (v double);
        from S#window.length(10)
        select distinctCount(v) as d insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for v in [1.5, 1.5, 2.5, -0.0, 0.0]:
        h.send([v])
    m.shutdown()
    got = [e.data[0] for e in c.events]
    # bit-pattern identity: -0.0 and 0.0 are distinct patterns
    assert got == [1, 1, 2, 3, 4]


def test_distinct_count_differential_random():
    rng = np.random.default_rng(31)
    m, rt, c = build("""
        define stream S (sym string);
        from S#window.length(5)
        select distinctCount(sym) as d insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    dq = collections.deque()
    model = []
    for _ in range(300):
        s = f"k{int(rng.integers(0, 6))}"
        h.send([s])
        dq.append(s)
        if len(dq) > 5:
            dq.popleft()
        model.append(len(set(dq)))
    m.shutdown()
    got = [e.data[0] for e in c.events]
    assert got == model


def test_distinct_count_capacity_overflow_raises():
    from siddhi_tpu import SiddhiManager

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v long);
        from S#window.length(100)
        select distinctCount(v) as d insert into OutStream;
    """)
    q = next(iter(rt.query_runtimes.values()))
    for spec in q.selector_plan.specs:
        spec.distinct_capacity = 4   # shrink the value table
    h = rt.get_input_handler("S")
    import pytest
    with pytest.raises(RuntimeError, match="distinct_values_capacity"):
        for v in range(10):          # 10 live distinct values > 4 slots
            h.send([v])
    m.shutdown()


def test_distinct_count_unbounded_cardinality_reuses_dead_slots():
    # 70 unique all-time values but never more than 3 live: zero-count
    # slots must be reclaimed, not exhaust the table
    m, rt, c = build("""
        define stream S (sym string);
        from S#window.length(3)
        select distinctCount(sym) as d insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for i in range(70):
        h.send([f"v{i}"])
    m.shutdown()
    got = [e.data[0] for e in c.events]
    assert got[:3] == [1, 2, 3] and all(d == 3 for d in got[3:])
