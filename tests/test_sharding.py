"""Sharded == unsharded equivalence on the virtual 8-device CPU mesh.

Drives the same event sequences through an unsharded app and one whose
query state is sharded over the key axis (``parallel/mesh.py``), asserting
identical outputs — the suite-level guarantee behind ``dryrun_multichip``
(SURVEY.md §2.13: key-space sharding over ICI).
"""

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.parallel.mesh import make_mesh, shard_query_step


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def _build(app, out_stream):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out_stream, c)
    return m, rt, c


def _drive_pair(app, out_stream, shard_query, feed):
    """Run `feed(rt)` against unsharded and sharded runtimes; return the
    two sorted output lists."""
    m1, rt1, c1 = _build(app, out_stream)
    feed(rt1)
    m1.shutdown()

    m2, rt2, c2 = _build(app, out_stream)
    mesh = make_mesh(8)
    shard_query_step(rt2.query_runtimes[shard_query], mesh)
    feed(rt2)
    m2.shutdown()
    # identical event order in == identical output order out
    return c1.events, c2.events


def test_sharded_group_by_window_aggregation():
    # BASELINE config #2/#3 family: length window -> group-by avg/sum
    app = """
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.length(16)
        select symbol, avg(price) as ap, sum(volume) as tv
        group by symbol
        insert into Out;
    """
    rng = np.random.default_rng(7)

    def feed(rt):
        h = rt.get_input_handler("S")
        for i in range(120):
            h.send([f"K{int(rng.integers(0, 24)) if False else i % 24}",
                    float(i % 13) + 0.5, int(i)])

    a, b = _drive_pair(app, "Out", "q", feed)
    assert len(a) > 0
    assert [e.data for e in a] == [e.data for e in b]


def test_sharded_partitioned_keyed_window():
    app = """
        @app:playback
        define stream S (k string, v double);
        partition with (k of S)
        begin
          @info(name = 'q')
          from S#window.length(4) select k, sum(v) as s insert into Out;
        end;
    """
    rng = np.random.default_rng(11)

    def feed(rt):
        h = rt.get_input_handler("S")
        for i in range(200):
            h.send(1000 + i, [f"P{int(rng.integers(0, 32))}", float(i % 7)])

    # second runtime must see identical key arrival order: regenerate rng
    def feed2(rt):
        r = np.random.default_rng(11)
        h = rt.get_input_handler("S")
        for i in range(200):
            h.send(1000 + i, [f"P{int(r.integers(0, 32))}", float(i % 7)])

    m1, rt1, c1 = _build(app, "Out")
    feed2(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app, "Out")
    shard_query_step(rt2.query_runtimes["q"], make_mesh(8))
    feed2(rt2)
    m2.shutdown()
    assert len(c1.events) > 0
    assert [e.data for e in c1.events] == [e.data for e in c2.events]


def test_sharded_partitioned_nfa_pattern():
    # BASELINE config #4 family: every A -> B[v > e1.v] within, partitioned
    app = """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        partition with (k of A, k of B)
        begin
          @info(name = 'q')
          from every e1=A -> e2=B[e2.v > e1.v] within 5 sec
          select e1.v as v1, e2.v as v2
          insert into Out;
        end;
    """

    def feed(rt):
        r = np.random.default_rng(3)
        ha = rt.get_input_handler("A")
        hb = rt.get_input_handler("B")
        t = 1000
        for i in range(60):
            k = f"P{int(r.integers(0, 24))}"
            va = float(r.random() * 10)
            ha.send(t, [k, va])
            hb.send(t + 1, [k, va + (1.0 if i % 3 else -1.0)])
            t += 50

    m1, rt1, c1 = _build(app, "Out")
    feed(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app, "Out")
    shard_query_step(rt2.query_runtimes["q"], make_mesh(8))
    feed(rt2)
    m2.shutdown()
    assert len(c1.events) > 0
    assert [e.data for e in c1.events] == [e.data for e in c2.events]


def test_distributed_single_process_cluster():
    """jax.distributed bring-up: a 1-process cluster initializes, the
    global mesh spans its devices, and a sharded query runs over it —
    exercised in a subprocess (distributed init is process-global)."""
    import subprocess
    import sys

    script = r'''
from siddhi_tpu.parallel.mesh import force_host_devices
force_host_devices(4)   # the axon plugin overrides JAX_PLATFORMS env
from siddhi_tpu.parallel.distributed import (
    global_mesh, initialize_cluster, process_info)
initialize_cluster(coordinator_address="127.0.0.1:18476",
                   num_processes=1, process_id=0)
info = process_info()
assert info["process_count"] == 1 and info["global_devices"] == 4, info

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.parallel.mesh import shard_query_step
m = SiddhiManager()
rt = m.create_siddhi_app_runtime("""
    define stream S (sym string, v int);
    @info(name='q')
    from S select sym, sum(v) as s group by sym insert into Out;
""")
seen = []
class C(StreamCallback):
    def receive(self, events):
        seen.extend(tuple(e.data) for e in events)
rt.add_callback("Out", C())
shard_query_step(rt.query_runtimes["q"], global_mesh())
h = rt.get_input_handler("S")
h.send(["a", 1]); h.send(["b", 2]); h.send(["a", 3])
m.shutdown()
assert seen == [("a", 1), ("b", 2), ("a", 4)], seen
print("DIST_OK")
'''
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True, timeout=300,
                       env={**__import__("os").environ,
                            "JAX_COMPILATION_CACHE_DIR": ""})
    assert "DIST_OK" in r.stdout, r.stderr[-2000:]


def test_sharded_partitioned_absent_pattern():
    """Absent deadlines + scheduler TIMER sweeps over key-sharded [K, S]
    NFA state must match the unsharded run."""
    app = """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        partition with (k of A, k of B)
        begin
          @info(name = 'q')
          from every e1=A -> not B[v > e1.v] for 200 milliseconds
          select e1.v as v1
          insert into Out;
        end;
    """

    def feed(rt):
        r = np.random.default_rng(9)
        ha = rt.get_input_handler("A")
        hb = rt.get_input_handler("B")
        t = 1000
        for i in range(50):
            k = f"P{int(r.integers(0, 12))}"
            va = float(int(r.random() * 10))
            ha.send(t, [k, va])
            if i % 3 == 0:
                hb.send(t + 50, [k, va + 1.0])   # violates that key's wait
            t += 120   # advances past earlier deadlines -> timer sweeps
        ha.send(t + 1000, ["PX", 0.0])           # final clock advance

    m1, rt1, c1 = _build(app, "Out")
    feed(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app, "Out")
    shard_query_step(rt2.query_runtimes["q"], make_mesh(8))
    feed(rt2)
    m2.shutdown()
    assert len(c1.events) > 0
    assert [e.data for e in c1.events] == [e.data for e in c2.events]


def test_shard_map_routed_keyed_window_matches_unsharded():
    """Round-5 zero-collective path: host router + shard_map over local
    [K/n] keyed state must reproduce the unsharded per-key output
    sequences exactly (tools/hlo_audit.py separately asserts the compiled
    HLO carries no collectives)."""
    import jax

    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.ops.expressions import PK_KEY, TS_KEY, TYPE_KEY, VALID_KEY
    from siddhi_tpu.parallel.mesh import (
        route_batch_to_shards, shard_keyed_query_step)

    APP = """
        define stream S (symbol string, price float, volume long);
        partition with (symbol of S)
        begin
          @info(name = 'q')
          from S#window.length(8)
          select symbol, avg(price) as ap, sum(volume) as tv
          insert into Out;
        end;
    """
    NUM_KEYS, B, N = 40, 64, 8
    rng = np.random.default_rng(0)

    def make_batch(i):
        sym = rng.integers(0, NUM_KEYS, B, dtype=np.int64)
        return {
            TS_KEY: np.arange(i * B, (i + 1) * B, dtype=np.int64),
            TYPE_KEY: np.zeros(B, np.int8),
            VALID_KEY: np.ones(B, bool),
            "symbol": sym, "symbol?": np.zeros(B, bool),
            "price": (rng.random(B) * 100).astype(np.float32),
            "price?": np.zeros(B, bool),
            "volume": rng.integers(1, 1000, B, np.int64),
            "volume?": np.zeros(B, bool),
            GK_KEY: sym.astype(np.int32), PK_KEY: sym.astype(np.int32),
        }

    batches = [make_batch(i) for i in range(3)]

    def collect(outs, n_shards=None):
        rows = {}
        for out in outs:
            v = np.asarray(out[VALID_KEY])
            pk = np.asarray(out[PK_KEY])
            r_local = len(v) // (n_shards or 1)
            for j in np.nonzero(v)[0]:
                k = int(pk[j])
                if n_shards is not None:
                    k = k * n_shards + j // r_local  # local id -> global
                rows.setdefault(k, []).append((
                    int(out[TS_KEY][j]), int(out[TYPE_KEY][j]),
                    round(float(out["ap"][j]), 3), int(out["tv"][j])))
        return rows

    m1 = SiddhiManager()
    rt1 = m1.create_siddhi_app_runtime(APP)
    rt1.start()
    q1 = rt1.query_runtimes["q"]
    q1.selector_plan.num_keys = 64
    q1._win_keys = 64
    state = q1._init_state()
    step = jax.jit(q1.build_step_fn())
    uns = []
    for i, b in enumerate(batches):
        state, out = step(state, b, np.int64(10_000 + i))
        uns.append(jax.device_get(out))
    m1.shutdown()

    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(APP)
    rt2.start()
    q2 = rt2.query_runtimes["q"]
    q2.selector_plan.num_keys = 16   # local capacity: ceil(40/8) -> 16
    q2._win_keys = 16
    sstep, sstate = shard_keyed_query_step(q2, make_mesh(8), rows_per_shard=B)
    sh = []
    for i, b in enumerate(batches):
        rb = route_batch_to_shards(b, 8, B)
        sstate, out = sstep(sstate, rb, np.int64(10_000 + i))
        sh.append(jax.device_get(out))
    m2.shutdown()

    u, s = collect(uns), collect(sh, n_shards=8)
    assert set(u) == set(s)
    assert all(u[k] == s[k] for k in u)


def test_route_batch_overflow_raises():
    """Round-6: the legacy host router's overflow follows the
    FatalQueryError + knob-naming convention (it used to die with a bare
    ValueError), and the router itself is a deprecated shim."""
    import warnings

    import pytest

    from siddhi_tpu.core.plan.selector_plan import GK_KEY
    from siddhi_tpu.core.stream.junction import FatalQueryError
    from siddhi_tpu.ops.expressions import PK_KEY, VALID_KEY
    from siddhi_tpu.parallel.mesh import route_batch_to_shards

    cols = {PK_KEY: np.zeros(16, np.int32), GK_KEY: np.zeros(16, np.int32),
            VALID_KEY: np.ones(16, bool)}
    with warnings.catch_warnings():
        warnings.simplefilter("error", DeprecationWarning)
        with pytest.raises(DeprecationWarning):
            route_batch_to_shards(cols, 4, 16)   # shim warns
    with pytest.raises(FatalQueryError, match="rows_per_shard"):
        route_batch_to_shards(cols, 4, 2)  # 16 rows all on shard 0 > 2
