"""Sharded == unsharded equivalence on the virtual 8-device CPU mesh.

Drives the same event sequences through an unsharded app and one whose
query state is sharded over the key axis (``parallel/mesh.py``), asserting
identical outputs — the suite-level guarantee behind ``dryrun_multichip``
(SURVEY.md §2.13: key-space sharding over ICI).
"""

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.parallel.mesh import make_mesh, shard_query_step


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def _build(app, out_stream):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out_stream, c)
    return m, rt, c


def _drive_pair(app, out_stream, shard_query, feed):
    """Run `feed(rt)` against unsharded and sharded runtimes; return the
    two sorted output lists."""
    m1, rt1, c1 = _build(app, out_stream)
    feed(rt1)
    m1.shutdown()

    m2, rt2, c2 = _build(app, out_stream)
    mesh = make_mesh(8)
    shard_query_step(rt2.query_runtimes[shard_query], mesh)
    feed(rt2)
    m2.shutdown()
    # identical event order in == identical output order out
    return c1.events, c2.events


def test_sharded_group_by_window_aggregation():
    # BASELINE config #2/#3 family: length window -> group-by avg/sum
    app = """
        define stream S (symbol string, price double, volume long);
        @info(name = 'q')
        from S#window.length(16)
        select symbol, avg(price) as ap, sum(volume) as tv
        group by symbol
        insert into Out;
    """
    rng = np.random.default_rng(7)

    def feed(rt):
        h = rt.get_input_handler("S")
        for i in range(120):
            h.send([f"K{int(rng.integers(0, 24)) if False else i % 24}",
                    float(i % 13) + 0.5, int(i)])

    a, b = _drive_pair(app, "Out", "q", feed)
    assert len(a) > 0
    assert [e.data for e in a] == [e.data for e in b]


def test_sharded_partitioned_keyed_window():
    app = """
        @app:playback
        define stream S (k string, v double);
        partition with (k of S)
        begin
          @info(name = 'q')
          from S#window.length(4) select k, sum(v) as s insert into Out;
        end;
    """
    rng = np.random.default_rng(11)

    def feed(rt):
        h = rt.get_input_handler("S")
        for i in range(200):
            h.send(1000 + i, [f"P{int(rng.integers(0, 32))}", float(i % 7)])

    # second runtime must see identical key arrival order: regenerate rng
    def feed2(rt):
        r = np.random.default_rng(11)
        h = rt.get_input_handler("S")
        for i in range(200):
            h.send(1000 + i, [f"P{int(r.integers(0, 32))}", float(i % 7)])

    m1, rt1, c1 = _build(app, "Out")
    feed2(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app, "Out")
    shard_query_step(rt2.query_runtimes["q"], make_mesh(8))
    feed2(rt2)
    m2.shutdown()
    assert len(c1.events) > 0
    assert [e.data for e in c1.events] == [e.data for e in c2.events]


def test_sharded_partitioned_nfa_pattern():
    # BASELINE config #4 family: every A -> B[v > e1.v] within, partitioned
    app = """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        partition with (k of A, k of B)
        begin
          @info(name = 'q')
          from every e1=A -> e2=B[e2.v > e1.v] within 5 sec
          select e1.v as v1, e2.v as v2
          insert into Out;
        end;
    """

    def feed(rt):
        r = np.random.default_rng(3)
        ha = rt.get_input_handler("A")
        hb = rt.get_input_handler("B")
        t = 1000
        for i in range(60):
            k = f"P{int(r.integers(0, 24))}"
            va = float(r.random() * 10)
            ha.send(t, [k, va])
            hb.send(t + 1, [k, va + (1.0 if i % 3 else -1.0)])
            t += 50

    m1, rt1, c1 = _build(app, "Out")
    feed(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app, "Out")
    shard_query_step(rt2.query_runtimes["q"], make_mesh(8))
    feed(rt2)
    m2.shutdown()
    assert len(c1.events) > 0
    assert [e.data for e in c1.events] == [e.data for e in c2.events]


def test_distributed_single_process_cluster():
    """jax.distributed bring-up: a 1-process cluster initializes, the
    global mesh spans its devices, and a sharded query runs over it —
    exercised in a subprocess (distributed init is process-global)."""
    import subprocess
    import sys

    script = r'''
from siddhi_tpu.parallel.mesh import force_host_devices
force_host_devices(4)   # the axon plugin overrides JAX_PLATFORMS env
from siddhi_tpu.parallel.distributed import (
    global_mesh, initialize_cluster, process_info)
initialize_cluster(coordinator_address="127.0.0.1:18476",
                   num_processes=1, process_id=0)
info = process_info()
assert info["process_count"] == 1 and info["global_devices"] == 4, info

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.parallel.mesh import shard_query_step
m = SiddhiManager()
rt = m.create_siddhi_app_runtime("""
    define stream S (sym string, v int);
    @info(name='q')
    from S select sym, sum(v) as s group by sym insert into Out;
""")
seen = []
class C(StreamCallback):
    def receive(self, events):
        seen.extend(tuple(e.data) for e in events)
rt.add_callback("Out", C())
shard_query_step(rt.query_runtimes["q"], global_mesh())
h = rt.get_input_handler("S")
h.send(["a", 1]); h.send(["b", 2]); h.send(["a", 3])
m.shutdown()
assert seen == [("a", 1), ("b", 2), ("a", 4)], seen
print("DIST_OK")
'''
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True, timeout=300,
                       env={**__import__("os").environ,
                            "JAX_COMPILATION_CACHE_DIR": "/root/repo/.jax_cache"})
    assert "DIST_OK" in r.stdout, r.stderr[-2000:]


def test_sharded_partitioned_absent_pattern():
    """Absent deadlines + scheduler TIMER sweeps over key-sharded [K, S]
    NFA state must match the unsharded run."""
    app = """
        @app:playback
        define stream A (k string, v double);
        define stream B (k string, v double);
        partition with (k of A, k of B)
        begin
          @info(name = 'q')
          from every e1=A -> not B[v > e1.v] for 200 milliseconds
          select e1.v as v1
          insert into Out;
        end;
    """

    def feed(rt):
        r = np.random.default_rng(9)
        ha = rt.get_input_handler("A")
        hb = rt.get_input_handler("B")
        t = 1000
        for i in range(50):
            k = f"P{int(r.integers(0, 12))}"
            va = float(int(r.random() * 10))
            ha.send(t, [k, va])
            if i % 3 == 0:
                hb.send(t + 50, [k, va + 1.0])   # violates that key's wait
            t += 120   # advances past earlier deadlines -> timer sweeps
        ha.send(t + 1000, ["PX", 0.0])           # final clock advance

    m1, rt1, c1 = _build(app, "Out")
    feed(rt1)
    m1.shutdown()
    m2, rt2, c2 = _build(app, "Out")
    shard_query_step(rt2.query_runtimes["q"], make_mesh(8))
    feed(rt2)
    m2.shutdown()
    assert len(c1.events) > 0
    assert [e.data for e in c1.events] == [e.data for e in c2.events]
