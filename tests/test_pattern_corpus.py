"""Reference pattern-corpus differential: scenarios ported verbatim
(inputs AND expected outputs) from the reference test suite —
``query/pattern/CountPatternTestCase.java`` (Q1-Q8 count accumulation and
``e1[i]`` nulls, Q17-Q20 every-count with `within` expiry, the
not-and tail at :886, the unbounded-min login pipeline at :1319) and
``query/pattern/EveryPatternTestCase.java`` (grouped every chains).
Thread.sleep pacing becomes explicit playback timestamps.

These pin exactly the multi-pending shapes the dense-slot NFA's
"furthest-advanced transition wins" policy could diverge on.
"""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutputStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


TWO_STREAMS = """@app:playback
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
"""

COUNT_25 = TWO_STREAMS + """
    from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>20]
    select e1[0].price as p0, e1[1].price as p1, e1[2].price as p2,
           e1[3].price as p3, e2.price as p4
    insert into OutputStream;
"""


def _rows(c):
    # 'float' attrs are float32: round back to the literal's precision
    return [tuple(round(v, 4) if isinstance(v, float) else v
                  for v in e.data) for e in c.events]


def test_count_q1_accumulate_with_filter_gap():
    # CountPatternTestCase.testQuery1: filtered-out A leaves a null slot gap
    m, rt, c = build(COUNT_25)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["WSO2", 25.6, 100]); t += 100
    s1.send(t, ["GOOG", 47.6, 100]); t += 100
    s1.send(t, ["GOOG", 13.7, 100]); t += 100   # fails e1 filter
    s1.send(t, ["GOOG", 47.8, 100]); t += 100
    s2.send(t, ["IBM", 45.7, 100]); t += 100    # match
    s2.send(t, ["IBM", 55.7, 100]); t += 100    # no pending AA: no match
    m.shutdown()
    assert _rows(c) == [(25.6, 47.6, 47.8, None, 45.7)]


def test_count_q2_b_mid_accumulation_matches_then_rearms_partially():
    # testQuery2: B after 2 As matches; a single further A cannot reach min
    m, rt, c = build(COUNT_25)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["WSO2", 25.6, 100]); t += 100
    s1.send(t, ["GOOG", 47.6, 100]); t += 100
    s1.send(t, ["GOOG", 13.7, 100]); t += 100
    s2.send(t, ["IBM", 45.7, 100]); t += 100    # match {25.6, 47.6}
    s1.send(t, ["GOOG", 47.8, 100]); t += 100
    s2.send(t, ["IBM", 55.7, 100]); t += 100    # count 1 < min 2: no match
    m.shutdown()
    assert _rows(c) == [(25.6, 47.6, None, None, 45.7)]


def test_count_q3_below_min_b_skipped_accumulation_continues():
    # testQuery3: B while count<min does not kill the pattern (not a
    # sequence); accumulation continues and the NEXT B matches
    m, rt, c = build(COUNT_25)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["WSO2", 25.6, 100]); t += 100
    s2.send(t, ["IBM", 45.7, 100]); t += 100    # count 1 < 2: skipped
    s1.send(t, ["GOOG", 47.8, 100]); t += 100
    s2.send(t, ["IBM", 55.7, 100]); t += 100    # match {25.6, 47.8}
    m.shutdown()
    assert _rows(c) == [(25.6, 47.8, None, None, 55.7)]


def test_count_q5_max_stops_absorbing():
    # testQuery5: the 6th/7th A beyond max 5 are not absorbed; match shows
    # the FIRST four captures
    m, rt, c = build(COUNT_25)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    for p in (25.6, 47.6, 23.7, 24.7, 25.7, 27.6):
        s1.send(t, ["WSO2", p, 100]); t += 100
    s2.send(t, ["IBM", 45.7, 100]); t += 100    # match, captures first 5
    s1.send(t, ["GOOG", 47.8, 100]); t += 100
    s2.send(t, ["IBM", 55.7, 100]); t += 100
    m.shutdown()
    assert _rows(c)[0] == (25.6, 47.6, 23.7, 24.7, 45.7)


def test_count_q6_e2_filter_on_indexed_capture_failing_b_skipped():
    # testQuery6: e2 references e1[1].price; a failing B does NOT kill
    m, rt, c = build(TWO_STREAMS + """
        from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>e1[1].price]
        select e1[0].price as p0, e1[1].price as p1, e2.price as p2
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["WSO2", 25.6, 100]); t += 100
    s1.send(t, ["GOOG", 47.6, 100]); t += 100
    s2.send(t, ["IBM", 45.7, 100]); t += 100    # 45.7 < 47.6: skipped
    s2.send(t, ["IBM", 55.7, 100]); t += 100    # match
    m.shutdown()
    assert _rows(c) == [(25.6, 47.6, 55.7)]


def test_count_q7_min_zero_b_alone_matches():
    # testQuery7: <0:5> start state is skippable
    m, rt, c = build(TWO_STREAMS + """
        from e1=Stream1[price>20] <0:5> -> e2=Stream2[price>20]
        select e1[0].price as p0, e1[1].price as p1, e2.price as p2
        insert into OutputStream;
    """)
    s2 = rt.get_input_handler("Stream2")
    s2.send(1000, ["IBM", 45.7, 100])
    m.shutdown()
    assert _rows(c) == [(None, None, 45.7)]


def test_count_q8_min_zero_with_capture_reference():
    # testQuery8: one A absorbed, one filtered out; e2 compares to e1[0]
    m, rt, c = build(TWO_STREAMS + """
        from e1=Stream1[price>20] <0:5> -> e2=Stream2[price>e1[0].price]
        select e1[0].price as p0, e1[1].price as p1, e2.price as p2
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["WSO2", 25.6, 100]); t += 100
    s1.send(t, ["GOOG", 7.6, 100]); t += 100    # fails filter
    s2.send(t, ["IBM", 45.7, 100]); t += 100    # 45.7 > 25.6: match
    m.shutdown()
    assert _rows(c) == [(25.6, None, 45.7)]


IN_STREAM = "@app:playback define stream InputStream (name string);\n"
EVERY_A2_B = IN_STREAM + """
    from every e1=InputStream[(e1.name == 'A')]<2>
       -> e2=InputStream[(e2.name == 'B')]
       within 3 seconds
    select 'rule1' as ruleId, count() as numOfEvents
    insert into OutputStream;
"""


def _feed(h, t, names, step=100):
    for n in names:
        if n == "|":        # 4-second clock jump (Thread.sleep(4000))
            t += 4000
            continue
        h.send(t, [n])
        t += step
    return t


def test_count_q17_every_exact2_within():
    # testQuery17: AABB AABB A |sleep4s| ABB AABB -> 3 matches
    m, rt, c = build(EVERY_A2_B)
    h = rt.get_input_handler("InputStream")
    _feed(h, 1000, list("AABBAABB") + ["A", "|"] + list("ABBAABB"))
    m.shutdown()
    assert len(c.events) == 3


def test_count_q18_every_exact2_within_extra_bs():
    # testQuery18: AABBB AABB A |4s| ABB AABB -> 3 matches
    m, rt, c = build(EVERY_A2_B)
    h = rt.get_input_handler("InputStream")
    _feed(h, 1000, list("AABBB") + list("AABB") + ["A", "|"]
          + list("ABB") + list("AABB"))
    m.shutdown()
    assert len(c.events) == 3


def test_count_q19_every_exact2_within_four_matches():
    # testQuery19: AABBBB AABB A |4s| ABB AAB AABB -> 4 matches
    m, rt, c = build(EVERY_A2_B)
    h = rt.get_input_handler("InputStream")
    _feed(h, 1000, list("AABBBB") + list("AABB") + ["A", "|"]
          + list("ABB") + list("AAB") + list("AABB"))
    m.shutdown()
    assert len(c.events) == 4


def test_count_q20_non_every_rearms_after_completion_and_expiry():
    # testQuery20 (NON-every): AABB BB AB |4s| B AABB -> 2 matches — the
    # start state re-initializes after a completed match AND after a
    # within-expiry ("AA are not consumed after within time period")
    m, rt, c = build(IN_STREAM + """
        from e1=InputStream[(e1.name == 'A')]<2>
           -> e2=InputStream[(e2.name == 'B')]
           within 3 seconds
        select 'rule1' as ruleId, count() as numOfEvents
        insert into OutputStream;
    """)
    h = rt.get_input_handler("InputStream")
    _feed(h, 1000, list("AABB") + list("BB") + list("AB") + ["|"]
          + ["B"] + list("AABB"))
    m.shutdown()
    assert len(c.events) == 2


def test_count_mid_chain_count_then_not_and():
    # CountPatternTestCase:886 — every e1 -> e2<2> -> not ... and e3
    m, rt, c = build(TWO_STREAMS + """
        from every e1=Stream1[price>20] -> e2=Stream1[price>20]<2>
           -> not Stream1[price>20] and e3=Stream2
        select e1.price as p0, e2[0].price as p1, e2[1].price as p2,
               e2[2].price as p3, e3.price as p4
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["WSO2", 25.6, 100]); t += 100
    s1.send(t, ["WSO2", 23.6, 100]); t += 100
    s1.send(t, ["WSO2", 23.6, 100]); t += 100
    s1.send(t, ["GOOG", 27.6, 100]); t += 100
    s1.send(t, ["GOOG", 28.6, 100]); t += 100
    s2.send(t, ["IBM", 45.7, 100]); t += 100
    m.shutdown()
    assert len(c.events) == 1
    assert _rows(c)[0] == (23.6, 27.6, 28.6, None, 45.7)


LOGIN = """@app:playback
    define stream LoginFailure (id string, user string, type string);
    define stream LoginSuccess (id string, user string, type string);
    from every (e1=LoginFailure<3:> -> e2=LoginSuccess)
    select e1[0].id as id, e2.user as user
    insert into OutputStream;
"""


def test_count_unbounded_min_login_pipeline():
    # CountPatternTestCase:1319 — min-3 unbounded accumulation, every
    # group re-arms after each completed match
    m, rt, c = build(LOGIN)
    f = rt.get_input_handler("LoginFailure")
    s = rt.get_input_handler("LoginSuccess")
    now = 1000
    for i in range(1, 7):
        now += 1; f.send(now, [f"id_{i}", "hans", "failure"])
    now += 1; s.send(now, ["id_7", "hans", "success"])
    for i in range(8, 16):
        now += 1; f.send(now, [f"id_{i}", "werner", "failure"])
    now += 1; s.send(now, ["id_16", "werner", "success"])
    for i in range(17, 20):
        now += 1; f.send(now, [f"id_{i}", "hans", "failure"])
    now += 1; s.send(now, ["id_20", "hans", "success"])
    m.shutdown()
    got = _rows(c)
    assert got == [("id_1", "hans"), ("id_8", "werner"), ("id_17", "hans")]


EVENT_STREAM = ("@app:playback define stream EventStream "
                "(symbol string, price float, volume int);\n")


def test_count_q10_ambiguous_event_advances_not_absorbs():
    # testQuery10/11: GOOG matches BOTH the e2 count absorb and e3 —
    # the reference takes the ADVANCE (the dense-slot "furthest-advanced
    # transition wins" policy is reference-faithful here): one match with
    # an EMPTY e2, and no second match from an absorb fork
    m, rt, c = build(EVENT_STREAM + """
        from e1 = EventStream[price >= 50 and volume > 100]
          -> e2 = EventStream[price <= 40] <:5>
          -> e3 = EventStream[volume <= 70]
        select e1.symbol as s1, e2[0].symbol as s2, e3.symbol as s3
        insert into OutputStream;
    """)
    h = rt.get_input_handler("EventStream")
    t = 1000
    h.send(t, ["IBM", 75.6, 105]); t += 100
    h.send(t, ["GOOG", 21.0, 61]); t += 100   # matches e2 AND e3
    h.send(t, ["WSO2", 21.0, 61]); t += 100
    m.shutdown()
    assert _rows(c) == [("IBM", None, "GOOG")]


def test_count_q12_last_indexing():
    # testQuery12: e2[last] reads the final collected occurrence
    m, rt, c = build(EVENT_STREAM + """
        from e1 = EventStream[price >= 50 and volume > 100]
          -> e2 = EventStream[price <= 40] <:5>
          -> e3 = EventStream[volume <= 70]
        select e1.symbol as s1, e2[last].symbol as s2, e3.symbol as s3
        insert into OutputStream;
    """)
    h = rt.get_input_handler("EventStream")
    t = 1000
    h.send(t, ["IBM", 75.6, 105]); t += 100
    h.send(t, ["GOOG", 21.0, 91]); t += 100   # absorbed (vol 91 > 70)
    h.send(t, ["FB", 21.0, 81]); t += 100     # absorbed
    h.send(t, ["WSO2", 21.0, 61]); t += 100   # advances e3
    m.shutdown()
    assert _rows(c) == [("IBM", "FB", "WSO2")]


# --------------------------------------------------- EveryPatternTestCase


def test_every_single_state_emits_per_match():
    # EveryPatternTestCase:488 — `every e1=S[price>20]` alone
    m, rt, c = build(TWO_STREAMS + """
        from every e1=Stream1[price>20]
        select e1.price as p1 insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(1000, ["MSFT", 55.6, 100])
    s1.send(1100, ["WSO2", 57.6, 100])
    m.shutdown()
    assert _rows(c) == [(55.6,), (57.6,)]


def test_every_duplicate_ref_id_resolves_first_capture():
    # EveryPatternTestCase:549 — `every e1=[MSFT] -> e1=[WSO2]` reuses
    # one reference id; the select's e1 reads the FIRST state's capture
    # (reference expects the MSFT prices, one per pending chain)
    m, rt, c = build(TWO_STREAMS + """
        from every e1=Stream1[symbol == 'MSFT'] -> e1=Stream1[symbol == 'WSO2']
        select e1.price as p1 insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(1000, ["MSFT", 55.6, 100])
    s1.send(1100, ["MSFT", 77.6, 100])
    s1.send(1200, ["WSO2", 57.6, 100])
    m.shutdown()
    assert sorted(_rows(c)) == [(55.6,), (77.6,)]


def test_every_group_chain_restarts_per_group():
    # EveryPatternTestCase:227 — every (e1 -> e3) -> e2[price > e1.price]
    m, rt, c = build(TWO_STREAMS + """
        from every (e1=Stream1[price>20] -> e3=Stream1[price>20])
           -> e2=Stream2[price>e1.price]
        select e1.price as p1, e3.price as p3, e2.price as p2
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["WSO2", 55.6, 100]); t += 100
    s1.send(t, ["GOOG", 54.0, 100]); t += 100
    s2.send(t, ["IBM", 57.7, 100]); t += 100
    m.shutdown()
    assert _rows(c) == [(55.6, 54.0, 57.7)]


def test_every_group_two_rounds():
    # EveryPatternTestCase:282 — two grouped rounds, one e2 closes both
    m, rt, c = build(TWO_STREAMS + """
        from every (e1=Stream1[price>20] -> e3=Stream1[price>20])
           -> e2=Stream2[price>e1.price]
        select e1.price as p1, e3.price as p3, e2.price as p2
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["WSO2", 55.6, 100]); t += 100
    s1.send(t, ["GOOG", 54.0, 100]); t += 100
    s1.send(t, ["WSO2", 53.6, 100]); t += 100
    s1.send(t, ["GOOG", 53.0, 100]); t += 100
    s2.send(t, ["IBM", 57.7, 100]); t += 100
    m.shutdown()
    got = _rows(c)
    assert sorted(got) == sorted([(55.6, 54.0, 57.7), (53.6, 53.0, 57.7)])


def test_lead_then_every_group():
    # EveryPatternTestCase:351 — e4=MSFT -> every (e1 -> e3) -> e2
    m, rt, c = build(TWO_STREAMS + """
        from e4=Stream1[symbol=='MSFT'] ->
             every (e1=Stream1[price>20] -> e3=Stream1[price>20])
           -> e2=Stream2[price>e1.price]
        select e4.price as p4, e1.price as p1, e3.price as p3,
               e2.price as p2
        insert into OutputStream;
    """)
    s1, s2 = rt.get_input_handler("Stream1"), rt.get_input_handler("Stream2")
    t = 1000
    s1.send(t, ["MSFT", 55.6, 100]); t += 100
    s1.send(t, ["WSO2", 55.7, 100]); t += 100
    s1.send(t, ["GOOG", 54.0, 100]); t += 100
    s1.send(t, ["WSO2", 53.6, 100]); t += 100
    s1.send(t, ["GOOG", 53.0, 100]); t += 100
    s2.send(t, ["IBM", 57.7, 100]); t += 100
    m.shutdown()
    got = _rows(c)
    assert sorted(got) == sorted([(55.6, 55.7, 54.0, 57.7),
                                  (55.6, 53.6, 53.0, 57.7)])
