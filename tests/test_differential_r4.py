"""Differential harness round 4: random traces over this round's features
— stream functions, post-window filters, every-count patterns, and keyed
externalTime / timeLength windows — vs plain-Python reference models."""

import collections
import math

import numpy as np

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.query.callback import QueryCallback


class SCollect(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend(tuple(e.data) for e in events)


def _run_engine_stream(app, sends, out="Out"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = SCollect()
    rt.add_callback(out, c)
    handlers = {}
    for ts, sid, row in sends:
        h = handlers.get(sid)
        if h is None:
            h = handlers[sid] = rt.get_input_handler(sid)
        if ts is None:
            h.send(row)
        else:
            h.send(ts, row)
    m.shutdown()
    return c.rows


class QCollect(QueryCallback):
    def __init__(self):
        self.rows = []   # (kind, tuple) in arrival order

    def receive(self, timestamp, in_events, remove_events):
        for e in in_events or []:
            self.rows.append(("in", tuple(e.data)))
        for e in remove_events or []:
            self.rows.append(("rm", tuple(e.data)))


def _run_engine(app, sends, qname="q"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback(qname, q)
    handlers = {}
    for ts, sid, row in sends:
        h = handlers.get(sid)
        if h is None:
            h = handlers[sid] = rt.get_input_handler(sid)
        if ts is None:
            h.send(row)
        else:
            h.send(ts, row)
    m.shutdown()
    return q.rows


def test_differential_pol2cart_filter_window_sum():
    rng = np.random.default_rng(7)
    sends = []
    for _ in range(200):
        theta = float(rng.choice([0.0, 30.0, 90.0, 150.0, 210.0, 330.0]))
        rho = float(rng.integers(1, 5))
        sends.append((None, "P", [theta, rho]))
    app = """
        define stream P (theta double, rho double);
        @info(name='q')
        from P#pol2Cart(theta, rho)[y > 0.0]#window.length(5)
        select sum(y) as total insert into Out;
    """
    got = _run_engine(app, sends)
    dq = collections.deque()
    model = []
    for _, _, (theta, rho) in sends:
        y = rho * math.sin(math.radians(theta))
        if y <= 0:
            continue
        dq.append(y)
        if len(dq) > 5:
            dq.popleft()
        model.append(("in", (sum(dq),)))
    assert len(got) == len(model)
    for (gk, gv), (mk, mv) in zip(got, model):
        assert gk == mk and abs(gv[0] - mv[0]) < 1e-9


def test_differential_post_window_filter_all_events():
    rng = np.random.default_rng(11)
    sends = [(None, "S", [int(rng.integers(-50, 50))]) for _ in range(300)]
    app = """
        define stream S (v int);
        @info(name='q')
        from S#window.length(4)[v > 0]
        select v insert all events into Out;
    """
    got = _run_engine(app, sends)
    dq = collections.deque()
    model = []
    for _, _, (v,) in sends:
        # QueryCallback groups each chunk's in-events before remove-events
        rm = None
        if len(dq) == 4:
            ev = dq.popleft()
            if ev > 0:
                rm = ("rm", (ev,))
        dq.append(v)
        if v > 0:
            model.append(("in", (v,)))
        if rm is not None:
            model.append(rm)
    assert got == model


def test_differential_every_count_tail():
    rng = np.random.default_rng(13)
    ts, sends, names = 1000, [], []
    for _ in range(120):
        ts += int(rng.integers(1, 40))
        n = str(rng.choice(["A", "B"]))
        names.append((ts, n))
        sends.append((ts, "In", [n]))
    app = """
        @app:playback define stream In (name string);
        @info(name='q')
        from e1=In[name == 'A']<2:2> -> every e2=In[name == 'B']<2:2>
        select e2[0].name as n0, e2[1].name as n1 insert into Out;
    """
    got = _run_engine(app, sends)
    # model: first two A's arm; afterwards every non-overlapping B pair emits
    a_seen, b_in_group, armed = 0, 0, False
    model = []
    for _ts, n in names:
        if not armed:
            if n == "A":
                a_seen += 1
                if a_seen == 2:
                    armed = True
        elif n == "B":
            b_in_group += 1
            if b_in_group == 2:
                model.append(("in", ("B", "B")))
                b_in_group = 0
    assert got == model


def test_differential_keyed_external_time():
    rng = np.random.default_rng(17)
    T = 400
    ts, sends = 1000, []
    for _ in range(250):
        ts += int(rng.integers(1, 90))
        sends.append((ts, "S", [f"k{int(rng.integers(0, 4))}", ts,
                                int(rng.integers(1, 9))]))
    app = f"""
        @app:playback define stream S (sym string, ets long, v int);
        partition with (sym of S) begin
        from S#window.externalTime(ets, {T} milliseconds)
        select sym, sum(v) as total insert into Out; end;
    """
    got = _run_engine_stream(app, sends)
    held = collections.defaultdict(collections.deque)
    model = []
    for ts_i, _sid, (sym, _ets, v) in sends:
        d = held[sym]
        while d and d[0][0] + T <= ts_i:   # key's own clock advance
            d.popleft()
        d.append((ts_i, v))
        model.append((sym, sum(x for _, x in d)))
    assert got == model


def test_differential_keyed_timelength():
    rng = np.random.default_rng(23)
    T, L = 600, 3
    ts, sends = 1000, []
    for _ in range(250):
        ts += int(rng.integers(1, 60))
        sends.append((ts, "S", [f"k{int(rng.integers(0, 4))}",
                                int(rng.integers(1, 9))]))
    app = f"""
        @app:playback define stream S (sym string, v int);
        partition with (sym of S) begin
        from S#window.timeLength({T} milliseconds, {L})
        select sym, sum(v) as total insert into Out; end;
    """
    got = _run_engine_stream(app, sends)
    held = collections.defaultdict(collections.deque)
    model = []
    for ts_i, _sid, (sym, v) in sends:
        for d in held.values():            # shared live clock
            while d and d[0][0] + T <= ts_i:
                d.popleft()
        d = held[sym]
        d.append((ts_i, v))
        if len(d) > L:
            d.popleft()
        model.append((sym, sum(x for _, x in d)))
    assert got == model
