"""Differential harness: random event traces replayed through the engine
AND through plain-Python reference models, outputs compared exactly.

This is the parity mechanism SURVEY.md §4 calls for: instead of porting
the reference's 103k-LoC behavioral corpus, the engine's compiled device
pipelines are checked event-for-event against trivially-auditable Python
models (deque windows, dict group states) over randomized traces — shapes,
values, key skew, and interleavings the hand-written tests don't reach.
"""

import collections

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.query.callback import QueryCallback


class QCollect(QueryCallback):
    def __init__(self):
        self.rows = []   # (kind, tuple) in arrival order

    def receive(self, timestamp, in_events, remove_events):
        for e in in_events or []:
            self.rows.append(("in", tuple(e.data)))
        for e in remove_events or []:
            self.rows.append(("rm", tuple(e.data)))


def _run_engine(app, sends):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback("q", q)
    handlers = {}
    for ts, sid, row in sends:
        h = handlers.get(sid)
        if h is None:
            h = handlers[sid] = rt.get_input_handler(sid)
        if ts is None:
            h.send(row)
        else:
            h.send(ts, row)
    m.shutdown()
    return q.rows


def test_differential_filter_projection():
    rng = np.random.default_rng(0)
    sends = [(None, "S", [f"k{int(rng.integers(0, 5))}",
                          float(np.round(rng.normal() * 50, 3)),
                          int(rng.integers(-100, 100))])
             for _ in range(300)]
    app = """
        define stream S (sym string, price double, v int);
        @info(name='q')
        from S[price > 0.0 and v != 0]
        select sym, price * 2.0 as p2, v + 1 as v1
        insert into Out;
    """
    got = _run_engine(app, sends)
    model = [("in", (sym, price * 2.0, v + 1))
             for _ts, _sid, (sym, price, v) in sends
             if price > 0.0 and v != 0]
    assert got == model


def test_differential_length_window_group_sum_avg():
    rng = np.random.default_rng(1)
    W = 7
    sends = [(None, "S", [f"k{int(rng.integers(0, 4))}",
                          float(int(rng.integers(1, 50)))])
             for _ in range(400)]
    app = f"""
        define stream S (sym string, price double);
        @info(name='q')
        from S#window.length({W})
        select sym, sum(price) as s, avg(price) as a, count() as n
        group by sym
        insert into Out;
    """
    got = _run_engine(app, sends)
    # model: sliding window of last W events; per-event CURRENT emission
    # carries the group's running aggregates AFTER insert+evict
    win = collections.deque()
    sums = collections.defaultdict(float)
    cnts = collections.defaultdict(int)
    model = []
    for _ts, _sid, (sym, price) in sends:
        win.append((sym, price))
        sums[sym] += price
        cnts[sym] += 1
        if len(win) > W:
            esym, eprice = win.popleft()
            sums[esym] -= eprice
            cnts[esym] -= 1
        model.append(("in", (sym, sums[sym],
                             sums[sym] / cnts[sym] if cnts[sym] else None,
                             cnts[sym])))
    assert len(got) == len(model)
    for (gk, gv), (mk, mv) in zip(got, model):
        assert gk == mk and gv[0] == mv[0] and gv[3] == mv[3]
        assert gv[1] == pytest.approx(mv[1], abs=1e-6)
        assert gv[2] == pytest.approx(mv[2], abs=1e-6)


def test_differential_time_window_playback():
    rng = np.random.default_rng(2)
    T = 500
    ts = 1000
    sends = []
    for _ in range(250):
        ts += int(rng.integers(0, 120))
        sends.append((ts, "S", [f"k{int(rng.integers(0, 3))}",
                                float(int(rng.integers(1, 9)))]))
    app = f"""
        @app:playback
        define stream S (sym string, v double);
        @info(name='q')
        from S#window.time({T} milliseconds)
        select sym, v insert all events into Out;
    """
    got = _run_engine(app, sends)
    # model: CURRENT on arrival; EXPIRED when a later arrival advances the
    # clock past ts+T (lazy, in FIFO order, before the new CURRENT)
    model = []
    held = collections.deque()
    for ts_i, _sid, (sym, v) in sends:
        while held and held[0][0] + T <= ts_i:
            _ets, esym, ev = held.popleft()
            model.append(("rm", (esym, ev)))
        model.append(("in", (sym, v)))
        held.append((ts_i, sym, v))
    # engine may also expire via shutdown-time timers; compare the prefix
    assert got[: len(model)] == model


def test_differential_pattern_counts():
    rng = np.random.default_rng(3)
    sends = []
    for _ in range(200):
        if rng.random() < 0.5:
            sends.append((None, "A", [float(int(rng.integers(0, 50)))]))
        else:
            sends.append((None, "B", [float(int(rng.integers(0, 50)))]))
    app = """
        define stream A (v double);
        define stream B (v double);
        @info(name='q')
        from every a=A -> b=B[b.v > a.v]
        select a.v as av, b.v as bv
        insert into Out;
    """
    got = _run_engine(app, sends)
    # model: pending A's; each B consumes ALL pendings it beats
    pend = []
    model = []
    for _ts, sid, (v,) in sends:
        if sid == "A":
            pend.append(v)
        else:
            matched = [a for a in pend if v > a]
            for a in matched:
                model.append(("in", (a, v)))
            pend = [a for a in pend if v <= a]
    assert sorted(got) == sorted(model)
    assert len(got) == len(model)


def test_differential_length_batch():
    rng = np.random.default_rng(4)
    N = 5
    sends = [(None, "S", [f"k{int(rng.integers(0, 3))}",
                          float(int(rng.integers(1, 20)))])
             for _ in range(123)]
    app = f"""
        define stream S (sym string, v double);
        @info(name='q')
        from S#window.lengthBatch({N})
        select sym, v insert all events into Out;
    """
    got = _run_engine(app, sends)
    # one callback chunk per flush: in-events list precedes remove-events
    # (QueryCallback groups them; order between the lists is by-list)
    model = []
    buf, prev = [], []
    for _ts, _sid, row in sends:
        buf.append(tuple(row))
        if len(buf) == N:
            for r in buf:
                model.append(("in", r))
            for r in prev:
                model.append(("rm", r))
            prev, buf = buf, []
    assert got == model


def test_differential_window_join():
    rng = np.random.default_rng(5)
    sends = []
    for i in range(120):
        side = "L" if rng.random() < 0.5 else "R"
        sends.append((None, side, [f"k{int(rng.integers(0, 3))}",
                                   int(rng.integers(0, 100))]))
    app = """
        define stream L (sym string, v int);
        define stream R (sym string, w int);
        @info(name='q')
        from L#window.length(6) join R#window.length(6)
          on L.sym == R.sym
        select L.v as v, R.w as w
        insert into Out;
    """
    got = _run_engine(app, sends)
    # model: arriving row joins the OTHER side's current window (post-
    # insert of its own window); CURRENT matches only (default output)
    lwin, rwin = collections.deque(maxlen=6), collections.deque(maxlen=6)
    model = []
    for _ts, side, (sym, x) in sends:
        if side == "L":
            lwin.append((sym, x))
            matches = [("in", (x, w)) for (rs, w) in rwin if rs == sym]
        else:
            rwin.append((sym, x))
            matches = [("in", (v, x)) for (ls, v) in lwin if ls == sym]
        model.extend(matches)
    assert sorted(got) == sorted(model)
    assert len(got) == len(model)


def test_differential_partitioned_length_window():
    rng = np.random.default_rng(6)
    W = 4
    sends = [(1000 + i, "S", [f"p{int(rng.integers(0, 6))}",
                              float(int(rng.integers(1, 30)))])
             for i in range(300)]
    app = f"""
        @app:playback
        define stream S (k string, v double);
        partition with (k of S)
        begin
          @info(name='q')
          from S#window.length({W})
          select k, sum(v) as s insert into Out;
        end;
    """
    got = _run_engine(app, sends)
    wins = collections.defaultdict(lambda: collections.deque(maxlen=W))
    model = []
    for _ts, _sid, (k, v) in sends:
        wins[k].append(v)
        model.append(("in", (k, sum(wins[k]))))
    assert len(got) == len(model)
    for (gk, gv), (mk, mv) in zip(got, model):
        assert gk == mk and gv[0] == mv[0]
        assert gv[1] == pytest.approx(mv[1], abs=1e-6)


def test_differential_session_window():
    rng = np.random.default_rng(7)
    GAP = 300
    ts = 1000
    sends = []
    for _ in range(160):
        ts += int(rng.integers(0, 250))
        sends.append((ts, "S", [f"u{int(rng.integers(0, 4))}",
                                int(rng.integers(1, 9))]))
    app = f"""
        @app:playback
        define stream S (user string, v int);
        @info(name='q')
        from S#window.session({GAP} milliseconds, user)
        select user, v insert all events into Out;
    """
    got = _run_engine(app, sends)
    # model: CURRENT on arrival; a user's session expires as one chunk
    # when the clock passes last+GAP. Each session's timer fires AT its
    # own deadline (Scheduler.sendTimerEvents), so sessions expiring in
    # the same inter-event interval emit in DEADLINE order (stable for
    # ties — the engine's sweep sorts by session end)
    sessions = {}
    model = []
    for ts_i, _sid, (u, v) in sends:
        due = [uu for uu in sessions if sessions[uu][0] + GAP <= ts_i]
        for uu in sorted(due, key=lambda x: sessions[x][0]):
            for r in sessions[uu][1]:
                model.append(("rm", r))
            del sessions[uu]
        model.append(("in", (u, v)))
        last, rows = sessions.get(u, (0, []))
        rows.append((u, v))
        sessions[u] = (ts_i, rows)
    assert got[: len(model)] == model


def test_differential_absent_pattern_timer():
    rng = np.random.default_rng(8)
    WAIT = 400
    ts = 1000
    sends = []
    for _ in range(120):
        ts += int(rng.integers(50, 300))
        if rng.random() < 0.55:
            sends.append((ts, "A", [int(rng.integers(0, 100))]))
        else:
            sends.append((ts, "B", [int(rng.integers(0, 100))]))
    app = f"""
        @app:playback
        define stream A (v int);
        define stream B (v int);
        @info(name='q')
        from every a=A -> not B for {WAIT} milliseconds
        select a.v as av
        insert into Out;
    """
    got = _run_engine(app, sends)
    # model: each A arms a deadline; a B before it cancels ALL pending
    # waits; the deadline passing (timers fire on clock advance) emits
    pending = []   # (deadline, av)
    model = []
    for ts_i, sid, (v,) in sends:
        still = []
        for dl, av in pending:
            if dl <= ts_i:
                model.append(("in", (av,)))
            else:
                still.append((dl, av))
        pending = still
        if sid == "A":
            pending.append((ts_i + WAIT, v))
        else:
            pending = []          # violation kills every pending wait
    assert got[: len(model)] == model
