"""Reference absent-pattern corpus — scenarios ported verbatim from
``query/pattern/absent/AbsentPatternTestCase.java`` (tail/head/mid
`not ... for t` shapes with exact feeds; sleeps become playback clock
jumps, with a final drain event to release pending deadlines)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


THREE = """@app:playback
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
    define stream Stream3 (symbol string, price float, volume int);
    define stream Tick (x int);
    from Tick select x insert into TickOut;
"""


def build(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("OutputStream", c)
    return m, rt, c


def _rows(c):
    return [tuple(round(v, 4) if isinstance(v, float) else v
                  for v in e.data) for e in c.events]


TAIL_NOT = THREE + """
    from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
    select e1.symbol as s1 insert into OutputStream;
"""


def test_absent_q1_tail_not_completes_at_deadline():
    # AbsentPatternTestCase.testQueryAbsent1 (adapted callback): quiet
    # second after e1 -> match at the deadline
    m, rt, c = build(TAIL_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 55.6, 100])
    rt.get_input_handler("Tick").send(3000, [0])   # clock past deadline
    m.shutdown()
    assert _rows(c) == [("WSO2",)]


def test_absent_q3_tail_not_violated():
    # testQueryAbsent3: a higher-priced Stream2 event inside the window
    # kills the wait
    m, rt, c = build(TAIL_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 55.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 58.7, 100])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q5_head_not_then_stream():
    # testQueryAbsent5: quiet first second, then e2 -> match
    m, rt, c = build(THREE + """
        from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
        select e2.symbol as s1 insert into OutputStream;
    """)
    rt.get_input_handler("Tick").send(1000, [0])    # playback clock start
    rt.get_input_handler("Stream2").send(2200, ["IBM", 58.7, 100])
    m.shutdown()
    assert _rows(c) == [("IBM",)]


MID_TAIL = THREE + """
    from e1=Stream1[price>10] -> e2=Stream2[price>20]
      -> not Stream3[price>30] for 1 sec
    select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;
"""


def test_absent_q9_chain_then_not_violated():
    m, rt, c = build(MID_TAIL)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 28.7, 100])
    rt.get_input_handler("Stream3").send(1200, ["GOOGLE", 55.7, 100])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q10_chain_then_not_nonmatching_event_ok():
    # testQueryAbsent10: the Stream3 event fails the not-filter -> match
    m, rt, c = build(MID_TAIL)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 28.7, 100])
    rt.get_input_handler("Stream3").send(1200, ["GOOGLE", 25.7, 100])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert _rows(c) == [("WSO2", "IBM")]


MID_NOT = THREE + """
    from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
      -> e3=Stream3[price>30]
    select e1.symbol as s1, e3.symbol as s3 insert into OutputStream;
"""


def test_absent_q12_mid_not_quiet_then_e3():
    # testQueryAbsent12: quiet second, then e3 -> match
    m, rt, c = build(MID_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream3").send(2200, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOGLE")]


def test_absent_q13_mid_not_nonmatching_stream2_ok():
    # testQueryAbsent13: a Stream2 event FAILING the not-filter does not
    # violate the wait
    m, rt, c = build(MID_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 8.7, 100])
    rt.get_input_handler("Stream3").send(2300, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOGLE")]


def test_absent_q14_mid_not_violated_before_e3():
    # testQueryAbsent14: a matching Stream2 event inside the window kills
    # the chain; the later e3 finds nothing
    m, rt, c = build(MID_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 28.7, 100])
    rt.get_input_handler("Stream3").send(1200, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == []
