"""Reference absent-pattern corpus — scenarios ported verbatim from
``query/pattern/absent/AbsentPatternTestCase.java`` (tail/head/mid
`not ... for t` shapes with exact feeds; sleeps become playback clock
jumps, with a final drain event to release pending deadlines)."""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


THREE = """@app:playback
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
    define stream Stream3 (symbol string, price float, volume int);
    define stream Tick (x int);
    from Tick select x insert into TickOut;
"""


def build(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("OutputStream", c)
    return m, rt, c


def _rows(c):
    return [tuple(round(v, 4) if isinstance(v, float) else v
                  for v in e.data) for e in c.events]


TAIL_NOT = THREE + """
    from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
    select e1.symbol as s1 insert into OutputStream;
"""


def test_absent_q1_tail_not_completes_at_deadline():
    # AbsentPatternTestCase.testQueryAbsent1 (adapted callback): quiet
    # second after e1 -> match at the deadline
    m, rt, c = build(TAIL_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 55.6, 100])
    rt.get_input_handler("Tick").send(3000, [0])   # clock past deadline
    m.shutdown()
    assert _rows(c) == [("WSO2",)]


def test_absent_q3_tail_not_violated():
    # testQueryAbsent3: a higher-priced Stream2 event inside the window
    # kills the wait
    m, rt, c = build(TAIL_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 55.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 58.7, 100])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q5_head_not_then_stream():
    # testQueryAbsent5: quiet first second, then e2 -> match
    m, rt, c = build(THREE + """
        from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
        select e2.symbol as s1 insert into OutputStream;
    """)
    rt.get_input_handler("Tick").send(1000, [0])    # playback clock start
    rt.get_input_handler("Stream2").send(2200, ["IBM", 58.7, 100])
    m.shutdown()
    assert _rows(c) == [("IBM",)]


MID_TAIL = THREE + """
    from e1=Stream1[price>10] -> e2=Stream2[price>20]
      -> not Stream3[price>30] for 1 sec
    select e1.symbol as s1, e2.symbol as s2 insert into OutputStream;
"""


def test_absent_q9_chain_then_not_violated():
    m, rt, c = build(MID_TAIL)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 28.7, 100])
    rt.get_input_handler("Stream3").send(1200, ["GOOGLE", 55.7, 100])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q10_chain_then_not_nonmatching_event_ok():
    # testQueryAbsent10: the Stream3 event fails the not-filter -> match
    m, rt, c = build(MID_TAIL)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 28.7, 100])
    rt.get_input_handler("Stream3").send(1200, ["GOOGLE", 25.7, 100])
    rt.get_input_handler("Tick").send(3000, [0])
    m.shutdown()
    assert _rows(c) == [("WSO2", "IBM")]


MID_NOT = THREE + """
    from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
      -> e3=Stream3[price>30]
    select e1.symbol as s1, e3.symbol as s3 insert into OutputStream;
"""


def test_absent_q12_mid_not_quiet_then_e3():
    # testQueryAbsent12: quiet second, then e3 -> match
    m, rt, c = build(MID_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream3").send(2200, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOGLE")]


def test_absent_q13_mid_not_nonmatching_stream2_ok():
    # testQueryAbsent13: a Stream2 event FAILING the not-filter does not
    # violate the wait
    m, rt, c = build(MID_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 8.7, 100])
    rt.get_input_handler("Stream3").send(2300, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", "GOOGLE")]


def test_absent_q14_mid_not_violated_before_e3():
    # testQueryAbsent14: a matching Stream2 event inside the window kills
    # the chain; the later e3 finds nothing
    m, rt, c = build(MID_NOT)
    rt.get_input_handler("Stream1").send(1000, ["WSO2", 15.6, 100])
    rt.get_input_handler("Stream2").send(1100, ["IBM", 28.7, 100])
    rt.get_input_handler("Stream3").send(1200, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == []


# ---------------------------------------------------------------------------
# Round-4 expansion: the remaining AbsentPatternTestCase.java scenarios
# (testQueryAbsent2..43, feeds and expected counts verbatim; sleeps become
# playback timestamps offset from the 1000 ms clock-start, with a trailing
# Tick where a deadline must fire before shutdown).

FOUR = """@app:playback
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
    define stream Stream3 (symbol string, price float, volume int);
    define stream Stream4 (symbol string, price float, volume int);
    define stream Tick (x int);
    from Tick select x insert into TickOut;
"""

HEAD_NOT = THREE + """
    from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
    select e2.symbol as s insert into OutputStream;
"""

HEAD_CHAIN = THREE + """
    from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
      -> e3=Stream3[price>30]
    select e2.symbol as s2, e3.symbol as s3 insert into OutputStream;
"""

E123_NOT4 = FOUR + """
    from e1=Stream1[price>10] -> e2=Stream2[price>20] -> e3=Stream3[price>30]
      -> not Stream4[price>40] for 1 sec
    select e1.symbol as s1, e2.symbol as s2, e3.symbol as s3
    insert into OutputStream;
"""

E12_NOT3_E4 = FOUR + """
    from e1=Stream1[price>10] -> e2=Stream2[price>20]
      -> not Stream3[price>30] for 1 sec -> e4=Stream4[price>40]
    select e1.symbol as s1, e2.symbol as s2, e4.symbol as s4
    insert into OutputStream;
"""

NOT1_E234 = FOUR + """
    from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
      -> e3=Stream3[price>30] -> e4=Stream4[price>40]
    select e2.symbol as s2, e3.symbol as s3, e4.symbol as s4
    insert into OutputStream;
"""

NOT1_E2_NOT3_E4 = FOUR + """
    from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
      -> not Stream3[price>30] for 1 sec -> e4=Stream4[price>40]
    select e2.symbol as s2, e4.symbol as s4 insert into OutputStream;
"""

E1_NOT2_AND = FOUR + """
    from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
      -> e2=Stream3[price>30] and e3=Stream4[price>40]
    select e1.symbol as s1, e2.symbol as s2, e3.symbol as s3
    insert into OutputStream;
"""

E1_NOT2_OR = FOUR + """
    from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
      -> e2=Stream3[price>30] or e3=Stream4[price>40]
    select e1.symbol as s1, e2.symbol as s2, e3.symbol as s3
    insert into OutputStream;
"""

NOT1_COUNT = THREE + """
    from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]<2:5>
    select e2[0].symbol as s0, e2[1].symbol as s1, e2[2].symbol as s2,
           e2[3].symbol as s3
    insert into OutputStream;
"""


def _send(rt, stream, ts, row):
    rt.get_input_handler(stream).send(ts, row)


def test_absent_q2_tail_not_violation_after_deadline():
    # testQueryAbsent2: the violating Stream2 event arrives AFTER the
    # 1-sec deadline -> the match already fired
    m, rt, c = build(TAIL_NOT)
    _send(rt, "Stream1", 1000, ["WSO2", 55.6, 100])
    _send(rt, "Stream2", 2100, ["IBM", 58.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2",)]


def test_absent_q4_tail_not_nonmatching_stream2_ok():
    # testQueryAbsent4: Stream2 event fails [price>e1.price] -> no
    # violation, match at the deadline
    m, rt, c = build(TAIL_NOT)
    _send(rt, "Stream1", 1000, ["WSO2", 55.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 50.7, 100])
    _send(rt, "Tick", 2100, [0])
    m.shutdown()
    assert _rows(c) == [("WSO2",)]


def test_absent_q6_head_not_rearms_after_violation():
    # testQueryAbsent6: Stream1 kills the first wait; a later quiet
    # second + e2 still match (head wait re-arms)
    m, rt, c = build(HEAD_NOT)
    _send(rt, "Tick", 1000, [0])
    _send(rt, "Stream1", 1100, ["WSO2", 59.6, 100])
    _send(rt, "Stream2", 3200, ["IBM", 58.7, 100])
    m.shutdown()
    assert _rows(c) == [("IBM",)]


def test_absent_q7_head_not_e2_before_deadline():
    # testQueryAbsent7: non-violating Stream1, but e2 arrives inside the
    # quiet window -> no match
    m, rt, c = build(HEAD_NOT)
    _send(rt, "Stream1", 1000, ["WSO2", 5.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 58.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q8_head_not_violated_then_e2_early():
    # testQueryAbsent8: violation then e2 before the re-armed deadline
    m, rt, c = build(HEAD_NOT)
    _send(rt, "Stream1", 1000, ["WSO2", 55.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 58.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q11_chain_then_not_quiet():
    # testQueryAbsent11: e1, e2, quiet second -> match at deadline
    m, rt, c = build(MID_TAIL)
    _send(rt, "Stream1", 1000, ["WSO2", 15.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 28.7, 100])
    _send(rt, "Tick", 2200, [0])
    m.shutdown()
    assert _rows(c) == [("WSO2", "IBM")]


def test_absent_q15_head_chain_violated():
    # testQueryAbsent15: Stream1 violates the head wait -> no match
    m, rt, c = build(HEAD_CHAIN)
    _send(rt, "Stream1", 1000, ["WSO2", 15.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 1200, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q16_head_chain_quiet_then_e2_e3():
    # testQueryAbsent16: quiet head window, then e2 -> e3
    m, rt, c = build(HEAD_CHAIN)
    _send(rt, "Tick", 1000, [0])
    _send(rt, "Stream2", 3200, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 3300, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("IBM", "GOOGLE")]


def test_absent_q17_head_chain_nonviolating_stream1():
    # testQueryAbsent17: a Stream1 event FAILING [price>10] inside the
    # wait does not violate it
    m, rt, c = build(HEAD_CHAIN)
    _send(rt, "Tick", 1000, [0])
    _send(rt, "Stream1", 1500, ["WSO2", 5.6, 100])
    _send(rt, "Stream2", 2100, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 2200, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("IBM", "GOOGLE")]


def test_absent_q18_head_chain_violation_then_rearm():
    # testQueryAbsent18: violation at start; after a quiet re-armed
    # second, e2 -> e3 match
    m, rt, c = build(HEAD_CHAIN)
    _send(rt, "Stream1", 1000, ["WSO2", 25.6, 100])
    _send(rt, "Stream2", 2100, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 2200, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == [("IBM", "GOOGLE")]


def test_absent_q19_three_then_tail_not_quiet():
    # testQueryAbsent19: e1 -> e2 -> e3 then a quiet second on Stream4
    m, rt, c = build(E123_NOT4)
    _send(rt, "Stream1", 1000, ["WSO2", 15.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 1200, ["GOOGLE", 35.7, 100])
    _send(rt, "Tick", 2300, [0])
    m.shutdown()
    assert _rows(c) == [("WSO2", "IBM", "GOOGLE")]


def test_absent_q20_three_then_tail_not_violated():
    # testQueryAbsent20: Stream4 inside the window -> no match
    m, rt, c = build(E123_NOT4)
    _send(rt, "Stream1", 1000, ["WSO2", 15.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 1200, ["GOOGLE", 35.7, 100])
    _send(rt, "Stream4", 1300, ["ORACLE", 44.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q21_mid_not_then_e4():
    # testQueryAbsent21: e1, e2, quiet second, e4 -> match
    m, rt, c = build(E12_NOT3_E4)
    _send(rt, "Stream1", 1000, ["WSO2", 15.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 28.7, 100])
    _send(rt, "Stream4", 2200, ["ORACLE", 44.7, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", "IBM", "ORACLE")]


def test_absent_q22_mid_not_violated_then_e4():
    # testQueryAbsent22: Stream3 violates the mid wait; the later e4
    # cannot complete the chain
    m, rt, c = build(E12_NOT3_E4)
    _send(rt, "Stream1", 1000, ["WSO2", 15.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 1200, ["GOOGLE", 38.7, 100])
    _send(rt, "Stream4", 2300, ["ORACLE", 44.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q23_head_not_violated_chain_dead():
    # testQueryAbsent23: head wait violated -> e2/e3/e4 never accepted
    m, rt, c = build(NOT1_E234)
    _send(rt, "Stream1", 1000, ["WSO2", 15.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 1200, ["GOOGLE", 38.7, 100])
    _send(rt, "Stream4", 1300, ["ORACLE", 44.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q24_double_not_both_quiet():
    # testQueryAbsent24: quiet, e2, quiet, e4 -> match
    m, rt, c = build(NOT1_E2_NOT3_E4)
    _send(rt, "Tick", 1000, [0])
    _send(rt, "Stream2", 2100, ["IBM", 28.7, 100])
    _send(rt, "Stream4", 3300, ["ORACLE", 44.7, 100])
    m.shutdown()
    assert _rows(c) == [("IBM", "ORACLE")]


def test_absent_q25_double_not_first_violated():
    # testQueryAbsent25: Stream1 violates head; nothing matches
    m, rt, c = build(NOT1_E2_NOT3_E4)
    _send(rt, "Stream1", 1000, ["WSO2", 15.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 1200, ["GOOGLE", 38.7, 100])
    _send(rt, "Stream4", 1300, ["ORACLE", 44.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q26_double_not_e2_before_head_deadline():
    # testQueryAbsent26: e2 arrives before the head wait completes
    m, rt, c = build(NOT1_E2_NOT3_E4)
    _send(rt, "Stream2", 1000, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 1100, ["GOOGLE", 38.7, 100])
    _send(rt, "Stream4", 1200, ["ORACLE", 44.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q27_head_not_e2_immediately():
    # testQueryAbsent27: e2 at clock start, quiet second not elapsed
    m, rt, c = build(HEAD_NOT)
    _send(rt, "Stream2", 1000, ["IBM", 58.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q28_mid_not_then_and_pair():
    # testQueryAbsent28: quiet second then e3 AND e4 -> one match
    m, rt, c = build(E1_NOT2_AND)
    _send(rt, "Stream1", 1000, ["IBM", 18.7, 100])
    _send(rt, "Stream3", 2100, ["WSO2", 35.0, 100])
    _send(rt, "Stream4", 2200, ["GOOGLE", 56.86, 100])
    m.shutdown()
    assert _rows(c) == [("IBM", "WSO2", "GOOGLE")]


def test_absent_q29_mid_not_and_pair_too_early():
    # testQueryAbsent29: the and-pair arrives inside the quiet window
    m, rt, c = build(E1_NOT2_AND)
    _send(rt, "Stream1", 1000, ["IBM", 18.7, 100])
    _send(rt, "Stream3", 1100, ["WSO2", 35.0, 100])
    _send(rt, "Stream4", 1200, ["GOOGLE", 56.86, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q30_mid_not_then_or_left():
    # testQueryAbsent30: quiet second then the left or-side alone
    m, rt, c = build(E1_NOT2_OR)
    _send(rt, "Stream1", 1000, ["IBM", 18.7, 100])
    _send(rt, "Stream3", 2100, ["WSO2", 35.0, 100])
    m.shutdown()
    assert _rows(c) == [("IBM", "WSO2", None)]


def test_absent_q31_mid_not_then_or_right():
    # testQueryAbsent31: quiet second then the right or-side alone
    m, rt, c = build(E1_NOT2_OR)
    _send(rt, "Stream1", 1000, ["IBM", 18.7, 100])
    _send(rt, "Stream4", 2100, ["GOOGLE", 56.86, 100])
    m.shutdown()
    assert _rows(c) == [("IBM", None, "GOOGLE")]


def test_absent_q32_mid_not_or_too_early():
    # testQueryAbsent32: or-sides inside the quiet window -> nothing
    m, rt, c = build(E1_NOT2_OR)
    _send(rt, "Stream1", 1000, ["IBM", 18.7, 100])
    _send(rt, "Stream3", 1100, ["WSO2", 35.0, 100])
    _send(rt, "Stream4", 1200, ["GOOGLE", 56.86, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q33_mid_not_violated_and_pair():
    # testQueryAbsent33: Stream2 violates the wait; and-pair wasted
    m, rt, c = build(E1_NOT2_AND)
    _send(rt, "Stream1", 1000, ["IBM", 18.7, 100])
    _send(rt, "Stream2", 1100, ["ORACLE", 25.0, 100])
    _send(rt, "Stream3", 1200, ["WSO2", 35.0, 100])
    _send(rt, "Stream4", 1300, ["GOOGLE", 56.86, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q34_mid_not_violated_or_pair():
    # testQueryAbsent34: same with or
    m, rt, c = build(E1_NOT2_OR)
    _send(rt, "Stream1", 1000, ["IBM", 18.7, 100])
    _send(rt, "Stream2", 1100, ["ORACLE", 25.0, 100])
    _send(rt, "Stream3", 1200, ["WSO2", 35.0, 100])
    _send(rt, "Stream4", 1300, ["GOOGLE", 56.86, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q35_head_not_violated_count_tail():
    # testQueryAbsent35: violated head wait -> the <2:5> count never
    # starts collecting
    m, rt, c = build(NOT1_COUNT)
    _send(rt, "Stream1", 1000, ["WSO2", 15.0, 100])
    _send(rt, "Stream2", 1100, ["GOOGLE", 35.0, 100])
    _send(rt, "Stream2", 1200, ["ORACLE", 45.0, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q36_head_not_quiet_count_tail():
    # testQueryAbsent36: quiet second then two Stream2 events satisfy
    # the <2:5> minimum -> one match with e2[0], e2[1] captured
    m, rt, c = build(NOT1_COUNT)
    _send(rt, "Tick", 1000, [0])
    _send(rt, "Stream2", 2100, ["WSO2", 35.0, 100])
    _send(rt, "Stream2", 2200, ["IBM", 45.0, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2", "IBM", None, None)]


def test_absent_q37_head_not_single_match_no_every():
    # testQueryAbsent37: without `every`, only the first e2 after the
    # quiet second matches
    m, rt, c = build(THREE + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
        select e2.symbol as s insert into OutputStream;
    """)
    _send(rt, "Tick", 1000, [0])
    _send(rt, "Stream2", 3100, ["WSO2", 35.0, 100])
    _send(rt, "Stream2", 3200, ["IBM", 45.0, 100])
    m.shutdown()
    assert _rows(c) == [("WSO2",)]


def test_absent_q38_mid_not_violated_then_late_e3():
    # testQueryAbsent38: Stream2 violates inside the window; e3 after the
    # deadline cannot resurrect the chain
    m, rt, c = build(MID_NOT)
    _send(rt, "Stream1", 1000, ["WSO2", 15.6, 100])
    _send(rt, "Stream2", 1100, ["IBM", 28.7, 100])
    _send(rt, "Stream3", 2200, ["GOOGLE", 55.7, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q39_mid_not_violated_or_after_delay():
    # testQueryAbsent39: violation, then the or-side after the deadline
    m, rt, c = build(E1_NOT2_OR)
    _send(rt, "Stream1", 1000, ["IBM", 18.7, 100])
    _send(rt, "Stream2", 1100, ["WSO2", 25.5, 100])
    _send(rt, "Stream4", 2200, ["GOOGLE", 56.86, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q40_head_not_no_rearm_second_e2():
    # testQueryAbsent40: after the first match, a second quiet period +
    # e2 do NOT match again (no `every`)
    m, rt, c = build(HEAD_NOT)
    _send(rt, "Tick", 1000, [0])
    _send(rt, "Stream2", 2100, ["IBM", 58.7, 100])
    _send(rt, "Stream2", 3300, ["WSO2", 68.7, 100])
    m.shutdown()
    assert _rows(c) == [("IBM",)]


def test_absent_q41_every_not_violated_no_output_yet():
    # testQueryAbsent41: `every not ... for 1 sec` select *; the matching
    # Stream1 event kills the current wait and nothing has fired by then
    m, rt, c = build(THREE + """
        from every not Stream1[price>20] for 1 sec
        select * insert into OutputStream;
    """)
    _send(rt, "Stream1", 1000, ["WSO2", 55.6, 100])
    m.shutdown()
    assert _rows(c) == []


def test_absent_q42_head_not_within_counts_captured_events():
    # testQueryAbsent42: `within 2 sec` measures across CAPTURED events;
    # with only e2 captured it cannot be violated even 3 sec in
    m, rt, c = build(THREE + """
        from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
          within 2 sec
        select e2.symbol as s insert into OutputStream;
    """)
    _send(rt, "Tick", 1000, [0])
    _send(rt, "Stream2", 4100, ["IBM", 58.7, 100])
    m.shutdown()
    assert _rows(c) == [("IBM",)]


def test_absent_q43_partitioned_same_stream_absence():
    # testQueryAbsent43: partitioned e1 -> not same-stream same-key for
    # 1 sec; customerA stays quiet -> matches, customerB repeats -> killed
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream CustomerStream (customerId string);
        define stream Tick (x int);
        from Tick select x insert into TickOut;
        partition with (customerId of CustomerStream)
        begin
          from e1=CustomerStream
            -> not CustomerStream[customerId == e1.customerId] for 1 sec
          select e1.customerId insert into OutputStream;
        end;
    """)
    c = Collector()
    rt.add_callback("OutputStream", c)
    h = rt.get_input_handler("CustomerStream")
    h.send(1000, ["customerA"])
    h.send(1000, ["customerB"])
    h.send(1500, ["customerB"])
    rt.get_input_handler("Tick").send(2600, [0])
    m.shutdown()
    assert _rows(c) == [("customerA",)]


def test_select_star_emits_null_columns_for_absent_elements():
    """select * on a pattern with a capture-less absent element EMITS a
    row: captured attrs filled, absent element's attrs null (regression:
    the typed-null scalar mask crashed event decoding). Distinct attr
    names via two differently-shaped streams."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream Alerts (aName string, aLevel int);
        define stream Metrics (mName string, mValue double);
        define stream Tick (x int);
        from Tick select x insert into TickOut;
        from not Alerts[aLevel > 2] for 1 sec -> e2=Metrics[mValue > 10.0]
        select * insert into OutputStream;
    """)
    c = Collector()
    rt.add_callback("OutputStream", c)
    rt.get_input_handler("Tick").send(1000, [0])
    rt.get_input_handler("Metrics").send(2500, ["cpu", 55.5])
    m.shutdown()
    assert _rows(c) == [(None, None, "cpu", 55.5)]
