"""`every` on count states — reference CountPatternTestCase.testQuery20
(tail `-> every e2=B<2> within 3 sec`: one emission per completed,
non-overlapping group; the whole chain still dies at `within`) and the
mid-chain fork shape `A -> every B<n:n> -> C`."""

from siddhi_tpu import SiddhiManager, StreamCallback
import pytest

from siddhi_tpu.ops.expressions import CompileError


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


APP = "@app:playback define stream InputStream (name string);\n"


def test_every_count_tail_groups_non_overlapping():
    # CountPatternTestCase.testQuery20 without the within expiry part:
    # A A B B -> 1, B B -> 1 more (pairs are consumed, not sliding)
    m, rt, c = build(APP + """
        from e1=InputStream[name == 'A']<2:2>
          -> every e2=InputStream[name == 'B']<2:2>
        select e2[0].name as n0, e2[1].name as n1
        insert into OutStream;
    """)
    h = rt.get_input_handler("InputStream")
    for i, n in enumerate(["A", "A", "B", "B", "B", "B", "B"]):
        h.send(1000 + i * 100, [n])
    m.shutdown()
    # 4 B's -> 2 groups; the 5th B starts an incomplete group
    assert [tuple(e.data) for e in c.events] == [("B", "B"), ("B", "B")]


def test_every_count_tail_within_kills_the_chain():
    # within 3 sec anchored at the first A: the post-expiry lone B emits
    # nothing, and a COUNT-head non-every pattern re-arms once no chain is
    # live (CountPatternTestCase.testQuery20: "AA are not consumed after
    # within time period" — then a fresh AA DOES start a new chain), so
    # the final AABB yields a third group
    m, rt, c = build(APP + """
        from e1=InputStream[name == 'A']<2:2>
          -> every e2=InputStream[name == 'B']<2:2>
          within 3 sec
        select e2[0].name as n0
        insert into OutStream;
    """)
    h = rt.get_input_handler("InputStream")
    t = 1000
    for n in ["A", "A", "B", "B", "B", "B"]:
        h.send(t, [n]); t += 100
    h.send(t, ["A"]); t += 100
    h.send(t, ["B"]); t += 100
    t += 4000                      # past the 3 sec window
    h.send(t, ["B"]); t += 100
    for n in ["A", "A", "B", "B"]:
        h.send(t, [n]); t += 100
    m.shutdown()
    # two pre-expiry groups + one from the re-armed post-expiry chain
    assert [e.timestamp for e in c.events] == [1300, 1500, 6200]


def test_every_count_midchain_forks_completed_groups():
    # A -> every B<2:2> -> C: completed pairs wait; each C consumes all
    # waiting pairs collected so far
    m, rt, c = build(APP + """
        from e1=InputStream[name == 'A']
          -> every e2=InputStream[name == 'B']<2:2>
          -> e3=InputStream[name == 'C']
        select e2[0].name as n0, e2[1].name as n1
        insert into OutStream;
    """)
    h = rt.get_input_handler("InputStream")
    for i, n in enumerate(["A", "B", "B", "C", "B", "B", "C"]):
        h.send(1000 + i * 100, [n])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [("B", "B"), ("B", "B")]


def test_every_count_midchain_two_groups_before_consumer():
    # both completed pairs wait at the count step; one C event consumes
    # both (reference every semantics: each waiting instance matches)
    m, rt, c = build(APP + """
        from e1=InputStream[name == 'A']
          -> every e2=InputStream[name == 'B']<2:2>
          -> e3=InputStream[name == 'C']
        select e2[0].name as n0
        insert into OutStream;
    """)
    h = rt.get_input_handler("InputStream")
    for i, n in enumerate(["A", "B", "B", "B", "B", "C"]):
        h.send(1000 + i * 100, [n])
    m.shutdown()
    assert len(c.events) == 2


def test_every_range_count_rearms_on_consumption():
    # range counts re-arm when the next step's event consumes the group
    m, rt, c = build(APP + """
        from e1=InputStream[name == 'A']
          -> every e2=InputStream[name == 'B']<1:3>
          -> e3=InputStream[name == 'C']
        select e2[0].name as n0
        insert into OutStream;
    """)
    h = rt.get_input_handler("InputStream")
    for i, n in enumerate(["A", "B", "B", "C", "B", "C"]):
        h.send(1000 + i * 100, [n])
    m.shutdown()
    # group1 = B,B consumed by first C; group2 = B consumed by second C
    assert len(c.events) == 2


def test_every_count_followed_by_logical_rejected():
    with pytest.raises(CompileError, match="every.*count"):
        build(APP + """
            define stream S2 (name string);
            from e1=InputStream[name == 'A']
              -> every e2=InputStream[name == 'B']<2:2>
              -> e3=InputStream[name == 'C'] and e4=S2[name == 'D']
            select e2[0].name as n0
            insert into OutStream;
        """)


def test_head_every_count_non_overlapping_with_within():
    # CountPatternTestCase.testQuery18: every e1=A<2> -> e2=B within 3 sec
    # over the reference trace — exactly 3 matches (non-overlapping pairs,
    # the 4s gap expires pending chains)
    m, rt, c = build(APP + """
        from every e1=InputStream[name == 'A']<2:2>
          -> e2=InputStream[name == 'B'] within 3 sec
        select e1[0].name as n insert into OutStream;
    """)
    h = rt.get_input_handler("InputStream")
    t = 1000
    for n in ["A", "A", "B", "B", "A", "A", "B", "B", "A"]:
        h.send(t, [n]); t += 100
    t += 4000
    for n in ["A", "B", "B", "A", "A", "B", "B"]:
        h.send(t, [n]); t += 100
    m.shutdown()
    assert len(c.events) == 3
