"""Incremental aggregation round-2 features: distinctCount, cross-bucket
out-of-order ingestion, @purge retention, restart rebuild from a persisted
revision, @PartitionById shard mode — mirroring reference
``aggregation/*TestCase`` + ``IncrementalDataPurger`` behavior.
"""

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.aggregation.incremental import Duration
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

APP = """
@app:playback
define stream TradeStream (symbol string, price double, volume long);
define aggregation TradeAgg
  from TradeStream
  select symbol, sum(price) as total, distinctCount(volume) as dvol
  group by symbol
  aggregate every sec ... min;
"""


def _send(rt, ts, rows):
    h = rt.get_input_handler("TradeStream")
    for r in rows:
        h.send(ts, r)


def test_distinct_count():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    _send(rt, 10_000, [["A", 1.0, 5], ["A", 2.0, 5], ["A", 3.0, 7]])
    agg = rt.aggregations["TradeAgg"]
    rows = agg.rows(Duration.SECONDS)
    m.shutdown()
    assert len(rows) == 1
    ts, sym, total, dvol = rows[0]
    assert (total, dvol) == (6.0, 2)      # volumes {5, 7}


def test_cross_bucket_out_of_order():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    _send(rt, 10_000, [["A", 1.0, 1]])
    _send(rt, 12_000, [["A", 2.0, 2]])
    _send(rt, 10_500, [["A", 4.0, 3]])    # LATE: lands in the 10s bucket
    agg = rt.aggregations["TradeAgg"]
    rows = {r[0]: r[2] for r in agg.rows(Duration.SECONDS)}
    m.shutdown()
    assert rows == {10_000: 5.0, 12_000: 2.0}


def test_purge_retention():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream TradeStream (symbol string, price double, volume long);
        @purge(enable='true', interval='10 sec',
               @retentionPeriod(sec='120 sec', min='24 hours'))
        define aggregation TradeAgg
          from TradeStream
          select symbol, sum(price) as total
          group by symbol
          aggregate every sec ... min;
    """)
    agg = rt.aggregations["TradeAgg"]
    _send(rt, 10_000, [["A", 1.0, 1]])
    _send(rt, 400_000, [["A", 2.0, 1]])
    # the 10s-interval purge job rides the playback event clock, so the
    # jump to 400s already swept the expired sec bucket; an explicit purge
    # afterwards finds nothing more to do
    agg.purge(now=400_000)                # sec retention 120s: 10s bucket dies
    rows = {r[0]: r[2] for r in agg.rows(Duration.SECONDS)}
    min_rows = {r[0]: r[2] for r in agg.rows(Duration.MINUTES)}
    m.shutdown()
    assert rows == {400_000: 2.0}
    # the minute store still holds the older data (coarse retention)
    assert min_rows == {0: 1.0, 360_000: 2.0}


def test_restart_rebuild_from_revision():
    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    _send(rt, 10_000, [["A", 1.0, 1], ["B", 2.0, 2]])
    rt.persist()
    m.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    rt2.restore_last_revision()
    agg2 = rt2.aggregations["TradeAgg"]
    before = sorted(map(tuple, agg2.rows(Duration.SECONDS)))
    # aggregation continues on the rebuilt buckets
    _send(rt2, 10_100, [["A", 4.0, 9]])
    after = {(r[0], r[2]) for r in agg2.rows(Duration.SECONDS)}
    m2.shutdown()
    assert len(before) == 2
    assert (10_000, 5.0) in after          # 1.0 persisted + 4.0 new


SHARD_APP = """
    @app:playback
    define stream S (symbol string, price double);
    @PartitionById(enable='true')
    define aggregation Agg
      from S select symbol, sum(price) as total, count() as n
      group by symbol aggregate every sec;
"""


def test_shard_mode_flag():
    from siddhi_tpu.core.util.config import InMemoryConfigManager

    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager({"shardId": "node-7"}))
    rt = m.create_siddhi_app_runtime(SHARD_APP)
    agg = rt.aggregations["Agg"]
    m.shutdown()
    assert agg.shard_mode and agg.shard_id == "node-7"


def test_distributed_aggregation_two_shards_stitch():
    # two runtimes (shard-0/shard-1) each aggregate their half of the
    # event stream and publish partial buckets to ONE shared persistence
    # store; a reader stitches them back — cross-shard sums/counts equal
    # the unsharded totals (reference per-shardId aggregation tables,
    # AggregationParser.java:171-197)
    from siddhi_tpu.core.aggregation.incremental import Duration
    from siddhi_tpu.core.util.config import InMemoryConfigManager
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    shared = InMemoryPersistenceStore()
    aggs = []
    for shard, rows in (
        ("0", [(1000, ["A", 1.0]), (1100, ["A", 2.0]), (1200, ["B", 5.0])]),
        ("1", [(1300, ["A", 4.0]), (2200, ["B", 8.0])]),
    ):
        m = SiddhiManager()
        m.set_persistence_store(shared)
        m.set_config_manager(InMemoryConfigManager({"shardId": shard}))
        rt = m.create_siddhi_app_runtime(SHARD_APP)
        h = rt.get_input_handler("S")
        for ts, data in rows:
            h.send(ts, data)
        agg = rt.aggregations["Agg"]
        assert agg.shard_id == shard
        agg.publish_shard()
        aggs.append((m, agg))

    # reader: a third runtime with the same store stitches both shards
    # (every @PartitionById node needs its own configured shardId)
    mr = SiddhiManager()
    mr.set_persistence_store(shared)
    mr.set_config_manager(InMemoryConfigManager({"shardId": "reader"}))
    rtr = mr.create_siddhi_app_runtime(SHARD_APP)
    reader = rtr.aggregations["Agg"]
    assert reader.stitch_shards() == 2
    # on-demand query over the stitched reader: cross-shard sums/counts
    out = rtr.query("from Agg within 0, 10000 per 'seconds' "
                    "select AGG_TIMESTAMP, symbol, total, n return;")
    got = {(e.data[0], e.data[1]): (e.data[2], e.data[3]) for e in out}
    # bucket 1000: A = 1+2+4 over both shards (3 events), B = 5 (1 event)
    assert got[(1000, "A")] == (7.0, 3)
    assert got[(1000, "B")] == (5.0, 1)
    # bucket 2000: B = 8 from shard 1 only
    assert got[(2000, "B")] == (8.0, 1)
    for m, _ in aggs:
        m.shutdown()
    mr.shutdown()
