"""Device-path string -> numeric casts via the host parse-LUT transform
(ConvertFunctionExecutor semantics: unparseable -> null)."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


def test_convert_string_to_double_in_select_and_filter():
    m, rt, c = build("""
        define stream S (txt string);
        from S[convert(txt, 'double') > 10.0]
        select convert(txt, 'double') as v insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for s in ["5.5", "42.25", "nope", "100"]:
        h.send([s])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [42.25, 100.0]


def test_convert_string_to_long_with_window_sum():
    m, rt, c = build("""
        define stream S (txt string);
        from S#window.length(2)
        select sum(convert(txt, 'long')) as total insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for s in ["3", "4", "5"]:
        h.send([s])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [3, 7, 9]


def test_convert_unparseable_yields_null():
    m, rt, c = build("""
        define stream S (txt string);
        from S select convert(txt, 'double') as v insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send(["abc"])
    h.send(["1.5"])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [None, 1.5]


def test_convert_overflow_values_yield_null():
    m, rt, c = build("""
        define stream S (txt string);
        from S select convert(txt, 'int') as v insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for s in ["1e400", "3000000000", "7"]:
        h.send([s])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [None, None, 7]


def test_convert_numeric_to_string():
    m, rt, c = build("""
        define stream S (v int, d double, b bool);
        from S select convert(v, 'string') as vs, convert(d, 'string') as ds,
                      convert(b, 'string') as bs
        insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    h.send([42, 1.5, True])
    h.send([-3, 0.25, False])
    m.shutdown()
    assert [tuple(e.data) for e in c.events] == [
        ("42", "1.5", "true"), ("-3", "0.25", "false")]


def test_convert_numeric_to_string_in_filter():
    m, rt, c = build("""
        define stream S (v int);
        from S[convert(v, 'string') == '7']
        select v insert into OutStream;
    """)
    h = rt.get_input_handler("S")
    for v in [5, 7, 9]:
        h.send([v])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [7]
