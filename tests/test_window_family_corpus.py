"""Reference per-family window corpus — scenarios ported verbatim from
``query/window/{Length,Time,ExternalTime,Sort,Frequent,LossyFrequent,Cron}
WindowTestCase.java`` (feeds and expected outputs; Thread.sleep becomes
playback clock jumps where timers must fire)."""

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.query.callback import QueryCallback
from siddhi_tpu.compiler.errors import (SiddhiParserException,
                                        SiddhiAppValidationException)
from siddhi_tpu.ops.expressions import CompileError

CREATION_ERRORS = (CompileError, SiddhiParserException,
                   SiddhiAppValidationException)


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []       # in_events (CURRENT)
        self.expired = []      # remove_events (EXPIRED)
        self.order = []        # interleaved arrival order: ('in'|'rm', data)

    def receive(self, timestamp, in_events, remove_events):
        for e in (in_events or []):
            self.events.append(e)
            self.order.append(("in", tuple(e.data)))
        for e in (remove_events or []):
            self.expired.append(e)
            self.order.append(("rm", tuple(e.data)))


def build(app, out="OutStream"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback(out, c)
    return m, rt, c


def build_q(app, query="query1"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    q = QCollect()
    rt.add_callback(query, q)
    return m, rt, q


# --------------------------------------------------- LengthWindowTestCase


def test_length_window_fewer_events_than_size():
    """lengthWindowTest1 (:52-84): 2 events into length(4) — all CURRENT,
    none expired, arrival order preserved."""
    m, rt, q = build_q("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.length(4)
        select symbol, price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    h.send(["IBM", 700.0, 0])
    h.send(["WSO2", 60.5, 1])
    m.shutdown()
    assert [e.data[2] for e in q.events] == [0, 1]
    assert q.expired == []


def test_length_window_overflow_stream_view_order():
    """lengthWindowTest2 (:86-133): 6 events into length(4), StreamCallback
    view — the 5th/6th arrivals each emit [expired oldest, current new];
    expired rows precede their triggering current row
    (LengthWindowProcessor.java:124-137)."""
    m, rt, c = build("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.length(4)
        select symbol, price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    for v in range(1, 7):
        h.send(["IBM" if v % 2 else "WSO2", 700.0 if v % 2 else 60.5, v])
    m.shutdown()
    assert [e.data[2] for e in c.events] == [1, 2, 3, 4, 1, 5, 2, 6]


def test_length_window_overflow_query_view_counts():
    """lengthWindowTest3 (:135-187): same feed, QueryCallback view — 6 in
    events, 2 remove events."""
    m, rt, q = build_q("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.length(4)
        select symbol, price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    for v in range(1, 7):
        h.send(["IBM" if v % 2 else "WSO2", 700.0 if v % 2 else 60.5, v])
    m.shutdown()
    assert len(q.events) == 6
    assert len(q.expired) == 2
    assert [e.data[2] for e in q.expired] == [1, 2]


def test_length_window_null_rows_do_not_move_aggregates():
    """lengthWindowTest4 (:190-253): all-aggregator projection over
    length(4) with interleaved all-null rows — the null row after the 2nd
    event leaves min/sum/avg unchanged (aggregators skip nulls)."""
    m, rt, q = build_q("""
        define stream cseEventStream (symbol string, price float, volume int,
                                      price2 double, volume2 long, active bool);
        @info(name = 'query1')
        from cseEventStream#window.length(4)
        select max(price) as maxp, min(price) as minp, sum(price) as sump,
               avg(price) as avgp, stdDev(price) as stdp, count() as cp,
               distinctCount(price) as dcp, max(volume) as maxv,
               min(volume) as minv, sum(volume) as sumv,
               max(price2) as maxp2, sum(price2) as sump2,
               max(volume2) as maxv2, sum(volume2) as sumv2
        insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    h.send([None, None, None, None, None, None])
    h.send(["IBM", 700.0, 0, 0.0, 5, True])
    h.send([None, None, None, None, None, None])
    for _ in range(5):
        h.send(["IBM", 700.0, 0, 0.0, 5, True])
    m.shutdown()
    assert len(q.events) == 8
    second, third = q.events[1], q.events[2]
    # reference asserts data(1..3): minp, sump, avgp unchanged by the null
    assert third.data[1] == second.data[1] == 700.0
    assert third.data[2] == second.data[2] == 700.0
    assert third.data[3] == second.data[3] == 700.0


def test_length_window_rejects_second_parameter():
    """lengthWindowTest5 (:255-281): window.length(2, price) fails app
    creation (single-int @ParameterOverload)."""
    m = SiddhiManager()
    with pytest.raises(CREATION_ERRORS):
        m.create_siddhi_app_runtime("""
            define stream cseEventStream (symbol string, price float, volume int);
            from cseEventStream#window.length(2, price)
            select symbol, price, volume insert all events into OutStream;
        """)


def test_sum_rejects_two_arguments():
    """sumAggregatorTest57 (:283-316): sum(weight, deviceId) fails app
    creation."""
    m = SiddhiManager()
    with pytest.raises(CREATION_ERRORS):
        m.create_siddhi_app_runtime("""
            define stream cseEventStream (weight double, deviceId string);
            from cseEventStream#window.length(3)
            select sum(weight, deviceId) as total insert into OutStream;
        """)


def test_sum_rejects_string_argument():
    """sumAggregatorTest58 (:318-351): sum over a string attribute fails
    app creation."""
    m = SiddhiManager()
    with pytest.raises(CREATION_ERRORS):
        m.create_siddhi_app_runtime("""
            define stream cseEventStream (weight double, deviceId string);
            from cseEventStream#window.length(3)
            select sum(deviceId) as total insert into OutStream;
        """)


def test_avg_rejects_two_arguments():
    """avgAggregatorTest59 (:353-389): avg(weight, deviceId) fails app
    creation."""
    m = SiddhiManager()
    with pytest.raises(CREATION_ERRORS):
        m.create_siddhi_app_runtime("""
            define stream cseEventStream (weight double, deviceId string);
            from cseEventStream#window.length(5)
            select avg(weight, deviceId) as avgWeight insert into OutStream;
        """)


# ----------------------------------------------------- TimeWindowTestCase


TIME_APP = """@app:playback
    define stream cseEventStream (symbol string, price float, volume int);
    define stream Tick (x int);
    @info(name = 'query1')
    from cseEventStream#window.time({dur})
    select symbol, price, volume insert all events into OutStream;
    from Tick select x insert into TickOut;
"""


def test_time_window_expires_all_after_duration():
    """timeWindowTest1 (:45-86): 2 events into time(2 sec); after the
    duration both expire; in events always precede their removes."""
    m, rt, q = build_q(TIME_APP.format(dur="2 sec"))
    h = rt.get_input_handler("cseEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 0])
    h.send(1010, ["WSO2", 60.5, 1])
    tick.send(5100, [0])                    # Thread.sleep(4000)
    m.shutdown()
    assert len(q.events) == 2
    assert len(q.expired) == 2
    # in-before-remove: the interleaved order never shows a remove first
    seen_in = 0
    for kind, _ in q.order:
        if kind == "rm":
            assert seen_in > 0
        else:
            seen_in += 1


def test_time_window_rolling_batches_expire_in_order():
    """timeWindowTest2 (:94-139): three pairs spaced over 1 sec into
    time(1 sec) — 6 in, 6 remove."""
    m, rt, q = build_q(TIME_APP.format(dur="1 sec"))
    h = rt.get_input_handler("cseEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 1])
    h.send(1010, ["WSO2", 60.5, 2])
    h.send(2110, ["IBM", 700.0, 3])         # Thread.sleep(1100)
    h.send(2120, ["WSO2", 60.5, 4])
    h.send(3220, ["IBM", 700.0, 5])         # Thread.sleep(1100)
    h.send(3230, ["WSO2", 60.5, 6])
    tick.send(7300, [0])                    # Thread.sleep(4000)
    m.shutdown()
    assert [e.data[2] for e in q.events] == [1, 2, 3, 4, 5, 6]
    assert [e.data[2] for e in q.expired] == [1, 2, 3, 4, 5, 6]


def test_time_window_expired_feed_downstream_query():
    """timeWindowTest3 (:141-176): `insert expired events` output of a
    time(30 ms) window feeds a second query; both device ids arrive on the
    intermediate stream."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream fireAlarmEventStream (deviceID string, sonar double);
        define stream Tick (x int);
        @info(name = 'query1')
        from fireAlarmEventStream#window.time(30 milliseconds)
        select deviceID insert expired events into analyzeStream;
        @info(name = 'query2')
        from analyzeStream select deviceID insert into bulbOnStream;
        from Tick select x insert into TickOut;
    """)
    mid, out = Collector(), Collector()
    rt.add_callback("analyzeStream", mid)
    rt.add_callback("bulbOnStream", out)
    h = rt.get_input_handler("fireAlarmEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["id1", 20.0])
    h.send(1005, ["id2", 20.0])
    tick.send(3100, [0])                    # Thread.sleep(2000)
    m.shutdown()
    assert [e.data[0] for e in mid.events] == ["id1", "id2"]
    assert [e.data[0] for e in out.events] == ["id1", "id2"]


def test_time_window_rejects_second_parameter():
    """timeWindowTest4 (:178-192): window.time(2 sec, 5) fails creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream cseEventStream (symbol string, price float, volume int);
            from cseEventStream#window.time(2 sec, 5)
            select symbol, price, volume insert all events into OutStream;
        """)


def test_time_window_rejects_variable_parameter():
    """timeWindowTest5 (:194-208): window.time(time) with an attribute
    parameter fails creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream cseEventStream (symbol string, time long, volume int);
            from cseEventStream#window.time(time)
            select symbol, time, volume insert all events into OutStream;
        """)


def test_time_window_rejects_float_duration():
    """timeWindowTest6 (:210-224): window.time(4.7) fails creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream cseEventStream (symbol string, time long, volume int);
            from cseEventStream#window.time(4.7)
            select symbol, time, volume insert all events into OutStream;
        """)


# --------------------------------------------- ExternalTimeWindowTestCase


def test_external_time_window_event_driven_expiry():
    """externalTimeWindowTest1 (:48-97): externalTime(timestamp, 5 sec)
    over the reference's five login events — 5 in, 4 remove, expiry driven
    purely by the timestamp attribute."""
    m, rt, q = build_q("""
        define stream LoginEvents (timestamp long, ip string);
        @info(name = 'query1')
        from LoginEvents#window.externalTime(timestamp, 5 sec)
        select timestamp, ip insert all events into OutStream;
    """)
    h = rt.get_input_handler("LoginEvents")
    h.send([1366335804341, "192.10.1.3"])
    h.send([1366335804342, "192.10.1.4"])
    h.send([1366335814341, "192.10.1.5"])
    h.send([1366335814345, "192.10.1.6"])
    h.send([1366335824341, "192.10.1.7"])
    m.shutdown()
    assert len(q.events) == 5
    assert len(q.expired) == 4
    assert [e.data[1] for e in q.expired] == [
        "192.10.1.3", "192.10.1.4", "192.10.1.5", "192.10.1.6"]


def test_external_time_window_rejects_missing_duration():
    """externalTimeWindowTest2 (:99-149): externalTime(timestamp) without
    a duration fails creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream LoginEvents (timestamp long, ip string);
            from LoginEvents#window.externalTime(timestamp)
            select timestamp, ip insert all events into OutStream;
        """)


def test_external_time_window_rejects_int_timestamp():
    """externalTimeWindowTest3 (:151-185): an INT timestamp attribute
    fails creation (must be LONG)."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream LoginEvents (timestamp int, ip string);
            from LoginEvents#window.externalTime(timestamp, 5 sec)
            select timestamp, ip insert all events into OutStream;
        """)


def test_external_time_window_rejects_string_literal_timestamp():
    """externalTimeWindowTest4 (:187-225): a string constant in place of
    the timestamp attribute fails creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream LoginEvents (timestamp long, ip string);
            from LoginEvents#window.externalTime('timestamp', 5 sec)
            select timestamp, ip insert all events into OutStream;
        """)


# ----------------------------------------------------- SortWindowTestCase


def test_sort_window_single_key_counts():
    """sortWindowTest1 (:53-99): sort(2, volume, 'asc') keeps the two
    smallest volumes; 5 in, 3 remove."""
    m, rt, q = build_q("""
        define stream cseEventStream (symbol string, price float, volume long);
        @info(name = 'query1')
        from cseEventStream#window.sort(2, volume, 'asc')
        select volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    h.send(["WSO2", 55.6, 100])
    h.send(["IBM", 75.6, 300])
    h.send(["WSO2", 57.6, 200])
    h.send(["WSO2", 55.6, 20])
    h.send(["WSO2", 57.6, 40])
    m.shutdown()
    assert len(q.events) == 5
    assert len(q.expired) == 3
    # evictions: 300 (on 200's arrival), 200 (on 20's), 100 (on 40's)
    assert [e.data[0] for e in q.expired] == [300, 200, 100]


def test_sort_window_two_key_counts():
    """sortWindowTest2 (:101-148): sort(2, volume, 'asc', price, 'desc') —
    secondary descending price breaks volume ties; 5 in, 3 remove."""
    m, rt, q = build_q("""@app:name('sortWindow2')
        define stream cseEventStream (symbol string, price int, volume long);
        @info(name = 'query1')
        from cseEventStream#window.sort(2, volume, 'asc', price, 'desc')
        select price, volume insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    h.send(["WSO2", 50, 100])
    h.send(["IBM", 20, 100])
    h.send(["WSO2", 40, 50])
    h.send(["WSO2", 100, 20])
    h.send(["WSO2", 50, 50])
    m.shutdown()
    assert len(q.events) == 5
    assert len(q.expired) == 3


def test_sort_window_join():
    """sortWindowTest3 (:150-196): join of two sort(2, ...) windows on
    symbol == company — 3 joined outputs."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, index int);
        define stream twitterStream (id int, tweet string, company string);
        @info(name = 'query1')
        from cseEventStream#window.sort(2, index) join twitterStream#window.sort(2, id)
        on cseEventStream.symbol == twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    cse = rt.get_input_handler("cseEventStream")
    twitter = rt.get_input_handler("twitterStream")
    cse.send(["WSO2", 55.6, 100])
    cse.send(["IBM", 59.6, 101])
    twitter.send([10, "Hello World", "WSO2"])
    twitter.send([15, "Hello World2", "WSO2"])
    cse.send(["IBM", 75.6, 90])
    twitter.send([5, "Hello World2", "IBM"])
    m.shutdown()
    assert len(q.events) == 3


def test_sort_window_rejects_float_length():
    """sortWindowTest4 (:198-210): window.sort(2.5) fails creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream cseEventStream (symbol string, price float, volume int);
            from cseEventStream#window.sort(2.5)
            select symbol, price, volume insert all events into OutStream;
        """)


def test_sort_window_rejects_constant_sort_key():
    """sortWindowTest5 (:212-223): window.sort(2, 8) — a constant where an
    attribute is required fails creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream cseEventStream (symbol string, time long, volume int);
            from cseEventStream#window.sort(2, 8)
            select symbol, volume insert all events into OutStream;
        """)


def test_sort_window_rejects_bad_order_literal():
    """sortWindowTest6 (:225-235): 'ecs' is not a valid sort order."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream cseEventStream (symbol string, time long, volume int);
            from cseEventStream#window.sort(2, volume, 'ecs')
            select symbol, volume insert all events into OutStream;
        """)


# ------------------------------------------------- FrequentWindowTestCase


def test_frequent_window_all_attributes():
    """frequentUniqueWindowTest1 (:46-93): frequent(2) keyed on the whole
    row, 4 distinct rows fed twice — 8 in, 6 remove (Misra-Gries counter
    eviction, FrequentWindowProcessor)."""
    m, rt, q = build_q("""
        define stream purchase (cardNo string, price float);
        @info(name = 'query1')
        from purchase[price >= 30]#window.frequent(2)
        select cardNo, price insert all events into OutStream;
    """)
    h = rt.get_input_handler("purchase")
    for _ in range(2):
        h.send(["3234-3244-2432-4124", 73.36])
        h.send(["1234-3244-2432-123", 46.36])
        h.send(["5768-3244-2432-5646", 48.36])
        h.send(["9853-3244-2432-4125", 78.36])
    m.shutdown()
    assert len(q.events) == 8
    assert len(q.expired) == 6


def test_frequent_window_keyed_attribute():
    """frequentUniqueWindowTest2 (:96-146): frequent(2, cardNo) with two
    dominant cards — 8 in, 0 remove (the third card never displaces)."""
    m, rt, q = build_q("""
        define stream purchase (cardNo string, price float);
        @info(name = 'query1')
        from purchase[price >= 30]#window.frequent(2, cardNo)
        select cardNo, price insert all events into OutStream;
    """)
    h = rt.get_input_handler("purchase")
    for _ in range(2):
        h.send(["3234-3244-2432-4124", 73.36])
        h.send(["1234-3244-2432-123", 46.36])
        h.send(["3234-3244-2432-4124", 78.36])
        h.send(["1234-3244-2432-123", 86.36])
        h.send(["5768-3244-2432-5646", 48.36])
    m.shutdown()
    assert len(q.events) == 8
    assert len(q.expired) == 0


# --------------------------------------------- LossyFrequentWindowTestCase


def test_lossy_frequent_window_all_supported():
    """lossyFrequentUniqueWindowTest1 (:46-96): lossyFrequent(0.1, 0.01)
    over 4 rows × 25 — all 100 pass, the 2 tail events don't surface."""
    m, rt, q = build_q("""
        define stream purchase (cardNo string, price float);
        @info(name = 'query1')
        from purchase[price >= 30]#window.lossyFrequent(0.1, 0.01)
        select cardNo, price insert into OutStream;
    """)
    h = rt.get_input_handler("purchase")
    for _ in range(25):
        h.send(["3234-3244-2432-4124", 73.36])
        h.send(["1234-3244-2432-123", 46.36])
        h.send(["5768-3244-2432-5646", 48.36])
        h.send(["9853-3244-2432-4125", 78.36])
    h.send(["1124-3244-2432-4126", 78.36])
    h.send(["1124-3244-2432-4126", 78.36])
    m.shutdown()
    assert len(q.events) == 100
    assert len(q.expired) == 0


def test_lossy_frequent_window_support_threshold_eviction():
    """frequentUniqueWindowTest2 (:99-152): lossyFrequent(0.3, 0.05) — the
    lone first-card event is evicted when the frequency sweep runs; exactly
    1 remove."""
    m, rt, q = build_q("""
        define stream purchase (cardNo string, price float);
        @info(name = 'query1')
        from purchase[price >= 30]#window.lossyFrequent(0.3, 0.05)
        select cardNo, price insert all events into OutStream;
    """)
    h = rt.get_input_handler("purchase")
    h.send(["3224-3244-2432-4124", 73.36])
    for _ in range(25):
        h.send(["3234-3244-2432-4124", 73.36])
        h.send(["3234-3244-2432-4124", 78.36])
        h.send(["1234-3244-2432-123", 86.36])
        h.send(["5768-3244-2432-5646", 48.36])
    m.shutdown()
    assert len(q.expired) == 1


def test_lossy_frequent_window_keyed_attribute():
    """frequentUniqueWindowTest3 (:155-198): lossyFrequent(0.3, 0.05,
    cardNo) — keying on cardNo admits the third-priced row; 101 in, 1
    remove."""
    m, rt, q = build_q("""
        define stream purchase (cardNo string, price float);
        @info(name = 'query1')
        from purchase[price >= 30]#window.lossyFrequent(0.3, 0.05, cardNo)
        select cardNo, price insert all events into OutStream;
    """)
    h = rt.get_input_handler("purchase")
    h.send(["3224-3244-2432-4124", 73.36])
    for _ in range(25):
        h.send(["3234-3244-2432-4124", 73.36])
        h.send(["3234-3244-2432-4124", 78.36])
        h.send(["1234-3244-2432-123", 86.36])
        h.send(["3234-3244-2432-4124", 48.36])
    m.shutdown()
    assert len(q.events) == 101
    assert len(q.expired) == 1


# ----------------------------------------------------- CronWindowTestCase


CRON_APP = """@app:playback
    define stream cseEventStream (symbol string, price float, volume int);
    define stream Tick (x int);
    @info(name = 'query1')
    from cseEventStream#window.cron('*/5 * * * * ?')
    select symbol, price, volume insert {mode} into OutStream;
    from Tick select x insert into TickOut;
"""


def test_cron_window_current_events():
    """cronWindowTest1 (:46-91): three pairs sent across three */5 fires —
    6 current events flushed on the schedule."""
    m, rt, c = build(CRON_APP.format(mode=""))
    h = rt.get_input_handler("cseEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 0])
    h.send(1100, ["WSO2", 60.5, 1])
    tick.send(7000, [0])                 # Thread.sleep(7000): fire at 5000
    h.send(7100, ["IBM1", 700.0, 0])
    h.send(7200, ["WSO22", 60.5, 1])
    tick.send(14000, [0])                # fire at 10000
    h.send(14100, ["IBM43", 700.0, 0])
    h.send(14200, ["WSO4343", 60.5, 1])
    tick.send(21000, [0])                # fire at 15000/20000
    m.shutdown()
    assert [e.data[0] for e in c.events] == [
        "IBM", "WSO2", "IBM1", "WSO22", "IBM43", "WSO4343"]


def test_cron_window_expired_events():
    """cronWindowTest2 (:94-136): same feed, `insert expired events` — each
    fire expires the previous batch: 4 expired rows by the third fire."""
    m, rt, c = build(CRON_APP.format(mode="expired events"))
    h = rt.get_input_handler("cseEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 0])
    h.send(1100, ["WSO2", 60.5, 1])
    tick.send(7000, [0])
    h.send(7100, ["IBM1", 700.0, 0])
    h.send(7200, ["WSO22", 60.5, 1])
    tick.send(14000, [0])
    h.send(14100, ["IBM43", 700.0, 0])
    h.send(14200, ["WSO4343", 60.5, 1])
    # the reference polls until exactly 4 removes then shuts down — stop
    # the clock after the 15000 fire but before 20000 expires batch 3
    tick.send(16000, [0])
    m.shutdown()
    assert [e.data[0] for e in c.events] == [
        "IBM", "WSO2", "IBM1", "WSO22"]


# ----------------------------------------------- TimeLengthWindowTestCase


TL_APP = """@app:playback
    define stream S (symbol string, price float, volume int);
    define stream Tick (x int);
    @info(name = 'query1')
    from S#window.timeLength({params})
    select symbol, price, volume insert all events into OutStream;
    from Tick select x insert into TickOut;
"""


def test_time_length_under_both_bounds():
    """timeLengthWindowTest1 (:52-96): 4 events inside both the 4 sec and
    10-length bounds — all 4 expire by time after the wait."""
    m, rt, q = build_q(TL_APP.format(params="4 sec, 10"))
    h = rt.get_input_handler("S")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 1])
    h.send(1500, ["WSO2", 60.5, 2])
    h.send(2000, ["IBM", 700.0, 3])
    h.send(2500, ["WSO2", 60.5, 4])
    tick.send(7600, [0])                 # Thread.sleep(5000)
    m.shutdown()
    assert len(q.events) == 4
    assert [e.data[2] for e in q.expired] == [1, 2, 3, 4]


def test_time_length_time_expiry_between_arrivals():
    """timeLengthWindowTest2 (:102-150): arrivals spaced past the 2 sec
    bound — each expires before the suite ends; 4 in, 4 remove."""
    m, rt, q = build_q(TL_APP.format(params="2 sec, 10"))
    h = rt.get_input_handler("S")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 0])
    h.send(2200, ["WSO2", 60.5, 1])
    h.send(3400, ["Google", 80.5, 2])
    h.send(4600, ["Yahoo", 90.5, 3])
    tick.send(8700, [0])                 # Thread.sleep(4000)
    m.shutdown()
    assert len(q.events) == 4
    assert [e.data[2] for e in q.expired] == [0, 1, 2, 3]


def test_time_length_length_evictions_only():
    """timeLengthWindowTest3 (:156-212): 8 events within the 10 sec bound
    into length 4 — the 4 oldest are evicted by the length bound."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream sensorStream (id string, sensorValue double);
        define stream Tick (x int);
        @info(name = 'query1')
        from sensorStream#window.timeLength(10 sec, 4)
        select id, sensorValue insert all events into OutStream;
        from Tick select x insert into TickOut;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    h = rt.get_input_handler("sensorStream")
    tick = rt.get_input_handler("Tick")
    for i, v in enumerate([10.0, 20.0, 30.0, 40.0, 50.0, 60.0, 70.0, 80.0]):
        h.send(1000 + 500 * i, [f"id{i + 1}", v])
    tick.send(6600, [0])                 # Thread.sleep(2000)
    m.shutdown()
    assert len(q.events) == 8
    assert [e.data[0] for e in q.expired] == ["id1", "id2", "id3", "id4"]


def test_time_length_mixed_expiry():
    """timeLengthWindowTest4 (:215-260): 6 events, 2 sec / length 4 — every
    event leaves (by time or by eviction); 6 in, 6 remove."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream sensorStream (id string, sensorValue double);
        define stream Tick (x int);
        @info(name = 'query1')
        from sensorStream#window.timeLength(2 sec, 4)
        select id, sensorValue insert all events into OutStream;
        from Tick select x insert into TickOut;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    h = rt.get_input_handler("sensorStream")
    tick = rt.get_input_handler("Tick")
    for i, v in enumerate([10.0, 20.0, 30.0, 40.0, 50.0, 60.0]):
        h.send(1000 + 500 * i, [f"id{i + 1}", v])
    tick.send(5600, [0])                 # Thread.sleep(2100)
    m.shutdown()
    assert len(q.events) == 6
    assert len(q.expired) == 6


def test_time_length_window_length_five():
    """timeLengthWindowTest(:398-456): 8 events into timeLength(10 sec, 5)
    — 3 length evictions, no time expiry before shutdown."""
    m, rt, q = build_q(TL_APP.format(params="10 sec, 5"))
    h = rt.get_input_handler("S")
    tick = rt.get_input_handler("Tick")
    vols = [10, 20, 20, 40, 50, 60, 70, 80]
    for i, v in enumerate(vols):
        h.send(1000 + 500 * i, ["IBM" if i % 2 == 0 else "WSO2",
                                700.0 if i % 2 == 0 else 60.5, v])
    tick.send(9600, [0])                 # Thread.sleep(5000) < 10 sec bound
    m.shutdown()
    assert len(q.events) == 8
    assert len(q.expired) == 3


def test_time_length_rejects_single_parameter():
    """timeLengthWindowTest11 (:458-...): timeLength(4 sec) fails
    creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream S (symbol string, price float, volume int);
            from S#window.timeLength(4 sec)
            select symbol, price, volume insert all events into OutStream;
        """)


def test_time_length_rejects_expression_duration():
    """timeLengthWindowTest12: timeLength(1/2 sec, 4) — a computed
    duration fails creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream S (symbol string, price float, volume int);
            from S#window.timeLength(1/2 sec, 4)
            select symbol, price, volume insert all events into OutStream;
        """)


def test_time_length_rejects_string_duration():
    """timeLengthWindowTest13: timeLength('4 sec', 4) fails creation."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream S (symbol string, price float, volume int);
            from S#window.timeLength('4 sec', 4)
            select symbol, price, volume insert all events into OutStream;
        """)


# -------------------------------------- LengthBatch streamCurrentEvents


class ChunkCollector(StreamCallback):
    """Records per-delivery chunk sizes (the reference's StreamCallback
    receives one Event[] per output chunk)."""

    def __init__(self):
        super().__init__()
        self.chunks = []
        self.events = []

    def receive(self, events):
        self.chunks.append(len(events))
        self.events.extend(events)


def test_length_batch_stream_current_chunk_shapes():
    """lengthBatchWindowTest10 (:477-531): lengthBatch(4, true) `insert all
    events` — every arrival passes through as its own chunk; each cycle
    boundary delivers a 5-event chunk [4 expired, current]; 17 rows total
    (7 singles + 2 fives)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.lengthBatch(4, true)
        select symbol, price, volume insert all events into OutStream;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("cseEventStream")
    for v in [1, 2, 3, 4, 5, 6, 4, 5, 6]:
        h.send(["IBM", 700.0, v])
    m.shutdown()
    assert sum(c.chunks) == 17
    assert sum(1 for n in c.chunks if n == 1) == 7
    assert sum(1 for n in c.chunks if n == 5) == 2


def test_length_batch_stream_current_running_count():
    """lengthBatchWindowTest11 (:533-590): lengthBatch(4, true) + count()
    `insert into` — 9 single-row outputs whose count cycles within 1..4."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.lengthBatch(4, true)
        select symbol, price, count() as volumes insert into OutStream;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("cseEventStream")
    for v in [1, 2, 3, 4, 5, 6, 4, 5, 6]:
        h.send(["IBM", 700.0, v])
    m.shutdown()
    assert len(c.events) == 9
    assert all(n == 1 for n in c.chunks)
    counts = [e.data[2] for e in c.events]
    assert all(1 <= n <= 4 for n in counts)
    assert counts == [1, 2, 3, 4, 1, 2, 3, 4, 1]


def test_length_batch_stream_current_expired_collapse():
    """lengthBatchWindowTest12 (:592-645): lengthBatch(4, true) + count()
    `insert expired events` — each boundary's expired chunk collapses to
    one row whose count has decremented back to 0."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.lengthBatch(4, true)
        select symbol, price, count() as volumes insert expired events into OutStream;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("cseEventStream")
    for v in [1, 2, 3, 4, 5, 6, 4, 5, 6]:
        h.send(["IBM", 700.0, v])
    m.shutdown()
    assert len(c.events) == 2
    assert all(e.data[2] == 0 for e in c.events)


def test_length_batch_stream_current_join():
    """lengthBatchWindowTest13 (:647-694): join of two lengthBatch(2, true)
    sides — 2 in events, 1 remove."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        define stream twitterStream (user string, tweet string, company string);
        @info(name = 'query1')
        from cseEventStream#window.lengthBatch(2, true) join twitterStream#window.lengthBatch(2, true)
        on cseEventStream.symbol == twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert all events into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    cse = rt.get_input_handler("cseEventStream")
    twitter = rt.get_input_handler("twitterStream")
    cse.send(["WSO2", 55.6, 100])
    twitter.send(["User1", "Hello World", "WSO2"])
    cse.send(["IBM", 75.6, 100])
    cse.send(["WSO2", 57.6, 100])
    m.shutdown()
    assert len(q.events) == 2
    assert len(q.expired) == 1


# ---------------------------------------- TimeBatch streamCurrentEvents


TB_STREAM_APP = """@app:playback
    define stream cseEventStream (symbol string, price float, volume int);
    define stream Tick (x int);
    @info(name = 'query1')
    from cseEventStream#window.timeBatch(1 sec, true)
    select {sel} insert all events into OutStream;
    from Tick select x insert into TickOut;
"""


def _feed_tb_stream(rt):
    h = rt.get_input_handler("cseEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 1])
    tick.send(2100, [0])                 # Thread.sleep(1100): flush {1}
    h.send(2150, ["WSO2", 60.5, 2])
    h.send(2160, ["IBM", 700.0, 3])
    h.send(2170, ["WSO2", 60.5, 4])
    tick.send(3300, [0])                 # flush {2,3,4}
    h.send(3350, ["IBM", 700.0, 5])
    h.send(3360, ["WSO2", 60.5, 6])
    tick.send(4600, [0])                 # flush {5,6}


def test_time_batch_stream_current_passthrough():
    """timeWindowBatchTest9 (:432-476): timeBatch(1 sec, true) no
    aggregate — 6 pass-through currents, 6 expired at the three flushes."""
    m, rt, q = build_q(TB_STREAM_APP.format(sel="symbol, price"))
    _feed_tb_stream(rt)
    m.shutdown()
    assert len(q.events) == 6
    assert len(q.expired) == 6


def test_time_batch_stream_current_sum_collapse():
    """timeWindowBatchTest10 (:478-529): timeBatch(1 sec, true) + sum —
    currents stream individually (6) while each flush's expired chunk
    collapses to a single aggregate row (3)."""
    m, rt, q = build_q(TB_STREAM_APP.format(sel="symbol, sum(price) as total"))
    _feed_tb_stream(rt)
    m.shutdown()
    assert len(q.events) == 6
    assert len(q.expired) == 3


def test_time_batch_rejects_bad_overloads():
    """timeWindowBatchTest11-16 (:531-1027): invalid second/third
    parameters fail creation; valid startTime forms are accepted."""
    bad = [
        "timeBatch(1 sec, 1/2)",
        "timeBatch(2 sec, 'string')",
        "timeBatch('2 sec', 0)",
        "timeBatch(1/2, 0)",
        "timeBatch(1 sec, true, 100)",
        "timeBatch(1 sec, 1/2, 100)",
        "timeBatch(1 sec, 0, 1/2)",
        "timeBatch(1 sec, 123L, 'true')",
        "timeBatch(1 sec, 123L, true, 100)",
    ]
    for w in bad:
        with pytest.raises(CREATION_ERRORS):
            SiddhiManager().create_siddhi_app_runtime(
                "define stream S (symbol string, price float, volume int); "
                f"from S#window.{w} select symbol insert all events into OutStream;")
    for w in ["timeBatch(2 sec, 0)", "timeBatch(2 sec, 123L)",
              "timeBatch(2 sec, 5 sec)", "timeBatch(1 sec, 123L, true)"]:
        m = SiddhiManager()
        m.create_siddhi_app_runtime(
            "define stream S (symbol string, price float, volume int); "
            f"from S#window.{w} select symbol insert all events into OutStream;")
        m.shutdown()


# --------------------------------------------------- batch(chunkLength)


def test_batch_window_chunk_length_splits_bulk_sends():
    """BatchWindowProcessor.java:107-118: batch(2) splits a 5-row chunk
    into flushes of ≤2 rows — running sums reset per sub-batch; batch()
    keeps the whole chunk as one batch."""
    import numpy as np

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (k string, v int);
        @info(name = 'query1')
        from S#window.batch(2) select k, sum(v) as t insert into OutStream;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("S")
    h.send_columns({"k": np.array(["a", "b", "c", "d", "e"]),
                    "v": np.array([1, 2, 3, 4, 5], np.int64)})
    m.shutdown()
    # flushes {1,2}, {3,4}, {5} — sum aggregates collapse per flush
    assert [e.data[1] for e in c.events] == [3, 7, 5]


def test_batch_window_rejects_string_length():
    """batch('2') fails creation (chunkLength must be int)."""
    with pytest.raises(CREATION_ERRORS):
        SiddhiManager().create_siddhi_app_runtime(
            "define stream S (k string, v int); "
            "from S#window.batch('2') select k insert into OutStream;")


# ------------------------------------------- LengthBatchWindowTestCase


LB_APP = """
    define stream cseEventStream (symbol string, price float, volume int);
    @info(name = 'query1')
    from cseEventStream#window.lengthBatch({params})
    select {sel} insert {mode} into OutStream;
"""


def _feed6(h):
    for v in range(1, 7):
        h.send(["IBM" if v % 2 else "WSO2", 700.0 if v % 2 else 60.5, v])


def test_length_batch_no_flush_below_length():
    """lengthBatchWindowTest1 (:51-88): 2 events into lengthBatch(4) —
    nothing flushes."""
    m, rt, q = build_q(LB_APP.format(params="4", sel="symbol, price, volume",
                                     mode=""))
    h = rt.get_input_handler("cseEventStream")
    h.send(["IBM", 700.0, 0])
    h.send(["WSO2", 60.5, 1])
    m.shutdown()
    assert q.events == [] and q.expired == []


def test_length_batch_single_flush_order():
    """lengthBatchWindowTest2 (:90-132): 6 events into lengthBatch(4) —
    one flush of the first 4, in order."""
    m, rt, c = build(LB_APP.format(params="4", sel="symbol, price, volume",
                                   mode=""))
    h = rt.get_input_handler("cseEventStream")
    _feed6(h)
    m.shutdown()
    assert [e.data[2] for e in c.events] == [1, 2, 3, 4]


def test_length_batch_all_events_expiry_interleave():
    """lengthBatchWindowTest3 (:134-190): lengthBatch(2) `insert all
    events` — flushes alternate [currents],[expired prev + currents]: the
    stream view sees 1,2 then 1,2,3,4 then 3,4,5,6."""
    m, rt, c = build(LB_APP.format(params="2", sel="symbol, price, volume",
                                   mode="all events"))
    h = rt.get_input_handler("cseEventStream")
    _feed6(h)
    m.shutdown()
    assert [e.data[2] for e in c.events] == [1, 2, 1, 2, 3, 4, 3, 4, 5, 6]


def test_length_batch_sum_single_row_per_flush():
    """lengthBatchWindowTest4 (:192-234): lengthBatch(4) + sum `insert
    into` — one row per flush, sum of the batch (100.0)."""
    m, rt, c = build(LB_APP.format(params="4",
                                   sel="symbol, sum(price) as sumPrice, volume",
                                   mode=""))
    h = rt.get_input_handler("cseEventStream")
    for sym, p, v in [("IBM", 10.0, 0), ("WSO2", 20.0, 1), ("IBM", 30.0, 0),
                      ("WSO2", 40.0, 1), ("IBM", 50.0, 0), ("WSO2", 60.0, 1)]:
        h.send([sym, p, v])
    m.shutdown()
    assert [e.data[1] for e in c.events] == [100.0]


def test_length_batch_expired_only_view():
    """lengthBatchWindowTest5 (:236-277): lengthBatch(2) `insert expired
    events` — the first batch expires when the second flushes: rows 1-4."""
    m, rt, c = build(LB_APP.format(params="2", sel="symbol, price, volume",
                                   mode="expired events"))
    h = rt.get_input_handler("cseEventStream")
    _feed6(h)
    m.shutdown()
    assert [e.data[2] for e in c.events] == [1, 2, 3, 4]


def test_length_batch_sum_all_events_collapse():
    """lengthBatchWindowTest6 (:279-326) / test7 (:329-373): lengthBatch(4)
    + sum `insert all events` — each flush chunk collapses to its LAST row
    (the final current), so the expired decrements never surface: 100.0
    then 240.0 (QuerySelector.processInBatchNoGroupBy keeps one lastEvent
    per chunk across both types)."""
    m, rt, q = build_q(LB_APP.format(params="4",
                                     sel="symbol, sum(price) as sumPrice, volume",
                                     mode="all events"))
    h = rt.get_input_handler("cseEventStream")
    for sym, p, v in [("IBM", 10.0, 0), ("WSO2", 20.0, 1), ("IBM", 30.0, 0),
                      ("WSO2", 40.0, 1), ("IBM", 50.0, 0), ("WSO2", 60.0, 1),
                      ("WSO2", 60.0, 1), ("IBM", 70.0, 0), ("WSO2", 80.0, 1)]:
        h.send([sym, p, v])
    m.shutdown()
    assert [e.data[1] for e in q.events] == [100.0, 240.0]
    assert q.expired == []


def test_length_batch_join():
    """lengthBatchWindowTest8 (:379-426): join of two lengthBatch(2) sides
    `insert all events` — 4 in, 2 remove."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        define stream twitterStream (user string, tweet string, company string);
        @info(name = 'query1')
        from cseEventStream#window.lengthBatch(2) join twitterStream#window.lengthBatch(2)
        on cseEventStream.symbol == twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert all events into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    cse = rt.get_input_handler("cseEventStream")
    twitter = rt.get_input_handler("twitterStream")
    cse.send(["WSO2", 55.6, 100])
    cse.send(["IBM", 59.6, 100])
    twitter.send(["User1", "Hello World", "WSO2"])
    twitter.send(["User2", "Hello World2", "WSO2"])
    cse.send(["IBM", 75.6, 100])
    cse.send(["WSO2", 57.6, 100])
    m.shutdown()
    assert len(q.events) == 4
    assert len(q.expired) == 2


def test_length_batch_join_current_only():
    """lengthBatchWindowTest9 (:428-475): same join `insert into` — only
    the 4 in events."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        define stream twitterStream (user string, tweet string, company string);
        @info(name = 'query1')
        from cseEventStream#window.lengthBatch(2) join twitterStream#window.lengthBatch(2)
        on cseEventStream.symbol == twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    cse = rt.get_input_handler("cseEventStream")
    twitter = rt.get_input_handler("twitterStream")
    cse.send(["WSO2", 55.6, 100])
    cse.send(["IBM", 59.6, 100])
    twitter.send(["User1", "Hello World", "WSO2"])
    twitter.send(["User2", "Hello World2", "WSO2"])
    cse.send(["IBM", 75.6, 100])
    cse.send(["WSO2", 57.6, 100])
    m.shutdown()
    assert len(q.events) == 4
    assert q.expired == []


def test_length_batch_stream_current_boundary_collapses_with_count():
    """lengthBatchWindowTest21 (:1045-1099): lengthBatch(3, true) + count()
    `insert all events` — 9 single-row chunks, counts cycling 1..3; the
    boundary chunk [expired×3, RESET, current] collapses to the current."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.lengthBatch(3, true)
        select symbol, price, count() as volumes insert all events into OutStream;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("cseEventStream")
    for v in [1, 2, 3, 4, 5, 6, 4, 5, 6]:
        h.send(["IBM", 700.0, v])
    m.shutdown()
    assert all(n == 1 for n in c.chunks)
    assert [e.data[2] for e in c.events] == [1, 2, 3, 1, 2, 3, 1, 2, 3]


def test_length_batch_length_one():
    """lengthBatchWindowTest16 (:798-852): lengthBatch(1) + count() — every
    event is its own batch; 9 single-row chunks with count 1."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.lengthBatch(1)
        select symbol, price, count() as volumes insert all events into OutStream;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("cseEventStream")
    for v in [1, 2, 3, 4, 5, 6, 4, 5, 6]:
        h.send(["IBM", 700.0, v])
    m.shutdown()
    assert all(n == 1 for n in c.chunks)
    assert [e.data[2] for e in c.events] == [1] * 9


def test_length_batch_length_zero():
    """lengthBatchWindowTest17 (:854-910): lengthBatch(0) + count() — each
    event is an instant batch [current, expired, RESET]; the chunk
    collapses to the expired clone whose count decremented back to 0."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.lengthBatch(0)
        select symbol, price, count() as volumes insert all events into OutStream;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("cseEventStream")
    for v in [1, 2, 3, 4, 5, 6, 4, 5, 6]:
        h.send(["IBM", 700.0, v])
    m.shutdown()
    assert all(n == 1 for n in c.chunks)
    assert [e.data[2] for e in c.events] == [0] * 9


def test_length_batch_rejects_bad_params():
    """lengthBatchWindowTest18-20 (:911-1044): three params, an expression
    length, and a non-bool second parameter all fail creation."""
    for w in ["lengthBatch(1, true, 100)", "lengthBatch(1/2)",
              "lengthBatch(3, 1/2)"]:
        with pytest.raises(CREATION_ERRORS):
            SiddhiManager().create_siddhi_app_runtime(
                "define stream S (symbol string, price float, volume int); "
                f"from S#window.{w} select symbol insert all events into OutStream;")


# --------------------------------------------- TimeBatchWindowTestCase


TB_APP = """@app:playback
    define stream cseEventStream (symbol string, price float, volume int);
    define stream Tick (x int);
    @info(name = 'query1')
    from cseEventStream#window.timeBatch({params})
    select {sel} insert {mode} into OutStream;
    from Tick select x insert into TickOut;
"""


def test_time_batch_first_flush_then_expiry():
    """timeWindowBatchTest1 (:47-90): 2 events in the first period of
    timeBatch(1 sec) + sum — one in row at the first flush, one remove row
    when the batch expires a period later."""
    m, rt, q = build_q(TB_APP.format(params="1 sec",
                                     sel="symbol, sum(price) as sumPrice, volume",
                                     mode="all events"))
    h = rt.get_input_handler("cseEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 0])
    h.send(1010, ["WSO2", 60.5, 1])
    tick.send(4100, [0])                 # Thread.sleep(3000)
    m.shutdown()
    assert len(q.events) == 1
    assert len(q.expired) == 1
    assert q.events[0].data[1] == 760.5


def _feed_tb(rt):
    h = rt.get_input_handler("cseEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 1])
    h.send(2150, ["WSO2", 60.5, 2])      # Thread.sleep(1100)
    h.send(2160, ["IBM", 700.0, 3])
    h.send(2170, ["WSO2", 60.5, 4])
    h.send(3300, ["IBM", 700.0, 5])      # Thread.sleep(1100)
    h.send(3310, ["WSO2", 60.5, 6])
    tick.send(5400, [0])                 # Thread.sleep(2000)


def test_time_batch_sum_all_events():
    """timeWindowBatchTest2 (:92-137): three non-empty batches collapse to
    3 in rows; the final period's expiry adds 1 remove row."""
    m, rt, q = build_q(TB_APP.format(params="1 sec",
                                     sel="symbol, sum(price) as price",
                                     mode="all events"))
    _feed_tb(rt)
    m.shutdown()
    assert len(q.events) == 3
    assert len(q.expired) == 1


def test_time_batch_sum_current_only():
    """timeWindowBatchTest3 (:139-184): `insert into` — 3 in rows, no
    removes."""
    m, rt, q = build_q(TB_APP.format(params="1 sec",
                                     sel="symbol, sum(price) as price",
                                     mode=""))
    _feed_tb(rt)
    m.shutdown()
    assert len(q.events) == 3
    assert q.expired == []


def test_time_batch_sum_expired_only():
    """timeWindowBatchTest4 (:186-231): `insert expired events` — each
    flush's expired chunk collapses to one row: 3 removes, no ins."""
    m, rt, q = build_q(TB_APP.format(params="1 sec",
                                     sel="symbol, sum(price) as price",
                                     mode="expired events"))
    _feed_tb(rt)
    m.shutdown()
    assert q.events == []
    assert len(q.expired) == 3


def _tb_join_app(window):
    return f"""@app:playback
        define stream cseEventStream (symbol string, price float, volume int);
        define stream twitterStream (user string, tweet string, company string);
        define stream Tick (x int);
        @info(name = 'query1')
        from cseEventStream#window.{window} join twitterStream#window.{window}
        on cseEventStream.symbol == twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert {{mode}} into OutStream;
        from Tick select x insert into TickOut;
    """


def _feed_tb_join(rt, end_ts):
    cse = rt.get_input_handler("cseEventStream")
    twitter = rt.get_input_handler("twitterStream")
    tick = rt.get_input_handler("Tick")
    cse.send(1000, ["WSO2", 55.6, 100])
    twitter.send(1010, ["User1", "Hello World", "WSO2"])
    cse.send(1020, ["IBM", 75.6, 100])
    tick.send(2150, [0])                 # Thread.sleep(1100)
    cse.send(2200, ["WSO2", 57.6, 100])
    tick.send(end_ts, [0])               # final sleep
    return rt


def test_time_batch_join_all_events():
    """timeWindowBatchTest5 (:233-280): join of two timeBatch(1 sec) sides
    `insert all events` — the reference accepts 1..2 in and 1..2 remove."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(_tb_join_app("timeBatch(1 sec)").format(mode="all events"))
    q = QCollect()
    rt.add_callback("query1", q)
    _feed_tb_join(rt, 3250)
    m.shutdown()
    assert 1 <= len(q.events) <= 2
    assert 1 <= len(q.expired) <= 2


def test_time_batch_join_current_only():
    """timeWindowBatchTest6 (:282-328): same join `insert into` — no
    removes reach the callback."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(_tb_join_app("timeBatch(1 sec)").format(mode=""))
    q = QCollect()
    rt.add_callback("query1", q)
    _feed_tb_join(rt, 3300)
    m.shutdown()
    assert q.expired == []


def test_time_batch_start_time_anchored_batches():
    """timeWindowBatchTest7 (:330-384): timeBatch(2 sec, 0) anchors
    boundaries at even seconds — three non-empty batches, three in rows,
    no removes for `insert into`."""
    m, rt, q = build_q(TB_APP.format(params="2 sec, 0",
                                     sel="symbol, sum(price) as sumPrice, volume",
                                     mode=""))
    h = rt.get_input_handler("cseEventStream")
    tick = rt.get_input_handler("Tick")
    h.send(1000, ["IBM", 700.0, 0])
    h.send(1010, ["WSO2", 60.5, 1])
    tick.send(9600, [0])                 # Thread.sleep(8500)
    h.send(9700, ["WSO2", 60.5, 1])
    h.send(9710, ["II", 60.5, 1])
    tick.send(22700, [0])                # Thread.sleep(13000)
    h.send(22800, ["TT", 60.5, 1])
    h.send(22810, ["YY", 60.5, 1])
    tick.send(27900, [0])                # Thread.sleep(5000)
    m.shutdown()
    assert len(q.events) == 3
    assert q.expired == []


def test_time_batch_stream_current_join():
    """timeWindowBatchTest8 (:386-430): join of two timeBatch(1 sec, true)
    sides — exactly one remove event."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        _tb_join_app("timeBatch(1 sec, true)").format(mode="all events"))
    q = QCollect()
    rt.add_callback("query1", q)
    _feed_tb_join(rt, 3650)
    m.shutdown()
    assert len(q.expired) == 1


# ------------------------------------- ExternalTimeBatchWindowTestCase


ETB_APP = """@app:playback
    define stream LoginEvents (timestamp long, ip string);
    define stream Tick (x int);
    @info(name = 'query1')
    from LoginEvents#window.externalTimeBatch({params})
    select timestamp, ip, count() as total insert all events into OutStream;
    from Tick select x insert into TickOut;
"""


def test_etb_no_crossing_no_output():
    """test02NoMsg (:56-82): five events inside one 10 sec window — no
    crossing, no output."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream jmxMetric (cpu int, timestamp long);
        @info(name = 'query')
        from jmxMetric#window.externalTimeBatch(timestamp, 10 sec)
        select avg(cpu) as avgCpu, count() as c insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query", q)
    h = rt.get_input_handler("jmxMetric")
    now = 1700000000000
    for i in range(5):
        h.send(now + i * 1000, [15, now + i * 1000])
    m.shutdown()
    assert q.events == []


def test_etb_edge_case_rounds_do_not_mix():
    """test05EdgeCase (:100-142): the crossing event starts the next batch
    and never joins the flushing one — avg 15 then avg 85, count 3 both."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream jmxMetric (cpu int, timestamp long);
        @info(name = 'query')
        from jmxMetric#window.externalTimeBatch(timestamp, 10 sec)
        select avg(cpu) as avgCpu, count() as c insert into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query", q)
    h = rt.get_input_handler("jmxMetric")
    for i in range(3):
        h.send(1000 + i, [15, i * 10])
    for i in range(3):
        h.send(2000 + i, [85, 10000 + i * 10])
    h.send(3000, [10000, 100000])
    m.shutdown()
    assert [(e.data[0], e.data[1]) for e in q.events] == [(15.0, 3), (85.0, 3)]


def test_etb_down_sampling_one_row_per_round():
    """test01DownSampling (:144-209): 5 rounds of 3 events 10 sec apart —
    the aggregate projection emits exactly one row per completed round (4),
    while the raw stream callback sees all 15."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream jmxMetric (cpu int, memory int, timestamp long);
        @info(name = 'downSample')
        from jmxMetric#window.externalTimeBatch(timestamp, 10 sec)
        select avg(cpu) as avgCpu, max(cpu) as maxCpu, min(cpu) as minCpu,
               avg(memory) as avgMem, timestamp as timeWindowEnds,
               count() as metric_count
        insert into OutStream;
    """)
    raw, q = Collector(), QCollect()
    rt.add_callback("jmxMetric", raw)
    rt.add_callback("downSample", q)
    h = rt.get_input_handler("jmxMetric")
    base = 1700000000000
    for ite in range(5):
        for i in range(3):
            h.send(base + ite * 10000 + i * 50,
                   [15 + 10 * i * ite, 1500 + 10 * i * ite,
                    base + ite * 10000 + i * 50])
    m.shutdown()
    assert len(raw.events) == 15
    assert len(q.events) == 4
    assert all(e.data[5] == 3 for e in q.events)


def test_etb_first_event_anchors_batches():
    """test1 (:226-286): externalTimeBatch(currentTime, 5 sec) without a
    startTime anchors on the first event — flushes lead with values 1, 6,
    11."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream inputStream (currentTime long, value int);
        @info(name = 'query')
        from inputStream#window.externalTimeBatch(currentTime, 5 sec)
        select value insert into OutStream;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("inputStream")
    feed = [(10000, 1), (11000, 2), (12000, 3), (13000, 4), (14000, 5),
            (15000, 6), (16500, 7), (17000, 8), (18000, 9), (19000, 10),
            (20000, 11), (20500, 12), (22000, 13), (25000, 14)]
    for ts, v in feed:
        h.send(ts, [ts, v])
    m.shutdown()
    assert len(c.chunks) == 3
    firsts = []
    i = 0
    for n in c.chunks:
        firsts.append(c.events[i].data[0])
        i += n
    assert firsts == [1, 6, 11]


def test_etb_start_time_anchors_batches():
    """test2 (:288-324): externalTimeBatch(currentTime, 5 sec, 1200) —
    boundaries at 1200+5000k: the first flush is values 0..11, the second
    starts at 12."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream inputStream (currentTime long, value int);
        @info(name = 'query')
        from inputStream#window.externalTimeBatch(currentTime, 5 sec, 1200)
        select value insert into OutStream;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("inputStream")
    for i in range(0, 10000, 100):
        h.send(i + 10000, [i + 10000, i // 100])
    m.shutdown()
    assert len(c.chunks) == 2
    assert c.events[0].data[0] == 0
    assert c.events[c.chunks[0] - 1].data[0] == 11
    assert c.events[c.chunks[0]].data[0] == 12


def test_etb_scheduler_flushes_last_batch():
    """schedulerLastBatchTriggerTest (:326-393): with a 6 sec timeout the
    trailing batches flush on the scheduler — flush heads 1, 6, 11, 14,
    15."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream inputStream (currentTime long, value int);
        define stream Tick (x int);
        @info(name = 'query')
        from inputStream#window.externalTimeBatch(currentTime, 5 sec, 0, 6 sec)
        select value, currentTime insert current events into OutStream;
        from Tick select x insert into TickOut;
    """)
    c = ChunkCollector()
    rt.add_callback("OutStream", c)
    h = rt.get_input_handler("inputStream")
    tick = rt.get_input_handler("Tick")
    feed = [(10000, 1), (11000, 2), (12000, 3), (13000, 4), (14000, 5),
            (15000, 6), (16500, 7), (17000, 8), (18000, 9), (19000, 10),
            (20100, 11), (20500, 12), (22000, 13), (25000, 14),
            (32000, 15), (33000, 16)]
    for ts, v in feed:
        h.send(ts, [ts, v])
    tick.send(40000, [0])                # Thread.sleep(6000): timeout flush
    m.shutdown()
    firsts = []
    i = 0
    for n in c.chunks:
        firsts.append(c.events[i].data[0])
        i += n
    assert firsts[:4] == [1, 6, 11, 14]
    assert 15 in firsts


def test_etb_timeout_batches_with_count():
    """externalTimeBatchWindowTest1 (:395-441): (timestamp, 1 sec, 0,
    6 sec) + count() `insert all events` — two crossings before the
    timeout would fire: 2 in rows, 0 removes."""
    m, rt, q = build_q(ETB_APP.format(params="timestamp, 1 sec, 0, 6 sec"))
    h = rt.get_input_handler("LoginEvents")
    for ts, ip in [(1366335804341, "192.10.1.3"), (1366335804342, "192.10.1.4"),
                   (1366335814341, "192.10.1.5"), (1366335814345, "192.10.1.6"),
                   (1366335824341, "192.10.1.7")]:
        h.send(ts, [ts, ip])
    m.shutdown()
    assert len(q.events) == 2
    assert q.expired == []


def test_etb_first_anchor_keeps_sub_window_event():
    """externalTimeBatchWindowTest2 (:443-491): without startTime the
    window anchors at the first event's ts, so 805340 (< 804341+1000)
    stays in batch 1 — 2 in rows."""
    m, rt, q = build_q(ETB_APP.format(params="timestamp, 1 sec"))
    h = rt.get_input_handler("LoginEvents")
    for ts, ip in [(1366335804341, "192.10.1.3"), (1366335804342, "192.10.1.4"),
                   (1366335805340, "192.10.1.4"), (1366335814341, "192.10.1.5"),
                   (1366335814345, "192.10.1.6"), (1366335824341, "192.10.1.7")]:
        h.send(ts, [ts, ip])
    m.shutdown()
    assert len(q.events) == 2
    assert q.expired == []


def test_etb_first_anchor_crossing_event():
    """externalTimeBatchWindowTest3 (:493-541): 805341 (== 804341+1000)
    crosses the anchored boundary — 3 in rows."""
    m, rt, q = build_q(ETB_APP.format(params="timestamp, 1 sec"))
    h = rt.get_input_handler("LoginEvents")
    for ts, ip in [(1366335804341, "192.10.1.3"), (1366335804342, "192.10.1.4"),
                   (1366335805341, "192.10.1.4"), (1366335814341, "192.10.1.5"),
                   (1366335814345, "192.10.1.6"), (1366335824341, "192.10.1.7")]:
        h.send(ts, [ts, ip])
    m.shutdown()
    assert len(q.events) == 3
    assert q.expired == []


def test_etb_absolute_second_boundaries():
    """externalTimeBatchWindowTest4 (:543-592): startTime 0 pins
    boundaries to absolute seconds — 805000 and 806000 cross: 3 in rows."""
    m, rt, q = build_q(ETB_APP.format(params="timestamp, 1 sec, 0, 6 sec"))
    h = rt.get_input_handler("LoginEvents")
    for ts, ip in [(1366335804341, "192.10.1.3"), (1366335804999, "192.10.1.4"),
                   (1366335805000, "192.10.1.4"), (1366335805999, "192.10.1.5"),
                   (1366335806000, "192.10.1.6"), (1366335806001, "192.10.1.6"),
                   (1366335824341, "192.10.1.7")]:
        h.send(ts, [ts, ip])
    m.shutdown()
    assert len(q.events) == 3
    assert q.expired == []


def test_etb_timeout_flushes_single_batch():
    """externalTimeBatchWindowTest5 (:594-641): four events in one window,
    3 sec timeout — the scheduler flushes the lone batch: 1 in row."""
    m, rt, q = build_q(ETB_APP.format(params="timestamp, 1 sec, 0, 3 sec"))
    h = rt.get_input_handler("LoginEvents")
    for ts, ip in [(1366335804341, "192.10.1.3"), (1366335804599, "192.10.1.4"),
                   (1366335804600, "192.10.1.5"), (1366335804607, "192.10.1.6")]:
        h.send(ts, [ts, ip])
    tick = rt.get_input_handler("Tick")
    tick.send(1366335809700, [0])        # Thread.sleep(5000)
    m.shutdown()
    assert len(q.events) == 1
    assert q.expired == []


def test_etb_timeout_splits_two_batches():
    """externalTimeBatchWindowTest6 (:643-692): 1 sec windows with a 3 sec
    timeout — the crossing flushes batch 1, the scheduler flushes batch 2:
    2 in rows, 0 removes."""
    m, rt, q = build_q(ETB_APP.format(params="timestamp, 1 sec, 0, 3 sec"))
    h = rt.get_input_handler("LoginEvents")
    for ts, ip in [(1366335804341, "192.10.1.3"), (1366335804599, "192.10.1.4"),
                   (1366335804600, "192.10.1.5"), (1366335804607, "192.10.1.6"),
                   (1366335805599, "192.10.1.4"), (1366335805600, "192.10.1.5"),
                   (1366335805607, "192.10.1.6")]:
        h.send(ts, [ts, ip])
    tick = rt.get_input_handler("Tick")
    tick.send(1366335810700, [0])        # Thread.sleep(5000)
    m.shutdown()
    assert len(q.events) == 2
    assert q.expired == []


def test_etb_append_after_timeout_counts():
    """externalTimeBatchWindowTest8 (:750-816): 1 sec windows, 2 sec
    timeout, out-of-order stragglers appended after timeout flushes — the
    running counts are 4, 3, 5, 7, 2 (appends continue the batch count
    without a RESET)."""
    m, rt, q = build_q(ETB_APP.format(params="timestamp, 1 sec, 0, 2 sec"))
    h = rt.get_input_handler("LoginEvents")
    tick = rt.get_input_handler("Tick")
    # wall clock (send ts) advances monotonically; the attribute carries
    # the reference feed verbatim, including the out-of-order stragglers
    feed1 = [(1366335804341, "192.10.1.3"), (1366335804599, "192.10.1.4"),
             (1366335804600, "192.10.1.5"), (1366335804607, "192.10.1.6"),
             (1366335805599, "192.10.1.4"), (1366335805600, "192.10.1.5"),
             (1366335805607, "192.10.1.6")]
    wall = 1000
    for ts, ip in feed1:
        h.send(wall, [ts, ip]); wall += 10
    tick.send(wall + 2100, [0])          # Thread.sleep(2100): timeout flush
    wall += 2200
    for ts, ip in [(1366335805606, "192.10.1.7"), (1366335805605, "192.10.1.8")]:
        h.send(wall, [ts, ip]); wall += 10
    tick.send(wall + 2100, [0])          # timeout append flush
    wall += 2200
    for ts, ip in [(1366335805606, "192.10.1.91"), (1366335805605, "192.10.1.92"),
                   (1366335806606, "192.10.1.9"), (1366335806690, "192.10.1.10")]:
        h.send(wall, [ts, ip]); wall += 10
    tick.send(wall + 3100, [0])          # final timeout flush
    m.shutdown()
    assert [e.data[2] for e in q.events] == [4, 3, 5, 7, 2]
    assert q.expired == []


# ---------------------------------------------- ExpressionWindowTestCase


EXPR_APP = """@app:playback
    define stream cseEventStream (symbol string, price float, volume int);
    @info(name = 'query1')
    from cseEventStream#window.expression({expr})
    select symbol, price insert all events into OutStream;
"""


def test_expression_window_count_retention():
    """expressionWindowTest1 (:50-92): count() <= 2 behaves as a sliding
    length(2); 5 in, 3 remove."""
    m, rt, q = build_q(EXPR_APP.format(expr="'count() <= 2'"))
    h = rt.get_input_handler("cseEventStream")
    for ts, (sym, p, v) in enumerate([("IBM", 700.0, 0), ("WSO2", 60.5, 1),
                                      ("WSO2", 61.5, 2), ("WSO2", 62.5, 3),
                                      ("WSO2", 63.5, 4)]):
        h.send(1000 + ts, [sym, p, v])
    m.shutdown()
    assert len(q.events) == 5
    assert len(q.expired) == 3


def test_expression_window_attribute_delta_retention():
    """expressionWindowTest2 (:94-135): last.volume - first.volume <= 2
    retains a value-bounded span; 5 in, 2 remove."""
    m, rt, q = build_q(EXPR_APP.format(
        expr="'last.volume - first.volume <= 2'"))
    h = rt.get_input_handler("cseEventStream")
    for ts, v in enumerate(range(5)):
        h.send(1000 + ts, ["WSO2", 60.5 + v, v])
    m.shutdown()
    assert len(q.events) == 5
    assert len(q.expired) == 2


def test_expression_window_timestamp_retention():
    """expressionWindowTest3 (:137-178): eventTimestamp(last) -
    eventTimestamp(first) <= 2 over ms-spaced sends; 5 in, 2 remove."""
    m, rt, q = build_q(EXPR_APP.format(
        expr="'eventTimestamp(last) - eventTimestamp(first) <= 2'"))
    h = rt.get_input_handler("cseEventStream")
    for ts in range(5):
        h.send(ts, ["WSO2", 60.5, ts])
    m.shutdown()
    assert len(q.events) == 5
    assert len(q.expired) == 2


def test_expression_window_dynamic_attribute():
    """expressionWindowTest5 (:227-269): the retention expression rides on
    a stream attribute; 5 in, 2 remove."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int, expr string);
        @info(name = 'query1')
        from cseEventStream#window.expression(expr)
        select symbol, price insert all events into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    h = rt.get_input_handler("cseEventStream")
    expr = "eventTimestamp(last) - eventTimestamp(first) <= 2"
    for ts in range(5):
        h.send(ts, ["WSO2", 60.5 + ts, ts, expr])
    m.shutdown()
    assert len(q.events) == 5
    assert len(q.expired) == 2


def test_expression_window_dynamic_attribute_change():
    """expressionWindowTest6 (:270-312): loosening the expression
    mid-stream widens retention; 5 in, 1 remove."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int, expr string);
        @info(name = 'query1')
        from cseEventStream#window.expression(expr)
        select symbol, price insert all events into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    h = rt.get_input_handler("cseEventStream")
    e1 = "eventTimestamp(last) - eventTimestamp(first) < 2"
    e2 = "eventTimestamp(last) - eventTimestamp(first) < 4"
    h.send(0, ["WSO2", 60.5, 0, e1])
    h.send(1, ["WSO2", 61.5, 1, e1])
    h.send(2, ["WSO2", 62.5, 2, e2])
    h.send(3, ["WSO2", 63.5, 3, e2])
    h.send(4, ["WSO2", 64.5, 4, e2])
    m.shutdown()
    assert len(q.events) == 5
    assert len(q.expired) == 1


def test_expression_window_dynamic_null_keeps_previous():
    """Dynamic expression windows: null expression values keep the one in
    force; leading nulls (no expression yet) retain everything."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int, expr string);
        @info(name = 'query1')
        from cseEventStream#window.expression(expr)
        select symbol, price insert all events into OutStream;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    h = rt.get_input_handler("cseEventStream")
    h.send(0, ["WSO2", 60.5, 0, None])           # no expression yet
    h.send(1, ["WSO2", 61.5, 1, "count() <= 2"])  # now a length-2 bound
    h.send(2, ["WSO2", 62.5, 2, None])            # null: bound stays
    h.send(3, ["WSO2", 63.5, 3, None])
    m.shutdown()
    assert len(q.events) == 4
    assert len(q.expired) == 2


# ----------------------------------------- ExpressionBatchWindowTestCase


EXPRB_APP = """@app:playback
    define stream cseEventStream (symbol string, price float, volume int);
    @info(name = 'query1')
    from cseEventStream#window.expressionBatch({expr})
    select symbol, price insert all events into OutStream;
"""


def _feed_exprb(rt, n=5):
    h = rt.get_input_handler("cseEventStream")
    rows = [("IBM", 700.0, 0), ("WSO2", 60.5, 1), ("WSO2", 61.5, 2),
            ("WSO2", 62.5, 3), ("WSO2", 63.5, 4), ("WSO2", 64.5, 5),
            ("WSO2", 65.5, 6)]
    for ts, (sym, p, v) in enumerate(rows[:n]):
        h.send(ts, [sym, p, v])


def test_expression_batch_count_tumbles():
    """expressionBatchWindowTest1 (:51-93): count() <= 2 tumbles in pairs —
    two 2-row flushes (4 in), first batch expired once (2 removes)."""
    m, rt, q = build_q(EXPRB_APP.format(expr="'count() <= 2'"))
    _feed_exprb(rt)
    m.shutdown()
    assert len(q.events) == 4
    assert len(q.expired) == 2


def test_expression_batch_attribute_delta():
    """expressionBatchWindowTest2 (:95-136): last.volume - first.volume < 2
    — same pair tumbling on the attribute span."""
    m, rt, q = build_q(EXPRB_APP.format(
        expr="'last.volume - first.volume < 2'"))
    _feed_exprb(rt)
    m.shutdown()
    assert len(q.events) == 4
    assert len(q.expired) == 2


def test_expression_batch_timestamp_span():
    """expressionBatchWindowTest3 (:138-179): eventTimestamp span < 2 ms
    with 1 ms sends — pairs again."""
    m, rt, q = build_q(EXPRB_APP.format(
        expr="'eventTimestamp(last) - eventTimestamp(first) < 2'"))
    _feed_exprb(rt)
    m.shutdown()
    assert len(q.events) == 4
    assert len(q.expired) == 2


def test_expression_batch_timestamp_span_triples():
    """expressionBatchWindowTest4 (:181-228): span <= 2 admits triples —
    two 3-row flushes from 7 events (6 in, 3 removes)."""
    m, rt, q = build_q(EXPRB_APP.format(
        expr="'eventTimestamp(last) - eventTimestamp(first) <= 2'"))
    _feed_exprb(rt, n=7)
    m.shutdown()
    assert len(q.events) == 6
    assert len(q.expired) == 3


def test_expression_batch_dynamic_attribute():
    """expressionBatchWindowTest5 (:230-273): the batch expression rides a
    stream attribute."""
    m, rt, q = build_q("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int, expr string);
        @info(name = 'query1')
        from cseEventStream#window.expressionBatch(expr)
        select symbol, price insert all events into OutStream;
    """)
    h = rt.get_input_handler("cseEventStream")
    expr = "count() <= 2"
    for ts in range(5):
        h.send(ts, ["WSO2", 60.5 + ts, ts, expr])
    m.shutdown()
    assert len(q.events) == 4
    assert len(q.expired) == 2
