"""Transport SPI tests: sources, sinks, mappers, InMemoryBroker, retry,
distribution strategies, and custom extensions through set_extension —
mirroring reference ``InMemorySourceTestCase`` / ``InMemorySinkTestCase`` /
``SiddhiExtensionLoader`` behaviors.
"""

import json
import time

from siddhi_tpu import SiddhiManager
from siddhi_tpu.extension import (
    ConnectionUnavailableException,
    InMemoryBroker,
    ScalarFunction,
    Source,
)
from siddhi_tpu.query_api.definitions import AttrType


def setup_function(_fn):
    InMemoryBroker.clear()


def test_inmemory_source_to_sink_roundtrip():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @source(type='inMemory', topic='in')
        define stream InStream (symbol string, price double);
        @sink(type='inMemory', topic='out')
        define stream OutStream (symbol string, price double);
        from InStream[price > 10] select symbol, price insert into OutStream;
    """)
    got = []

    class Sub(InMemoryBroker.Subscriber):
        topic = "out"

        def on_message(self, payload):
            got.append(payload)

    InMemoryBroker.subscribe(Sub())
    rt.start()
    InMemoryBroker.publish("in", ["WSO2", 55.5])
    InMemoryBroker.publish("in", ["IBM", 5.5])      # filtered
    InMemoryBroker.publish("in", ["GOOG", 20.0])
    m.shutdown()
    assert got == [["WSO2", 55.5], ["GOOG", 20.0]]


def test_json_mappers_roundtrip():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @source(type='inMemory', topic='jin', @map(type='json'))
        define stream InStream (symbol string, price double);
        @sink(type='inMemory', topic='jout', @map(type='json'))
        define stream OutStream (symbol string, price double);
        from InStream select symbol, price insert into OutStream;
    """)
    got = []

    class Sub(InMemoryBroker.Subscriber):
        topic = "jout"

        def on_message(self, payload):
            got.append(json.loads(payload))

    InMemoryBroker.subscribe(Sub())
    rt.start()
    InMemoryBroker.publish("jin", '{"event": {"symbol": "WSO2", "price": 55.5}}')
    m.shutdown()
    assert got == [{"event": {"symbol": "WSO2", "price": 55.5}}]


def test_custom_source_with_retry_backoff():
    attempts = []

    class FlakySource(Source):
        def connect(self):
            attempts.append(time.monotonic())
            if len(attempts) < 3:
                raise ConnectionUnavailableException("down")
            # connected: deliver one event through the mapper chain
            self.handler(["OK", 1.0])

    m = SiddhiManager()
    m.set_extension("source:flaky", FlakySource)
    rt = m.create_siddhi_app_runtime("""
        @source(type='flaky')
        define stream InStream (symbol string, price double);
        from InStream select symbol insert into OutStream;
    """)
    seen = []
    from siddhi_tpu import StreamCallback

    class C(StreamCallback):
        def receive(self, events):
            seen.extend(tuple(e.data) for e in events)

    rt.add_callback("OutStream", C())
    rt.start()
    deadline = time.monotonic() + 10
    while len(seen) < 1 and time.monotonic() < deadline:
        time.sleep(0.02)
    m.shutdown()
    assert len(attempts) == 3           # two refusals + one success
    assert seen == [("OK",)]


def test_custom_scalar_function_extension():
    class PriceInCents(ScalarFunction):
        return_type = AttrType.DOUBLE

        @staticmethod
        def apply(xp, price):
            return price * 100.0

    m = SiddhiManager()
    m.set_extension("function:cents", PriceInCents)
    rt = m.create_siddhi_app_runtime("""
        define stream InStream (symbol string, price double);
        from InStream select symbol, cents(price) as cents insert into OutStream;
    """)
    seen = []
    from siddhi_tpu import StreamCallback

    class C(StreamCallback):
        def receive(self, events):
            seen.extend(tuple(e.data) for e in events)

    rt.add_callback("OutStream", C())
    rt.get_input_handler("InStream").send(["WSO2", 55.5])
    m.shutdown()
    assert seen == [("WSO2", 5550.0)]


def test_distributed_sink_round_robin():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @source(type='inMemory', topic='din')
        define stream InStream (symbol string, price double);
        @sink(type='inMemory', @distribution(strategy='roundRobin',
              @destination(topic='d1'), @destination(topic='d2')))
        define stream OutStream (symbol string, price double);
        from InStream select symbol, price insert into OutStream;
    """)
    got = {"d1": [], "d2": []}

    class Sub1(InMemoryBroker.Subscriber):
        topic = "d1"

        def on_message(self, payload):
            got["d1"].append(payload)

    class Sub2(InMemoryBroker.Subscriber):
        topic = "d2"

        def on_message(self, payload):
            got["d2"].append(payload)

    InMemoryBroker.subscribe(Sub1())
    InMemoryBroker.subscribe(Sub2())
    rt.start()
    for i in range(4):
        InMemoryBroker.publish("din", [f"S{i}", float(i)])
    m.shutdown()
    assert len(got["d1"]) == 2 and len(got["d2"]) == 2


def test_persist_pauses_sources():
    from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore

    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime("""
        @source(type='inMemory', topic='pin')
        define stream InStream (symbol string, price double);
        from InStream select symbol insert into OutStream;
    """)
    rt.start()
    sr = rt.source_runtimes[0]
    assert not sr.is_paused
    rt.persist()
    assert not sr.is_paused     # resumed after the checkpoint
    InMemoryBroker.publish("pin", ["A", 1.0])   # still deliverable
    m.shutdown()


def test_sandbox_runtime_strips_sources_sinks_and_stores():
    """createSandboxSiddhiAppRuntime (SiddhiManager.java:104-116): every
    non-inMemory @source/@sink and every @store is stripped, so the app
    runs fully in-process; inMemory transports are KEPT (the reference
    filter only removes non-inMemory types)."""

    class Exploding(Source):
        """Would fail on connect — sandbox must never instantiate it."""

        def connect(self):
            raise ConnectionUnavailableException("must not be called")

    m = SiddhiManager()
    m.set_extension("source:kafkaish", Exploding)
    rt = m.create_sandbox_siddhi_app_runtime("""
        @source(type='kafkaish', topic='t')
        define stream S (symbol string, price double);
        @sink(type='inMemory', topic='sandbox.out')
        define stream OutStream (symbol string, price double);
        @store(type='someRdbms')
        define table T (symbol string, price double);
        from S[price > 10] select symbol, price insert into OutStream;
        from S select symbol, price insert into T;
    """)
    got = []

    class Sub(InMemoryBroker.Subscriber):
        topic = "sandbox.out"

        def on_message(self, payload):
            got.append(payload)

    InMemoryBroker.subscribe(Sub())
    rt.start()
    assert rt.source_runtimes == []          # external source stripped
    from siddhi_tpu.core.table.in_memory_table import InMemoryTable

    assert isinstance(rt.tables["T"], InMemoryTable)   # @store stripped
    h = rt.get_input_handler("S")            # feedable directly
    h.send(["WSO2", 55.5])
    h.send(["IBM", 5.5])
    m.shutdown()
    assert got == [["WSO2", 55.5]]           # inMemory sink survived


def test_on_demand_runtime_cache():
    """Compiled on-demand FIND runtimes are cached per query text, capped
    at 50 oldest-evicted (SiddhiAppRuntimeImpl.java:344-351)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price double);
        define table T (symbol string, price double);
        from S insert into T;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    h.send(["B", 2.0])
    q = "from T on price > 1.5 select symbol, price"
    r1 = rt.query(q)
    assert [e.data for e in r1] == [["B", 2.0]]
    assert q in rt._on_demand_cache
    compiled = rt._on_demand_cache[q]
    # cache HIT serves fresh data through the same compiled runtime
    h.send(["C", 3.0])
    r2 = rt.query(q)
    assert rt._on_demand_cache[q] is compiled
    assert [e.data for e in r2] == [["B", 2.0], ["C", 3.0]]
    # cap: 50 entries, oldest evicted first
    for i in range(51):
        rt.query(f"from T on price > {i}.5 select symbol")
    assert len(rt._on_demand_cache) == 50
    assert q not in rt._on_demand_cache
    m.shutdown()
