"""Multicore ingest pack pool (core/stream/input/pack_pool.py).

Ordered-merge exactness under concurrency, out-of-order sub-batch
completion, packer death (re-packed, never lost), WAL replay and shed
accounting bit-identical to the inline path, and journey pack-stage
attribution (max-not-sum) at pool sizes 0 and 2."""

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.compiler.errors import SiddhiAppValidationException
from siddhi_tpu.core.event import Event
from siddhi_tpu.core.util.config import InMemoryConfigManager
from siddhi_tpu.core.util.persistence import InMemoryPersistenceStore
from siddhi_tpu.observability import journey
from siddhi_tpu.resilience.faults import FaultInjector

APP = """
@app:enforceOrder
define stream S (sym string, v double, n long);
@info(name='q') from S#window.length(64)
  select sym, sum(v) as sv, count() as c group by sym
  insert into Out;
"""

ASYNC_APP = """
@Async(buffer.size='8')
define stream S (sym string, v double, n long);
@info(name='q') from S
  select sym, v, n insert into Out;
"""


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.rows = []

    def receive(self, events):
        self.rows.extend((e.timestamp, tuple(e.data)) for e in events)


def _manager(pool, split=128, extra=None):
    m = SiddhiManager()
    cfg = {"siddhi_tpu.ingest_pool": str(pool),
           "siddhi_tpu.ingest_split": str(split)}
    cfg.update(extra or {})
    m.set_config_manager(InMemoryConfigManager(cfg))
    return m


def _batches(n_batches=5, rows=700, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    ts = 0
    for b in range(n_batches):
        keys = rng.integers(0, 15 + 25 * b, rows)   # new strings per batch
        evs = []
        for i in range(rows):
            sym = None if i % 97 == 5 else f"K{keys[i]}"
            evs.append(Event(timestamp=ts, data=[
                sym, float(np.round(rng.random() * 10, 6)), int(i)]))
            ts += 1
        out.append(evs)
    return out


def _run(pool, app=APP, arm=None, split=128):
    m = _manager(pool, split=split)
    rt = m.create_siddhi_app_runtime(app)
    c = Collector()
    rt.add_callback("Out", c)
    rt.start()
    pl = rt.app_context.ingest_pack_pool
    if arm is not None:
        arm(pl)
    h = rt.get_input_handler("S")
    for evs in _batches():
        h.send(evs)
    strings = list(rt.app_context.string_dictionary._to_str)
    tel = rt.app_context.telemetry.snapshot()
    stats = {"repacks": getattr(pl, "repacked_subbatches", 0),
             "deaths": getattr(pl, "worker_deaths", 0),
             "alive": pl.alive_workers() if pl is not None else 0}
    m.shutdown()
    return c.rows, strings, tel, stats


REF = None


def _reference():
    global REF
    if REF is None:
        REF = _run(pool=0)
    return REF


# ---------------------------------------------------------------- identity


def test_pool_bit_identity_and_dictionary_order():
    ref_rows, ref_strings, _, _ = _reference()
    rows, strings, tel, _ = _run(pool=2)
    assert rows == ref_rows and len(rows) > 0
    assert strings == ref_strings          # id ASSIGNMENT order identical
    hists = tel.get("histograms", {})
    assert hists.get("ingest.pack_ms", {}).get("count", 0) > 0
    assert hists.get("ingest.merge_ms", {}).get("count", 0) > 0


def test_columns_path_bit_identity():
    def run(pool):
        m = _manager(pool)
        rt = m.create_siddhi_app_runtime(APP)
        c = Collector()
        rt.add_callback("Out", c)
        h = rt.get_input_handler("S")
        rng = np.random.default_rng(11)
        ts = 0
        for b in range(4):
            n = 900
            keys = rng.integers(0, 30 + 30 * b, n)
            syms = np.array([f"C{k}" for k in keys], dtype=object)
            syms[7] = None
            h.send_columns(
                {"sym": syms, "v": np.round(rng.random(n), 6),
                 "n": np.arange(n, dtype=np.int64)},
                timestamps=np.arange(ts, ts + n, dtype=np.int64))
            ts += n
        strings = list(rt.app_context.string_dictionary._to_str)
        m.shutdown()
        return c.rows, strings

    r0, s0 = run(0)
    r2, s2 = run(2)
    assert r0 == r2 and len(r0) > 0
    assert s0 == s2


def test_out_of_order_subbatch_completion_stays_ordered():
    """FaultInjector.delay_packer: one sub-batch completes LATE, so the
    pool observes out-of-order completion — the ordered merge (and
    everything downstream: emission order, @app:enforceOrder) must be
    bit-identical anyway."""
    inj = FaultInjector()
    try:
        rows, strings, _, _ = _run(
            pool=2, arm=lambda p: inj.delay_packer(p, 0.1))
    finally:
        inj.clear()
    ref_rows, ref_strings, _, _ = _reference()
    assert rows == ref_rows
    assert strings == ref_strings


def test_kill_packer_subbatch_repacked_not_lost():
    inj = FaultInjector()
    try:
        rows, strings, tel, stats = _run(
            pool=2, arm=lambda p: inj.kill_packer(p))
    finally:
        inj.clear()
    ref_rows, ref_strings, _, _ = _reference()
    assert rows == ref_rows                # nothing lost, order exact
    assert strings == ref_strings
    assert stats["repacks"] >= 1
    assert stats["deaths"] == 1
    assert stats["alive"] == 2             # respawned on a later submit
    assert tel["counters"].get("ingest.pool.repacks", 0) >= 1


def test_supervisor_heals_dead_packers():
    m = _manager(2)
    rt = m.create_siddhi_app_runtime(APP)
    rt.add_callback("Out", Collector())
    rt.start()
    sup = rt.supervise(interval_s=0.05)
    pool = rt.app_context.ingest_pack_pool
    inj = FaultInjector()
    inj.kill_packer(pool)
    h = rt.get_input_handler("S")
    h.send([Event(timestamp=i, data=[f"K{i % 9}", 1.0, i])
            for i in range(1000)])
    import time

    deadline = time.time() + 5.0
    while pool.alive_workers() < 2 and time.time() < deadline:
        time.sleep(0.05)
    alive = pool.alive_workers()
    inj.clear()
    sup.stop()
    m.shutdown()
    assert alive == 2


# ------------------------------------------------------------ WAL / shed


def test_wal_replay_after_restore_bit_identical_with_pool():
    """persist -> crash -> restore + WAL suffix replay with pool=2
    reproduces EXACTLY the uninterrupted pool-0 output stream."""
    batches = _batches()

    def uninterrupted():
        m = _manager(0)
        rt = m.create_siddhi_app_runtime(APP)
        c = Collector()
        rt.add_callback("Out", c)
        h = rt.get_input_handler("S")
        for evs in batches:
            h.send(evs)
        m.shutdown()
        return c.rows

    store = InMemoryPersistenceStore()
    m1 = _manager(2)
    m1.set_persistence_store(store)
    rt1 = m1.create_siddhi_app_runtime(APP)
    c1 = Collector()
    rt1.add_callback("Out", c1)
    wal = rt1.enable_wal()
    h = rt1.get_input_handler("S")
    for evs in batches[:2]:
        h.send(evs)
    rt1.persist()
    for evs in batches[2:4]:
        h.send(evs)
    assert len(wal) == 2
    rows_before = list(c1.rows)
    m1.shutdown()

    m2 = _manager(2)
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(APP)
    c2 = Collector()
    rt2.add_callback("Out", c2)
    rt2.app_context.ingest_wal = wal
    assert rt2.restore_last_revision() is not None
    h2 = rt2.get_input_handler("S")
    for evs in batches[4:]:
        h2.send(evs)
    m2.shutdown()

    expected = uninterrupted()
    assert rows_before == expected[:len(rows_before)]
    # checkpoint covered batches 0-1 (one output row per input event);
    # the new runtime replays the WAL suffix (batches 2-3, exactly once)
    # and continues live — together the uninterrupted stream, bit-exact
    n_checkpoint = 2 * 700
    assert rows_before[:n_checkpoint] + c2.rows == expected


def test_shed_accounting_identical_inline_vs_pool():
    """shed_newest past the queue quota with a wedged consumer: shed
    counts, emitted rows and WAL retention are identical at pool 0 and
    2 (admission runs BEFORE pack — the pool must not perturb it)."""
    def run(pool):
        m = _manager(pool, extra={
            "siddhi_tpu.quota_queue_depth.S": "3",
            "siddhi_tpu.shed_policy.S": "shed_newest"})
        rt = m.create_siddhi_app_runtime(ASYNC_APP)
        c = Collector()
        rt.add_callback("Out", c)
        rt.start()
        wal = rt.enable_wal()
        inj = FaultInjector()
        j = rt.junctions["S"]
        inj.wedge_worker(j)
        h = rt.get_input_handler("S")
        h.send([Event(timestamp=0, data=["w", 0.0, 0])])   # enter the wedge
        assert inj.wait_wedged()
        for b in range(8):                  # quota 3: the tail is shed
            h.send([Event(timestamp=1 + b, data=[f"K{b}", float(b), b])])
        shed = rt.app_context.telemetry.snapshot()["counters"].get(
            "junction.S.shed_events", 0)
        retained = [r.seq for r in wal.records_after(0)]
        inj.release()
        import time

        deadline = time.time() + 5.0
        while time.time() < deadline and j._queue.qsize() > 0:
            time.sleep(0.02)
        inj.clear()
        rows = list(c.rows)
        m.shutdown()
        return shed, retained, rows

    shed0, ret0, rows0 = run(0)
    shed2, ret2, rows2 = run(2)
    assert shed0 > 0
    assert (shed0, ret0) == (shed2, ret2)
    assert rows0 == rows2


# ------------------------------------------------------- journey / knobs


@pytest.mark.parametrize("pool", [0, 2])
def test_pack_bottleneck_named_at_both_pool_sizes(pool):
    """FaultInjector.delay_stage('pack') plants the bottleneck inside
    the pack stage; the critical-path report must name pack whether the
    stage runs inline or as parallel sub-batches (max-not-sum: two
    concurrent delayed packers must not double the attributed time)."""
    m = _manager(pool, split=128)
    rt = m.create_siddhi_app_runtime(APP)
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    h.send([Event(timestamp=i, data=[f"K{i % 9}", 1.0, i])
            for i in range(300)])          # warm compiles pre-journey
    journey.enable()
    inj = FaultInjector()
    inj.delay_stage("pack", 0.02)
    try:
        base = 300
        for b in range(6):
            h.send([Event(timestamp=base, data=[f"K{b}", 1.0, b]),
                    *[Event(timestamp=base + i, data=[f"K{i % 9}", 1.0, i])
                      for i in range(1, 300)]])
            base += 300
    finally:
        inj.clear()
        journey.disable(force=True)
    rep = journey.critical_path_report(m)
    q = rep["apps"][rt.name]["queries"]["q"]
    assert q["bottleneck"] is not None
    assert q["bottleneck"]["stage"] == "pack", q["bottleneck"]
    mean = q["stages"]["pack"]["mean_service_ms"]
    assert mean >= 15.0
    # max-not-sum: 2 concurrent delayed sub-batches attribute ~one delay
    # (+ merge), never the 40ms+ a sum-over-workers would report
    assert mean < 38.0, mean
    m.shutdown()


def test_small_batches_stay_inline():
    m = _manager(4, split=8192)
    rt = m.create_siddhi_app_runtime(APP)
    rt.add_callback("Out", Collector())
    h = rt.get_input_handler("S")
    h.send([Event(timestamp=i, data=["a", 1.0, i]) for i in range(64)])
    snap = rt.app_context.telemetry.snapshot()
    assert snap.get("histograms", {}).get(
        "ingest.pack_ms", {}).get("count", 0) == 0
    m.shutdown()


def test_pool_gauges_registered_and_removed():
    m = _manager(2)
    rt = m.create_siddhi_app_runtime(APP)
    rt.add_callback("Out", Collector())
    rt.start()
    gauges = rt.app_context.telemetry.snapshot()["gauges"]
    assert gauges.get("ingest.pool.workers") == 2.0
    assert "ingest.pool.queue_depth" in gauges
    assert "ingest.pool.utilization" in gauges
    tel = rt.app_context.telemetry
    m.shutdown()
    assert "ingest.pool.workers" not in tel.snapshot()["gauges"]


def test_ingest_knob_junk_raises():
    m = SiddhiManager()
    m.set_config_manager(InMemoryConfigManager(
        {"siddhi_tpu.ingest_pool": "many"}))
    with pytest.raises(SiddhiAppValidationException,
                       match="ingest_pool"):
        m.create_siddhi_app_runtime(APP)
