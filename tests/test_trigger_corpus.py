"""Reference trigger corpus — scenarios ported verbatim from
``query/trigger/TriggerTestCase.java``: 'start'/periodic/cron triggers
and trigger-vs-stream definition collisions."""

import time

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.compiler.errors import SiddhiAppValidationException


class Collect(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def test_trigger_conflicting_stream_schema_rejected():
    """testQuery3 (TriggerTestCase:81-95): a trigger whose id collides
    with a stream of a DIFFERENT schema is a duplicate definition."""
    m = SiddhiManager()
    with pytest.raises(SiddhiAppValidationException):
        m.create_siddhi_app_runtime(
            "define stream StockStream (symbol string, price float, "
            "volume long); "
            "define trigger StockStream at 'start' ")
    m.shutdown()


def test_trigger_equivalent_stream_schema_ok():
    """testQuery4 (:97-111): the same id is fine when the stream already
    has the trigger's (triggered_time long) shape."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream StockStream (triggered_time long); "
        "define trigger StockStream at 'start' ")
    rt.start()
    m.shutdown()


def test_start_trigger_fires_once():
    """testQuery5 (:114-143): `at 'start'` fires exactly one event."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define trigger triggerStream at 'start';")
    c = Collect()
    rt.add_callback("triggerStream", c)
    rt.start()
    time.sleep(0.1)
    m.shutdown()
    assert len(c.events) == 1
    assert isinstance(c.events[0].data[0], int)   # triggered_time ms


def test_periodic_trigger():
    """testQuery6 (:145-174): `at every 500 milliseconds` fires at least
    twice within ~1.1s."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define trigger triggerStream at every 500 milliseconds ;")
    c = Collect()
    rt.add_callback("triggerStream", c)
    rt.start()
    time.sleep(1.2)
    m.shutdown()
    assert len(c.events) >= 2


def test_cron_trigger():
    """testQuery7 (:176-213): a `*/1 * * * * ?` cron trigger fires about
    once a second."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define trigger triggerStream at '*/1 * * * * ?' ;")
    c = Collect()
    rt.add_callback("triggerStream", c)
    rt.start()
    time.sleep(2.2)
    m.shutdown()
    assert len(c.events) >= 2
    gaps = [b.timestamp - a.timestamp
            for a, b in zip(c.events, c.events[1:])]
    assert all(500 <= g <= 1600 for g in gaps), gaps
