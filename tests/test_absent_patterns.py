"""Absent (`not ...`), mid-chain `every`, scoped `within` and group-by
pattern tests — expectations mirror the reference corpus:
``query/pattern/absent/{AbsentPatternTestCase,LogicalAbsentPatternTestCase,
AbsentWithEveryPatternTestCase}.java``.

All apps run in `@app:playback` so deadlines fire deterministically off the
event-time clock (the reference tests Thread.sleep past the `for` windows).
"""

from siddhi_tpu import SiddhiManager, StreamCallback


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []

    def receive(self, events):
        self.events.extend(events)


def build(app, out="OutStream"):
    manager = SiddhiManager()
    runtime = manager.create_siddhi_app_runtime(app)
    collector = Collector()
    runtime.add_callback(out, collector)
    return manager, runtime, collector


STREAMS = """
    define stream Stream1 (symbol string, price float, volume int);
    define stream Stream2 (symbol string, price float, volume int);
    define stream Stream3 (symbol string, price float, volume int);
"""


# ------------------------------------------------------------- tail absent


def test_tail_absent_emits_at_deadline():
    # AbsentPatternTestCase.testQueryAbsent1: A -> not B for 1 sec
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
        select e1.symbol as symbol1
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(1000, ["WSO2", 55.5, 100])
    s1.send(2500, ["LATE", 15.0, 100])   # advances time past the deadline
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("WSO2",)]


def test_tail_absent_violated_by_matching_event():
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
        select e1.symbol as symbol1
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.5, 100])
    s2.send(1500, ["IBM", 60.0, 100])    # violates the absence
    s1.send(3000, ["LATE", 15.0, 100])
    m.shutdown()
    assert c.events == []


def test_tail_absent_non_matching_event_keeps_wait():
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
        select e1.symbol as symbol1
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(1000, ["WSO2", 55.5, 100])
    s2.send(1500, ["IBM", 50.0, 100])    # below e1.price: no violation
    s1.send(2500, ["LATE", 15.0, 100])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("WSO2",)]


# ------------------------------------------------------------- head absent


def test_head_absent_then_stream():
    # AbsentPatternTestCase: not Stream1 for 1 sec -> e2=Stream2
    m, rt, c = build("@app:playback " + STREAMS + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
        select e2.symbol as symbol2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    # playback head waits anchor at the app clock's FIRST value: start
    # the timeline with a non-violating event (price <= 10)
    s1.send(0, ["start", 5.0, 100])
    s2.send(1500, ["IBM", 30.0, 100])    # past the armed deadline: match
    s2.send(1600, ["DUP", 35.0, 100])    # chain consumed: single match
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("IBM",)]


def test_head_absent_violated():
    m, rt, c = build("@app:playback " + STREAMS + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
        select e2.symbol as symbol2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(0, ["start", 5.0, 100])      # clock start (non-violating)
    s1.send(500, ["V", 20.0, 100])       # violates inside the window
    # the violated head RE-ARMS at 500 (AbsentPatternTestCase q6/q8):
    # e2 inside the re-armed window still finds no completed absence
    s2.send(1400, ["IBM", 30.0, 100])
    m.shutdown()
    assert c.events == []


def test_head_absent_stream_before_deadline_no_match():
    m, rt, c = build("@app:playback " + STREAMS + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
        select e2.symbol as symbol2
        insert into OutStream;
    """)
    s2 = rt.get_input_handler("Stream2")
    s2.send(500, ["EARLY", 30.0, 100])   # the wait has not elapsed yet
    m.shutdown()
    assert c.events == []


def test_mid_chain_absent():
    # A -> not B for 1 sec -> C: C only matches after a silent window
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
            -> e3=Stream3[price>30]
        select e1.symbol as s1, e3.symbol as s3
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s3 = rt.get_input_handler("Stream3")
    s1.send(1000, ["A", 15.0, 100])
    s3.send(1500, ["EARLY", 35.0, 100])  # before the deadline: no match
    s3.send(2500, ["C", 40.0, 100])      # after: match
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("A", "C")]


# ---------------------------------------------------------- logical absent


def test_and_not_without_for():
    # LogicalAbsentPatternTestCase: not Stream1 and e2=Stream2
    m, rt, c = build("@app:playback " + STREAMS + """
        from not Stream1[price>50] and e2=Stream2[price>20]
        select e2.symbol as symbol2
        insert into OutStream;
    """)
    s2 = rt.get_input_handler("Stream2")
    s2.send(1000, ["IBM", 30.0, 100])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("IBM",)]


def test_and_not_without_for_violated():
    m, rt, c = build("@app:playback " + STREAMS + """
        from not Stream1[price>50] and e2=Stream2[price>20]
        select e2.symbol as symbol2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(500, ["V", 60.0, 100])       # Stream1 arrived first: dead
    s2.send(1000, ["IBM", 30.0, 100])
    m.shutdown()
    assert c.events == []


def test_chained_and_not_with_for_completes_at_deadline():
    # e1 -> (not Stream2 for 1 sec and e3=Stream3): both conditions needed
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
             and e3=Stream3[price>30]
        select e1.symbol as s1, e3.symbol as s3
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s3 = rt.get_input_handler("Stream3")
    s1.send(1000, ["A", 15.0, 100])
    s3.send(1400, ["C", 40.0, 100])      # present side fires inside window
    s1.send(2500, ["T", 1.0, 100])       # advances past the deadline
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("A", "C")]


def test_chained_and_not_with_for_violated():
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
             and e3=Stream3[price>30]
        select e1.symbol as s1, e3.symbol as s3
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s3 = rt.get_input_handler("Stream3")
    s1.send(1000, ["A", 15.0, 100])
    s3.send(1400, ["C", 40.0, 100])
    s2.send(1600, ["V", 25.0, 100])      # violation before the deadline
    s1.send(2500, ["T", 1.0, 100])
    m.shutdown()
    assert c.events == []


def test_or_not_present_side_wins():
    # e1 -> e2 or not Stream3 for 1 sec: the present side can fire early
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>10] -> e2=Stream2[price>20]
             or not Stream3[price>30] for 1 sec
        select e1.symbol as s1, e2.symbol as s2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(1000, ["A", 15.0, 100])
    s2.send(1400, ["B", 25.0, 100])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("A", "B")]


def test_or_not_deadline_side_emits_null():
    # absent side completes: e2 never captured -> null
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>10] -> e2=Stream2[price>20]
             or not Stream3[price>30] for 1 sec
        select e1.symbol as s1, e2.symbol as s2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(1000, ["A", 15.0, 100])
    s1.send(2500, ["T", 1.0, 100])       # advance past deadline
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("A", None)]


def test_both_absent_and_completes():
    # (not Stream1 for 1 sec and not Stream2 for 1 sec) -> e3=Stream3
    m, rt, c = build("@app:playback " + STREAMS + """
        from not Stream1[price>10] for 1 sec and not Stream2[price>20] for 1 sec
             -> e3=Stream3[price>30]
        select e3.symbol as s3
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s3 = rt.get_input_handler("Stream3")
    s1.send(0, ["start", 5.0, 100])      # clock start (non-violating)
    s3.send(1500, ["C", 40.0, 100])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("C",)]


def test_both_absent_and_violated_by_either():
    m, rt, c = build("@app:playback " + STREAMS + """
        from not Stream1[price>10] for 1 sec and not Stream2[price>20] for 1 sec
             -> e3=Stream3[price>30]
        select e3.symbol as s3
        insert into OutStream;
    """)
    s2 = rt.get_input_handler("Stream2")
    s3 = rt.get_input_handler("Stream3")
    s2.send(500, ["V", 25.0, 100])
    s3.send(1500, ["C", 40.0, 100])
    m.shutdown()
    assert c.events == []


# ------------------------------------------------------------ every shapes


def test_every_tail_absent_emits_per_period():
    # e1 -> every not Stream2 for 1 sec: one emission per silent period
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>20] -> every not Stream2[price>e1.price] for 1 sec
        select e1.symbol as symbol1
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(1000, ["WSO2", 55.5, 100])
    s1.send(4500, ["LATE", 15.0, 100])   # deadlines at 2000, 3000, 4000
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("WSO2",), ("WSO2",), ("WSO2",)]


def test_mid_chain_every_stream():
    # A -> every B: each B after A completes (sticky fork keeps A armed)
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] -> every e2=Stream2[price>20]
        select e1.price as p1, e2.price as p2
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A", 25.0, 1])
    s2.send(["X", 45.0, 1])
    s2.send(["Y", 46.0, 1])
    s2.send(["Z", 47.0, 1])
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [(25.0, 45.0), (25.0, 46.0), (25.0, 47.0)]


def test_mid_chain_every_with_continuation():
    # A -> every (B) -> C: every B opens a fresh (B -> C) attempt
    m, rt, c = build(STREAMS + """
        from e1=Stream1[price>20] -> every e2=Stream2[price>20]
             -> e3=Stream3[price>e2.price]
        select e1.price as p1, e2.price as p2, e3.price as p3
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s3 = rt.get_input_handler("Stream3")
    s1.send(["A", 25.0, 1])
    s2.send(["X", 45.0, 1])
    s2.send(["Y", 50.0, 1])
    s3.send(["M", 48.0, 1])   # completes only the X attempt (48 > 45)
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [(25.0, 45.0, 48.0)]


# ------------------------------------------------------------ scoped within


def test_scoped_within_sub_pattern():
    # A -> (B -> C) within 1 sec: the bound clocks from B, not A
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>10] ->
             (e2=Stream2[price>20] -> e3=Stream3[price>30]) within 1 sec
        select e1.symbol as s1, e3.symbol as s3
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s3 = rt.get_input_handler("Stream3")
    s1.send(1000, ["A", 15.0, 100])
    s2.send(5000, ["B", 25.0, 100])      # far from A: scope starts here
    s3.send(5800, ["C", 40.0, 100])      # inside the 1 sec scope
    m.shutdown()
    got = [tuple(e.data) for e in c.events]
    assert got == [("A", "C")]


def test_scoped_within_expires():
    m, rt, c = build("@app:playback " + STREAMS + """
        from e1=Stream1[price>10] ->
             (e2=Stream2[price>20] -> e3=Stream3[price>30]) within 1 sec
        select e1.symbol as s1, e3.symbol as s3
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s3 = rt.get_input_handler("Stream3")
    s1.send(1000, ["A", 15.0, 100])
    s2.send(5000, ["B", 25.0, 100])
    s3.send(6500, ["C", 40.0, 100])      # past the scope bound: expired
    m.shutdown()
    assert c.events == []


# ---------------------------------------------------------------- group by


def test_pattern_group_by_aggregation():
    m, rt, c = build(STREAMS + """
        from every e1=Stream1[price>20] -> e2=Stream2[price>e1.price]
        select e1.symbol as symbol, sum(e2.volume) as total
        group by e1.symbol
        insert into OutStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["AAA", 25.0, 1])
    s2.send(["X", 30.0, 10])     # AAA: 10 (AAA's pending is consumed)
    s1.send(["AAA", 26.0, 1])
    s1.send(["BBB", 28.0, 1])
    s2.send(["Y", 30.0, 5])      # matches both pendings: AAA: 15, BBB: 5
    m.shutdown()
    got = sorted(tuple(e.data) for e in c.events)
    assert got == [("AAA", 10), ("AAA", 15), ("BBB", 5)]
