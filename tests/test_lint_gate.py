"""The lint gate as a tier-1 test: the production tree lints clean
with every rule active, and the guarded-by contract coverage holds.

``tools/graftlint.py`` is the CI spelling of this gate; running the
same engine in-process here means a tree that regresses any rule
(R1–R8) fails the ordinary test run too — nobody has to remember to
run the linter. The coverage floor stops the R8 contract from rotting
by deletion: suppress-or-declare triage must keep a critical mass of
threaded classes declaring ``GUARDED_BY``.
"""

from __future__ import annotations

import ast
import json
import os
import subprocess
import sys

from siddhi_tpu.analysis import default_rules, load_modules, run_lint

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
ROOTS = ("siddhi_tpu", "tools", "bench.py", "__graft_entry__.py")


def _production_modules():
    return load_modules(ROOTS, REPO)


def test_full_gate_zero_findings():
    """Every rule, every production file, zero findings."""
    modules = _production_modules()
    rules = default_rules()
    assert [r.id for r in rules] == [f"R{i}" for i in range(1, 9)]
    findings = run_lint(modules, rules=rules)
    assert not findings, "\n".join(f.format() for f in findings)


def test_guarded_by_coverage_floor():
    """At least 8 production classes declare a non-empty GUARDED_BY —
    the R8 contract is load-bearing, not vestigial."""
    declaring = []
    for mod in _production_modules():
        if not mod.path.startswith("siddhi_tpu/"):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign)
                        and any(isinstance(t, ast.Name)
                                and t.id == "GUARDED_BY"
                                for t in stmt.targets)
                        and isinstance(stmt.value, ast.Dict)
                        and stmt.value.keys):
                    declaring.append(f"{mod.path}:{node.name}")
    assert len(declaring) >= 8, declaring


def test_json_gate_output():
    """--json emits machine-readable records with the same exit-code
    contract as the text mode."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         "--json"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == []
    assert doc["files"] > 100
    assert doc["rules"] == [f"R{i}" for i in range(1, 9)]
