"""Reference @synchronized corpus — scenarios from
``managment/QuerySyncTestCase.java``. Synchronization is by construction
here (single host pump + per-query lock), so the corpus pins that the
annotation parses everywhere the reference allows it and the query
behavior is unchanged."""

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.query.callback import QueryCallback


class QCollect(QueryCallback):
    def __init__(self):
        self.events = []
        self.expired = []

    def receive(self, timestamp, in_events, remove_events):
        if in_events:
            self.events.extend(in_events)
        if remove_events:
            self.expired.extend(remove_events)


class Collector(StreamCallback):
    def __init__(self):
        super().__init__()
        self.events = []
        self.expired = []

    def receive(self, events):
        for e in events:
            (self.expired if e.is_expired else self.events).append(e)


def test_synchronized_time_window():
    """querySyncTest1 (:51-95): a @synchronized time(2 sec) query — 2 in,
    2 remove."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int);
        define stream Tick (x int);
        @info(name = 'query1')
        @synchronized('true')
        from cseEventStream#window.time(2 sec)
        select symbol, price, volume insert all events into OutStream;
        from Tick select x insert into TickOut;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    h = rt.get_input_handler("cseEventStream")
    h.send(1000, ["IBM", 700.0, 0])
    h.send(1010, ["WSO2", 60.5, 1])
    rt.get_input_handler("Tick").send(4100, [0])
    m.shutdown()
    assert len(q.events) == 2
    assert len(q.expired) == 2


def test_synchronized_snapshot_rate_limit():
    """querySyncTest2 (:97-155): @synchronized + `output snapshot every
    1 sec` — only the live snapshot rows surface, never removes."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        @app:name('SnapshotOutputRateLimitTest3')
        define stream LoginEvents (timestamp long, ip string);
        define stream Tick (x int);
        @info(name = 'query1')
        @synchronized('true')
        from LoginEvents
        select ip
        output snapshot every 1 sec
        insert all events into uniqueIps;
        from Tick select x insert into TickOut;
    """)
    c = Collector()
    rt.add_callback("uniqueIps", c)
    h = rt.get_input_handler("LoginEvents")
    tick = rt.get_input_handler("Tick")
    h.send(1000, [1000, "192.10.1.5"])
    h.send(1100, [1100, "192.10.1.3"])
    tick.send(3300, [0])                 # snapshots at 2000/3000: last = .3
    h.send(3400, [3400, "192.10.1.9"])
    h.send(3500, [3500, "192.10.1.4"])
    tick.send(4600, [0])                 # snapshot at 4000: last = .4
    m.shutdown()
    assert c.expired == []
    assert c.events                      # snapshots arrived
    assert all(e.data[0] in ("192.10.1.3", "192.10.1.4") for e in c.events)


def test_synchronized_join():
    """querySyncTest3 (:157-205): @synchronized join of two time(1 sec)
    windows — 2 in events, 2 removes."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int);
        define stream twitterStream (user string, tweet string, company string);
        define stream Tick (x int);
        @info(name = 'query1')
        @synchronized('true')
        from cseEventStream#window.time(1 sec) as a join twitterStream#window.time(1 sec) as b
        on a.symbol == b.company
        select a.symbol as symbol, b.tweet, a.price
        insert all events into OutStream;
        from Tick select x insert into TickOut;
    """)
    q = QCollect()
    rt.add_callback("query1", q)
    cse = rt.get_input_handler("cseEventStream")
    twitter = rt.get_input_handler("twitterStream")
    cse.send(1000, ["WSO2", 55.6, 100])
    twitter.send(1010, ["User1", "Hello World", "WSO2"])
    cse.send(1020, ["IBM", 75.6, 100])
    cse.send(1520, ["WSO2", 57.6, 100])  # Thread.sleep(500)
    rt.get_input_handler("Tick").send(3200, [0])
    m.shutdown()
    assert len(q.events) == 2            # tweet x 55.6, then 57.6 x tweet
    assert len(q.expired) == 2
