"""Runtime sanitizer tests (SIDDHI_TPU_SANITIZE=1).

The detectors are armed per-call against the env var, so these tests
monkeypatch it on, plant each violation class, and assert the sanitizer
names the culprit — then the teardown restores the env and every patch
goes inert for the rest of the suite."""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from siddhi_tpu.analysis import sanitize
from siddhi_tpu.analysis.locks import CheckedRLock, LockOrderError, make_lock


@pytest.fixture
def sanitized(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_SANITIZE", "1")
    sanitize.enable()
    yield
    sanitize.disable()


# ------------------------------------------------------------ pull guard

def test_planted_host_pull_is_caught(sanitized):
    arr = jax.jit(lambda x: x + 1)(jnp.arange(4.0))
    with pytest.raises(sanitize.HostPullError, match="host pull"):
        float(arr[0])
    with pytest.raises(sanitize.HostPullError):
        arr[0].item()
    with pytest.raises(sanitize.HostPullError):
        bool(arr[0] > 0)
    with pytest.raises(sanitize.HostPullError):
        int(arr[1])


def test_sanctioned_pulls_stay_allowed(sanitized):
    arr = jax.jit(lambda x: x * 2)(jnp.arange(4.0))
    # the engine's batched pull point is explicit and allowed
    host = jax.device_get(arr)
    assert host[1] == 2.0
    # cold-path reads declare themselves
    with sanitize.allowed_pull():
        assert float(arr[0]) == 0.0


def test_pull_guard_inert_without_env():
    arr = jnp.arange(3.0)
    assert float(arr[2]) == 2.0     # no env var -> patched dunder passes


def test_lazycolumns_pop_is_explicit(sanitized):
    """The LazyColumns.pop meta pull (every drain's first touch) must be
    transfer-guard-clean."""
    from siddhi_tpu.core.event import LazyColumns

    out = LazyColumns({"__meta__": jax.jit(
        lambda: jnp.array([0, -1, 3], jnp.int64))()})
    meta = out.pop("__meta__")
    assert isinstance(meta, np.ndarray) and meta[2] == 3


# ------------------------------------------------------- recompile guard

def _registry():
    from siddhi_tpu.observability.telemetry import TelemetryRegistry

    return TelemetryRegistry()


def test_planted_post_warmup_recompile_is_caught(sanitized):
    tel = _registry()
    step = tel.instrument_jit(jax.jit(lambda x: x * 2), "test.step")
    step(jnp.ones(4))               # warmup compile
    sanitize.freeze_compiles()
    with pytest.raises(sanitize.RecompileError, match="test.step"):
        step(jnp.ones(8))           # new shape -> cache miss -> raise
    sanitize.thaw_compiles()


def test_recompile_budget(sanitized, monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_SANITIZE_MAX_COMPILES", "2")
    tel = _registry()
    step = tel.instrument_jit(jax.jit(lambda x: x + 1), "test.budget")
    step(jnp.ones(2))
    step(jnp.ones(4))               # compile 2: at budget, fine
    with pytest.raises(sanitize.RecompileError, match="test.budget"):
        step(jnp.ones(8))           # compile 3: past budget
    # telemetry recorded every compile, not just the first
    assert tel.jit["test.budget"]["compiles"] >= 3


def test_stable_shapes_never_trip(sanitized):
    sanitize.freeze_compiles()
    try:
        tel = _registry()
        step = tel.instrument_jit(jax.jit(lambda x: x - 1), "test.stable")
        sanitize.thaw_compiles()
        step(jnp.ones(16))
        sanitize.freeze_compiles()
        for _ in range(5):
            step(jnp.ones(16))      # cache hits: silent
    finally:
        sanitize.thaw_compiles()


# ------------------------------------------------------- lock-order shim

def test_lock_order_inversion_raises():
    pump, owner = CheckedRLock("pump"), CheckedRLock("owner")
    with pump:
        with pytest.raises(LockOrderError, match="owner.*pump"):
            with owner:
                pass


def test_lock_order_declared_direction_ok():
    barrier, owner, pump = (CheckedRLock("barrier"), CheckedRLock("owner"),
                            CheckedRLock("pump"))
    with barrier:
        with owner:
            with pump:
                pass
    # shard -> wal likewise
    with CheckedRLock("shard"):
        with CheckedRLock("wal"):
            pass


def test_lock_order_same_rank_and_reentry_ok():
    a, b = CheckedRLock("owner"), CheckedRLock("owner")
    with a:
        with a:         # re-entrant
            with b:     # same-rank chain (emit cascades)
                pass


def test_lock_order_is_per_thread():
    pump, owner = CheckedRLock("pump"), CheckedRLock("owner")
    errs = []

    def other():
        try:
            with owner:
                pass
        except Exception as e:      # pragma: no cover — would be a bug
            errs.append(e)

    with pump:
        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert not errs


def test_make_lock_plain_without_env(monkeypatch):
    monkeypatch.delenv("SIDDHI_TPU_SANITIZE", raising=False)
    lk = make_lock("pump")
    assert isinstance(lk, type(threading.RLock()))


def test_make_lock_checked_with_env(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_SANITIZE", "1")
    lk = make_lock("pump")
    assert isinstance(lk, CheckedRLock)
    with pytest.raises(ValueError, match="undeclared"):
        make_lock("nonsense")


def test_engine_runs_clean_under_sanitize(monkeypatch):
    """End-to-end: a real app (pipelined, ranked locks active) runs a
    batch with every sanitizer armed and trips nothing."""
    monkeypatch.setenv("SIDDHI_TPU_SANITIZE", "1")
    sanitize.enable()
    try:
        from siddhi_tpu import SiddhiManager

        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime("""
define stream S (sym string, v long);
@info(name='q') from S#window.length(4)
  select sym, sum(v) as total group by sym insert into Out;
""")
        got = []
        rt.add_callback("Out", _collect(got))
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(8):
            h.send([f"k{i % 2}", i])
        assert len(got) == 8
        m.shutdown()
    finally:
        sanitize.disable()


def _collect(sink):
    from siddhi_tpu import StreamCallback

    class _C(StreamCallback):
        def receive(self, events):
            sink.extend(events)

    return _C()
